"""The node API layer — ComfyUI-style declarative nodes over the TPU framework.

This re-exposes the reference's entire L4 surface (SURVEY §2a) with the same node
protocol (``INPUT_TYPES`` / ``RETURN_TYPES`` / ``RETURN_NAMES`` / ``FUNCTION`` /
``CATEGORY`` / ``DESCRIPTION``) so a ComfyUI-style graph host can register and drive
the framework exactly as it drives the reference:

- ``ParallelDevice``      — one chain link, chainable (any_device_parallel.py:768-832)
- ``ParallelDeviceList``  — flat 1-4 device/percentage variant (834-882)
- ``ParallelAnything``    — the orchestrator node (884-1471)
- ``NODE_CLASS_MAPPINGS`` / ``NODE_DISPLAY_NAME_MAPPINGS`` (1473-1483)

The DEVICE_CHAIN wire value is the reference's: a plain list of
``{"device": str, "percentage": float, "weight": float}`` dicts (823-832). The
``weight`` key is written for wire parity but never read back — the orchestrator
renormalizes from ``percentage`` only, exactly like setup_parallel (1019-1027, where
the SURVEY flags ``weight`` as dead data).
"""

from __future__ import annotations

from typing import Any

from .devices.discovery import available_devices
from .parallel.chain import DeviceChain
from .parallel.orchestrator import ParallelConfig, parallelize

CATEGORY = "parallel/tpu"

# Stock ComfyUI seed widgets are 64-bit: the UI's "randomize" fills the full
# [0, 2**64) range. jax.random.key takes a SIGNED int64, so a seed >= 2**63
# coming through the stock shims (nodes_compat) would raise OverflowError in
# roughly half of randomly-seeded exported workflows.
SEED_MAX = 2**64 - 1


def seed_key(seed: int):
    """``jax.random.key`` for any ComfyUI seed, folding the stock 64-bit range
    deterministically into jax's signed-int64 domain."""
    import jax

    return jax.random.key(int(seed) % 2**63)


def chain_from_wire(entries: list[dict[str, Any]] | None) -> DeviceChain:
    """DEVICE_CHAIN wire format → DeviceChain (drops pct <= 0, parity 876-882)."""
    if not entries:
        return DeviceChain()
    return DeviceChain.from_pairs(
        (e["device"], float(e.get("percentage", 0.0))) for e in entries
    )


def chain_to_wire(chain: DeviceChain) -> list[dict[str, Any]]:
    """DeviceChain → the reference's wire format, including the dead ``weight`` key
    (pct/100, written at 826/880 and never read)."""
    return [
        {"device": l.device, "percentage": l.percentage, "weight": l.percentage / 100.0}
        for l in chain.links
    ]


class ParallelDevice:
    """One link in the device chain: pick a device + workload %, chainable via the
    optional ``previous_devices`` input (parity: 768-832)."""

    DESCRIPTION = (
        "Add a device to the parallel chain with a workload percentage. "
        "Chain multiple nodes to build an N-device setup."
    )
    RETURN_TYPES = ("DEVICE_CHAIN",)
    RETURN_NAMES = ("device_chain",)
    FUNCTION = "add_device"
    CATEGORY = CATEGORY

    @classmethod
    def get_available_devices(cls) -> list[str]:
        return available_devices()

    @classmethod
    def INPUT_TYPES(cls):
        devices = cls.get_available_devices()
        return {
            "required": {
                "device_id": (
                    devices,
                    {"default": devices[0], "tooltip": "Device to add to the chain"},
                ),
                "percentage": (
                    "FLOAT",
                    {
                        "default": 50.0,
                        "min": 1.0,
                        "max": 100.0,
                        "step": 1.0,
                        "tooltip": "Share of the workload for this device",
                    },
                ),
            },
            "optional": {
                "previous_devices": (
                    "DEVICE_CHAIN",
                    {"tooltip": "Chain from an upstream Parallel Device node"},
                ),
            },
        }

    def add_device(self, device_id: str, percentage: float, previous_devices=None):
        # Copy-then-append, like the reference (821-832) — upstream lists are never
        # mutated, so re-running a graph node is side-effect free.
        chain = list(previous_devices) if previous_devices else []
        chain.append(
            {
                "device": device_id,
                "percentage": float(percentage),
                "weight": float(percentage) / 100.0,
            }
        )
        return (chain,)


class ParallelDeviceList:
    """Flat alternative: one node, four device+percentage pairs; entries with
    percentage <= 0 are dropped (parity: 834-882)."""

    DESCRIPTION = "Configure up to 4 devices in one node; 0% disables a slot."
    RETURN_TYPES = ("DEVICE_CHAIN",)
    RETURN_NAMES = ("device_chain",)
    FUNCTION = "create_list"
    CATEGORY = CATEGORY
    N_SLOTS = 4

    @classmethod
    def get_available_devices(cls) -> list[str]:
        return available_devices()

    @classmethod
    def INPUT_TYPES(cls):
        devices = cls.get_available_devices()
        required = {}
        for i in range(1, cls.N_SLOTS + 1):
            required[f"device_{i}"] = (
                devices,
                {"default": devices[0], "tooltip": f"Device for slot {i}"},
            )
            required[f"percentage_{i}"] = (
                "FLOAT",
                {
                    "default": 50.0 if i <= 2 else 0.0,
                    "min": 0.0,
                    "max": 100.0,
                    "step": 1.0,
                    "tooltip": f"Workload share for slot {i}; 0 disables",
                },
            )
        return {"required": required}

    def create_list(self, **kwargs):
        chain = []
        for i in range(1, self.N_SLOTS + 1):
            pct = float(kwargs.get(f"percentage_{i}", 0.0))
            if pct <= 0:
                continue
            dev = kwargs[f"device_{i}"]
            chain.append({"device": dev, "percentage": pct, "weight": pct / 100.0})
        return (chain,)


class ParallelAnything:
    """The orchestrator node: takes MODEL + DEVICE_CHAIN, wraps the model so every
    sampler step runs parallel over the chain, returns the wrapped MODEL
    (parity: 884-1471)."""

    DESCRIPTION = (
        "True multi-device parallelism: shards each denoise step across the device "
        "chain as one SPMD program (data parallel for batches, pipeline block "
        "placement for batch=1)."
    )
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "setup_parallel"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL", {"tooltip": "Diffusion model to parallelize"}),
                "parallel_devices": (
                    "DEVICE_CHAIN",
                    {"tooltip": "Device chain from Parallel Device node(s)"},
                ),
                # Widget defaults match the reference's effective values (SURVEY §5.6:
                # the auto_vram_balance widget default True wins over the python
                # signature default False because hosts always pass widget values).
                "workload_split": (
                    "BOOLEAN",
                    {"default": True, "tooltip": "Split batches across devices"},
                ),
                "auto_vram_balance": (
                    "BOOLEAN",
                    {
                        "default": True,
                        "tooltip": "Blend workload split with free device memory",
                    },
                ),
                "purge_cache": (
                    "BOOLEAN",
                    {"default": True, "tooltip": "Release caches at teardown"},
                ),
                "purge_models": (
                    "BOOLEAN",
                    {"default": False, "tooltip": "Also drop compiled programs"},
                ),
            },
        }

    def setup_parallel(
        self,
        model,
        parallel_devices,
        workload_split: bool = True,
        auto_vram_balance: bool = True,
        purge_cache: bool = True,
        purge_models: bool = False,
        **config_extra,
    ):
        chain = chain_from_wire(parallel_devices)
        if not config_extra.get("reactivate_after"):
            # Widget convention: 0 = off. ParallelConfig uses None for off —
            # a literal 0 would mean "reactivate on the very next step".
            config_extra.pop("reactivate_after", None)
        config = ParallelConfig(
            workload_split=workload_split,
            auto_memory_balance=auto_vram_balance,
            purge_cache=purge_cache,
            purge_models=purge_models,
            **config_extra,
        )
        # parallelize returns the model unchanged on an unusable chain, matching the
        # reference's abort paths (1019-1027, 1037-1042).
        return (parallelize(model, chain, config),)


class ParallelAnythingAdvanced(ParallelAnything):
    """The orchestrator node with the beyond-reference knobs exposed: weight
    sharding (FSDP for models bigger than one chip) and tensor parallelism."""

    DESCRIPTION = (
        ParallelAnything.DESCRIPTION
        + " Advanced: FSDP weight sharding and tensor parallelism for models "
        "larger than a single device."
    )
    # setup_parallel's **config_extra already routes the extra widgets into
    # ParallelConfig — no forwarding override needed.
    FUNCTION = "setup_parallel"

    @classmethod
    def INPUT_TYPES(cls):
        base = ParallelAnything.INPUT_TYPES()
        base["required"]["weight_sharding"] = (
            ["replicate", "fsdp"],
            {
                "default": "replicate",
                "tooltip": "fsdp shards each weight across the chain (model > 1 chip)",
            },
        )
        base["required"]["tensor_parallel"] = (
            "INT",
            {
                "default": 1,
                "min": 1,
                "max": 64,
                "tooltip": "model-axis size; >1 partitions the matmuls (GSPMD TP)",
            },
        )
        base["optional"] = dict(base.get("optional") or {})
        base["optional"]["pipeline_microbatches"] = (
            "INT",
            {
                "default": 0,
                "min": 0,
                "max": 64,
                "tooltip": "GPipe-style throughput pipelining for batch>1: "
                           "split the batch into this many microbatches "
                           "streamed through the stage chain (0 or 1 = off; "
                           "needs >=2 to pipeline)",
            },
        )
        base["optional"]["reactivate_after"] = (
            "INT",
            {
                "default": 0,
                "min": 0,
                "max": 10000,
                "tooltip": "auto-resume the parallel path this many single-"
                           "device steps after a step-OOM demotion (0 = "
                           "permanent demotion until manual reactivate)",
            },
        )
        return base


# ---------------------------------------------------------------------------
# Host-layer nodes (beyond the reference's 3 nodes).
#
# The reference assumes ComfyUI provides the rest of the graph —
# CheckpointLoaderSimple → CLIPTextEncode → KSampler → VAEDecode — around its
# wrapped MODEL (SURVEY §2g lists exactly what it consumes from that host).
# Standalone, this framework supplies those surrounding nodes itself, with the
# same wire vocabulary (MODEL / CLIP / CONDITIONING / LATENT / VAE / IMAGE), so a
# reference user's whole workflow maps node-for-node.
# ---------------------------------------------------------------------------

_MODEL_FAMILIES = (
    "sd15", "sd15-inpaint", "sd21", "sd21-v", "sd21-inpaint", "sd21-unclip",
    "sdxl", "sdxl-inpaint", "sdxl-refiner",
    "sd3-medium", "sd35-medium", "sd35-large",
    "flux-dev", "flux-schnell", "zimage-turbo", "wan-1.3b", "wan-14b",
)


class TPUCheckpointLoader:
    """Checkpoint file → (MODEL, VAE). The diffusion subtree and (when present in
    the file) the first_stage_model VAE subtree load together, like the host
    loader the reference defers to."""

    DESCRIPTION = "Load a diffusion checkpoint (and its bundled VAE) for a family."
    RETURN_TYPES = ("MODEL", "VAE")
    RETURN_NAMES = ("model", "vae")
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "ckpt_path": ("STRING", {"default": "", "tooltip": "safetensors path"}),
                "family": (
                    list(_MODEL_FAMILIES),
                    {"default": "sd15", "tooltip": "model family / config preset"},
                ),
            },
            "optional": {
                "vae_path": (
                    "STRING",
                    {"default": "", "tooltip": "separate VAE file (flux ae, fixed vae)"},
                ),
                "lora_path": ("STRING", {"default": ""}),
                "lora_strength": ("FLOAT", {"default": 1.0, "min": -4.0, "max": 4.0}),
                "quantize": (
                    ["none", "int8"],
                    {"default": "none",
                     "tooltip": "int8 halves weight HBM (per-channel symmetric; "
                                "e.g. flux-dev fits one v5e chip replicated)"},
                ),
            },
        }

    def load(
        self,
        ckpt_path: str,
        family: str,
        vae_path: str = "",
        lora_path: str = "",
        lora_strength: float = 1.0,
        quantize: str = "none",
        load_vae: bool = True,
    ):
        # load_vae=False skips the VAE conversion and returns (MODEL, None) —
        # for re-load paths that only need the diffusion model (the
        # LoraLoader shim re-bakes and discards everything else).
        from .models import (
            flux_dev_config,
            flux_schnell_config,
            flux_vae_config,
            load_flux_checkpoint,
            load_safetensors,
            load_sd_unet_checkpoint,
            load_vae_checkpoint,
            sd15_config,
            sd21_config,
            sd_vae_config,
            sdxl_config,
            sdxl_refiner_config,
            sdxl_vae_config,
            z_image_turbo_config,
        )

        lora = lora_path or None

        import contextlib

        import jax

        # int8 load path: conversion materializes the FULL-precision pytree —
        # on the accelerator that would OOM before quantization can help (the
        # whole point is that flux-dev-class f32 does NOT fit a v5e). Pin the
        # load to host CPU RAM, quantize there, and let placement (parallelize)
        # move only the int8 payload to the chips.
        load_ctx = (
            jax.default_device(jax.devices("cpu")[0])
            if quantize == "int8"
            else contextlib.nullcontext()
        )

        def maybe_quant(m):
            if quantize == "int8":
                from .models import quantize_model

                return quantize_model(m)
            return m

        sd = load_safetensors(ckpt_path)
        if family.startswith("wan"):
            # WAN family: video DiT + causal 3D VAE (its own checkpoint file —
            # WAN releases don't bundle the VAE with the DiT weights).
            from .models import (
                load_wan_checkpoint,
                load_wan_vae_checkpoint,
                wan_1_3b_config,
                wan_14b_config,
            )

            wcfg = (wan_14b_config if family == "wan-14b" else wan_1_3b_config)()
            # Variant sniffing within the family: i2v checkpoints carry extra
            # in-channels (36 = latent + frame mask + cond latent) and the
            # WAN2.1-style ones add the CLIP-vision branch (img_emb.* — its
            # proj.1 Linear's input width is the CLIP hidden size).
            import dataclasses as _dc

            pe = sd.get("patch_embedding.weight")
            img_w = sd.get("img_emb.proj.1.weight")
            wcfg = _dc.replace(
                wcfg,
                in_channels=(
                    int(pe.shape[1]) if pe is not None else wcfg.in_channels
                ),
                img_dim=(
                    int(img_w.shape[1]) if img_w is not None else None
                ),
            )
            with load_ctx:
                model = load_wan_checkpoint(sd, wcfg, lora, lora_strength)
                model = maybe_quant(model)
            if not load_vae:
                return model, None
            if not vae_path:
                raise ValueError(
                    "wan checkpoints don't bundle a VAE — set vae_path to the "
                    "Wan VAE safetensors file (convert the official .pth once "
                    "with safetensors.torch.save_file)"
                )
            return model, load_wan_vae_checkpoint(vae_path)
        with load_ctx:
            if family in ("sd15", "sd15-inpaint"):
                # Kwargs only for the inpaint variant: tests monkeypatch the
                # preset factories with zero-arg tiny versions.
                ucfg = sd15_config(
                    **({"in_channels": 9} if family == "sd15-inpaint" else {})
                )
                model = load_sd_unet_checkpoint(sd, ucfg, lora, lora_strength)
                vae_cfg = sd_vae_config()
            elif family in ("sd3-medium", "sd35-medium", "sd35-large"):
                from .models import (
                    load_mmdit_checkpoint,
                    sd3_medium_config,
                    sd3_vae_config,
                    sd35_large_config,
                    sd35_medium_config,
                )

                mcfg = {
                    "sd35-large": sd35_large_config,
                    "sd35-medium": sd35_medium_config,
                    "sd3-medium": sd3_medium_config,
                }[family]()
                model = load_mmdit_checkpoint(sd, mcfg, lora, lora_strength)
                vae_cfg = sd3_vae_config()
            elif family in ("sd21", "sd21-v", "sd21-inpaint", "sd21-unclip"):
                ucfg = sd21_config(
                    prediction="v" if family == "sd21-v" else "eps",
                    **({"in_channels": 9} if family == "sd21-inpaint" else {}),
                )
                if family == "sd21-unclip":
                    # The unCLIP variants derive from the 768-v model
                    # (v-prediction) and add an adm head whose width the
                    # checkpoint's label_emb records (1536 = ViT-L embeds +
                    # level embedding, 2048 = ViT-H).
                    import dataclasses as _dc

                    le = sd.get("label_emb.0.0.weight")
                    if le is None:
                        le = sd.get("model.diffusion_model.label_emb.0.0.weight")
                    if le is None:
                        raise ValueError(
                            "sd21-unclip checkpoint has no label_emb — "
                            "not an unCLIP variant"
                        )
                    ucfg = _dc.replace(
                        ucfg, prediction="v",
                        adm_in_channels=int(le.shape[1]),
                    )
                model = load_sd_unet_checkpoint(sd, ucfg, lora, lora_strength)
                vae_cfg = sd_vae_config()
            elif family in ("sdxl", "sdxl-inpaint", "sdxl-refiner"):
                if family == "sdxl-refiner":
                    xcfg = sdxl_refiner_config()
                else:
                    xcfg = sdxl_config(
                        **({"in_channels": 9} if family == "sdxl-inpaint" else {})
                    )
                model = load_sd_unet_checkpoint(sd, xcfg, lora, lora_strength)
                vae_cfg = sdxl_vae_config()
            else:
                cfg = {
                    "flux-dev": flux_dev_config,
                    "flux-schnell": flux_schnell_config,
                    "zimage-turbo": z_image_turbo_config,
                }[family]()
                model = load_flux_checkpoint(sd, cfg, lora, lora_strength)
                vae_cfg = flux_vae_config()
            model = maybe_quant(model)
        if not load_vae:
            return model, None
        vae_sd = load_safetensors(vae_path) if vae_path else sd
        from .models.convert_vae import strip_vae_prefix

        if not any(
            k.startswith("decoder.") for k in strip_vae_prefix(vae_sd)
        ):
            raise ValueError(
                f"no VAE weights in {'vae_path' if vae_path else 'the checkpoint'} — "
                "flux/bare-UNet checkpoints don't bundle one; set vae_path to the "
                "autoencoder file (e.g. ae.safetensors)"
            )
        vae = load_vae_checkpoint(vae_sd, cfg=vae_cfg)
        return model, vae


class TPUCLIPLoader:
    """Tokenizer+encoder files → CLIP wire value (encoder plus its tokenizer)."""

    DESCRIPTION = "Load a CLIP/T5 text encoder and its tokenizer tables."
    RETURN_TYPES = ("CLIP",)
    RETURN_NAMES = ("clip",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "encoder_path": ("STRING", {"default": ""}),
                "encoder_type": (
                    ["clip-l", "open-clip-g", "open-clip-h", "t5", "umt5"],
                    {"default": "clip-l"},
                ),
            },
            "optional": {
                "vocab_path": ("STRING", {"default": "", "tooltip": "CLIP vocab.json"}),
                "merges_path": ("STRING", {"default": "", "tooltip": "CLIP merges.txt"}),
                "tokenizer_json": ("STRING", {"default": "", "tooltip": "tokenizer.json"}),
                "max_len": ("INT", {"default": 77, "min": 8, "max": 4096}),
            },
        }

    def load(
        self,
        encoder_path: str,
        encoder_type: str,
        vocab_path: str = "",
        merges_path: str = "",
        tokenizer_json: str = "",
        max_len: int = 77,
    ):
        from .models import load_clip_text_checkpoint, load_t5_checkpoint
        from .utils.tokenizer import CLIPBPETokenizer, load_tokenizer_json

        if encoder_type in ("t5", "umt5"):
            if not tokenizer_json:
                raise ValueError(
                    f"encoder_type={encoder_type!r} requires tokenizer_json (no "
                    "vocab.json/merges.txt form exists for these tokenizers)"
                )
            if encoder_type == "umt5":
                from .models import umt5_xxl_config

                enc = load_t5_checkpoint(encoder_path, umt5_xxl_config())
            else:
                enc = load_t5_checkpoint(encoder_path)
            tok = load_tokenizer_json(tokenizer_json, max_len=max_len, eos_id=1)
        else:
            cfg = None
            if encoder_type == "open-clip-h":
                from .models import open_clip_h_config

                cfg = open_clip_h_config()
            enc = load_clip_text_checkpoint(
                encoder_path, cfg=cfg,
                open_clip=encoder_type in ("open-clip-g", "open-clip-h")
            )
            if tokenizer_json:
                tok = load_tokenizer_json(tokenizer_json, max_len=max_len)
            elif vocab_path and merges_path:
                tok = CLIPBPETokenizer.from_files(
                    vocab_path, merges_path, max_len=max_len,
                    pad_id=(
                        0
                        if encoder_type in ("open-clip-g", "open-clip-h")
                        else None
                    ),
                )
            else:
                raise ValueError(
                    "CLIP loading needs tokenizer_json OR both vocab_path and "
                    "merges_path"
                )
        # Content stamp for the cross-request embed cache: a stable model
        # key (file identity — path + size + mtime, so an in-place file
        # replacement changes the key — plus tower config) so two loads of
        # one checkpoint share cache entries across prompts and restarts
        # of the wire.
        import hashlib as _hashlib

        from .models.embed_cache import file_stamp

        model_key = _hashlib.md5(repr(
            [file_stamp(encoder_path), encoder_type, max_len,
             vocab_path, merges_path, tokenizer_json],
        ).encode()).hexdigest()
        return ({"encoder": enc, "tokenizer": tok, "type": encoder_type,
                 "model_key": model_key},)


class TPUTextEncode:
    """(CLIP, text) → CONDITIONING: {'context', 'pooled'} wire dict."""

    DESCRIPTION = "Encode a prompt with a loaded text encoder."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP", {}),
                "text": ("STRING", {"default": "", "multiline": True}),
            },
            "optional": {
                "clip_skip": (
                    "INT",
                    {"default": 0, "min": 0, "max": 2,
                     "tooltip": "host CLIPSetLastLayer semantics: 0 = model "
                                "default (SD2 towers auto-use penultimate), "
                                "1 = final layer, 2 = penultimate"},
                ),
            },
        }

    def encode(self, clip, text: str, clip_skip: int = 0):
        import jax.numpy as jnp

        if clip_skip == 0:
            # CLIPSetLastLayer shim tags the wire (nodes_compat.py); an
            # explicit widget value wins over the tag.
            clip_skip = int(clip.get("clip_skip", 0))
        if clip_skip in (-1, -2):
            # Host CLIPSetLastLayer convention (stop_at_clip_layer).
            clip_skip = -clip_skip
        if clip_skip not in (0, 1, 2):
            raise ValueError(
                f"clip_skip must be 0 (model default), 1/-1 (final layer) or "
                f"2/-2 (penultimate); got {clip_skip}"
            )
        ctype = clip.get("type")
        if ctype == "sdxl-dual":
            # Bundled SDXL towers (CheckpointLoaderSimple shim): encode both,
            # assemble the (2048-d context, 2816-d pooled) pair exactly like
            # TPUConditioningCombine(mode='sdxl') with stock 1024² size tags.
            from .models.text_encoders import sdxl_text_conditioning

            (cl,) = self.encode(clip["l"], text, clip_skip)
            (cg,) = self.encode(clip["g"], text, clip_skip)
            # Default (0) = penultimate, SDXL's training-time convention; an
            # explicit clip_skip selects per-tower streams via each tower's
            # own skip-resolved "context" (1 = final layer, 2 = penultimate).
            str_l = cl["penultimate"] if clip_skip == 0 else cl["context"]
            str_g = cg["penultimate"] if clip_skip == 0 else cg["context"]
            context, y = sdxl_text_conditioning(
                str_l, str_g, cg["pooled"], width=1024, height=1024,
            )
            return ({"context": context, "penultimate": None, "pooled": y},)
        if ctype == "sd3-triple":
            # Stock TripleCLIPLoader (or DualCLIPLoader type=sd3, any one
            # tower absent): encode every present tower and assemble SD3's
            # (context, y) — TPUConditioningCombine(mode='sd3') semantics in
            # one encode. Penultimate streams unconditionally: SD3 trains on
            # layer -2. A missing CLIP tower zero-fills, the stock SD3
            # CLIP's convention, and ALIGNMENT matters: the model was
            # trained with L at joint[0:768] and G at joint[768:2048], so a
            # missing L must still occupy its slot as zeros (canonical 768,
            # clamped so resized test towers compose — the same derived-
            # geometry rule as context_dim below) or G's features shift to
            # offset 0. A missing G needs only a width-0 stream: its slot is
            # trailing, and zeros ⊕ pad-to-4096 equals pad-to-4096. Pooled
            # halves zero-fill at the canonical widths (768/1280) so y keeps
            # the model's vec_in geometry.
            from .models.text_encoders import sd3_text_conditioning

            cl = cg = None
            if clip.get("l") is not None:
                (cl,) = self.encode(clip["l"], text, clip_skip)
            if clip.get("g") is not None:
                (cg,) = self.encode(clip["g"], text, clip_skip)
            if cl is None and cg is None:
                raise ValueError(
                    "sd3 conditioning needs at least one CLIP tower "
                    "(clip_l or clip_g); got T5 only"
                )
            t5_ctx = None
            if clip.get("t5") is not None:
                (ct5,) = self.encode(clip["t5"], text, clip_skip)
                t5_ctx = ct5["context"]
            # The sequence-concat requires the CLIP joint padded to the T5
            # width — 4096 for the real t5xxl, derived so resized towers
            # compose.
            context_dim = t5_ctx.shape[-1] if t5_ctx is not None else 4096
            present = cl if cl is not None else cg
            batch, seq = present["penultimate"].shape[:2]
            if cl is not None:
                l_pen, l_pooled = cl["penultimate"], cl["pooled"]
            else:
                g_width = cg["penultimate"].shape[-1]
                l_pen = jnp.zeros(
                    (batch, seq,
                     min(768, max(0, context_dim - g_width))),
                    jnp.float32,
                )
                l_pooled = jnp.zeros((batch, 768), jnp.float32)
            if cg is not None:
                g_pen, g_pooled = cg["penultimate"], cg["pooled"]
            else:
                g_pen = jnp.zeros((batch, seq, 0), jnp.float32)
                g_pooled = jnp.zeros((batch, 1280), jnp.float32)
            context, y = sd3_text_conditioning(
                l_pen, g_pen, l_pooled, g_pooled, t5_ctx,
                context_dim=context_dim,
            )
            return ({"context": context, "penultimate": None, "pooled": y},)
        if ctype == "flux-dual":
            # Stock DualCLIPLoader(type=flux): T5 context + CLIP-L pooled —
            # TPUConditioningCombine(mode='flux') semantics in one encode.
            (ct5,) = self.encode(clip["t5"], text, clip_skip)
            (cl,) = self.encode(clip["l"], text, clip_skip)
            return (
                {"context": ct5["context"], "penultimate": None,
                 "pooled": cl["pooled"]},
            )
        enc, tok = clip["encoder"], clip["tokenizer"]
        if enc is None or tok is None:
            raise ValueError(
                clip.get("tokenizer_error")
                or "CLIP wire has no encoder/tokenizer"
            )
        # Cross-request reuse (models/embed_cache.py): encoder outputs are
        # content-addressed on (model key, tower, token ids) — a hit skips
        # the encoder program entirely and returns the SAME arrays, so
        # cached-vs-fresh is bitwise-equal and same-prompt requests share
        # one cond object (the serving tier's sibling-seed broadcast seam).
        from .models import embed_cache

        ids, mask = tok([text])
        if clip["type"] in ("t5", "umt5"):
            context = embed_cache.cached_encode(
                enc, clip.get("model_key"), clip["type"], ids, mask,
                lambda: enc(jnp.asarray(ids, jnp.int32),
                            mask=jnp.asarray(mask)),
            )
            return ({"context": context, "pooled": None},)
        last, penultimate, pooled = embed_cache.cached_encode(
            enc, clip.get("model_key"), clip["type"], ids, None,
            lambda: enc(jnp.asarray(ids, jnp.int32)),
        )
        if clip_skip == 1:
            context = last
        elif clip_skip == 2:
            context = penultimate
        else:
            # Model default: SD2 towers (penultimate_ln configs) were trained
            # with penultimate-layer conditioning — route it automatically.
            context = (
                penultimate
                if getattr(enc.cfg, "penultimate_ln", False)
                else last
            )
        return (
            {
                "context": context,
                "penultimate": penultimate,
                "pooled": pooled,
            },
        )


class TPUConditioningCombine:
    """Assemble multi-tower conditioning:

    - ``sdxl``: CLIP-L + OpenCLIP-G CONDITIONINGs → 2048-d context ‖ 2816-d
      pooled/size vector (``sdxl_text_conditioning`` — what the SDXL UNet's
      cross-attention and label embed expect).
    - ``flux``: T5 CONDITIONING (context) + CLIP-L CONDITIONING (pooled vec) →
      the (context, y) pair the MMDiT consumes.
    - ``sd3``: CLIP-L (a) + OpenCLIP-G (b) [+ T5 (conditioning_c)] → the L⊕G
      joint stream padded to 4096 into the T5 context ‖ 2048-d pooled
      (``sd3_text_conditioning``).

    Without this node the individual towers' outputs are dimensionally wrong for
    those families — TPUTextEncode alone only serves SD1.5/SD2.x."""

    DESCRIPTION = "Combine text-encoder outputs for SDXL (L+G), FLUX (T5+CLIP), or SD3 (L+G+T5)."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "combine"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning_a": (
                    "CONDITIONING",
                    {"tooltip": "CLIP-L (sdxl) / T5 (flux)"},
                ),
                "conditioning_b": (
                    "CONDITIONING",
                    {"tooltip": "OpenCLIP-G (sdxl) / CLIP-L (flux)"},
                ),
                "mode": (["sdxl", "flux", "sd3"], {"default": "sdxl"}),
            },
            "optional": {
                "width": ("INT", {"default": 1024, "min": 16, "max": 8192}),
                "height": ("INT", {"default": 1024, "min": 16, "max": 8192}),
                "conditioning_c": (
                    "CONDITIONING",
                    {"tooltip": "T5 (sd3; optional but recommended)"},
                ),
            },
        }

    def combine(
        self, conditioning_a, conditioning_b, mode: str,
        width: int = 1024, height: int = 1024, conditioning_c=None,
    ):
        if mode == "sd3":
            from .models.text_encoders import sd3_text_conditioning

            pen_l = conditioning_a.get("penultimate")
            pooled_l = conditioning_a.get("pooled")
            pen_g = conditioning_b.get("penultimate")
            pooled_g = conditioning_b.get("pooled")
            if pen_l is None or pen_g is None or pooled_l is None or pooled_g is None:
                raise ValueError(
                    "sd3 mode needs CLIP-L as a and OpenCLIP-G as b, both "
                    "from TPUTextEncode (penultimate + pooled)"
                )
            t5_ctx = conditioning_c["context"] if conditioning_c else None
            context, y = sd3_text_conditioning(
                pen_l, pen_g, pooled_l, pooled_g, t5_ctx
            )
            return ({"context": context, "pooled": y},)
        if mode == "flux":
            if conditioning_b.get("pooled") is None:
                raise ValueError("flux mode needs a CLIP conditioning (pooled) as b")
            return (
                {"context": conditioning_a["context"],
                 "pooled": conditioning_b["pooled"]},
            )
        from .models.text_encoders import sdxl_text_conditioning

        pen_l = conditioning_a.get("penultimate")
        pen_g = conditioning_b.get("penultimate")
        pooled_g = conditioning_b.get("pooled")
        if pen_l is None or pen_g is None or pooled_g is None:
            raise ValueError(
                "sdxl mode needs CLIP-L as a and OpenCLIP-G (with text_projection) "
                "as b, both from TPUTextEncode"
            )
        context, y = sdxl_text_conditioning(
            pen_l, pen_g, pooled_g, width=width, height=height
        )
        return ({"context": context, "pooled": y},)


class TPUEmptyLatent:
    """(width, height, batch) → LATENT noise-free zeros, ComfyUI-style."""

    DESCRIPTION = "Allocate an empty latent batch for sampling."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "generate"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 512, "min": 16, "max": 8192, "step": 8}),
                "height": ("INT", {"default": 512, "min": 16, "max": 8192, "step": 8}),
                "batch_size": ("INT", {"default": 1, "min": 1, "max": 64}),
                "channels": ("INT", {"default": 4, "min": 1, "max": 64}),
            }
        }

    def generate(self, width: int, height: int, batch_size: int, channels: int = 4):
        import jax.numpy as jnp

        return (
            {"samples": jnp.zeros((batch_size, height // 8, width // 8, channels))},
        )


class TPUVAEEncode:
    """(VAE, IMAGE) → LATENT — the img2img entry: encode pixels (floats in
    [0, 1], as TPUVAEDecode emits) to the latent an init-capable KSampler run
    starts from (denoise < 1)."""

    DESCRIPTION = "Encode images to latents for img2img / inpaint workflows."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"vae": ("VAE", {}), "image": ("IMAGE", {})},
            "optional": {
                "seed": ("INT", {"default": -1, "min": -1, "max": 2**31 - 1,
                                 "tooltip": "-1 = deterministic posterior mean; "
                                            ">=0 samples the posterior"}),
                "tile_size": ("INT", {"default": 0, "min": 0, "max": 4096,
                                      "step": 32,
                                      "tooltip": "0 = no tiling (pixels, "
                                                 "multiple of the VAE factor; "
                                                 "bounds encoder memory)"}),
            },
        }

    def encode(self, vae, image, seed: int = -1, tile_size: int = 0):
        import jax

        from .models.vae import encode_maybe_tiled, images_to_vae_input

        x = images_to_vae_input(image)
        if tile_size:
            if seed >= 0:
                raise ValueError(
                    "tiled encode is deterministic (posterior mean) — "
                    "seeded sampling and tile_size are exclusive"
                )
            return ({"samples": encode_maybe_tiled(vae, x, tile_size)},)
        rng = seed_key(seed) if seed >= 0 else None
        return ({"samples": vae.encode(x, rng)},)


# Resize methods shared by the two hi-res-fix siblings (latent- and
# image-space); both validate against it so a workflow typo gets a clear
# error instead of a jax internal one.
RESIZE_METHODS = ("nearest", "bilinear", "lanczos3")


class TPULatentUpscale:
    """(LATENT, scale) → LATENT resized in latent space — the hi-res-fix step
    between a low-res sample and a denoise<1 KSampler pass."""

    DESCRIPTION = "Resize latents (hi-res fix); follow with a denoise<1 KSampler."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "upscale"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "latent": ("LATENT", {}),
                "scale": ("FLOAT", {"default": 2.0, "min": 0.25, "max": 8.0,
                                    "step": 0.25}),
                "method": (list(RESIZE_METHODS), {"default": "bilinear"}),
            }
        }

    def upscale(self, latent, scale: float, method: str = "bilinear",
                scale_w: float | None = None):
        """``scale_w`` (optional, defaults to ``scale``) resizes width by its
        own factor — aspect-changing upscales, e.g. the stock LatentUpscale
        node's absolute width/height targets (nodes_compat.py)."""
        import jax

        if method not in RESIZE_METHODS:
            raise ValueError(
                f"method must be one of {RESIZE_METHODS}, got {method!r}"
            )

        z = latent["samples"]
        # Spatial dims are the two before channels (works for image 4-D and
        # video 5-D latents; time is never resized). Snap to even dims — odd
        # latent sizes break UNet stride-2 skip concats and DiT patchify, the
        # same boundary validation TPUKSampler applies.
        h, w = z.shape[-3], z.shape[-2]

        def snap(v: float) -> int:
            s = round(v)
            return s + (s % 2)

        th = snap(h * scale)
        tw = snap(w * (scale if scale_w is None else scale_w))
        if th < 2 or tw < 2:
            raise ValueError(
                f"scale {scale} shrinks the {h}x{w} latent to {th}x{tw}"
            )
        target = (*z.shape[:-3], th, tw, z.shape[-1])
        out = {**latent, "samples": jax.image.resize(z, target, method=method)}
        # A stale noise_mask no longer matches the spatial dims; rescale it too.
        if "noise_mask" in latent:
            m = latent["noise_mask"]
            out["noise_mask"] = jax.image.resize(
                m, (*m.shape[:-3], target[-3], target[-2], 1), method="bilinear"
            )
        return (out,)


class TPUSetLatentNoiseMask:
    """(LATENT, MASK) → LATENT with a noise mask attached — inpainting: the
    KSampler denoises only where mask=1 and re-pins mask=0 regions to the input
    latent at every step (ComfyUI SetLatentNoiseMask semantics)."""

    DESCRIPTION = "Attach an inpainting mask to a latent (1 = regenerate)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "set_mask"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"latent": ("LATENT", {}), "mask": ("MASK", {})}}

    def set_mask(self, latent, mask):
        import jax
        import jax.numpy as jnp

        samples = latent["samples"]
        m = jnp.asarray(mask, jnp.float32)
        video = samples.ndim == 5
        if video and m.ndim == 3:
            # (B, H, W) spatial mask on a video latent: applies to every frame.
            m = m[:, None]
        if m.ndim == samples.ndim - 1:
            m = m[..., None]
        if m.ndim != samples.ndim:
            raise ValueError(
                f"mask rank {jnp.asarray(mask).ndim} does not fit latent rank "
                f"{samples.ndim} (expected a (B, H, W)"
                f"{' or (B, T, H, W)' if video else ''} mask)"
            )
        spatial = samples.shape[1:-1]
        if m.shape[1:-1] != spatial:
            target = (m.shape[0], *spatial, 1)
            if video and m.shape[1] == 1:
                # Broadcast frame axis: resize spatially only, keep T=1.
                target = (m.shape[0], 1, *spatial[1:], 1)
            m = jax.image.resize(m, target, method="bilinear")
        return ({**latent, "noise_mask": m},)


class TPUEmptyVideoLatent:
    """(width, height, frames, batch) → 5-D video LATENT zeros for the WAN
    family; frame count follows the causal 4k+1 schedule (81 by convention)."""

    DESCRIPTION = "Allocate an empty video latent batch (WAN-class, 5-D)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "generate"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 832, "min": 16, "max": 8192, "step": 16}),
                "height": ("INT", {"default": 480, "min": 16, "max": 8192, "step": 16}),
                "frames": ("INT", {"default": 81, "min": 1, "max": 1024, "step": 4,
                                   "tooltip": "pixel frames; must be 1 mod 4 "
                                              "(causal VAE schedule)"}),
                "batch_size": ("INT", {"default": 1, "min": 1, "max": 16}),
                "channels": ("INT", {"default": 16, "min": 1, "max": 64}),
            }
        }

    def generate(
        self, width: int, height: int, frames: int, batch_size: int,
        channels: int | None = None,
    ):
        import jax.numpy as jnp

        from .models.video_vae import wan_vae_config

        cfg = wan_vae_config()
        t_lat = cfg.latent_frames(frames)  # raises on off-schedule counts
        f = cfg.spatial_factor
        if channels is None:
            # Default from the SAME config that owns the schedule/factors
            # (16 for real WAN) — every caller stays consistent with it.
            channels = cfg.z_channels
        return (
            {
                "samples": jnp.zeros(
                    (batch_size, t_lat, height // f, width // f, channels)
                )
            },
        )


def _scheduler_menu() -> list[str]:
    """The KSampler scheduler dropdown — sourced from the sampling layer's
    registry so the menu and make_sigmas dispatch cannot drift."""
    from .sampling import SCHEDULER_NAMES

    return list(SCHEDULER_NAMES)


_SHIFT_WIDGET_DEFAULT = 1.15


def _shift_from_prefs(model, shift: float) -> float:
    """Resolve the flow-shift the sampler actually runs with.

    ModelSamplingSD3/ModelSamplingFlux (stock schedule patches) attach a
    shift default to the MODEL via sampler_prefs; a shift widget left at its
    default (1.15) yields to it, an explicit non-default value wins — the
    same precedence RescaleCFG's cfg_rescale uses."""
    prefs = getattr(model, "sampler_prefs", None) or {}
    if shift == _SHIFT_WIDGET_DEFAULT and "shift" in prefs:
        return float(prefs["shift"])
    return shift


def _collect_control(positive) -> tuple:
    """Every control spec reachable from the positive conditioning: the
    top-level ``control`` tuple plus tags riding combined ``extras`` entries
    (ConditioningCombine moves the second cond — control tag included — into
    extras; dropping those silently would make control order-dependent)."""
    def tags(cond):
        c = cond.get("control") or ()
        return tuple(c) if isinstance(c, (list, tuple)) else (c,)

    specs = tags(positive)
    for e in positive.get("extras", ()):
        specs += tags(e)
    return specs


def _split_lora_delegate(model, positive):
    """(model, lora_factors) for the sampler call: a baked-LoRA model whose
    LoraLoader attached a clean serving delegate samples through the
    UNPATCHED base + per-request factors, so the continuous-batching
    scheduler seats it as a LoRA lane of the base model's bucket (any LoRA
    mix co-batches with plain traffic in one program; run_sampler merges the
    factors eagerly on inline legs). The bake stays authoritative whenever
    the request also carries state the factor recompose can't thread —
    multi-controlnet chains, inpaint, i2v."""
    delegate = getattr(model, "lora_delegate", None)
    if (delegate is None or not delegate.get("factors")
            or positive.get("inpaint") is not None
            or positive.get("i2v") is not None
            or len(_collect_control(positive)) > 1):
        return model, None
    return delegate["base"], delegate["factors"]


def _model_with_control(model, specs, inpaint=None, i2v=None):
    """Compose ControlNet residual injection into the MODEL (the ``control``
    tags Apply nodes leave on the positive conditioning — chained Apply nodes
    stack and their residuals sum, the host's multi-controlnet accumulation).
    The composition is a single merged DiffusionModel — every control trunk +
    the base trunk in one jit program — and a parallelized MODEL
    re-parallelizes the composition over its own chain/config, so DP/FSDP
    placement covers all the networks. Control therefore conditions every
    model call (cond AND uncond) — the host's ControlNetApplyAdvanced
    semantics; for the plain positive-only ControlNetApply this is a
    documented divergence (stock scopes it to cond).

    The composition is CACHED on the base model keyed by the spec identities
    (strong refs held, so ids stay valid) and stays resident across prompts —
    re-running with the same ControlNet setup reuses the placed params and
    compiled programs instead of paying placement + XLA compile per prompt.
    A different setup replaces the cache entry (the old composition's
    placement is cleaned up); memory note: for a parallelized MODEL the base
    placement (the cached workflow output) and the composed placement coexist
    while control is in use — a placement OOM degrades through the normal
    drop-device path."""
    if not specs and not inpaint and not i2v:
        return model
    from .models.api import DiffusionModel
    from .models.controlnet import apply_control
    from .models.unet import apply_inpaint_conditioning
    from .models.wan import apply_i2v_conditioning
    from .parallel.orchestrator import ParallelModel, parallelize

    key = tuple(
        (id(s["model"]), id(s["hint"]), float(s.get("strength", 1.0)),
         float(s.get("start_percent", 0.0)), float(s.get("end_percent", 1.0)))
        for s in specs
    ) + ((id(inpaint["mask"]), id(inpaint["masked_latent"]))
         if inpaint else ()) + (
        (id(i2v.get("cond")), id(i2v.get("clip_fea"))) if i2v else ()
    )
    cached = getattr(model, "_control_composed", None)
    if cached is not None and cached[0] == key:
        return cached[1]

    def compose(base):
        if i2v:
            # Innermost: the WAN i2v channel-concat (+ optional CLIP branch)
            # wraps the raw model; control residuals apply to the wrapped step.
            base = apply_i2v_conditioning(
                base, i2v.get("cond"), i2v.get("clip_fea")
            )
        if inpaint:
            # Innermost: the 9-channel input convention wraps the raw model;
            # control residuals then apply to the wrapped step.
            base = apply_inpaint_conditioning(
                base, inpaint["mask"], inpaint["masked_latent"]
            )
        for spec in specs:
            base = apply_control(
                base, spec["model"], spec["hint"],
                strength=float(spec.get("strength", 1.0)),
                start_percent=float(spec.get("start_percent", 0.0)),
                end_percent=float(spec.get("end_percent", 1.0)),
            )
        return base

    if isinstance(model, ParallelModel):
        if model._pipeline_spec is not None:
            from .utils.logging import get_logger

            get_logger().info(
                "ControlNet composition: batch==1 pipeline placement is "
                "unavailable for the composed model (no staged decomposition "
                "of the control trunk) — DP/single-device routing only"
            )
        base = DiffusionModel(
            apply=model._apply, params=model._host_params,
            config=model.model_config,
        )
        composed = parallelize(compose(base), model.chain, config=model.config)
    else:
        if not (hasattr(model, "apply") and hasattr(model, "params")):
            raise ValueError(
                "ControlNet needs a MODEL with (apply, params) — wire the "
                "loader output (optionally through ParallelAnything) into "
                "the sampler"
            )
        composed = compose(model)
    if cached is not None and hasattr(cached[1], "cleanup"):
        cached[1].cleanup()  # a replaced composition frees its placement
    # specs/inpaint/i2v kept in the entry: the id()-based key stays valid only
    # while the tagged objects are alive.
    try:
        object.__setattr__(
            model, "_control_composed", (key, composed, specs, inpaint, i2v)
        )
    except (AttributeError, TypeError):
        pass  # uncacheable model object: composition still works, uncached
    return composed


def _prepare_sampling_inputs(model, positive, negative, latent, rng=None):
    """Shared sampler-node boundary (TPUKSampler + TPUSamplerCustomAdvanced):
    conditioning batch broadcast (ComfyUI semantics: one encoded prompt
    conditions the whole latent batch, tiled when it divides evenly),
    patch-size divisibility validation (a mismatch otherwise dies deep in
    patchify with an opaque reshape error), the missing-pooled FLUX warning,
    and uncond kwargs assembly.

    Returns ``(model_cfg, context, pooled, uncond_context, uncond_kwargs,
    cond_extra)`` where ``cond_extra`` is the multi-cond kwargs dict for
    ``run_sampler`` (``extra_conds`` / ``cond_area`` / ``cond_strength`` —
    the stock ConditioningCombine/SetArea wire)."""
    from .parallel.orchestrator import model_config_of
    from .sampling.k_samplers import broadcast_cond_batch

    shape = latent["samples"].shape
    batch = shape[0]

    def bcast(arr):
        return broadcast_cond_batch(arr, batch)

    context = bcast(positive["context"])
    pooled = bcast(positive.get("pooled"))
    model_cfg = model_config_of(model)
    patch = getattr(model_cfg, "patch_size", None)
    if isinstance(patch, int):
        bad = [d for d in shape[1:3] if d % patch]
        if bad:
            raise ValueError(
                f"latent spatial dims {shape[1:3]} must be multiples of the "
                f"model patch size {patch}"
            )
    if pooled is None and hasattr(model_cfg, "vec_in_dim"):
        from .utils.logging import get_logger

        get_logger().warning(
            "FLUX-family model sampled without a pooled vector (y falls back "
            "to zeros) — route T5 + CLIP conditioning through "
            "TPUConditioningCombine(mode='flux')"
        )
    uncond_context = bcast(negative["context"]) if negative else None
    uncond_kwargs = (
        {"y": bcast(negative["pooled"])}
        if negative and negative.get("pooled") is not None
        else None
    )
    adm = getattr(model_cfg, "adm_in_channels", None)
    if positive.get("unclip") and adm:
        # SD2.x-unCLIP: the adm vector comes from the unCLIPConditioning tags
        # (noise-augmented CLIP image embeds ‖ level embedding); an untagged
        # negative samples against zeros — host SD21UNCLIP.encode_adm.
        import jax.numpy as jnp

        from .models.unet import unclip_adm

        pooled = bcast(unclip_adm(positive["unclip"], adm, rng=rng))
        uncond_kwargs = {
            "y": (
                bcast(unclip_adm(negative["unclip"], adm, rng=rng))
                if negative and negative.get("unclip")
                else jnp.zeros_like(pooled)
            )
        }
    elif adm:
        # adm models sampled without an adm-shaped pooled: stock zero-fills
        # (SD21UNCLIP.encode_adm for untagged conditioning; SDXL encode_adm
        # defaults a missing pooled_output to zeros) rather than erroring.
        # On sd21-unclip the TEXT tower's 1024-wide pooled is dropped — it
        # never feeds the 1536/2048 label_emb; a wrong-width pooled on other
        # adm families (bare SDXL CLIPTextEncode wiring) raises with the fix.
        import jax.numpy as jnp

        def adm_or_none(vec, what):
            if vec is not None and vec.shape[-1] != adm:
                if getattr(model_cfg, "context_dim", None) == 1024:
                    return None
                raise ValueError(
                    f"{what} pooled vector is {vec.shape[-1]}-wide but this "
                    f"model's adm head expects {adm} — route the prompt "
                    "through CLIPTextEncodeSDXL / "
                    "TPUConditioningCombine(mode='sdxl')"
                )
            return vec

        pooled = adm_or_none(pooled, "positive")
        if pooled is None:
            pooled = jnp.zeros((batch, adm), jnp.float32)
        # The NEGATIVE side needs the same treatment: uncond_kwargs was
        # assigned from negative["pooled"] above, and a 1024-wide text pooled
        # there would reach label_emb on the uncond half of CFG.
        uncond_y = adm_or_none(
            uncond_kwargs.get("y") if uncond_kwargs else None, "negative"
        )
        if negative:
            uncond_kwargs = {
                "y": uncond_y if uncond_y is not None
                else jnp.zeros((batch, adm), jnp.float32)
            }
    # Multi-cond wire (stock ConditioningCombine/SetArea shims): extra conds
    # ride the positive dict's "extras" tuple; a SetArea on the primary rides
    # "area"/"strength". Negative-side extras have no uncond slot — warn and
    # sample with the primary negative only (documented divergence).
    extras = [
        {**e, "context": bcast(e["context"]),
         "pooled": bcast(e.get("pooled"))}
        for e in positive.get("extras", ())
    ]
    if negative and (negative.get("extras") or negative.get("area") is not None
                     or negative.get("area_pct") is not None
                     or negative.get("mask") is not None):
        from .utils.logging import get_logger

        get_logger().warning(
            "combined/area NEGATIVE conditioning is not supported — sampling "
            "with the primary negative prompt, full-frame"
        )
    if positive.get("timestep_range") is not None:
        from .utils.logging import get_logger

        get_logger().warning(
            "ConditioningSetTimestepRange on the PRIMARY positive cond is "
            "ignored (a step with no active conditioning has no fallback) — "
            "route ranged prompts through ConditioningCombine so they ride "
            "the extras, where the window gates them"
        )
    if negative and negative.get("timestep_range") is not None:
        from .utils.logging import get_logger

        get_logger().warning(
            "ConditioningSetTimestepRange on the NEGATIVE conditioning is "
            "not supported — the negative prompt applies across the whole run"
        )
    if negative and negative.get("control"):
        from .utils.logging import get_logger

        get_logger().warning(
            "a ControlNet tag on the NEGATIVE conditioning is ignored — "
            "control composes into the MODEL from the positive tag and "
            "conditions cond AND uncond calls alike (ControlNetApplyAdvanced "
            "semantics)"
        )
    cond_extra = {
        "extra_conds": extras,
        "cond_area": positive.get("area"),
        "cond_area_pct": positive.get("area_pct"),
        "cond_mask": positive.get("mask"),
        "cond_strength": float(positive.get("strength", 1.0)),
        "cond_mask_strength": float(positive.get("mask_strength", 1.0)),
    }
    return model_cfg, context, pooled, uncond_context, uncond_kwargs, cond_extra


class TPUKSampler:
    """(MODEL, positive, negative, LATENT) → LATENT — the per-step driver whose
    forwards route through the parallel scheduler when MODEL came from
    ParallelAnything (the reference's KSampler relationship, 1287)."""

    DESCRIPTION = "Sample latents with the loaded (optionally parallelized) model."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "sample"
    CATEGORY = CATEGORY


    @classmethod
    def INPUT_TYPES(cls):
        from .sampling.runner import SAMPLER_NAMES

        return {
            "required": {
                "model": ("MODEL", {}),
                "positive": ("CONDITIONING", {}),
                "latent": ("LATENT", {}),
                "seed": ("INT", {"default": 0, "min": 0, "max": SEED_MAX}),
                "steps": ("INT", {"default": 20, "min": 1, "max": 200}),
                "cfg": ("FLOAT", {"default": 7.5, "min": 1.0, "max": 30.0}),
                "sampler_name": (list(SAMPLER_NAMES), {"default": "dpmpp_2m"}),
            },
            "optional": {
                "negative": ("CONDITIONING", {}),
                "guidance": (
                    "FLOAT",
                    {"default": 3.5, "min": 0.0, "max": 30.0,
                     "tooltip": "flux-dev distilled guidance embed; 0 disables "
                                "(schnell)"},
                ),
                "shift": (
                    "FLOAT",
                    {"default": 1.15, "min": 0.25, "max": 8.0,
                     "tooltip": "rectified-flow timestep shift (flow_euler only)"},
                ),
                "denoise": (
                    "FLOAT",
                    {"default": 1.0, "min": 0.01, "max": 1.0, "step": 0.01,
                     "tooltip": "img2img strength: < 1 starts from the input "
                                "LATENT (wire a VAE Encode) instead of noise"},
                ),
                "scheduler": (
                    _scheduler_menu(),
                    {"default": "karras",
                     "tooltip": "sigma spacing for the k-samplers"},
                ),
                "cfg_rescale": (
                    "FLOAT",
                    {"default": 0.0, "min": 0.0, "max": 1.0, "step": 0.05,
                     "tooltip": "CFG rescale phi (Lin et al.): tames high-cfg "
                                "over-saturation, esp. v-prediction models"},
                ),
                "compile_loop": (
                    "BOOLEAN",
                    {"default": False,
                     "tooltip": "compile the WHOLE denoise loop into one XLA "
                                "program (zero per-step dispatch; single-"
                                "program chains only — hybrid chains fall "
                                "back to the eager loop)"},
                ),
            },
        }

    def sample(
        self,
        model,
        positive,
        latent,
        seed: int,
        steps: int,
        cfg: float,
        sampler_name: str,
        negative=None,
        guidance: float = 3.5,
        shift: float = 1.15,
        denoise: float = 1.0,
        scheduler: str = "karras",
        cfg_rescale: float = 0.0,
        compile_loop: bool = False,
    ):
        import jax
        import jax.numpy as jnp

        from .sampling.runner import run_sampler

        rng = seed_key(seed)
        shape = latent["samples"].shape
        noise = jax.random.normal(rng, shape, jnp.float32)
        shift = _shift_from_prefs(model, shift)
        model_cfg, context, pooled, uncond_context, uncond_kwargs, cond_extra = (
            _prepare_sampling_inputs(model, positive, negative, latent,
                                     rng=rng)
        )
        model, lora = _split_lora_delegate(model, positive)
        model = _model_with_control(
            model, _collect_control(positive), inpaint=positive.get("inpaint"),
            i2v=positive.get("i2v"),
        )
        kwargs = {} if pooled is None else {"y": pooled}
        out = run_sampler(
            model, noise, context, sampler=sampler_name, steps=steps,
            cfg_scale=cfg, uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs, rng=rng, shift=shift, **cond_extra,
            guidance=guidance if guidance > 0 else None,
            scheduler=scheduler,
            cfg_rescale=cfg_rescale,
            compile_loop=compile_loop,
            prediction=getattr(model_cfg, "prediction", "eps"),
            init_latent=(
                latent["samples"]
                if (denoise < 1.0 or "noise_mask" in latent)
                else None
            ),
            denoise=denoise,
            latent_mask=latent.get("noise_mask"),
            lora=lora,
            **kwargs,
        )
        return ({"samples": out},)


class TPUKSamplerAdvanced:
    """The host's KSamplerAdvanced: a KSampler whose denoise run covers an
    explicit step window [start_at_step, end_at_step) of the full ``steps``
    schedule — the stock SDXL base→refiner template's driver (base renders
    steps 0..N with leftover noise, the refiner continues N..end from the
    base's latent with ``add_noise`` disabled).

    Semantics matched to stock: ``add_noise="disable"`` drives the run with a
    zero noise tensor (the latent arrives already-noised from the previous
    stage); ``return_with_leftover_noise="enable"`` stops the ladder at
    sigma[end_at_step] without denoising to zero (the leftover the next stage
    consumes); with it disabled and ``end_at_step < steps`` the final sigma is
    forced to 0 (stock's force_full_denoise). Host-provided builtin the
    reference's workflows drive steps through
    (any_device_parallel.py:1287 assumes the host sampler calls forward)."""

    DESCRIPTION = "Sample a step window of the schedule (base→refiner driver)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "sample"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        from .sampling.runner import SAMPLER_NAMES

        return {
            "required": {
                "model": ("MODEL", {}),
                "add_noise": (["enable", "disable"], {"default": "enable"}),
                "noise_seed": ("INT", {"default": 0, "min": 0, "max": SEED_MAX}),
                "steps": ("INT", {"default": 20, "min": 1, "max": 200}),
                "cfg": ("FLOAT", {"default": 8.0, "min": 1.0, "max": 30.0}),
                "sampler_name": (list(SAMPLER_NAMES), {"default": "euler"}),
                "scheduler": (_scheduler_menu(), {"default": "normal"}),
                "positive": ("CONDITIONING", {}),
                "negative": ("CONDITIONING", {}),
                "latent_image": ("LATENT", {}),
                "start_at_step": ("INT", {"default": 0, "min": 0, "max": 10000}),
                "end_at_step": ("INT", {"default": 10000, "min": 0,
                                        "max": 10000}),
                "return_with_leftover_noise": (["enable", "disable"],
                                               {"default": "disable"}),
            },
            "optional": {
                "shift": ("FLOAT", {"default": 1.15, "min": 0.25, "max": 8.0}),
                "compile_loop": ("BOOLEAN", {"default": False}),
            },
        }

    def sample(self, model, add_noise: str, noise_seed: int, steps: int,
               cfg: float, sampler_name: str, scheduler: str, positive,
               negative, latent_image, start_at_step: int, end_at_step: int,
               return_with_leftover_noise: str, shift: float = 1.15,
               compile_loop: bool = False):
        import jax
        import jax.numpy as jnp

        from .sampling.runner import run_sampler

        latent = latent_image
        shift = _shift_from_prefs(model, shift)
        (sigmas,) = TPUBasicScheduler().get_sigmas(
            model, scheduler, steps, denoise=1.0, shift=shift
        )
        realized = len(sigmas) - 1  # dedup schedulers may realize fewer
        start = min(start_at_step, realized)
        end = min(end_at_step, realized)
        if end <= start:
            return (dict(latent),)  # empty window: stock returns the latent
        sigmas = sigmas[start:end + 1]
        if return_with_leftover_noise != "enable" and end < realized:
            sigmas = sigmas.at[-1].set(0.0)  # stock force_full_denoise

        shape = latent["samples"].shape
        rng = seed_key(noise_seed)
        noise = (
            jax.random.normal(rng, shape, jnp.float32)
            if add_noise == "enable"
            else jnp.zeros(shape, jnp.float32)
        )
        model_cfg, context, pooled, uncond_context, uncond_kwargs, cond_extra = (
            _prepare_sampling_inputs(model, positive, negative, latent,
                                     rng=rng)
        )
        model, lora = _split_lora_delegate(model, positive)
        model = _model_with_control(
            model, _collect_control(positive), inpaint=positive.get("inpaint"),
            i2v=positive.get("i2v"),
        )
        kwargs = {} if pooled is None else {"y": pooled}
        out = run_sampler(
            model, noise, context, sampler=sampler_name,
            steps=max(1, len(sigmas) - 1), sigmas=sigmas,
            cfg_scale=cfg, uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs, rng=rng, shift=shift, **cond_extra,
            guidance=positive.get("guidance"),
            prediction=getattr(model_cfg, "prediction", "eps"),
            init_latent=latent["samples"],
            latent_mask=latent.get("noise_mask"),
            compile_loop=compile_loop,
            lora=lora,
            **kwargs,
        )
        return ({"samples": out},)


class TPUVAEDecode:
    """(VAE, LATENT) → IMAGE floats in [0, 1]; tiled when the latent is large."""

    DESCRIPTION = "Decode latents to images (auto-tiled for large resolutions)."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "decode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"vae": ("VAE", {}), "latent": ("LATENT", {})},
            "optional": {
                "tile_size": ("INT", {"default": 0, "min": 0, "max": 512,
                                      "tooltip": "0 = no tiling"}),
            },
        }

    def decode(self, vae, latent, tile_size: int = 0):
        from .models.vae import decode_maybe_tiled, vae_output_to_images
        from .serving.decode import get_decode_queue

        # Batched tail decode (serving/decode.py): when the server installed
        # a decode queue, eligible latents batch into a shared compiled
        # decode dispatch instead of serializing inline behind the next
        # prompt's denoise. Ineligible work (tiled, video, odd rank) falls
        # through to the inline path unchanged.
        q = get_decode_queue()
        if q is not None:
            ticket = q.submit(vae, latent["samples"], tile_size)
            if ticket is not None:
                return (vae_output_to_images(ticket.result()),)
        return (vae_output_to_images(decode_maybe_tiled(vae, latent["samples"], tile_size)),)


def resolve_save_target(filename_prefix: str, output_dir: str = "",
                        suffix: str = "png") -> tuple:
    """Shared host-SaveImage path semantics for every save-family node:
    empty ``output_dir`` = the served PA_OUTPUT_DIR root; the prefix may carry
    a subfolder ("run1/img", created + counted within); absolute or
    parent-escaping prefixes are rejected; the numbered counter continues past
    the HIGHEST existing ``{name}_{N}.{suffix}`` index so re-runs never
    overwrite. Returns ``(target_dir, name, start_index)``."""
    import os
    import re as _re

    output_dir = output_dir or os.environ.get("PA_OUTPUT_DIR", "output")
    subdir, name = os.path.split(filename_prefix)
    target_dir = os.path.join(output_dir, subdir) if subdir else output_dir
    root = os.path.realpath(output_dir)
    if os.path.commonpath([root, os.path.realpath(target_dir)]) != root:
        raise ValueError(
            f"filename_prefix {filename_prefix!r} resolves outside "
            f"output_dir {output_dir!r}"
        )
    os.makedirs(target_dir, exist_ok=True)
    pat = _re.compile(_re.escape(name) + r"_(\d+)\." + _re.escape(suffix) + "$")
    taken = [
        int(m.group(1)) for f in os.listdir(target_dir) if (m := pat.match(f))
    ]
    return target_dir, name, (max(taken) + 1 if taken else 0)


class TPUSaveImage:
    """IMAGE → PNG files on disk — the terminal node every exported ComfyUI
    txt2img workflow ends with (the reference relies on the host's SaveImage;
    standalone, the framework supplies its own). Returns the written paths."""

    DESCRIPTION = "Save a batch of images as numbered PNGs."
    RETURN_TYPES = ("PATHS",)
    RETURN_NAMES = ("paths",)
    FUNCTION = "save"
    CATEGORY = CATEGORY
    OUTPUT_NODE = True

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "images": ("IMAGE", {}),
                "filename_prefix": ("STRING", {"default": "tpu"}),
            },
            "optional": {
                "output_dir": (
                    "STRING",
                    {"default": "",
                     "tooltip": "empty = $PA_OUTPUT_DIR, else ./output — the "
                                "same root the API server serves /view from"},
                ),
                "metadata": (
                    "STRING",
                    {"default": "", "multiline": True,
                     "tooltip": "embedded as the PNG 'parameters' text chunk "
                                "(the A1111-style key most galleries/readers "
                                "parse)"},
                ),
            },
            # Host-injected (ComfyUI executor semantics): the whole workflow
            # dict, embedded as the 'prompt' PNG chunk so a saved image can be
            # dragged back into a graph editor to restore its workflow.
            "hidden": {"prompt": "PROMPT"},
        }

    def save(self, images, filename_prefix: str = "tpu", output_dir: str = "",
             metadata: str = "", prompt=None):
        import os

        import numpy as np
        from PIL import Image

        # Shared host-SaveImage path semantics (resolve_save_target):
        # PA_OUTPUT_DIR default, subfolder prefixes, escape rejection, and a
        # past-highest-index counter.
        target_dir, name, start = resolve_save_target(
            filename_prefix, output_dir, "png"
        )
        arr = np.asarray(images)
        if arr.ndim == 3:
            arr = arr[None]
        elif arr.ndim == 5:
            # Video floats (B, F, H, W, 3) — the WAN decode shape: write every
            # frame of every clip as its own numbered PNG, in order.
            arr = arr.reshape((-1,) + arr.shape[2:])
        arr = (np.clip(arr, 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)
        pnginfo = None
        if metadata or prompt is not None:
            import json as _json

            from PIL.PngImagePlugin import PngInfo

            pnginfo = PngInfo()
            if metadata:
                pnginfo.add_text("parameters", metadata)
            if prompt is not None:
                try:
                    pnginfo.add_text("prompt", _json.dumps(prompt, default=repr))
                except Exception:
                    pass  # unserializable custom-node state: skip, still save
        paths = []
        for i, img in enumerate(arr):
            path = os.path.join(target_dir, f"{name}_{start + i:05d}.png")
            Image.fromarray(img).save(path, pnginfo=pnginfo)
            paths.append(path)
        return (tuple(paths),)


class TPULoadImage:
    """Image file → (IMAGE floats in [0,1], MASK from alpha) — the img2img /
    inpaint entry node of exported workflows (host LoadImage semantics: mask is
    1 where the alpha channel is transparent; zeros when no alpha)."""

    DESCRIPTION = "Load an image file as IMAGE (+ alpha-derived MASK)."
    RETURN_TYPES = ("IMAGE", "MASK")
    RETURN_NAMES = ("image", "mask")
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image_path": ("STRING", {"default": ""})}}

    def load(self, image_path: str):
        import jax.numpy as jnp
        import numpy as np
        from PIL import Image, ImageOps

        img = Image.open(image_path)
        # Camera JPEGs carry orientation in EXIF; the host LoadImage applies it
        # before handing pixels downstream — match that.
        img = ImageOps.exif_transpose(img)
        # Convert FIRST: palette-mode PNGs carry transparency without an 'A'
        # band, and RGBA conversion materializes it into the alpha channel.
        rgba = np.asarray(img.convert("RGBA"), np.float32) / 255.0
        image = jnp.asarray(rgba[None, :, :, :3])
        alpha = rgba[None, :, :, 3]
        mask = (
            jnp.asarray(1.0 - alpha)
            if float(alpha.min()) < 1.0
            else jnp.zeros(image.shape[:3], jnp.float32)
        )
        return (image, mask)


class TPUImageScale:
    """IMAGE → resized IMAGE (bilinear/nearest/lanczos) — the image-space half
    of the hi-res-fix surface (TPULatentUpscale covers latent space)."""

    DESCRIPTION = "Resize images to an exact width/height."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "scale"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE", {}),
                # step 8: diffusion consumers need factor-of-8-aligned pixel
                # dims (TPUEmptyLatent uses the same step; TPUKSampler's
                # boundary validation rejects misaligned latents).
                "width": ("INT", {"default": 1024, "min": 8, "max": 16384,
                                  "step": 8}),
                "height": ("INT", {"default": 1024, "min": 8, "max": 16384,
                                   "step": 8}),
                "method": (list(RESIZE_METHODS), {"default": "bilinear"}),
            }
        }

    def scale(self, image, width: int, height: int, method: str = "bilinear"):
        import jax
        import jax.numpy as jnp

        if method not in RESIZE_METHODS:
            raise ValueError(
                f"method must be one of {RESIZE_METHODS}, got {method!r}"
            )
        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        out = jax.image.resize(
            img, (img.shape[0], height, width, img.shape[-1]), method=method
        )
        return (jnp.clip(out, 0.0, 1.0),)


class TPURandomNoise:
    """seed → NOISE — the host's custom-sampling noise source (RandomNoise).
    The wire carries the seed; SamplerCustomAdvanced generates noise shaped
    like the latent it receives, exactly as the host's NOISE object does."""

    DESCRIPTION = "Noise source for the custom-sampling graph."
    RETURN_TYPES = ("NOISE",)
    RETURN_NAMES = ("noise",)
    FUNCTION = "get_noise"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "noise_seed": ("INT", {"default": 0, "min": 0, "max": SEED_MAX}),
        }}

    def get_noise(self, noise_seed: int):
        return ({"seed": int(noise_seed)},)


class TPUKSamplerSelect:
    """sampler_name → SAMPLER — the host's KSamplerSelect."""

    DESCRIPTION = "Pick the sampler for the custom-sampling graph."
    RETURN_TYPES = ("SAMPLER",)
    RETURN_NAMES = ("sampler",)
    FUNCTION = "get_sampler"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        from .sampling.runner import SAMPLER_NAMES

        return {"required": {
            "sampler_name": (list(SAMPLER_NAMES), {"default": "euler"}),
        }}

    def get_sampler(self, sampler_name: str):
        return ({"sampler": sampler_name},)


class TPUBasicScheduler:
    """(MODEL, scheduler, steps, denoise) → SIGMAS — the host's BasicScheduler:
    the named spacing over the MODEL's sigma space (flow models range over the
    shift-warped CONST table; eps/v over the alpha-bar table), with the host's
    denoise semantics (steps/denoise total, last steps+1 kept)."""

    DESCRIPTION = "Compute the sigma schedule for the custom-sampling graph."
    RETURN_TYPES = ("SIGMAS",)
    RETURN_NAMES = ("sigmas",)
    FUNCTION = "get_sigmas"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL", {}),
                "scheduler": (_scheduler_menu(), {"default": "normal"}),
                "steps": ("INT", {"default": 20, "min": 1, "max": 200}),
                "denoise": ("FLOAT", {"default": 1.0, "min": 0.01, "max": 1.0,
                                      "step": 0.01}),
            },
            "optional": {
                "shift": ("FLOAT", {
                    "default": 1.15, "min": 0.25, "max": 8.0,
                    "tooltip": "rectified-flow timestep shift (flow models; "
                               "the host sets this via ModelSamplingFlux)"}),
            },
        }

    def get_sigmas(self, model, scheduler: str, steps: int, denoise: float,
                   shift: float = 1.15):
        from .parallel.orchestrator import model_config_of
        from .sampling.k_samplers import flow_sigma_table, make_sigmas

        shift = _shift_from_prefs(model, shift)
        total = max(steps, int(round(steps / denoise))) if denoise < 1.0 else steps
        if getattr(model_config_of(model), "prediction", "eps") == "flow":
            sigmas = make_sigmas(scheduler, total,
                                 sigma_table=flow_sigma_table(shift))
        else:
            sigmas = make_sigmas(scheduler, total)
        if denoise < 1.0:
            # Same degenerate-schedule guard as run_sampler's truncation: a
            # scheduler that realizes fewer sigmas than requested (beta dedup)
            # would otherwise keep the WHOLE ladder and silently run at full
            # strength.
            realized = len(sigmas) - 1
            if realized > steps:
                sigmas = sigmas[-(steps + 1):]
            else:
                keep = min(realized, max(1, round(steps * realized / total)))
                sigmas = sigmas[-(keep + 1):]
        return (sigmas,)


class TPUFluxGuidance:
    """(CONDITIONING, guidance) → CONDITIONING — the host's FluxGuidance: tags
    the conditioning with the FLUX-dev distilled-guidance value the sampler
    feeds to the model's guidance embed."""

    DESCRIPTION = "Attach flux distilled guidance to a conditioning."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "append"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "conditioning": ("CONDITIONING", {}),
            "guidance": ("FLOAT", {"default": 3.5, "min": 0.0, "max": 100.0}),
        }}

    def append(self, conditioning, guidance: float):
        return ({**conditioning, "guidance": float(guidance)},)


class TPUBasicGuider:
    """(MODEL, CONDITIONING) → GUIDER — the host's BasicGuider: unguided
    (cfg=1) sampling driver for distilled models (FLUX)."""

    DESCRIPTION = "Guider without CFG (distilled models)."
    RETURN_TYPES = ("GUIDER",)
    RETURN_NAMES = ("guider",)
    FUNCTION = "get_guider"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "conditioning": ("CONDITIONING", {}),
        }}

    def get_guider(self, model, conditioning):
        return ({"model": model, "positive": conditioning, "negative": None,
                 "cfg": 1.0},)


class TPUCFGGuider:
    """(MODEL, positive, negative, cfg) → GUIDER — the host's CFGGuider."""

    DESCRIPTION = "Classifier-free-guidance guider."
    RETURN_TYPES = ("GUIDER",)
    RETURN_NAMES = ("guider",)
    FUNCTION = "get_guider"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "positive": ("CONDITIONING", {}),
            "negative": ("CONDITIONING", {}),
            "cfg": ("FLOAT", {"default": 7.5, "min": 1.0, "max": 30.0}),
        }}

    def get_guider(self, model, positive, negative, cfg: float):
        return ({"model": model, "positive": positive, "negative": negative,
                 "cfg": float(cfg)},)


class TPUDisableNoise:
    """→ NOISE that generates zeros — the host's DisableNoise: stage 2+ of a
    split-sigma graph continues from an already-noised latent, so the wired
    LATENT must pass through unchanged (zeros noise + noise_scaling keeps the
    init as the base)."""

    DESCRIPTION = "Zero-noise source for split-sigma continuation stages."
    RETURN_TYPES = ("NOISE",)
    RETURN_NAMES = ("noise",)
    FUNCTION = "get_noise"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {}}

    def get_noise(self):
        return ({"seed": None},)


class TPUSplitSigmas:
    """(SIGMAS, step) → (SIGMAS, SIGMAS) — the host's SplitSigmas: the ladder
    cut at ``step`` with the boundary sigma shared, so running the high half
    then the low half (with DisableNoise) reproduces the unsplit run."""

    DESCRIPTION = "Split a sigma ladder for multi-stage sampling."
    RETURN_TYPES = ("SIGMAS", "SIGMAS")
    RETURN_NAMES = ("high_sigmas", "low_sigmas")
    FUNCTION = "split"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "sigmas": ("SIGMAS", {}),
            "step": ("INT", {"default": 0, "min": 0, "max": 10000}),
        }}

    def split(self, sigmas, step: int):
        return (sigmas[: step + 1], sigmas[step:])


class TPUFlipSigmas:
    """SIGMAS → SIGMAS reversed — the host's FlipSigmas (unsampling graphs);
    a leading zero is bumped to a tiny value so samplers never divide by a
    zero starting sigma."""

    DESCRIPTION = "Reverse a sigma ladder (unsampling)."
    RETURN_TYPES = ("SIGMAS",)
    RETURN_NAMES = ("sigmas",)
    FUNCTION = "flip"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"sigmas": ("SIGMAS", {})}}

    def flip(self, sigmas):
        import jax.numpy as jnp

        flipped = jnp.flip(sigmas, axis=0)
        # Host-faithful: ONLY an exact-zero start is bumped (a small nonzero
        # start from a truncated ladder is preserved).
        return (flipped.at[0].set(
            jnp.where(flipped[0] == 0.0, 1e-4, flipped[0])
        ),)


class TPUSamplerCustomAdvanced:
    """(NOISE, GUIDER, SAMPLER, SIGMAS, LATENT) → (LATENT, LATENT) — the
    host's SamplerCustomAdvanced: the custom-sampling execution node that
    exported FLUX workflows drive instead of the one-box KSampler. The wired
    LATENT is always the noising base (host noise_scaling: a zero EmptyLatent
    degenerates to pure noise; a VAE-encoded one + truncated SIGMAS is
    img2img). The second output mirrors the host's ``denoised_output``; on a
    terminal (σ→0) schedule the two coincide exactly, and this node returns
    the same array for both (divergence only for partial sigma ranges)."""

    DESCRIPTION = "Custom-sampling driver (noise + guider + sampler + sigmas)."
    RETURN_TYPES = ("LATENT", "LATENT")
    RETURN_NAMES = ("output", "denoised_output")
    FUNCTION = "sample"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "noise": ("NOISE", {}),
                "guider": ("GUIDER", {}),
                "sampler": ("SAMPLER", {}),
                "sigmas": ("SIGMAS", {}),
                "latent_image": ("LATENT", {}),
            },
            "optional": {
                "compile_loop": ("BOOLEAN", {"default": False}),
            },
        }

    def sample(self, noise, guider, sampler, sigmas, latent_image,
               compile_loop: bool = False):
        import jax
        import jax.numpy as jnp

        from .sampling.runner import run_sampler

        model = guider["model"]
        positive, negative = guider["positive"], guider.get("negative")
        cfg = guider.get("cfg", 1.0)
        shape = latent_image["samples"].shape
        seed = noise["seed"]
        rng = seed_key(0 if seed is None else seed)
        # DisableNoise (seed None) wires zeros: noise_scaling then keeps the
        # latent as the base — the split-sigma continuation contract.
        noise_arr = (
            jnp.zeros(shape, jnp.float32) if seed is None
            else jax.random.normal(rng, shape, jnp.float32)
        )
        model_cfg, context, pooled, uncond_context, uncond_kwargs, cond_extra = (
            _prepare_sampling_inputs(model, positive, negative, latent_image,
                                     rng=rng)
        )
        model = _model_with_control(
            model, _collect_control(positive), inpaint=positive.get("inpaint"),
            i2v=positive.get("i2v"),
        )
        prediction = getattr(model_cfg, "prediction", "eps")
        out = run_sampler(
            model, noise_arr, context,
            sampler=sampler["sampler"],
            **cond_extra,
            steps=max(1, len(sigmas) - 1),
            sigmas=sigmas,
            cfg_scale=cfg,
            uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs,
            rng=rng,
            guidance=positive.get("guidance"),
            prediction=prediction,
            init_latent=latent_image["samples"],
            latent_mask=latent_image.get("noise_mask"),
            compile_loop=compile_loop,
            **({} if pooled is None else {"y": pooled}),
        )
        # Host inverse_noise_scaling: a PARTIAL flow run (split sigmas, final
        # σ > 0) stores its output un-interpolated, so the next stage's
        # (1−σ)·latent noise_scaling restores the in-flight state exactly;
        # terminal runs (σ→0) are untouched. eps inverse scaling is identity.
        s_last = float(sigmas[-1])
        if prediction == "flow" and s_last > 0:
            if s_last >= 1.0:
                # σ_last = 1 means pure noise: 1/(1−σ) is infinite. The host
                # divides anyway and silently emits inf (its unsampling graphs
                # hit this); reject loudly instead — documented divergence.
                raise ValueError(
                    "flow sigma ladder ends at 1.0 (pure noise): the partial-"
                    "run inverse noise scaling 1/(1-sigma) is undefined there. "
                    "Split or flip the ladder so the final sigma is below 1."
                )
            out = out / (1.0 - s_last)
        return ({"samples": out}, {"samples": out})


class TPUControlNetLoader:
    """ControlNet checkpoint file → CONTROL_NET wire. The base-UNet family is
    sniffed off the checkpoint (context width / label_emb) unless the caller
    passes one of the UNet families explicitly."""

    DESCRIPTION = "Load an SD-family ControlNet (family sniffed)."
    RETURN_TYPES = ("CONTROL_NET",)
    RETURN_NAMES = ("control_net",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "ckpt_path": ("STRING", {"default": "",
                                         "tooltip": "safetensors path"}),
            }
        }

    def load(self, ckpt_path: str):
        from .models import load_controlnet_checkpoint

        return ({"model": load_controlnet_checkpoint(ckpt_path)},)


class TPUControlNetApply:
    """Tag a conditioning with ControlNet guidance: the sampler nodes compose
    the control trunk into the MODEL for the run (one jit program; see
    models/controlnet.apply_control), so the residuals condition every model
    call — cond and uncond alike, the host's behavior. ``image`` is the hint
    in pixels (8x the latent grid); ``start_percent``/``end_percent`` gate by
    sampling progress."""

    DESCRIPTION = "Apply a ControlNet hint image to conditioning."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "apply"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING", {}),
                "control_net": ("CONTROL_NET", {}),
                "image": ("IMAGE", {}),
                "strength": ("FLOAT", {"default": 1.0, "min": 0.0,
                                       "max": 10.0, "step": 0.01}),
            },
            "optional": {
                "start_percent": ("FLOAT", {"default": 0.0, "min": 0.0,
                                            "max": 1.0, "step": 0.001}),
                "end_percent": ("FLOAT", {"default": 1.0, "min": 0.0,
                                          "max": 1.0, "step": 0.001}),
            },
        }

    def apply(self, conditioning, control_net, image, strength: float = 1.0,
              start_percent: float = 0.0, end_percent: float = 1.0):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        spec = {
            "model": control_net["model"],
            "hint": img,
            "strength": float(strength),
            "start_percent": float(start_percent),
            "end_percent": float(end_percent),
        }
        # Chained Apply nodes STACK (residuals sum, the host's
        # multi-controlnet accumulation) — a tuple on the wire.
        prior = conditioning.get("control") or ()
        prior = prior if isinstance(prior, (list, tuple)) else (prior,)
        return ({**conditioning, "control": tuple(prior) + (spec,)},)


class TPUInpaintModelConditioning:
    """(positive, negative, VAE, pixels, mask) → the wire trio that drives a
    DEDICATED inpainting checkpoint (family sd15-inpaint/sdxl-inpaint, 9 input
    channels): conditioning tagged with the latent-space mask + masked-image
    latent (the sampler composes them into the model input via
    ``apply_inpaint_conditioning``), plus the encoded source latent. ``mask``
    is 1 where content regenerates, pixel resolution; masked pixels neutralize
    to 0.5 gray before encoding (the checkpoint's training convention).
    ``noise_mask=True`` additionally pins the keep region each step (the
    latent-noise-mask mechanism — matching host behavior)."""

    DESCRIPTION = "Conditioning + latents for dedicated inpainting checkpoints."
    RETURN_TYPES = ("CONDITIONING", "CONDITIONING", "LATENT")
    RETURN_NAMES = ("positive", "negative", "latent")
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "positive": ("CONDITIONING", {}),
                "negative": ("CONDITIONING", {}),
                "vae": ("VAE", {}),
                "pixels": ("IMAGE", {}),
                "mask": ("MASK", {}),
            },
            "optional": {
                "noise_mask": ("BOOLEAN", {"default": True}),
            },
        }

    def encode(self, positive, negative, vae, pixels, mask,
               noise_mask: bool = True):
        import jax
        import jax.numpy as jnp

        from .models.vae import images_to_vae_input, normalize_mask

        px = images_to_vae_input(pixels)
        m = normalize_mask(mask, px.shape[1:3])
        # Neutralize the regenerate region to 0.5 gray pre-encode (the
        # inpainting checkpoints' training convention). px is already in the
        # VAE's [-1, 1] input space, where 0.5-gray is 0.0.
        masked_px = px * (1.0 - m)
        masked_latent = vae.encode(masked_px, None)
        latent = vae.encode(px, None)
        lat_mask = jax.image.resize(
            m, (m.shape[0], *latent.shape[1:3], 1), method="nearest"
        )
        tag = {"mask": lat_mask, "masked_latent": masked_latent}
        out_latent = {"samples": latent}
        if noise_mask:
            out_latent["noise_mask"] = lat_mask
        return (
            {**positive, "inpaint": tag},
            {**negative, "inpaint": tag},
            out_latent,
        )


class TPUUpscaleModelLoader:
    """ESRGAN-family upscaler checkpoint → UPSCALE_MODEL wire (nf/nb/gc/scale
    sniffed; both public key layouts accepted — models/upscale.py)."""

    DESCRIPTION = "Load an ESRGAN-family (RRDBNet) image upscaler."
    RETURN_TYPES = ("UPSCALE_MODEL",)
    RETURN_NAMES = ("upscale_model",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "ckpt_path": ("STRING", {"default": "",
                                         "tooltip": "safetensors path"}),
            }
        }

    def load(self, ckpt_path: str):
        from .models import load_upscale_checkpoint

        return (load_upscale_checkpoint(ckpt_path),)


class TPUImageUpscaleWithModel:
    """(UPSCALE_MODEL, IMAGE) → model-upscaled IMAGE; large images process as
    overlapping tiles blended linearly (bounded activation memory)."""

    DESCRIPTION = "Upscale images with an ESRGAN-family model (tiled)."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "upscale"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "upscale_model": ("UPSCALE_MODEL", {}),
                "image": ("IMAGE", {}),
            },
            "optional": {
                "tile": ("INT", {"default": 512, "min": 64, "max": 4096,
                                 "tooltip": "tile size for large images"}),
            },
        }

    def upscale(self, upscale_model, image, tile: int = 512):
        from .models import upscale_image

        return (upscale_image(upscale_model, image, tile=tile),)


NODE_CLASS_MAPPINGS = {
    "ParallelAnything": ParallelAnything,
    "ParallelAnythingAdvanced": ParallelAnythingAdvanced,
    "ParallelDevice": ParallelDevice,
    "ParallelDeviceList": ParallelDeviceList,
    "TPUCheckpointLoader": TPUCheckpointLoader,
    "TPUCLIPLoader": TPUCLIPLoader,
    "TPUTextEncode": TPUTextEncode,
    "TPUConditioningCombine": TPUConditioningCombine,
    "TPUEmptyLatent": TPUEmptyLatent,
    "TPUVAEEncode": TPUVAEEncode,
    "TPUSetLatentNoiseMask": TPUSetLatentNoiseMask,
    "TPULatentUpscale": TPULatentUpscale,
    "TPUEmptyVideoLatent": TPUEmptyVideoLatent,
    "TPUKSampler": TPUKSampler,
    "TPUKSamplerAdvanced": TPUKSamplerAdvanced,
    "TPUVAEDecode": TPUVAEDecode,
    "TPUSaveImage": TPUSaveImage,
    "TPULoadImage": TPULoadImage,
    "TPUImageScale": TPUImageScale,
    "TPURandomNoise": TPURandomNoise,
    "TPUKSamplerSelect": TPUKSamplerSelect,
    "TPUBasicScheduler": TPUBasicScheduler,
    "TPUFluxGuidance": TPUFluxGuidance,
    "TPUBasicGuider": TPUBasicGuider,
    "TPUCFGGuider": TPUCFGGuider,
    "TPUSamplerCustomAdvanced": TPUSamplerCustomAdvanced,
    "TPUDisableNoise": TPUDisableNoise,
    "TPUSplitSigmas": TPUSplitSigmas,
    "TPUFlipSigmas": TPUFlipSigmas,
    "TPUControlNetLoader": TPUControlNetLoader,
    "TPUControlNetApply": TPUControlNetApply,
    "TPUUpscaleModelLoader": TPUUpscaleModelLoader,
    "TPUImageUpscaleWithModel": TPUImageUpscaleWithModel,
    "TPUInpaintModelConditioning": TPUInpaintModelConditioning,
}

NODE_DISPLAY_NAME_MAPPINGS = {
    "ParallelAnything": "Parallel Anything (True Multi-Device TPU)",
    "ParallelAnythingAdvanced": "Parallel Anything (Advanced: FSDP/TP)",
    "ParallelDevice": "Parallel Device Config",
    "ParallelDeviceList": "Parallel Device List (1-4x)",
    "TPUCheckpointLoader": "Load Checkpoint (TPU)",
    "TPUCLIPLoader": "Load Text Encoder (TPU)",
    "TPUTextEncode": "Text Encode (TPU)",
    "TPUSaveImage": "Save Image (TPU)",
    "TPULoadImage": "Load Image (TPU)",
    "TPUImageScale": "Image Scale (TPU)",
    "TPUConditioningCombine": "Conditioning Combine (TPU, SDXL/FLUX)",
    "TPUEmptyLatent": "Empty Latent (TPU)",
    "TPUVAEEncode": "VAE Encode (TPU)",
    "TPUSetLatentNoiseMask": "Set Latent Noise Mask (TPU)",
    "TPULatentUpscale": "Latent Upscale (TPU)",
    "TPUEmptyVideoLatent": "Empty Video Latent (TPU, WAN)",
    "TPUKSampler": "KSampler (TPU)",
    "TPUKSamplerAdvanced": "KSampler Advanced (TPU)",
    "TPUVAEDecode": "VAE Decode (TPU)",
    "TPURandomNoise": "Random Noise (TPU)",
    "TPUKSamplerSelect": "KSampler Select (TPU)",
    "TPUBasicScheduler": "Basic Scheduler (TPU)",
    "TPUFluxGuidance": "Flux Guidance (TPU)",
    "TPUBasicGuider": "Basic Guider (TPU)",
    "TPUCFGGuider": "CFG Guider (TPU)",
    "TPUSamplerCustomAdvanced": "Sampler Custom Advanced (TPU)",
    "TPUDisableNoise": "Disable Noise (TPU)",
    "TPUSplitSigmas": "Split Sigmas (TPU)",
    "TPUFlipSigmas": "Flip Sigmas (TPU)",
    "TPUControlNetLoader": "Load ControlNet (TPU)",
    "TPUControlNetApply": "Apply ControlNet (TPU)",
    "TPUUpscaleModelLoader": "Load Upscale Model (TPU)",
    "TPUImageUpscaleWithModel": "Upscale Image With Model (TPU)",
    "TPUInpaintModelConditioning": "Inpaint Model Conditioning (TPU)",
}

# Stock-ComfyUI class-name shims (CheckpointLoaderSimple, CLIPTextEncode,
# KSampler, …) so exported API-format workflows resolve unchanged — see
# nodes_compat.py. setdefault-merged: native names always win.
from . import nodes_compat as _compat  # noqa: E402  (needs the classes above)

_compat.register(NODE_CLASS_MAPPINGS, NODE_DISPLAY_NAME_MAPPINGS)
