"""The node API layer — ComfyUI-style declarative nodes over the TPU framework.

This re-exposes the reference's entire L4 surface (SURVEY §2a) with the same node
protocol (``INPUT_TYPES`` / ``RETURN_TYPES`` / ``RETURN_NAMES`` / ``FUNCTION`` /
``CATEGORY`` / ``DESCRIPTION``) so a ComfyUI-style graph host can register and drive
the framework exactly as it drives the reference:

- ``ParallelDevice``      — one chain link, chainable (any_device_parallel.py:768-832)
- ``ParallelDeviceList``  — flat 1-4 device/percentage variant (834-882)
- ``ParallelAnything``    — the orchestrator node (884-1471)
- ``NODE_CLASS_MAPPINGS`` / ``NODE_DISPLAY_NAME_MAPPINGS`` (1473-1483)

The DEVICE_CHAIN wire value is the reference's: a plain list of
``{"device": str, "percentage": float, "weight": float}`` dicts (823-832). The
``weight`` key is written for wire parity but never read back — the orchestrator
renormalizes from ``percentage`` only, exactly like setup_parallel (1019-1027, where
the SURVEY flags ``weight`` as dead data).
"""

from __future__ import annotations

from typing import Any

from .devices.discovery import available_devices
from .parallel.chain import DeviceChain
from .parallel.orchestrator import ParallelConfig, parallelize

CATEGORY = "parallel/tpu"


def chain_from_wire(entries: list[dict[str, Any]] | None) -> DeviceChain:
    """DEVICE_CHAIN wire format → DeviceChain (drops pct <= 0, parity 876-882)."""
    if not entries:
        return DeviceChain()
    return DeviceChain.from_pairs(
        (e["device"], float(e.get("percentage", 0.0))) for e in entries
    )


def chain_to_wire(chain: DeviceChain) -> list[dict[str, Any]]:
    """DeviceChain → the reference's wire format, including the dead ``weight`` key
    (pct/100, written at 826/880 and never read)."""
    return [
        {"device": l.device, "percentage": l.percentage, "weight": l.percentage / 100.0}
        for l in chain.links
    ]


class ParallelDevice:
    """One link in the device chain: pick a device + workload %, chainable via the
    optional ``previous_devices`` input (parity: 768-832)."""

    DESCRIPTION = (
        "Add a device to the parallel chain with a workload percentage. "
        "Chain multiple nodes to build an N-device setup."
    )
    RETURN_TYPES = ("DEVICE_CHAIN",)
    RETURN_NAMES = ("device_chain",)
    FUNCTION = "add_device"
    CATEGORY = CATEGORY

    @classmethod
    def get_available_devices(cls) -> list[str]:
        return available_devices()

    @classmethod
    def INPUT_TYPES(cls):
        devices = cls.get_available_devices()
        return {
            "required": {
                "device_id": (
                    devices,
                    {"default": devices[0], "tooltip": "Device to add to the chain"},
                ),
                "percentage": (
                    "FLOAT",
                    {
                        "default": 50.0,
                        "min": 1.0,
                        "max": 100.0,
                        "step": 1.0,
                        "tooltip": "Share of the workload for this device",
                    },
                ),
            },
            "optional": {
                "previous_devices": (
                    "DEVICE_CHAIN",
                    {"tooltip": "Chain from an upstream Parallel Device node"},
                ),
            },
        }

    def add_device(self, device_id: str, percentage: float, previous_devices=None):
        # Copy-then-append, like the reference (821-832) — upstream lists are never
        # mutated, so re-running a graph node is side-effect free.
        chain = list(previous_devices) if previous_devices else []
        chain.append(
            {
                "device": device_id,
                "percentage": float(percentage),
                "weight": float(percentage) / 100.0,
            }
        )
        return (chain,)


class ParallelDeviceList:
    """Flat alternative: one node, four device+percentage pairs; entries with
    percentage <= 0 are dropped (parity: 834-882)."""

    DESCRIPTION = "Configure up to 4 devices in one node; 0% disables a slot."
    RETURN_TYPES = ("DEVICE_CHAIN",)
    RETURN_NAMES = ("device_chain",)
    FUNCTION = "create_list"
    CATEGORY = CATEGORY
    N_SLOTS = 4

    @classmethod
    def get_available_devices(cls) -> list[str]:
        return available_devices()

    @classmethod
    def INPUT_TYPES(cls):
        devices = cls.get_available_devices()
        required = {}
        for i in range(1, cls.N_SLOTS + 1):
            required[f"device_{i}"] = (
                devices,
                {"default": devices[0], "tooltip": f"Device for slot {i}"},
            )
            required[f"percentage_{i}"] = (
                "FLOAT",
                {
                    "default": 50.0 if i <= 2 else 0.0,
                    "min": 0.0,
                    "max": 100.0,
                    "step": 1.0,
                    "tooltip": f"Workload share for slot {i}; 0 disables",
                },
            )
        return {"required": required}

    def create_list(self, **kwargs):
        chain = []
        for i in range(1, self.N_SLOTS + 1):
            pct = float(kwargs.get(f"percentage_{i}", 0.0))
            if pct <= 0:
                continue
            dev = kwargs[f"device_{i}"]
            chain.append({"device": dev, "percentage": pct, "weight": pct / 100.0})
        return (chain,)


class ParallelAnything:
    """The orchestrator node: takes MODEL + DEVICE_CHAIN, wraps the model so every
    sampler step runs parallel over the chain, returns the wrapped MODEL
    (parity: 884-1471)."""

    DESCRIPTION = (
        "True multi-device parallelism: shards each denoise step across the device "
        "chain as one SPMD program (data parallel for batches, pipeline block "
        "placement for batch=1)."
    )
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "setup_parallel"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL", {"tooltip": "Diffusion model to parallelize"}),
                "parallel_devices": (
                    "DEVICE_CHAIN",
                    {"tooltip": "Device chain from Parallel Device node(s)"},
                ),
                # Widget defaults match the reference's effective values (SURVEY §5.6:
                # the auto_vram_balance widget default True wins over the python
                # signature default False because hosts always pass widget values).
                "workload_split": (
                    "BOOLEAN",
                    {"default": True, "tooltip": "Split batches across devices"},
                ),
                "auto_vram_balance": (
                    "BOOLEAN",
                    {
                        "default": True,
                        "tooltip": "Blend workload split with free device memory",
                    },
                ),
                "purge_cache": (
                    "BOOLEAN",
                    {"default": True, "tooltip": "Release caches at teardown"},
                ),
                "purge_models": (
                    "BOOLEAN",
                    {"default": False, "tooltip": "Also drop compiled programs"},
                ),
            },
        }

    def setup_parallel(
        self,
        model,
        parallel_devices,
        workload_split: bool = True,
        auto_vram_balance: bool = True,
        purge_cache: bool = True,
        purge_models: bool = False,
        **config_extra,
    ):
        chain = chain_from_wire(parallel_devices)
        config = ParallelConfig(
            workload_split=workload_split,
            auto_memory_balance=auto_vram_balance,
            purge_cache=purge_cache,
            purge_models=purge_models,
            **config_extra,
        )
        # parallelize returns the model unchanged on an unusable chain, matching the
        # reference's abort paths (1019-1027, 1037-1042).
        return (parallelize(model, chain, config),)


class ParallelAnythingAdvanced(ParallelAnything):
    """The orchestrator node with the beyond-reference knobs exposed: weight
    sharding (FSDP for models bigger than one chip) and tensor parallelism."""

    DESCRIPTION = (
        ParallelAnything.DESCRIPTION
        + " Advanced: FSDP weight sharding and tensor parallelism for models "
        "larger than a single device."
    )
    # setup_parallel's **config_extra already routes the extra widgets into
    # ParallelConfig — no forwarding override needed.
    FUNCTION = "setup_parallel"

    @classmethod
    def INPUT_TYPES(cls):
        base = ParallelAnything.INPUT_TYPES()
        base["required"]["weight_sharding"] = (
            ["replicate", "fsdp"],
            {
                "default": "replicate",
                "tooltip": "fsdp shards each weight across the chain (model > 1 chip)",
            },
        )
        base["required"]["tensor_parallel"] = (
            "INT",
            {
                "default": 1,
                "min": 1,
                "max": 64,
                "tooltip": "model-axis size; >1 partitions the matmuls (GSPMD TP)",
            },
        )
        return base


NODE_CLASS_MAPPINGS = {
    "ParallelAnything": ParallelAnything,
    "ParallelAnythingAdvanced": ParallelAnythingAdvanced,
    "ParallelDevice": ParallelDevice,
    "ParallelDeviceList": ParallelDeviceList,
}

NODE_DISPLAY_NAME_MAPPINGS = {
    "ParallelAnything": "Parallel Anything (True Multi-Device TPU)",
    "ParallelAnythingAdvanced": "Parallel Anything (Advanced: FSDP/TP)",
    "ParallelDevice": "Parallel Device Config",
    "ParallelDeviceList": "Parallel Device List (1-4x)",
}
