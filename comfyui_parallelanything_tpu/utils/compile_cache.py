"""Persistent XLA compilation cache.

The reference pays zero compile cost (CUDA eager kernels); on TPU every traced
program costs a 20-40 s XLA compile on first use. Enabling JAX's persistent
cache amortizes that across *processes* — a bench retried over a flaky tunnel,
or a workflow host restarted between runs, re-loads compiled executables from
disk instead of re-paying the compile (VERDICT r2 item 2c).
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.expanduser("~/.cache/comfyui_parallelanything_tpu/xla")


def enable_compilation_cache(cache_dir: str | None = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir`` (defaults to
    ``$PA_TPU_COMPILE_CACHE`` or ``~/.cache/comfyui_parallelanything_tpu/xla``)
    and lower the write thresholds so even fast-compiling programs persist.
    ``$PA_COMPILE_CACHE_MIN_S`` overrides the min-compile-time threshold
    (cross-process accounting tests pin it to 0 so sub-second programs
    persist). Also installs the compile-event watchers (utils/telemetry.py),
    so cache hit/miss accounting is on whenever the cache itself is.
    Idempotent; returns the directory in use."""
    import jax

    from .telemetry import watch_compiles

    cache_dir = (
        cache_dir
        or os.environ.get("PA_TPU_COMPILE_CACHE")
        or _DEFAULT_DIR
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    min_s = os.environ.get("PA_COMPILE_CACHE_MIN_S")
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(min_s) if min_s else 0.5,
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    watch_compiles()
    return cache_dir
