"""Latent → RGB preview approximation for per-step WS previews.

Stock ComfyUI streams a small per-step preview image over the WebSocket
(latent2rgb: a per-family linear projection of latent channels to RGB —
`LatentPreviewMethod.Latent2RGB`); the reference pack inherits that from the
host (any_device_parallel.py:1473-1483 registers only its own nodes — the
progress/preview surface is the host's). Standalone, this module is that projection: the per-channel-count
factor tables below are the public latent-RGB constants the ecosystem ships
(4-channel SD-class, 16-channel flux-class); anything else falls back to a
normalized first-3-channels view. Family selection is by channel count only
(the preview hook sees latents, not configs) — preview fidelity, not decode
fidelity, is the contract.
"""

from __future__ import annotations

import io

import numpy as np

# Public SD-class latent→RGB projection (rows = latent channels).
_FACTORS_4 = np.array(
    [
        [0.3512, 0.2297, 0.3227],
        [0.3250, 0.4974, 0.2350],
        [-0.2829, 0.1762, 0.2721],
        [-0.2120, -0.2616, -0.7177],
    ],
    np.float32,
)

# Public flux-class 16-channel projection.
_FACTORS_16 = np.array(
    [
        [-0.0346, 0.0244, 0.0681],
        [0.0034, 0.0210, 0.0687],
        [0.0275, -0.0668, -0.0433],
        [-0.0174, 0.0160, 0.0617],
        [0.0859, 0.0721, 0.0329],
        [0.0004, 0.0383, 0.0115],
        [0.0405, 0.0861, 0.0915],
        [-0.0236, -0.0185, -0.0259],
        [-0.0245, 0.0250, 0.1180],
        [0.1008, 0.0755, -0.0421],
        [-0.0515, 0.0201, 0.0011],
        [0.0428, -0.0012, -0.0036],
        [0.0817, 0.0765, 0.0749],
        [-0.1264, -0.0522, -0.1103],
        [-0.0280, -0.0881, -0.0499],
        [-0.1262, -0.0982, -0.0778],
    ],
    np.float32,
)
_BIAS_16 = np.array([-0.0329, -0.0718, -0.0851], np.float32)


def latent_to_rgb(latent) -> np.ndarray:
    """(B, H, W, C) or (B, T, H, W, C) latent → (H, W, 3) float [0, 1] preview
    of batch 0 (frame 0 for video)."""
    arr = np.asarray(latent, np.float32)
    if arr.ndim == 5:  # video: first frame of the first clip
        arr = arr[:, 0]
    if arr.ndim != 4:
        raise ValueError(f"latent must be 4-D or 5-D, got shape {arr.shape}")
    x = arr[0]
    c = x.shape[-1]
    if c == 4:
        rgb = x @ _FACTORS_4
    elif c == 16:
        rgb = x @ _FACTORS_16 + _BIAS_16
    else:
        rgb = x[..., : min(3, c)]
        if rgb.shape[-1] < 3:
            rgb = np.concatenate(
                [rgb] + [rgb[..., -1:]] * (3 - rgb.shape[-1]), axis=-1
            )
        lo, hi = rgb.min(), rgb.max()
        return (rgb - lo) / max(hi - lo, 1e-6)
    return np.clip(rgb / 2.0 + 0.5, 0.0, 1.0)


def preview_png(latent, max_side: int = 256) -> bytes:
    """Latent → small PNG bytes (nearest-upscaled from the latent grid; the
    preview is a thumbnail, not a decode — stock's latent2rgb contract)."""
    from PIL import Image

    rgb = latent_to_rgb(latent)
    img = Image.fromarray((rgb * 255).astype(np.uint8))
    w, h = img.size
    scale = max(1, max_side // max(w, h))
    if scale > 1:
        img = img.resize((w * scale, h * scale), Image.NEAREST)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()
