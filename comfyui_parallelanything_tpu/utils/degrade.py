"""Bounded-retry degradation ladder: graceful, OBSERVABLE on-device fallback.

The reference's whole failure story is two silent demotions — drop a device
on clone OOM and renormalize (any_device_parallel.py:1114-1128), demote to
fewer devices on step OOM (1435-1448) — with a print as the only evidence.
This module is the accounting spine for every rung this repo has grown:

    stream OOM      → re-carve (finer stages)     rung "stream-recarve"
                    → …until one segment/stage    → exhaustion (clean error)
    serving OOM     → lane-width halve            rung "lane-width-halve"
                    → attn-chunk shrink           rung "attn-chunk-shrink"
                    → inline fallback             rung "inline-fallback"
    compile failure → eager loop fallback         rung "compile-eager"

Every rung taken is (1) logged through ``log_degradation`` (the reference's
print-site vocabulary), (2) counted as ``pa_degradation_total{rung=}``, (3)
recorded as an instant ``degrade``-category span on the tracer, and (4)
appended to the perf ledger as a ``kind="degradation"`` record — so a fleet
that is quietly degrading is VISIBLE in /metrics, in traces, and in the
ledger history, never just slower. Rung exhaustion (nothing left to shed)
dumps a postmortem bundle and re-raises the original error: graceful
degradation is bounded by construction, not a retry-forever loop.

The ladder MECHANICS live at the call sites that own the resources
(parallel/orchestrator.py re-carves, serving/scheduler.py re-buckets,
sampling/runner.py falls back to eager); this module owns the rung
vocabulary, the observability contract, and the shared failure
classification.
"""

from __future__ import annotations

# Rung vocabulary (the pa_degradation_total{rung=} label set + README table).
LADDER_RUNGS = {
    "stream-recarve": "streaming OOM: stage granularity halved "
                      "(parallel/orchestrator._stream_call)",
    "lane-width-halve": "serving dispatch OOM: bucket lane width halved, "
                        "requests re-seated from step 0 "
                        "(serving/scheduler.py)",
    "attn-chunk-shrink": "serving dispatch OOM at width 1: chunked-attention "
                         "threshold halved, programs rebuilt "
                         "(ops/attention.py)",
    "inline-fallback": "serving OOM with nothing left to shed: requests "
                       "resolve DegradedToInline and run_sampler runs the "
                       "inline eager path",
    "compile-eager": "compile failure: whole-loop/lane program abandoned for "
                     "the eager per-step loop (sampling/runner.py)",
    "exhausted": "a ladder ran out of rungs — clean error + postmortem "
                 "(labelled with the ladder that exhausted)",
}


class DegradedToInline(RuntimeError):
    """The serving layer shed this request: the submitter (run_sampler)
    must run the inline eager path instead. Never escapes run_sampler."""


def record_rung(rung: str, detail: str, **attrs) -> None:
    """One rung taken: log + counter + span + ledger. Never raises — the
    degradation path is exactly where secondary failures are likeliest."""
    assert rung in LADDER_RUNGS, f"unknown degradation rung {rung!r}"
    try:
        from .logging import log_degradation

        log_degradation(rung, detail)
    except Exception:  # noqa: BLE001
        pass
    try:
        from .metrics import registry

        registry.counter(
            "pa_degradation_total", labels={"rung": rung},
            help="degradation-ladder rungs taken (utils/degrade.py) — a "
                 "quietly degrading fleet is visible here, never just slower",
        )
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import tracing

        if tracing.on():
            now = tracing.now_us()
            tracing.record("degradation", now, 0.0, cat="degrade",
                           rung=rung, detail=detail, **attrs)
    except Exception:  # noqa: BLE001
        pass
    try:
        from .telemetry import append_ledger_record

        append_ledger_record(
            {"metric": "degradation", "rung": rung, "detail": detail, **attrs},
            "degradation",
        )
    except Exception:  # noqa: BLE001
        pass


def ladder_exhausted(ladder: str, error: BaseException,
                     detail: str = "") -> str | None:
    """A ladder ran out of rungs: count it, dump a postmortem bundle, and
    return the bundle path (caller re-raises the original error — bounded
    degradation ends in a CLEAN, attributable failure, not a spin)."""
    try:
        from .logging import log_degradation

        log_degradation("exhausted", f"{ladder}: {detail or error}")
    except Exception:  # noqa: BLE001
        pass
    try:
        from .metrics import registry

        registry.counter("pa_degradation_total",
                         labels={"rung": "exhausted", "ladder": ladder})
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import tracing

        if tracing.on():
            now = tracing.now_us()
            tracing.record("degradation", now, 0.0, cat="degrade",
                           rung="exhausted", ladder=ladder)
    except Exception:  # noqa: BLE001
        pass
    try:
        from .telemetry import write_postmortem

        return write_postmortem(
            f"degrade-exhausted-{ladder}", error=error,
            extra={"ladder": ladder, "detail": detail},
        )
    except Exception:  # noqa: BLE001
        return None


def is_compile_failure(err: BaseException) -> bool:
    """Classify an error as compile-side (→ the eager fallback rung applies)
    vs runtime. OOMs are never compile failures — they have their own
    ladder. Matches the injected ``compile-fail`` fault and XLA's
    compilation/lowering error vocabulary."""
    from .telemetry import looks_like_oom

    if looks_like_oom(err):
        return False
    msg = f"{type(err).__name__}: {err}".lower()
    return any(m in msg for m in
               ("injected compile failure", "compil", "lowering", "mosaic"))
