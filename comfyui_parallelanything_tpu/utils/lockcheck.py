"""Runtime lock-acquisition-order graph — the dynamic half of palint's
lock-discipline pass.

The static half (``scripts/palint/lockorder.py``) proves every write to a
``# guarded-by:`` attribute holds its declared lock; what it CANNOT prove
is that the locks themselves are acquired in a consistent global order —
the fleet/serving tier holds ~20 locks across server handler threads,
prompt workers, the serving dispatcher, monitor sweeps, and heartbeats,
and a cycle in the acquisition-order graph is a potential deadlock waiting
for the right interleaving. This module records that graph live:

- ``PA_LOCKCHECK=1`` + :func:`install` wrap ``threading.Lock`` /
  ``threading.RLock`` CONSTRUCTION: locks created by repo code (creation
  frame inside this checkout — jax/stdlib internals are handed the real
  primitive untouched) become :class:`TrackedLock` proxies.
- each thread keeps its held-set in acquisition order; acquiring B while
  holding A records the edge A→B (tagged with both creation sites and the
  acquiring file:line). RLock re-entry is not an edge.
- a cycle (A→…→B→A) means two code paths take the same locks in opposite
  orders — :func:`cycles` returns them, the first detection logs and
  writes a postmortem bundle (best-effort, the forensics rule), and the
  tier-1 fleet/serving/chaos tests + the chaos smoke gate on ZERO cycles
  (tests/conftest.py installs when the env flag is set;
  ``scripts/chaos.py`` folds ``lock_cycles`` into its verdict).

Edges are ORDER facts, not contention facts: a cycle is reported even if
the deadlock never fired in this run — that is the point (the interleaving
that fires it is the one CI never schedules). A false positive (two orders
serialized by an outer lock) is pragma territory: name the outer lock in
the test that asserts the cycle away, or restructure — the graph is small.

Known blind spot: nodes are CREATION SITES (lock classes, the lockdep
model), so two instances born at the same line — the HA router pair's
``_lock``, two scoreboards — alias to one node and a same-site pair never
records an edge (a self-edge would read as a spurious one-node cycle).
An AB-BA inversion BETWEEN two instances of the same class is therefore
invisible here; instance-level ordering is what the chaos matrix's real
kill/takeover interleavings exercise.

Module level is stdlib-only and free of package-relative imports (the
``utils/roofline.py`` standalone contract): tests and scripts load it by
path before the package (and jax) import, so installation precedes every
module-level ``threading.Lock()`` in the package.
"""

from __future__ import annotations

import os
import sys
import threading
import _thread

__all__ = [
    "enabled", "install", "uninstall", "installed", "TrackedLock",
    "cycles", "edges", "report", "reset",
]

# The raw primitive for the graph's own bookkeeping — NEVER the (possibly
# patched) threading.Lock, or every edge insert would record itself.
_graph_mutex = _thread.allocate_lock()
# (src site, dst site) -> {"count": n, "at": "file:line" of first observer}
_edges: dict = {}                      # guarded-by: _graph_mutex
_cycle_log: list = []                  # guarded-by: _graph_mutex
_tls = threading.local()               # per-thread held stack
_installed = [False]
# Unwrap a prior install (a second execution of this file — e.g. the
# package import racing a path-loaded boot copy — must not capture the
# patched factory as "original", or uninstall() would re-install it).
_orig_lock = getattr(threading.Lock, "_pa_lockcheck_orig", threading.Lock)
_orig_rlock = getattr(threading.RLock, "_pa_lockcheck_orig", threading.RLock)

# Creation-site scope: track only locks born in this checkout (the package,
# scripts/, bench.py, tests/) — wrapping jax's or the stdlib's own locks
# would put third-party ordering in OUR gate.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def enabled() -> bool:
    return os.environ.get("PA_LOCKCHECK") == "1"


def _creation_site() -> str | None:
    """file:line of the repo frame constructing the lock, or None when the
    constructor ran from outside the checkout (→ hand back a real lock)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        base = os.path.basename(fn)
        if base != "lockcheck.py" and "threading" not in base:
            if fn.startswith(_REPO_ROOT) and "site-packages" not in fn:
                rel = os.path.relpath(fn, _REPO_ROOT)
                return f"{rel}:{f.f_lineno}"
            return None
        f = f.f_back
    return None


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _find_path(src: str, dst: str) -> list | None:
    """DFS over _edges (caller holds _graph_mutex): a site path src→…→dst,
    or None."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for (a, b) in _edges:
            if a != node or b in seen:
                continue
            if b == dst:
                return path + [b]
            seen.add(b)
            stack.append((b, path + [b]))
    return None


def _acquire_site() -> str:
    """file:line of the nearest frame OUTSIDE this file performing the
    acquisition — with-statements route ``__enter__ → acquire →
    _note_acquire`` and Condition waits route ``_acquire_restore``, so a
    fixed frame depth would attribute every edge to lockcheck itself."""
    f = sys._getframe(2)
    while f is not None:
        base = os.path.basename(f.f_code.co_filename)
        if base != "lockcheck.py":
            return f"{base}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _note_acquire(lock: "TrackedLock") -> None:
    held = _held()
    if any(h is lock for h in held):     # RLock re-entry: not an edge
        held.append(lock)
        return
    at = _acquire_site()
    new_cycle = None
    with _graph_mutex:
        for h in held:
            if h.site == lock.site:
                continue
            key = (h.site, lock.site)
            e = _edges.get(key)
            if e is None:
                # New edge: does the reverse direction already exist
                # (directly or transitively)? Then this acquisition closed
                # a cycle in the order graph.
                back = _find_path(lock.site, h.site)
                _edges[key] = {"count": 1, "at": at}
                if back is not None:
                    new_cycle = back + [lock.site]
                    _cycle_log.append({"cycle": new_cycle, "at": at})
            else:
                e["count"] += 1
    held.append(lock)
    if new_cycle is not None:
        _report_cycle(new_cycle, at)


def _note_release(lock: "TrackedLock") -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return


def _report_cycle(cycle: list, at: str) -> None:
    """First-detection forensics: log + best-effort postmortem bundle. Any
    failure here must not break the locking it observes."""
    try:
        from .logging import get_logger

        get_logger().error(
            "lockcheck: lock-order cycle (potential deadlock) at %s: %s",
            at, " -> ".join(cycle))
    except Exception:
        pass
    try:
        from .telemetry import write_postmortem

        write_postmortem("lock-order-cycle", extras={
            "cycle": cycle, "observed_at": at, "report": report(),
        })
    except Exception:
        pass


class TrackedLock:
    """Proxy over a real Lock/RLock recording acquisition order. Supports
    the full context-manager/acquire/release protocol plus the private
    RLock hooks ``threading.Condition`` relies on."""

    __slots__ = ("_real", "site", "kind")

    def __init__(self, real, site: str, kind: str):
        self._real = real
        self.site = site
        self.kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._real.acquire(blocking, timeout)
        if ok:
            _note_acquire(self)
        return ok

    def release(self) -> None:
        self._real.release()
        _note_release(self)

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # threading.Condition(wrapped_rlock) support: wait() swaps the lock out
    # and back via these hooks — mirror the held-set so the order graph
    # stays truthful across a wait.
    def _release_save(self):
        state = self._real._release_save() if hasattr(
            self._real, "_release_save") else self._real.release()
        _note_release(self)
        return state

    def _acquire_restore(self, state) -> None:
        if hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(state)
        else:
            self._real.acquire()
        _note_acquire(self)

    def _is_owned(self) -> bool:
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        # plain Lock: owned iff locked (the stdlib's own fallback)
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<TrackedLock {self.kind} from {self.site}>"


def _make_factory(orig, kind: str):
    def factory(*args, **kwargs):
        real = orig(*args, **kwargs)
        site = _creation_site()
        if site is None:
            return real
        return TrackedLock(real, site, kind)
    factory._pa_lockcheck_orig = orig
    return factory


_prev = [None, None]  # what install() displaced — restored by uninstall()


def install() -> bool:
    """Patch threading.Lock/RLock construction (idempotent). Returns True
    when installed. Call BEFORE importing the package so its module-level
    locks are born tracked — tests/conftest.py does this when
    PA_LOCKCHECK=1."""
    if _installed[0]:
        return True
    _prev[0], _prev[1] = threading.Lock, threading.RLock
    threading.Lock = _make_factory(_orig_lock, "Lock")
    threading.RLock = _make_factory(_orig_rlock, "RLock")
    _installed[0] = True
    return True


def uninstall() -> None:
    """Restore whatever install() displaced — a second checker instance
    (tests path-load their own copy) must not strip the session's."""
    if not _installed[0]:
        return
    threading.Lock = _prev[0] or _orig_lock
    threading.RLock = _prev[1] or _orig_rlock
    _installed[0] = False


def installed() -> bool:
    return _installed[0]


def edges() -> list[dict]:
    with _graph_mutex:
        return [{"from": a, "to": b, **dict(v)}
                for (a, b), v in sorted(_edges.items())]


def cycles() -> list[list[str]]:
    """Every distinct cycle currently in the order graph (canonicalized so
    one cycle reports once regardless of entry point)."""
    with _graph_mutex:
        keys = list(_edges)
    adj: dict[str, list[str]] = {}
    for a, b in keys:
        adj.setdefault(a, []).append(b)
    found: dict[tuple, list[str]] = {}

    def dfs(start: str, node: str, path: list[str], seen: set):
        for nxt in adj.get(node, ()):
            if nxt == start and len(path) > 1:
                rot = min(range(len(path)),
                          key=lambda i: path[i])  # canonical rotation
                canon = tuple(path[rot:] + path[:rot])
                found.setdefault(canon, list(canon) + [canon[0]])
            elif nxt not in seen and nxt > start:
                # only walk nodes ≥ start: each cycle found exactly once,
                # from its smallest member
                dfs(start, nxt, path + [nxt], seen | {nxt})

    for a in sorted(adj):
        dfs(a, a, [a], {a})
    return sorted(found.values())


def report() -> dict:
    cyc = cycles()
    return {
        "schema": "pa-lockcheck/v1",
        "enabled": enabled(),
        "installed": installed(),
        "edges": edges(),
        "cycles": cyc,
        "ok": not cyc,
    }


def reset() -> None:
    """Clear the graph (tests). Held-sets are per-thread and survive — a
    reset mid-critical-section only forgets past edges, never present
    holds."""
    with _graph_mutex:
        _edges.clear()
        _cycle_log.clear()
