"""Numerics sentinel: non-finite quarantine, latent fingerprints, drift audit.

PR 5 bought a strong correctness contract — every sampler's output is a pure,
bitwise-stable function of (request, step) — and PRs 3-4 made time and memory
attributable. Nothing yet *watched* that contract or the numeric health of the
latents themselves: the reference's only numeric-failure story is coarse OOM
degradation (any_device_parallel.py:1114-1128, 1435-1448), and a NaN'd latent
there surfaces as a black image N seconds later with nothing to name the
block, step, or σ that produced it. This module is the audit surface every
next step (wider lane eligibility, multi-host failover mid-denoise, a Pallas
attention kernel behind an equivalence gate) needs before it can land safely:

- **On-device reductions** (:func:`array_stats` / :func:`lane_stats`): a tiny
  ``[nonfinite_count, max|x|, mean, rms]`` vector computed *inside* the
  compiled programs as an auxiliary output — no host sync on the hot path;
  the host reads it at boundaries that already block (the serving bucket's
  post-dispatch block, the streaming runner's backpressure block).
- **Latent fingerprints** (:func:`digest` / :func:`lane_digest` /
  :func:`latent_fingerprint`): a deterministic bf16-quantized digest of a
  latent. The digest is a wrapping-uint32 sum of position-weighted bf16 bit
  patterns — modular integer addition is exactly associative and commutative,
  so the value is invariant to XLA reduction order and therefore to dp
  sharding; per-lane digests use lane-local element indices, so a lane's
  digest is invariant to occupancy and bucket width by construction (the
  fold_in RNG contract makes the *values* bitwise-stable; the digest makes
  that checkable in four bytes). ``scripts/numerics_audit.py --check`` banks
  golden fingerprints per rung and fails on drift, like the perf gate.
- **The sentinel** (:data:`sentinel`): process-wide event/quarantine/
  fingerprint bookkeeping behind a single ``enabled`` flag. Disabled is one
  flag check and nothing else — the tracer's null-singleton discipline
  (utils/tracing.py), tier-1-tested as a no-op.
- **Per-lane quarantine support**: :func:`bisect_nonfinite` re-runs one
  failing model eval through the model's ``PipelineSpec`` stages
  (prepare → per-block segments → finalize) to name the FIRST block whose
  output goes non-finite — the forensic detail the serving bucket writes into
  its ``write_postmortem`` bundle when it retires a poisoned lane.
- **Failure injection**: ``PA_FAIL_INJECT=nan:<lane>`` (guarded by
  ``PA_LEDGER_DIR``/``PA_EVIDENCE_DIR``, like bench.py's injection) poisons
  one seated lane's next eval input once, so the quarantine path is
  rehearsed off-hardware — the round-3 lesson applied to the sentinel itself.

Import discipline: stdlib-only at module level (jax loads lazily inside the
device helpers), mirroring utils/telemetry.py, so schema-reading callers
never touch a wedged tunnel.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

__all__ = [
    "NonFiniteLatent",
    "NumericsSentinel",
    "array_stats",
    "bisect_nonfinite",
    "digest",
    "disable",
    "enable",
    "fail_inject_lane",
    "gate_status",
    "lane_digest",
    "lane_stats",
    "latent_fingerprint",
    "on",
    "sentinel",
    "stats_to_dict",
    "take_injection",
    "tree_nonfinite",
]

GATE_FILENAME = "numerics_gate.json"

# Stat-vector layout shared by every emitter and reader (the aux output of
# the compiled programs, the host dicts, the postmortem extras).
STAT_FIELDS = ("nonfinite", "max_abs", "mean", "rms")

# Digest constants: Knuth multiplicative hash step over lane-local element
# positions. Everything is mod 2^32, so summation order cannot matter.
_DIGEST_MULT = 2654435761
_DIGEST_SALT = 0x9E3779B9


class NonFiniteLatent(RuntimeError):
    """A lane's (or run's) latent state went NaN/Inf — raised to the
    submitter whose lane was quarantined (serving/bucket.py)."""


# ---------------------------------------------------------------------------
# on-device reductions (in-jit safe: jnp ops only, tiny outputs)
# ---------------------------------------------------------------------------


def array_stats(x):
    """``[nonfinite_count, max|x|, mean, rms]`` float32 vector for one array,
    with non-finite entries masked out of the max/mean/rms so the magnitudes
    stay readable even on a poisoned latent. In-jit safe (pure jnp)."""
    import jax.numpy as jnp

    xf = jnp.asarray(x, jnp.float32)
    finite = jnp.isfinite(xf)
    nf = jnp.sum(~finite).astype(jnp.float32)
    safe = jnp.where(finite, xf, 0.0)
    return jnp.stack([
        nf,
        jnp.max(jnp.abs(safe)),
        jnp.mean(safe),
        jnp.sqrt(jnp.mean(safe * safe)),
    ])


def lane_stats(x, extra=None):
    """Per-lane stats ``[W, 4]`` over a ``[W, ...]`` state stack. ``extra``
    (same leading dim) contributes its non-finite count only — the serving
    bucket passes the next eval input ``xe`` so a NaN parked mid-step by a
    two-eval sampler is caught at the dispatch that produced it, one eval
    before it would reach the latent."""
    import jax.numpy as jnp

    axes = tuple(range(1, jnp.ndim(x)))
    xf = jnp.asarray(x, jnp.float32)
    finite = jnp.isfinite(xf)
    nf = jnp.sum(~finite, axis=axes).astype(jnp.float32)
    if extra is not None:
        ef = jnp.asarray(extra, jnp.float32)
        nf = nf + jnp.sum(
            ~jnp.isfinite(ef), axis=tuple(range(1, jnp.ndim(ef)))
        ).astype(jnp.float32)
    safe = jnp.where(finite, xf, 0.0)
    return jnp.stack([
        nf,
        jnp.max(jnp.abs(safe), axis=axes),
        jnp.mean(safe, axis=axes),
        jnp.sqrt(jnp.mean(safe * safe, axis=axes)),
    ], axis=1)


def _bits_u32(x):
    """bf16-quantized bit patterns of ``x`` as uint32 (the digest's input)."""
    import jax
    import jax.numpy as jnp

    b16 = jnp.asarray(x, jnp.bfloat16)
    return jax.lax.bitcast_convert_type(b16, jnp.uint16).astype(jnp.uint32)


def digest(x):
    """Deterministic uint32 digest of one latent (in-jit safe).

    ``Σ (bits_i + 1) · (i · 2654435761 + salt)  (mod 2^32)`` over the
    flattened bf16 bit patterns: modular addition is order-independent, so
    the same values digest identically under any sharding/reduction order —
    the property that makes the fingerprint dp-sharding-invariant."""
    import jax.numpy as jnp

    bits = _bits_u32(x).reshape(-1)
    idx = jnp.arange(bits.shape[0], dtype=jnp.uint32)
    w = idx * jnp.uint32(_DIGEST_MULT) + jnp.uint32(_DIGEST_SALT)
    return jnp.sum((bits + jnp.uint32(1)) * w, dtype=jnp.uint32)


def lane_digest(x):
    """Per-lane digests ``[W]`` over a ``[W, ...]`` stack, each computed over
    LANE-LOCAL element positions — so ``lane_digest(stack)[i]`` equals
    ``digest(stack[i])`` regardless of where the lane sits or how wide the
    bucket is (occupancy/width invariance by construction)."""
    import jax.numpy as jnp

    w_lanes = x.shape[0]
    bits = _bits_u32(x).reshape(w_lanes, -1)
    idx = jnp.arange(bits.shape[1], dtype=jnp.uint32)
    w = idx * jnp.uint32(_DIGEST_MULT) + jnp.uint32(_DIGEST_SALT)
    return jnp.sum((bits + jnp.uint32(1)) * w[None, :], axis=1,
                   dtype=jnp.uint32)


def latent_fingerprint(x) -> str:
    """Host-side fingerprint string ``bf16:<shape>:<%08x>`` of a latent —
    what bench.py records per rung and the audit gate diffs. Pure function of
    the values: independent of the sentinel flag."""
    import numpy as np

    shape = "x".join(str(d) for d in getattr(x, "shape", ()))
    d = int(np.asarray(digest(x)))
    return f"bf16:{shape}:{d:08x}"


def stats_to_dict(vec) -> dict:
    """A host stats vector as the named dict the postmortems/events carry."""
    import numpy as np

    v = np.asarray(vec, np.float64).reshape(-1)
    out = {k: float(v[i]) for i, k in enumerate(STAT_FIELDS)}
    out["nonfinite"] = int(out["nonfinite"])
    return out


def tree_nonfinite(tree) -> int:
    """Total non-finite elements over all floating array leaves of a pytree
    (host-side; the streaming runner's per-stage check at sync boundaries)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            total += int(np.asarray(jnp.sum(~jnp.isfinite(
                jnp.asarray(leaf, jnp.float32)
            ))))
    return total


# ---------------------------------------------------------------------------
# the sentinel
# ---------------------------------------------------------------------------


class NumericsSentinel:
    """Process-wide numerics bookkeeping behind one ``enabled`` flag.

    Disabled costs instrumentation sites a single attribute read (the
    tracer's null-path discipline); enabled, it accumulates non-finite
    events, quarantine records, and bounded per-request fingerprint stacks,
    and mirrors them into ``pa_numerics_*`` metrics and ``numerics``-cat
    trace spans (both best-effort — a metrics hiccup must never break the
    path it observes)."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._events = 0
        self._quarantined = 0
        self.last_event: dict | None = None
        self.last_quarantine: dict | None = None
        # Per-request fingerprint records: {"rid", "sampler", "bucket",
        # "steps", "digests": [uint32 per eval]} — bounded; the invariance
        # tests and dryrun §15 read these back.
        self._fingerprints: deque = deque(maxlen=64)  # guarded-by: _lock
        self._inject_done = False

    # -- lifecycle ----------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Test/bench hygiene: zero the counters and records (flag
        untouched); re-arms the one-shot failure injection."""
        with self._lock:
            self._events = 0
            self._quarantined = 0
            self.last_event = None
            self.last_quarantine = None
            self._fingerprints.clear()
            self._inject_done = False

    # -- recording ----------------------------------------------------------

    def record_event(self, where: str, **info) -> dict:
        """One non-finite observation (NOT necessarily a quarantine: the
        streaming runner records stage events, bench records a poisoned
        final output). Feeds the counter, the last-event slot, and — when
        the tracer is on — an instant ``numerics`` span."""
        # palint: allow[observability] forensic-record epoch STAMP
        event = {"where": where, "ts": time.time(), **info}
        with self._lock:
            self._events += 1
            self.last_event = event
        try:
            from .metrics import registry

            registry.counter(
                "pa_numerics_nonfinite_total", labels={"where": where},
                help="non-finite latent/state observations by site",
            )
        except Exception:
            pass
        try:
            from . import tracing

            if tracing.on():
                tracing.record("nonfinite-event", tracing.now_us(), 0.0,
                               cat="numerics", **{k: v for k, v in info.items()
                                                  if isinstance(v, (str, int,
                                                                    float))},
                               where=where)
        except Exception:
            pass
        return event

    def record_quarantine(self, **info) -> dict:
        """One lane quarantine (serving/bucket.py): the full forensic record
        — bucket/lane/rid/sampler, the first non-finite step/σ/block, and the
        postmortem bundle path."""
        # palint: allow[observability] forensic-record epoch STAMP
        rec = {"ts": time.time(), **info}
        with self._lock:
            self._quarantined += 1
            self.last_quarantine = rec
        try:
            from .metrics import registry

            registry.counter(
                "pa_numerics_quarantined_total",
                labels={"bucket": str(info.get("bucket", "?"))},
                help="serving lanes retired by the non-finite quarantine",
            )
        except Exception:
            pass
        try:
            from . import tracing

            if tracing.on():
                tracing.record(
                    "quarantine", tracing.now_us(), 0.0, cat="numerics",
                    bucket=str(info.get("bucket")), lane=info.get("lane"),
                    step=info.get("step"), rid=info.get("rid"),
                )
        except Exception:
            pass
        return rec

    def record_fingerprints(self, **rec) -> None:
        with self._lock:
            self._fingerprints.append(rec)

    def recent_fingerprints(self) -> list[dict]:
        with self._lock:
            return list(self._fingerprints)

    # -- read side ----------------------------------------------------------

    @property
    def event_count(self) -> int:
        return self._events

    @property
    def quarantined_count(self) -> int:
        return self._quarantined

    def snapshot(self) -> dict:
        """The ``numerics`` section of ``GET /health``: flag state, event and
        quarantine totals, the last of each, and the fingerprint gate's last
        verdict (``scripts/numerics_audit.py --check`` writes it beside the
        ledger; None when the gate has never run)."""
        with self._lock:
            out = {
                "enabled": self.enabled,
                "nonfinite_events": self._events,
                "quarantined_lanes": self._quarantined,
                "last_event": dict(self.last_event) if self.last_event else None,
                "last_quarantine": (
                    dict(self.last_quarantine) if self.last_quarantine else None
                ),
            }
        out["fingerprint_gate"] = gate_status()
        return out

    def publish_gauges(self) -> None:
        """Mirror the totals into gauges so a /metrics scrape sees them even
        before the first event touches the counters."""
        try:
            from .metrics import registry

            registry.gauge("pa_numerics_sentinel_enabled",
                           1.0 if self.enabled else 0.0,
                           help="numerics sentinel flag (utils/numerics.py)")
            registry.gauge("pa_numerics_nonfinite_events", self._events,
                           help="non-finite observations this process")
            registry.gauge("pa_numerics_quarantined_lanes", self._quarantined,
                           help="lanes quarantined this process")
        except Exception:
            pass


sentinel = NumericsSentinel()


def on() -> bool:
    """The hot-path enabled check — guard stats computation with this."""
    return sentinel.enabled


def enable() -> None:
    sentinel.enable()


def disable() -> None:
    sentinel.disable()


def gate_status() -> dict | None:
    """Last fingerprint-gate verdict (``<ledger>/numerics_gate.json``,
    written by scripts/numerics_audit.py), or None."""
    try:
        from .telemetry import ledger_dir

        with open(os.path.join(ledger_dir(), GATE_FILENAME)) as f:
            return json.load(f)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# failure injection (PA_FAIL_INJECT=nan:<lane>)
# ---------------------------------------------------------------------------


def fail_inject_lane() -> int | None:
    """The lane index to poison, or None. Round 14: parsed by the unified
    fault registry (utils/faults.py ``lane-nan`` site) — one syntax
    (``PA_FAULT_PLAN`` or the legacy ``PA_FAIL_INJECT=nan:<lane>`` alias)
    and ONE arming rule (explicit ``PA_LEDGER_DIR``/``PA_EVIDENCE_DIR``
    redirect, so an injected NaN's postmortem bundle can never land in the
    repo's real ledger). ``refresh()`` honors env set after import (tests,
    the dryrun's §15 re-arm)."""
    from . import faults

    return faults.refresh().lane_nan_target()


def take_injection(active_lanes) -> int | None:
    """One-shot: the armed lane index if it is currently seated, consuming
    the injection; else None (stays armed until the lane exists). The
    serving bucket calls this per dispatch when the sentinel is on; tests
    and the dryrun re-arm via ``sentinel.reset()``. A consumed injection is
    reported to the fault registry (``faults``-cat span +
    ``pa_fault_injected_total{site="lane-nan"}``), so chaos postmortems
    prove the NaN was injected, not organic."""
    lane = fail_inject_lane()
    if lane is None or lane not in active_lanes:
        return None
    with sentinel._lock:
        if sentinel._inject_done:
            return None
        sentinel._inject_done = True
    from . import faults

    faults.registry.record_external("lane-nan", key=str(lane), mode="nan")
    return lane


# ---------------------------------------------------------------------------
# per-block bisection (the quarantine postmortem's "which block did it")
# ---------------------------------------------------------------------------


def _finite(tree) -> bool:
    return tree_nonfinite(tree) == 0


def _subset(params, keys):
    try:
        return {k: params[k] for k in keys}
    except (KeyError, TypeError):
        return params


def eval_input(xe, sigma_eval: float, prediction: str, log_sigmas):
    """Replicate the lane program's per-eval model-input prep for ONE
    request: ``(x_in, t_vec)`` from the eval-input latent and σ — the
    EpsDenoiser formulas (k_samplers.py:390-400) with the σ→timestep
    log-interp for eps/v and flow time passed through for flow."""
    import jax.numpy as jnp

    batch = xe.shape[0]
    s = jnp.float32(sigma_eval)
    if prediction == "flow":
        return xe, jnp.full((batch,), s, jnp.float32)
    scale = 1.0 / jnp.sqrt(s**2 + 1.0)
    t = jnp.interp(
        jnp.log(s), log_sigmas,
        jnp.arange(log_sigmas.shape[0], dtype=jnp.float32),
    )
    return xe * scale, jnp.full((batch,), t, jnp.float32)


def bisect_nonfinite(model, xe, sigma_eval: float, prediction: str,
                     log_sigmas, context, kwargs: dict | None = None) -> dict:
    """Re-run ONE model eval stage-by-stage to name the first non-finite
    block. Returns ``{"block": <label or None>, "sigma": σ, ...}``:

    - ``"lane-input"`` — the eval input itself was already poisoned (the
      injection rehearsal's shape, or an upstream sampler-update blowup);
    - a ``PipelineSpec`` stage label (``prepare`` / the segment's own label /
      ``finalize``) when the model declares staged structure — the per-block
      bisection through the same prepare→segments→finalize decomposition the
      pipeline/streaming executors run;
    - ``"model-output"`` — spec-less model whose whole forward emits the
      non-finite value;
    - ``None`` — nothing non-finite reproduced (a transient the re-run could
      not reproduce; the step/σ naming in the bundle still stands).

    Runs the cond branch only (CFG mixing is elementwise after the forward,
    so a block-level NaN shows up on either branch). Best-effort by
    contract: callers wrap it in try/except — forensics must never raise
    over the quarantine it documents."""
    out: dict = {"sigma": float(sigma_eval), "prediction": prediction}
    if not _finite(xe):
        out["block"] = "lane-input"
        return out
    x_in, t_vec = eval_input(xe, sigma_eval, prediction, log_sigmas)
    kwargs = dict(kwargs or {})
    spec = getattr(model, "pipeline_spec", None)
    params = getattr(model, "params", None)
    if spec is not None and params is not None and spec.segments:
        carry = spec.prepare(
            _subset(params, spec.prepare_keys), x_in, t_vec, context, **kwargs
        )
        if not _finite(carry):
            out["block"] = "prepare"
            return out
        for i, seg in enumerate(spec.segments):
            carry = seg.fn(_subset(params, seg.param_keys), carry)
            if not _finite(carry):
                out["block"] = seg.label or f"segment[{i}]"
                out["segment_index"] = i
                return out
        final = spec.finalize(
            _subset(params, spec.finalize_keys), carry, tuple(x_in.shape)
        )
        out["block"] = "finalize" if not _finite(final) else None
        return out
    try:
        y = model(x_in, t_vec, context, **kwargs)
        out["block"] = "model-output" if not _finite(y) else None
    except Exception as e:  # noqa: BLE001 — forensics, not control flow
        out["block"] = None
        out["rerun_error"] = f"{type(e).__name__}: {e}"
    return out
