"""Step-time / throughput metrics and profiler hooks.

The reference's observability is ~40 ``[ParallelAnything]`` print sites and the advice
to read s/it off the ComfyUI progress bar (SURVEY §5.1, §5.5). The BASELINE metric
("sec/it at batch=16 1024²; images/sec scaling 1→8 cores") must instead be emitted by
the framework itself:

- ``StepTimer`` — honest per-step wall timing (`block_until_ready` on the step output
  before the clock stops, because XLA dispatch is async), accumulating ``StepStats``
  (images/sec + sec/it) with warmup-step exclusion (first steps include compilation);
- ``trace`` — context manager around ``jax.profiler.trace`` for Perfetto/XProf dumps;
- ``MetricsRegistry`` — process-wide labeled counters/gauges/summaries with a
  Prometheus-text renderer (round 7): the serving subsystem's per-bucket
  occupancy, lane-wait, step-time, and dispatch-count instruments, exposed by
  the HTTP server's ``GET /metrics``.

Instrument families registered against this registry (create-on-first-touch
— no registration step): ``pa_serving_*`` (serving/), ``pa_compile_*`` /
``pa_hbm_*`` (utils/telemetry.py, devices/memory.py), ``pa_trace_span_*``
(utils/tracing.py), and ``pa_numerics_*`` (utils/numerics.py —
``pa_numerics_nonfinite_total{where=}`` / ``pa_numerics_quarantined_total``
counters at the event sites, plus the ``pa_numerics_sentinel_enabled`` /
``pa_numerics_nonfinite_events`` / ``pa_numerics_quarantined_lanes`` gauges
the server publishes at scrape time so healthy zeros are visible), and
``pa_fleet_*`` (fleet/ — router-side placement/failover accounting:
``pa_fleet_dispatch_total{host=}`` / ``pa_fleet_spill_total{host=}`` /
``pa_fleet_failover_total{host=}`` / ``pa_fleet_completed_total`` counters,
the CI-gated ``pa_fleet_prompts_lost_total``, and the scoreboard gauges
``pa_fleet_hosts`` / ``pa_fleet_hosts_healthy`` /
``pa_fleet_host_inflight{host=}`` / ``pa_fleet_host_accepting{host=}`` /
``pa_fleet_inflight`` / ``pa_fleet_queued`` published at scrape time).

Later rounds' families (this map is the OWNING REGISTRY: palint's
registry-consistency pass fails CI on any ``pa_*`` emission site whose
family is missing here): ``pa_server_*`` (server.py — queue depth /
running / rejected), ``pa_stream_overlap_efficiency`` (parallel/streaming
— stage-compute fraction of streamed-run wall), ``pa_slo_*`` (utils/slo.py
— burn rate / budget / objective verdicts / threshold-aligned request and
stage histograms), ``pa_roofline_*`` (utils/roofline.py + fleet/twin.py —
per-program predicted seconds, twin capacity source), ``pa_fault_injected_total{site=}``
(utils/faults.py — chaos attribution), and ``pa_degradation_total{rung=}``
(utils/degrade.py — ladder rungs taken).

Cross-request compute reuse (round 17): ``pa_embed_cache_*``
(models/embed_cache.py — content-addressed encoder-output cache hit/miss/
byte/eviction gauges, published at /metrics scrape), ``pa_encoder_*``
(the ``pa_encoder_invocations_total`` counter — real encoder program runs,
the loadgen ``encoder_invocations`` delta), and ``pa_decode_*``
(serving/decode.py — batched tail decode: dispatch/request counters,
queue-depth and batched-fraction gauges, wait/step histograms).

Auto-parallel planner (round 18): ``pa_planner_*`` (parallel/planner.py —
``pa_planner_decisions_total`` / ``pa_planner_divergence_total`` counters
per plan decision, and the ``pa_planner_predicted_s{mode=}`` /
``pa_planner_hand_predicted_s`` / ``pa_planner_candidates`` gauges carrying
the last decision's chosen-vs-shadow-hand score).

Universal lane batching (round 19, within the ``pa_serving_*`` family):
``pa_serving_lane_capability_total{kind=}`` (serving/bucket.py — lanes
seated by capability carried: ``img2img_mask`` / ``multi_cond`` /
``controlnet`` / ``lora``, plain lanes as ``txt2img``; a multi-capability
lane counts once per capability — the loadgen mixed-workload per-kind
deltas), ``pa_serving_inline_fallback_total{reason=,sampler=}``
(sampling/runner.py — runs bounced to the inline path, the
mixed-workload smoke's must-stay-zero gate for eligible shapes), and
``pa_serving_ctrl_conflict_total{bucket=}`` (serving/bucket.py — lanes
bounced because the bucket epoch already carries a different control
trunk).

Disaggregated role pools (round 20): ``pa_role_*`` (fleet/roles.py +
fleet/router.py + server.py — ``pa_role_pool_size{role=}`` gauges,
``pa_role_dispatch_total{role=,host=}`` /
``pa_role_stage_resolved_total{role=}`` /
``pa_role_handle_hits`` / ``pa_role_handle_misses`` counters, the
``pa_role_stage_seconds{role=}`` histogram, and the stage-store
``pa_role_stage_store_bytes`` / ``pa_role_stage_store_entries`` gauges),
plus ``pa_embed_cache_remote_hits`` / ``pa_embed_cache_remote_misses``
inside the existing ``pa_embed_cache_*`` family (models/embed_cache.py —
the cross-host second tier: a denoise host fetching conds from an encode
host's ``GET /embed/{key}``).

Request forensics (round 21): ``pa_trace_dropped_total{reason=}``
(utils/tracing.py — spans evicted from the tracer's bounded retention
tiers: ``retired-ring`` for dead-thread buffers pushed off the retired
ring, ``prompt-retention`` for completed-prompt snapshots LRU-evicted
past the budget; nonzero warns that a stitched ``GET /fleet/trace``
timeline may be incomplete).

Continuous telemetry (round 22): ``pa_history_*`` (utils/timeseries.py —
the bounded metric-history ring's occupancy gauges: ``pa_history_bytes``
/ ``pa_history_points`` / ``pa_history_span_seconds``, published at
snapshot time so the ring's coverage is itself observable),
``pa_anomaly_*`` (utils/anomaly.py — the online sentinel:
``pa_anomaly_active{signal=,host=}`` gauges,
``pa_anomaly_events_total{signal=}`` /
``pa_anomaly_unattributed_total{signal=}`` counters — the loadgen
``anomalies_fired`` / ``anomalies_unattributed`` deltas and the
scripts/anomaly_report.py attribution gate), and
``pa_disk_append_seconds{target=}`` (fleet/journal.py + this package's
utils/telemetry.py — journal/ledger append wall time, the slow-disk
chaos site's watched latency signal; ``target`` is ``journal`` or
``ledger``), plus ``pa_fleet_host_health_age_s{host=}`` inside the
existing ``pa_fleet_*`` family (fleet/scoreboard.py — seconds since each
backend's last successful health poll, the sentinel's
heartbeat-staleness signal).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Any

import jax

from .logging import get_logger


class MetricsRegistry:
    """Thread-safe labeled metrics with Prometheus text exposition.

    Four instrument kinds, created on first touch (no registration step —
    instrumentation sites must never crash a serving path over bookkeeping):
    ``counter`` (monotonic), ``gauge`` (set to the latest value), ``summary``
    (accumulates ``_sum``/``_count`` — enough for rate/mean queries without
    carrying quantile sketches), and ``histogram`` (log-spaced buckets by
    default, with Prometheus ``_bucket``/``_sum``/``_count`` exposition — the
    server-side quantile source, so a load generator can read p50/p95 off
    ``GET /metrics`` instead of only computing them client-side). Labels are
    a plain dict, canonicalized to a sorted tuple key.

    A histogram may declare EXPLICIT bucket bounds at first touch
    (``histogram(..., bounds=...)``) — the SLO plane aligns
    ``pa_slo_request_seconds`` edges to the declared latency thresholds so
    an objective verdict is a bucket read, never an interpolation. Bounds
    are per-metric and first-touch-wins (all label sets of one metric share
    one ladder, so exposition always merges across hosts that declared the
    same objectives)."""

    # Log-spaced duration buckets, 1 ms … 100 s (~2.5x steps): wide enough
    # for lane waits under load AND sub-5ms compiled step dispatches; the
    # shared default so two servers' exposition always merges.
    HIST_BOUNDS = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    )

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> {"type": kind, "help": str, "values": {label_key: float|[sum, count]}}
        self._metrics: dict[str, dict] = {}  # guarded-by: _lock

    @staticmethod
    def _label_key(labels: dict | None) -> tuple:
        return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))

    def _slot(self, name: str, kind: str, help_: str) -> dict:  # palint: holds _lock
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = {"type": kind, "help": help_, "values": {}}
        return m

    def counter(self, name: str, inc: float = 1.0, labels: dict | None = None,
                help: str = "") -> None:
        with self._lock:
            vals = self._slot(name, "counter", help)["values"]
            k = self._label_key(labels)
            vals[k] = vals.get(k, 0.0) + inc

    def gauge(self, name: str, value: float, labels: dict | None = None,
              help: str = "") -> None:
        with self._lock:
            self._slot(name, "gauge", help)["values"][self._label_key(labels)] = (
                float(value)
            )

    def observe(self, name: str, value: float, labels: dict | None = None,
                help: str = "") -> None:
        with self._lock:
            vals = self._slot(name, "summary", help)["values"]
            k = self._label_key(labels)
            acc = vals.get(k)
            if acc is None:
                acc = vals[k] = [0.0, 0.0]
            acc[0] += float(value)
            acc[1] += 1.0

    def histogram(self, name: str, value: float, labels: dict | None = None,
                  help: str = "", bounds=None) -> None:
        """Observe ``value`` (seconds) into the metric's buckets. ``bounds``
        (an ascending tuple of upper edges) fixes the ladder at the metric's
        FIRST touch — omitted, the log-spaced default applies; on later
        touches it is ignored (first wins: one ladder per metric, so every
        label set and every host's exposition stays mergeable)."""
        v = float(value)
        with self._lock:
            m = self._slot(name, "histogram", help)
            hb = m.get("bounds")
            if hb is None:
                hb = m["bounds"] = (
                    tuple(float(b) for b in bounds)
                    if bounds else self.HIST_BOUNDS
                )
            vals = m["values"]
            k = self._label_key(labels)
            acc = vals.get(k)
            if acc is None:
                # [per-bound counts..., +Inf count, sum, count]
                acc = vals[k] = [0.0] * (len(hb) + 1) + [0.0, 0.0]
            for i, bound in enumerate(hb):
                if v <= bound:
                    acc[i] += 1.0
                    break
            else:
                acc[len(hb)] += 1.0
            acc[-2] += v
            acc[-1] += 1.0

    def get(self, name: str, labels: dict | None = None):
        """Current value (float for counter/gauge, (sum, count) for summary
        AND histogram), or None — the test/introspection read side."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return None
            v = m["values"].get(self._label_key(labels))
            if isinstance(v, list):
                return (v[-2], v[-1]) if m["type"] == "histogram" else tuple(v)
            return v

    def quantile(self, name: str, q: float, labels: dict | None = None):
        """Histogram quantile (0-100) by linear interpolation within the
        bucket holding the target rank, or None. Merges across all label sets
        when ``labels`` is None — the read side loadgen's server-side p50/p95
        comes from (scraped over HTTP there; this is the in-process twin)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m["type"] != "histogram":
                return None
            if labels is None:
                accs = list(m["values"].values())
            else:
                acc = m["values"].get(self._label_key(labels))
                accs = [acc] if acc is not None else []
            if not accs:
                return None
            hb = m.get("bounds") or self.HIST_BOUNDS
            n = len(hb)
            counts = [sum(a[i] for a in accs) for i in range(n + 1)]
        total = sum(counts)
        if total <= 0:
            return None
        target = q / 100.0 * total
        cum = 0.0
        lo = 0.0
        for i, c in enumerate(counts):
            if i < n:
                hi = hb[i]
            else:
                hi = hb[-1]  # +Inf bucket clamps to last bound
            if cum + c >= target and c > 0:
                frac = (target - cum) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            cum += c
            lo = hi
        return lo

    def dump(self, prefix: str | None = None) -> dict:
        """Structured point-in-time copy of every metric (optionally name-
        prefix filtered): ``{name: {"type", "bounds", "values":
        {label_str: float | list}}}`` where ``label_str`` is the sorted
        ``k="v"`` comma join (empty for the unlabeled series) and histogram
        lists are the raw ``[per-bound counts..., +Inf, sum, count]``
        accumulator. The history ring's (utils/timeseries.py) snapshot
        source — one lock hold, values copied out."""
        out: dict = {}
        with self._lock:
            for name, m in self._metrics.items():
                if prefix is not None and not name.startswith(prefix):
                    continue
                values = {}
                for key, v in m["values"].items():
                    lbl = ",".join(f'{k}="{val}"' for k, val in key)
                    values[lbl] = list(v) if isinstance(v, list) else v
                out[name] = {
                    "type": m["type"],
                    "bounds": list(m["bounds"]) if m.get("bounds") else None,
                    "values": values,
                }
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def render(self) -> str:
        """Prometheus text format 0.0.4 (the GET /metrics body)."""

        def esc(v: str) -> str:
            return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")

        lines: list[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m["help"]:
                    lines.append(f"# HELP {name} {m['help']}")
                lines.append(f"# TYPE {name} {m['type']}")
                for key, v in sorted(m["values"].items()):
                    lbl = (
                        "{" + ",".join(f'{k}="{esc(val)}"' for k, val in key) + "}"
                        if key else ""
                    )
                    if m["type"] == "summary":
                        lines.append(f"{name}_sum{lbl} {v[0]:.9g}")
                        lines.append(f"{name}_count{lbl} {v[1]:.9g}")
                    elif m["type"] == "histogram":
                        def le_lbl(le: str) -> str:
                            pairs = list(key) + [("le", le)]
                            return "{" + ",".join(
                                f'{k}="{esc(val)}"' for k, val in pairs
                            ) + "}"

                        hb = m.get("bounds") or self.HIST_BOUNDS
                        cum = 0.0
                        for i, bound in enumerate(hb):
                            cum += v[i]
                            lines.append(
                                f"{name}_bucket{le_lbl(f'{bound:.9g}')} "
                                f"{cum:.9g}"
                            )
                        cum += v[len(hb)]
                        lines.append(f"{name}_bucket{le_lbl('+Inf')} {cum:.9g}")
                        lines.append(f"{name}_sum{lbl} {v[-2]:.9g}")
                        lines.append(f"{name}_count{lbl} {v[-1]:.9g}")
                    else:
                        lines.append(f"{name}{lbl} {v:.9g}")
        return "\n".join(lines) + "\n"


# The process-wide registry every instrumentation site writes to (serving/,
# server.py) and GET /metrics renders. Tests may reset() it.
registry = MetricsRegistry()


@dataclasses.dataclass
class StepStats:
    steps: int = 0
    total_s: float = 0.0
    last_s: float = 0.0
    images: int = 0

    @property
    def sec_per_it(self) -> float:
        return self.total_s / self.steps if self.steps else 0.0

    @property
    def images_per_sec(self) -> float:
        return self.images / self.total_s if self.total_s > 0 else 0.0


class StepTimer:
    """Times sampler steps honestly: blocks on the step's output before stopping the
    clock. Warmup steps (default 1 — the compile step) are recorded separately and
    excluded from the throughput stats."""

    def __init__(self, warmup_steps: int = 1):
        self.warmup_steps = warmup_steps
        self.warmup = StepStats()
        self.stats = StepStats()

    @contextlib.contextmanager
    def step(self, batch_size: int = 1):
        t0 = time.perf_counter()
        out_box: list[Any] = []
        yield out_box
        if out_box:
            jax.block_until_ready(out_box[0])
        dt = time.perf_counter() - t0
        target = (
            self.warmup
            if self.warmup.steps < self.warmup_steps
            else self.stats
        )
        target.steps += 1
        target.total_s += dt
        target.last_s = dt
        target.images += batch_size

    def time_step(self, fn, *args, batch_size: int = 1, **kwargs):
        """Run ``fn`` as one timed step and return its result."""
        with self.step(batch_size=batch_size) as box:
            out = fn(*args, **kwargs)
            box.append(out)
        return out

    def log_summary(self, label: str = "sampler") -> None:
        s = self.stats
        get_logger().info(
            "%s: %d steps, %.4f s/it, %.2f images/s (warmup %d steps, %.2fs)",
            label,
            s.steps,
            s.sec_per_it,
            s.images_per_sec,
            self.warmup.steps,
            self.warmup.total_s,
        )


def force_ready(v) -> float:
    """Force execution of ``v``'s whole dependency chain via a 4-byte
    device->host readback of a reduced scalar. Unlike ``block_until_ready``
    (which the experimental axon tunnel plugin has returned from without
    waiting — observed "timings" ~80x above chip peak), possessing the bytes
    on the host proves the computation actually finished."""
    import jax.numpy as jnp
    import numpy as np

    return float(np.asarray(jnp.sum(v.astype(jnp.float32))))


def chained_time(step, x0, iters: int, warmup: int = 2):
    """Tunnel-proof mean seconds per ``step`` call.

    ``step`` must map an array to a like-shaped array (denoise models and
    attention both do). Each iteration feeds its output back as the next
    input, making the timed region one serial dependency chain — no runtime
    can skip, dedupe, or overlap it — and it closes with a ``force_ready``
    readback. ``warmup`` calls (>= 2 — both the original and the chained
    dtype signatures must compile outside the timed region) run first; the
    count is explicit so bench.py can pin and record the protocol.

    Returns ``(sec_per_iter, last_output)``."""
    out = step(x0)
    for _ in range(max(2, warmup) - 1):
        out = step(out)
    force_ready(out)
    run = out
    t0 = time.perf_counter()
    for _ in range(iters):
        run = step(run)
    force_ready(run)
    return (time.perf_counter() - t0) / iters, run


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/parallelanything-trace"):
    """Profile a region → Perfetto/XProf trace in ``log_dir`` (SURVEY §5.1 plan)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
        get_logger().info("profiler trace written to %s", log_dir)
