"""Step-time / throughput metrics and profiler hooks.

The reference's observability is ~40 ``[ParallelAnything]`` print sites and the advice
to read s/it off the ComfyUI progress bar (SURVEY §5.1, §5.5). The BASELINE metric
("sec/it at batch=16 1024²; images/sec scaling 1→8 cores") must instead be emitted by
the framework itself:

- ``StepTimer`` — honest per-step wall timing (`block_until_ready` on the step output
  before the clock stops, because XLA dispatch is async), accumulating ``StepStats``
  (images/sec + sec/it) with warmup-step exclusion (first steps include compilation);
- ``trace`` — context manager around ``jax.profiler.trace`` for Perfetto/XProf dumps.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any

import jax

from .logging import get_logger


@dataclasses.dataclass
class StepStats:
    steps: int = 0
    total_s: float = 0.0
    last_s: float = 0.0
    images: int = 0

    @property
    def sec_per_it(self) -> float:
        return self.total_s / self.steps if self.steps else 0.0

    @property
    def images_per_sec(self) -> float:
        return self.images / self.total_s if self.total_s > 0 else 0.0


class StepTimer:
    """Times sampler steps honestly: blocks on the step's output before stopping the
    clock. Warmup steps (default 1 — the compile step) are recorded separately and
    excluded from the throughput stats."""

    def __init__(self, warmup_steps: int = 1):
        self.warmup_steps = warmup_steps
        self.warmup = StepStats()
        self.stats = StepStats()

    @contextlib.contextmanager
    def step(self, batch_size: int = 1):
        t0 = time.perf_counter()
        out_box: list[Any] = []
        yield out_box
        if out_box:
            jax.block_until_ready(out_box[0])
        dt = time.perf_counter() - t0
        target = (
            self.warmup
            if self.warmup.steps < self.warmup_steps
            else self.stats
        )
        target.steps += 1
        target.total_s += dt
        target.last_s = dt
        target.images += batch_size

    def time_step(self, fn, *args, batch_size: int = 1, **kwargs):
        """Run ``fn`` as one timed step and return its result."""
        with self.step(batch_size=batch_size) as box:
            out = fn(*args, **kwargs)
            box.append(out)
        return out

    def log_summary(self, label: str = "sampler") -> None:
        s = self.stats
        get_logger().info(
            "%s: %d steps, %.4f s/it, %.2f images/s (warmup %d steps, %.2fs)",
            label,
            s.steps,
            s.sec_per_it,
            s.images_per_sec,
            self.warmup.steps,
            self.warmup.total_s,
        )


def force_ready(v) -> float:
    """Force execution of ``v``'s whole dependency chain via a 4-byte
    device->host readback of a reduced scalar. Unlike ``block_until_ready``
    (which the experimental axon tunnel plugin has returned from without
    waiting — observed "timings" ~80x above chip peak), possessing the bytes
    on the host proves the computation actually finished."""
    import jax.numpy as jnp
    import numpy as np

    return float(np.asarray(jnp.sum(v.astype(jnp.float32))))


def chained_time(step, x0, iters: int):
    """Tunnel-proof mean seconds per ``step`` call.

    ``step`` must map an array to a like-shaped array (denoise models and
    attention both do). Each iteration feeds its output back as the next
    input, making the timed region one serial dependency chain — no runtime
    can skip, dedupe, or overlap it — and it closes with a ``force_ready``
    readback. Two warmup calls run first so both the original and the
    chained dtype signatures are compiled outside the timed region.

    Returns ``(sec_per_iter, last_output)``."""
    out = step(x0)
    out = step(out)
    force_ready(out)
    run = out
    t0 = time.perf_counter()
    for _ in range(iters):
        run = step(run)
    force_ready(run)
    return (time.perf_counter() - t0) / iters, run


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/parallelanything-trace"):
    """Profile a region → Perfetto/XProf trace in ``log_dir`` (SURVEY §5.1 plan)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
        get_logger().info("profiler trace written to %s", log_dir)
