"""Memory cleanup — the JAX analogue of aggressive_cleanup.

Reference (any_device_parallel.py:197-209): ``gc.collect()`` + per-device
``cuda.synchronize()/empty_cache()`` + host ``soft_empty_cache()``. Under JAX most of
that surface does not exist: buffers free when their `jax.Array`s die, and there is no
user-visible allocator cache to flush on TPU. What remains meaningful:

- drop Python garbage so dead `jax.Array` references release device buffers,
- optionally clear jit compilation caches (only on the OOM path — compiled executables
  themselves hold device allocations for constants).
"""

from __future__ import annotations

import gc

import jax


def aggressive_cleanup(clear_compile_cache: bool = False) -> None:
    gc.collect()
    if clear_compile_cache:
        try:
            from ..sampling.compiled import clear_compiled_loops

            clear_compiled_loops()
        except Exception:
            pass
        jax.clear_caches()
