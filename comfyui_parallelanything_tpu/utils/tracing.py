"""End-to-end span tracing: per-prompt timelines from HTTP ingress to TPU step.

The reference's observability is ~40 ``[ParallelAnything]`` print sites and
"read s/it off the progress bar" (SURVEY §5.1, §5.5). This reproduction has
far more moving parts — weight-streaming prefetch rings, continuous-batching
lane lifecycles, per-thread progress scopes — and every open ROADMAP item
("measure flux_stream on hardware", "measure serving latency on hardware")
is blocked on being able to *see* where time goes. This module is that layer:
a process-wide :class:`Tracer` producing per-prompt traces of nested spans

    prompt → workflow-node → sampler-run → lane-wait → step
                                              → stream-stage-{prefetch,compute}

exported in Chrome/Perfetto trace-event JSON (``GET /trace?prompt_id=...`` on
the server, ``--trace-out`` on bench.py, ``scripts/trace_summary.py`` offline).

Design rules (the near-zero-overhead contract):

- **disabled is a single flag check**: :func:`span` returns one shared
  ``_NULL`` singleton when tracing is off — no Span object, no clock read, no
  buffer touch. Instrumentation sites that must *compute* attributes guard on
  :func:`on` first.
- **recording is lock-free per thread**: every recording thread owns its own
  ring buffer (a bounded ``deque`` — old spans fall off instead of growing
  without bound); the tracer's lock is taken only once per thread, at
  registration, and at export (which snapshots the per-thread deques).
- **prompt correlation rides the progress scopes**: a span opened with
  ``prompt_id=...`` establishes the thread's current prompt; nested spans
  inherit it, and threads that carry no span context fall back to the
  per-thread ``utils.progress`` scope (the serving scheduler captures the
  submitting thread's identity at admission, so lane-wait/step spans recorded
  from the dispatcher thread land on the *prompt's* timeline).
- **cross-thread spans carry an explicit tid**: :func:`record` writes a
  completed span into the *recording* thread's buffer but may stamp it with
  the submitting thread's tid — per-tid interval nesting is preserved because
  the submitting thread is blocked in ``ticket.result()`` for exactly that
  interval.
- **metrics stay consistent with traces**: every span close feeds its
  duration into ``MetricsRegistry`` (``pa_trace_span_seconds{name=...}``
  histogram), so ``/metrics`` aggregates and ``/trace`` timelines are two
  views of the same measurements.

``block_until_ready`` discipline: instrumentation only ever *reads the clock*
at boundaries that already synchronize (the serving bucket's post-dispatch
block, the streaming runner's backpressure block, the eager loops' progress
callbacks) — tracing never adds a device sync of its own.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Optional

# Per-thread span buffer capacity: at ~150 bytes/span this bounds a thread's
# trace memory at a few MiB while holding minutes of step-granularity spans.
DEFAULT_CAPACITY = 16384

_span_ids = itertools.count(1)


def now_us() -> float:
    """Monotonic microseconds — the trace-event clock (Chrome ``ts`` unit)."""
    return time.perf_counter_ns() / 1e3


class _NullSpan:
    """The disabled-path singleton: a context manager that does nothing and
    allocates nothing. ``set()`` (attribute attach) is a no-op too, so call
    sites never need a second enabled-check."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NULL = _NullSpan()


class _OpenSpan:
    """One live span on the opening thread's stack; closing (context exit)
    records a completed ``X`` event into that thread's ring buffer."""

    __slots__ = ("_tracer", "_local", "name", "cat", "ts", "attrs", "span_id")

    def __init__(self, tracer, local, name, cat, attrs):
        self._tracer = tracer
        self._local = local
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.span_id = next(_span_ids)
        self.ts = 0.0

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        self._local.stack.append(self)
        self.ts = now_us()
        return self

    def __exit__(self, *exc):
        dur = now_us() - self.ts
        stack = self._local.stack
        # LIFO by construction (context managers); tolerate a corrupted stack
        # rather than poisoning the traced code path.
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:
            stack.remove(self)
        self._tracer._emit(
            self._local, self.name, self.ts, dur, self.cat,
            threading.get_ident(), self.attrs, self.span_id,
        )
        return False


class _Local(threading.local):
    """Per-thread recording state: the open-span stack and the ring buffer."""

    def __init__(self):
        self.stack: list[_OpenSpan] = []
        self.events: deque | None = None


class Tracer:
    """Process-wide span recorder. ``enabled`` is the hot-path flag; all other
    state is touched only while tracing is on."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.enabled = False
        self.capacity = capacity
        self._local = _Local()
        self._lock = threading.Lock()
        # thread ident -> (thread name, events deque) — registration happens
        # once per recording thread; export snapshots under the lock.
        self._buffers: dict[int, tuple[str, deque]] = {}  # guarded-by: _lock
        # Thread IDENTS ARE REUSED after a thread dies (pthread ids recycle
        # aggressively under http.server's thread-per-request churn): when a
        # new thread claims a dead recorder's ident, the dead thread's spans
        # must survive — they move to this bounded retired ring instead of
        # being silently replaced. Every event row carries its own tid, so
        # retired buffers export exactly like live ones.
        self._retired: deque = deque(maxlen=256)  # guarded-by: _lock
        self._epoch_us = now_us()

    # -- lifecycle ----------------------------------------------------------

    def enable(self, capacity: int | None = None) -> None:
        """Turn tracing on (clearing any previous trace). ``capacity`` is
        per-call, not sticky: omitting it restores the default — a tiny
        capacity chosen for one capture must not silently truncate the
        next."""
        with self._lock:
            self.capacity = DEFAULT_CAPACITY if capacity is None else capacity
            self._buffers.clear()
            self._retired.clear()
            self._epoch_us = now_us()
        self._local = _Local()
        self.enabled = True

    def disable(self) -> None:
        """Stop recording; the captured trace stays exportable until the next
        ``enable()``."""
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._buffers.clear()
            self._retired.clear()

    # -- recording ----------------------------------------------------------

    def _events(self, local) -> deque:
        ev = local.events
        if ev is None:
            ev = local.events = deque(maxlen=self.capacity)
            t = threading.current_thread()
            with self._lock:
                prev = self._buffers.get(threading.get_ident())
                if prev is not None and prev[1]:
                    # Recycled ident: retire the dead thread's spans rather
                    # than dropping them (short-lived HTTP handler threads
                    # record real spans — fleet dispatch hops among them).
                    self._retired.append(prev)
                self._buffers[threading.get_ident()] = (t.name, ev)
        return ev

    def _emit(self, local, name, ts, dur, cat, tid, attrs, span_id) -> None:
        self._events(local).append((name, ts, dur, cat, tid, attrs, span_id))
        self._feed_metrics(name, cat, dur)

    @staticmethod
    def _feed_metrics(name, cat, dur_us) -> None:
        # Lazy import: tracing must stay importable without jax (metrics.py
        # imports jax); a metrics hiccup must never break the traced path.
        try:
            from .metrics import registry

            registry.histogram(
                "pa_trace_span_seconds", dur_us / 1e6,
                labels={"name": name, "cat": cat},
                help="span durations from utils/tracing.py (trace/metrics "
                     "consistency: same measurements, two views)",
            )
        except Exception:
            pass

    def span(self, name: str, cat: str = "host",
             prompt_id: str | None = None, **attrs):
        """Open a nested span on the calling thread (context manager). When
        tracing is disabled this is the single flag check returning the
        shared null singleton."""
        if not self.enabled:
            return _NULL
        local = self._local
        if prompt_id is None:
            prompt_id = self._current_prompt_id(local)
        if prompt_id is not None:
            attrs["prompt_id"] = prompt_id
        return _OpenSpan(self, local, name, cat, attrs)

    def record(self, name: str, ts: float, dur: float, cat: str = "host",
               tid: int | None = None, prompt_id: str | None = None,
               **attrs) -> None:
        """Record an already-measured span (explicit interval). ``tid``
        attributes the span to another thread's timeline (the serving
        dispatcher recording on behalf of a blocked submitter); the write
        still goes to the *calling* thread's lock-free buffer."""
        if not self.enabled:
            return
        local = self._local
        if prompt_id is None:
            prompt_id = self._current_prompt_id(local)
        if prompt_id is not None:
            attrs["prompt_id"] = prompt_id
        self._emit(
            local, name, ts, max(0.0, dur), cat,
            tid if tid is not None else threading.get_ident(),
            attrs, next(_span_ids),
        )

    # -- context ------------------------------------------------------------

    def _current_prompt_id(self, local=None) -> Optional[str]:
        local = local if local is not None else self._local
        for s in reversed(local.stack):
            pid = s.attrs.get("prompt_id")
            if pid is not None:
                return pid
        # No span context on this thread: fall back to the per-thread
        # progress scope (the per-prompt correlation the server installs).
        try:
            from .progress import current_scope

            scope = current_scope()
            return getattr(scope, "prompt_id", None)
        except Exception:
            return None

    def current_prompt_id(self) -> Optional[str]:
        """The prompt the calling thread is working for right now, or None."""
        return self._current_prompt_id()

    def current_span_id(self) -> Optional[int]:
        stack = self._local.stack
        return stack[-1].span_id if stack else None

    # -- export -------------------------------------------------------------

    def export(self, prompt_id: str | None = None) -> dict:
        """Chrome/Perfetto trace-event JSON (the ``chrome://tracing`` /
        ui.perfetto.dev format): complete ``X`` events with ``ts``/``dur`` in
        microseconds, plus thread-name metadata. ``prompt_id`` filters to one
        prompt's timeline (spans stamped with that prompt_id)."""
        pid = os.getpid()
        with self._lock:
            snap = [(tid, name, list(ev))
                    for tid, (name, ev) in self._buffers.items()]
            # Retired buffers (dead threads whose ident was recycled): their
            # rows carry their own tids, so they render identically.
            snap.extend(
                (0, name, list(ev)) for name, ev in self._retired
            )
        events: list[dict] = []
        tids_seen: set[int] = set()
        for _rec_tid, _tname, recs in snap:
            for name, ts, dur, cat, tid, attrs, span_id in recs:
                if prompt_id is not None and attrs.get("prompt_id") != prompt_id:
                    continue
                args = dict(attrs)
                args["span_id"] = span_id
                events.append({
                    "ph": "X", "name": name, "cat": cat,
                    "ts": round(ts - self._epoch_us, 3),
                    "dur": round(dur, 3),
                    "pid": pid, "tid": tid, "args": args,
                })
                tids_seen.add(tid)
        events.sort(key=lambda e: (e["tid"], e["ts"], -e["dur"]))
        thread_names = {tid: tname for tid, tname, _ in snap}
        meta = [{
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": thread_names.get(tid, f"thread-{tid}")},
        } for tid in sorted(tids_seen)]
        meta.insert(0, {
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": "parallel_anything_tpu"},
        })
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


# The process-wide tracer every instrumentation site records into and the
# server's GET /trace renders. Tests may enable()/disable() it.
tracer = Tracer()


def on() -> bool:
    """The hot-path enabled check — guard attribute computation with this."""
    return tracer.enabled


def enable(capacity: int | None = None) -> None:
    tracer.enable(capacity)


def disable() -> None:
    tracer.disable()


def span(name: str, cat: str = "host", prompt_id: str | None = None, **attrs):
    return tracer.span(name, cat=cat, prompt_id=prompt_id, **attrs)


def record(name: str, ts: float, dur: float, cat: str = "host",
           tid: int | None = None, prompt_id: str | None = None, **attrs):
    tracer.record(name, ts, dur, cat=cat, tid=tid, prompt_id=prompt_id,
                  **attrs)


def export(prompt_id: str | None = None) -> dict:
    return tracer.export(prompt_id)


def current_prompt_id() -> Optional[str]:
    return tracer.current_prompt_id()


def current_span_id() -> Optional[int]:
    return tracer.current_span_id()


@contextlib.contextmanager
def hardware_trace(log_dir: str = "/tmp/parallelanything-trace"):
    """Bracket a span subtree with ``jax.profiler.trace`` so the XProf device
    timeline lines up with the host spans recorded inside the block: open the
    trace in Perfetto alongside the ``GET /trace`` export and the
    ``hardware-trace`` host span marks the profiled window."""
    import jax

    with span("hardware-trace", cat="profiler", log_dir=log_dir):
        jax.profiler.start_trace(log_dir)
        try:
            yield log_dir
        finally:
            jax.profiler.stop_trace()


# -- trace-derived aggregates ------------------------------------------------
#
# Shared by bench.py (every JSON line), __graft_entry__.dryrun_multichip, and
# scripts/trace_summary.py (which re-implements the same math stdlib-only; a
# tier-1 test pins the two against each other on the same fixture).


def _percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (scripts/loadgen.py convention)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
    return s[k]


def _x_events(events) -> list[dict]:
    if isinstance(events, dict):
        events = events.get("traceEvents", [])
    return [e for e in events if e.get("ph") == "X"]


def stream_overlap_efficiency(events) -> float | None:
    """Fraction of each ``stream-run`` span's wall time occupied by
    ``stream-stage-compute`` spans, averaged over runs; in (0, 1] by
    construction (compute spans are non-overlapping and contained in their
    run). Exposed transfer/backpressure time — the part double-buffering
    exists to hide — is exactly what pushes this below 1; it is the
    overlap-efficiency number the flux_stream live-window measurement needs.
    None when the trace holds no streamed runs."""
    xs = _x_events(events)
    runs = [e for e in xs if e["name"] == "stream-run" and e.get("dur", 0) > 0]
    if not runs:
        return None
    comps = [e for e in xs if e["name"] == "stream-stage-compute"]
    effs = []
    for r in runs:
        r0, r1 = r["ts"], r["ts"] + r["dur"]
        busy = sum(
            c["dur"] for c in comps
            if c["tid"] == r["tid"] and c["ts"] >= r0
            and c["ts"] + c["dur"] <= r1 + 1.0  # float-rounding slack (µs)
        )
        effs.append(min(1.0, busy / r["dur"]))
    return sum(effs) / len(effs)


def lane_wait_p95_s(events) -> float | None:
    """p95 of serving ``lane-wait`` spans (submit → seated), seconds."""
    waits = [e["dur"] / 1e6 for e in _x_events(events)
             if e["name"] == "lane-wait"]
    return _percentile(waits, 95) if waits else None


def host_gap_ms(events) -> float | None:
    """Mean host-side gap between consecutive ``step`` spans on each thread —
    the per-step scheduling overhead the device cannot see. None with fewer
    than two steps anywhere."""
    steps: dict[int, list[dict]] = {}
    for e in _x_events(events):
        if e["name"] == "step":
            steps.setdefault(e["tid"], []).append(e)
    gaps = []
    for evs in steps.values():
        evs.sort(key=lambda e: e["ts"])
        for a, b in zip(evs, evs[1:]):
            gaps.append(max(0.0, b["ts"] - (a["ts"] + a["dur"])) / 1e3)
    return sum(gaps) / len(gaps) if gaps else None


def fleet_hop_p95_ms(events) -> float | None:
    """p95 of the router's ``fleet-hop`` spans (place → backend accepted),
    milliseconds — the fleet tier's own overhead per dispatch, distinct from
    the backend-side prompt time. The hop span and the backend's prompt span
    share ``origin_prompt_id``/``prompt_id``, so one Perfetto export shows
    the prompt's timeline across the hop. None when no fleet routing ran
    inside the traced window (kept out of :func:`trace_aggregates`, whose
    key set is pinned against scripts/trace_summary.py)."""
    hops = [e["dur"] / 1e3 for e in _x_events(events)
            if e["name"] == "fleet-hop"]
    return round(_percentile(hops, 95), 4) if hops else None


def trace_aggregates(events) -> dict:
    """The trace-derived aggregate fields every bench.py JSON line carries."""
    eff = stream_overlap_efficiency(events)
    p95 = lane_wait_p95_s(events)
    gap = host_gap_ms(events)
    return {
        "stream_overlap_efficiency": None if eff is None else round(eff, 4),
        "lane_wait_p95": None if p95 is None else round(p95, 6),
        "host_gap_ms": None if gap is None else round(gap, 4),
    }
