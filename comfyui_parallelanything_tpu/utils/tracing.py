"""End-to-end span tracing: per-prompt timelines from HTTP ingress to TPU step.

The reference's observability is ~40 ``[ParallelAnything]`` print sites and
"read s/it off the progress bar" (SURVEY §5.1, §5.5). This reproduction has
far more moving parts — weight-streaming prefetch rings, continuous-batching
lane lifecycles, per-thread progress scopes — and every open ROADMAP item
("measure flux_stream on hardware", "measure serving latency on hardware")
is blocked on being able to *see* where time goes. This module is that layer:
a process-wide :class:`Tracer` producing per-prompt traces of nested spans

    prompt → workflow-node → sampler-run → lane-wait → step
                                              → stream-stage-{prefetch,compute}

exported in Chrome/Perfetto trace-event JSON (``GET /trace?prompt_id=...`` on
the server, ``--trace-out`` on bench.py, ``scripts/trace_summary.py`` offline).

Design rules (the near-zero-overhead contract):

- **disabled is a single flag check**: :func:`span` returns one shared
  ``_NULL`` singleton when tracing is off — no Span object, no clock read, no
  buffer touch. Instrumentation sites that must *compute* attributes guard on
  :func:`on` first.
- **recording is lock-free per thread**: every recording thread owns its own
  ring buffer (a bounded ``deque`` — old spans fall off instead of growing
  without bound); the tracer's lock is taken only once per thread, at
  registration, and at export (which snapshots the per-thread deques).
- **prompt correlation rides the progress scopes**: a span opened with
  ``prompt_id=...`` establishes the thread's current prompt; nested spans
  inherit it, and threads that carry no span context fall back to the
  per-thread ``utils.progress`` scope (the serving scheduler captures the
  submitting thread's identity at admission, so lane-wait/step spans recorded
  from the dispatcher thread land on the *prompt's* timeline).
- **cross-thread spans carry an explicit tid**: :func:`record` writes a
  completed span into the *recording* thread's buffer but may stamp it with
  the submitting thread's tid — per-tid interval nesting is preserved because
  the submitting thread is blocked in ``ticket.result()`` for exactly that
  interval.
- **metrics stay consistent with traces**: every span close feeds its
  duration into ``MetricsRegistry`` (``pa_trace_span_seconds{name=...}``
  histogram), so ``/metrics`` aggregates and ``/trace`` timelines are two
  views of the same measurements.

``block_until_ready`` discipline: instrumentation only ever *reads the clock*
at boundaries that already synchronize (the serving bucket's post-dispatch
block, the streaming runner's backpressure block, the eager loops' progress
callbacks) — tracing never adds a device sync of its own.
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Optional

# Per-thread span buffer capacity: at ~150 bytes/span this bounds a thread's
# trace memory at a few MiB while holding minutes of step-granularity spans.
DEFAULT_CAPACITY = 16384

# Explicit budgets for the two secondary retention tiers. Both tiers COUNT
# their evictions (``pa_trace_dropped_total{reason=}`` + ``Tracer.dropped``)
# instead of dropping silently — a full ring is an observability failure the
# operator must be able to see.
#
# - retired ring: dead threads whose pthread ident was recycled (one entry
#   per dead thread's whole buffer).
# - prompt retention: completed prompts snapshotted by :meth:`retain_prompt`
#   so a fleet collector can stitch a prompt's timeline after its recording
#   threads' rings have wrapped (one entry per prompt).
RETIRED_RING_BUDGET = 256
PROMPT_RETENTION = 64

_span_ids = itertools.count(1)

_HEX = set("0123456789abcdef")


def format_traceparent(trace_id: str, span_id: int | None = None,
                       sampled: bool = True) -> str:
    """W3C-traceparent-style context header: ``00-<32hex trace_id>-<16hex
    span_id>-<01|00>``. The fleet router uses the prompt_id lineage as the
    trace_id (``uuid4().hex`` is already 32 lowercase hex chars); any other
    string is md5-hashed into shape so callers never need to care.
    ``span_id`` defaults to a fresh id from the process-wide counter."""
    tid = str(trace_id).lower()
    if len(tid) != 32 or not set(tid) <= _HEX:
        tid = hashlib.md5(str(trace_id).encode()).hexdigest()
    if span_id is None:
        span_id = next(_span_ids)
    sid = format((int(span_id) & ((1 << 64) - 1)) or 1, "016x")
    return f"00-{tid}-{sid}-{'01' if sampled else '00'}"


def parse_traceparent(header) -> dict | None:
    """Inverse of :func:`format_traceparent`: ``{"trace_id", "parent_span_id",
    "sampled"}``, or ``None`` for anything malformed (unknown version,
    all-zero ids, wrong field widths) — a bad inbound context must degrade to
    an untraced hop, never to an exception on the serving path."""
    if not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    ver, tid, sid, flags = parts
    if ver != "00" or len(tid) != 32 or len(sid) != 16 or len(flags) != 2:
        return None
    if not (set(tid) <= _HEX and set(sid) <= _HEX and set(flags) <= _HEX):
        return None
    parent = int(sid, 16)
    if int(tid, 16) == 0 or parent == 0:
        return None
    return {
        "trace_id": tid,
        "parent_span_id": parent,
        "sampled": bool(int(flags, 16) & 1),
    }


def now_us() -> float:
    """Monotonic microseconds — the trace-event clock (Chrome ``ts`` unit)."""
    return time.perf_counter_ns() / 1e3


class _NullSpan:
    """The disabled-path singleton: a context manager that does nothing and
    allocates nothing. ``set()`` (attribute attach) is a no-op too, so call
    sites never need a second enabled-check."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NULL = _NullSpan()


class _OpenSpan:
    """One live span on the opening thread's stack; closing (context exit)
    records a completed ``X`` event into that thread's ring buffer."""

    __slots__ = ("_tracer", "_local", "name", "cat", "ts", "attrs", "span_id")

    def __init__(self, tracer, local, name, cat, attrs):
        self._tracer = tracer
        self._local = local
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.span_id = next(_span_ids)
        self.ts = 0.0

    def set(self, **attrs):
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        self._local.stack.append(self)
        self.ts = now_us()
        return self

    def __exit__(self, *exc):
        dur = now_us() - self.ts
        stack = self._local.stack
        # LIFO by construction (context managers); tolerate a corrupted stack
        # rather than poisoning the traced code path.
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:
            stack.remove(self)
        self._tracer._emit(
            self._local, self.name, self.ts, dur, self.cat,
            threading.get_ident(), self.attrs, self.span_id,
        )
        return False


class _Local(threading.local):
    """Per-thread recording state: the open-span stack, the ring buffer, and
    the active distributed-trace context (parsed traceparent or None)."""

    def __init__(self):
        self.stack: list[_OpenSpan] = []
        self.events: deque | None = None
        self.ctx: dict | None = None


class Tracer:
    """Process-wide span recorder. ``enabled`` is the hot-path flag; all other
    state is touched only while tracing is on."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.enabled = False
        self.capacity = capacity
        self._local = _Local()
        self._lock = threading.Lock()
        # thread ident -> (thread name, events deque) — registration happens
        # once per recording thread; export snapshots under the lock.
        self._buffers: dict[int, tuple[str, deque]] = {}  # guarded-by: _lock
        # Thread IDENTS ARE REUSED after a thread dies (pthread ids recycle
        # aggressively under http.server's thread-per-request churn): when a
        # new thread claims a dead recorder's ident, the dead thread's spans
        # must survive — they move to this bounded retired ring instead of
        # being silently replaced. Every event row carries its own tid, so
        # retired buffers export exactly like live ones. The ring's budget is
        # explicit and its evictions are COUNTED (``dropped`` below +
        # ``pa_trace_dropped_total{reason="retired-ring"}``), never silent.
        self._retired: deque = deque(maxlen=RETIRED_RING_BUDGET)  # guarded-by: _lock
        # Completed prompts snapshotted by retain_prompt(): prompt_id -> list
        # of event rows, LRU-bounded at PROMPT_RETENTION prompts so a fleet
        # trace collector can still stitch a finished prompt after the live
        # rings wrapped. guarded-by: _lock
        self._retained: OrderedDict[str, list] = OrderedDict()
        # Eviction accounting per reason — the local mirror of the
        # pa_trace_dropped_total counter (readable without a metrics scrape).
        self.dropped: dict[str, int] = {}  # guarded-by: _lock
        self._epoch_us = now_us()
        # Wall-clock anchor taken at the SAME moment as the monotonic epoch:
        # the cross-host stitcher aligns each process's trace-event clock
        # (perf_counter-based, per-process origin) onto a shared timeline via
        # these anchors. NTP-level skew (ms) is the accepted error bar.
        # palint: allow[observability] clock-alignment epoch STAMP
        self._epoch_wall_s = time.time()

    # -- lifecycle ----------------------------------------------------------

    def enable(self, capacity: int | None = None) -> None:
        """Turn tracing on (clearing any previous trace). ``capacity`` is
        per-call, not sticky: omitting it restores the default — a tiny
        capacity chosen for one capture must not silently truncate the
        next."""
        with self._lock:
            self.capacity = DEFAULT_CAPACITY if capacity is None else capacity
            self._buffers.clear()
            self._retired.clear()
            self._retained.clear()
            self.dropped = {}
            self._epoch_us = now_us()
            # palint: allow[observability] clock-alignment epoch STAMP
            self._epoch_wall_s = time.time()
        self._local = _Local()
        self.enabled = True

    def disable(self) -> None:
        """Stop recording; the captured trace stays exportable until the next
        ``enable()``."""
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._buffers.clear()
            self._retired.clear()
            self._retained.clear()
            self.dropped = {}

    # -- recording ----------------------------------------------------------

    def _events(self, local) -> deque:
        ev = local.events
        if ev is None:
            ev = local.events = deque(maxlen=self.capacity)
            t = threading.current_thread()
            evicted = 0
            with self._lock:
                prev = self._buffers.get(threading.get_ident())
                if prev is not None and prev[1]:
                    # Recycled ident: retire the dead thread's spans rather
                    # than dropping them (short-lived HTTP handler threads
                    # record real spans — fleet dispatch hops among them).
                    if len(self._retired) == self._retired.maxlen:
                        evicted = len(self._retired[0][1])
                        self.dropped["retired-ring"] = (
                            self.dropped.get("retired-ring", 0) + evicted
                        )
                    self._retired.append(prev)
                self._buffers[threading.get_ident()] = (t.name, ev)
            if evicted:
                # Counter emitted OUTSIDE the tracer lock (metrics registry
                # has its own lock; keep the order acyclic).
                self._count_dropped("retired-ring", evicted)
        return ev

    @staticmethod
    def _count_dropped(reason: str, n: int) -> None:
        # Same lazy-import/never-raise contract as _feed_metrics.
        try:
            from .metrics import registry

            registry.counter(
                "pa_trace_dropped_total", float(n),
                labels={"reason": reason},
                help="spans evicted from tracer retention tiers "
                     "(retired-thread ring, completed-prompt retention) — "
                     "nonzero means the stitched-timeline view is incomplete",
            )
        except Exception:
            pass

    def _emit(self, local, name, ts, dur, cat, tid, attrs, span_id) -> None:
        self._events(local).append((name, ts, dur, cat, tid, attrs, span_id))
        self._feed_metrics(name, cat, dur)

    @staticmethod
    def _feed_metrics(name, cat, dur_us) -> None:
        # Lazy import: tracing must stay importable without jax (metrics.py
        # imports jax); a metrics hiccup must never break the traced path.
        try:
            from .metrics import registry

            registry.histogram(
                "pa_trace_span_seconds", dur_us / 1e6,
                labels={"name": name, "cat": cat},
                help="span durations from utils/tracing.py (trace/metrics "
                     "consistency: same measurements, two views)",
            )
        except Exception:
            pass

    def span(self, name: str, cat: str = "host",
             prompt_id: str | None = None, **attrs):
        """Open a nested span on the calling thread (context manager). When
        tracing is disabled this is the single flag check returning the
        shared null singleton."""
        if not self.enabled:
            return _NULL
        local = self._local
        if prompt_id is None:
            prompt_id = self._current_prompt_id(local)
        if prompt_id is not None:
            attrs["prompt_id"] = prompt_id
        if local.ctx is not None:
            attrs.setdefault("trace_id", local.ctx["trace_id"])
        return _OpenSpan(self, local, name, cat, attrs)

    def record(self, name: str, ts: float, dur: float, cat: str = "host",
               tid: int | None = None, prompt_id: str | None = None,
               **attrs) -> None:
        """Record an already-measured span (explicit interval). ``tid``
        attributes the span to another thread's timeline (the serving
        dispatcher recording on behalf of a blocked submitter); the write
        still goes to the *calling* thread's lock-free buffer."""
        if not self.enabled:
            return
        local = self._local
        if prompt_id is None:
            prompt_id = self._current_prompt_id(local)
        if prompt_id is not None:
            attrs["prompt_id"] = prompt_id
        if local.ctx is not None:
            attrs.setdefault("trace_id", local.ctx["trace_id"])
        self._emit(
            local, name, ts, max(0.0, dur), cat,
            tid if tid is not None else threading.get_ident(),
            attrs, next(_span_ids),
        )

    # -- context ------------------------------------------------------------

    def _current_prompt_id(self, local=None) -> Optional[str]:
        local = local if local is not None else self._local
        for s in reversed(local.stack):
            pid = s.attrs.get("prompt_id")
            if pid is not None:
                return pid
        # No span context on this thread: fall back to the per-thread
        # progress scope (the per-prompt correlation the server installs).
        try:
            from .progress import current_scope

            scope = current_scope()
            return getattr(scope, "prompt_id", None)
        except Exception:
            return None

    def current_prompt_id(self) -> Optional[str]:
        """The prompt the calling thread is working for right now, or None."""
        return self._current_prompt_id()

    def current_span_id(self) -> Optional[int]:
        stack = self._local.stack
        return stack[-1].span_id if stack else None

    def current_trace_id(self) -> Optional[str]:
        """The distributed trace_id active on the calling thread (from
        :meth:`trace_context`, or inherited off the span stack), or None.
        The serving scheduler captures this at admission — lane-wait/step
        spans recorded later from the dispatcher thread carry the
        SUBMITTER's trace identity, same rule as the captured tid."""
        ctx = self._local.ctx
        if ctx is not None:
            return ctx["trace_id"]
        for s in reversed(self._local.stack):
            tid = s.attrs.get("trace_id")
            if tid:
                return tid
        return None

    @contextlib.contextmanager
    def trace_context(self, traceparent):
        """Activate a distributed-trace context (a traceparent header string
        or an already-parsed dict) on the calling thread: every span/record
        opened inside is stamped with the context's ``trace_id`` attr, so a
        backend's local spans join the router's cross-host trace. Malformed
        or absent context degrades to an untraced (but still locally
        recorded) scope — never an error on the serving path."""
        ctx = (parse_traceparent(traceparent)
               if not isinstance(traceparent, dict) else traceparent)
        if not self.enabled or not ctx:
            yield None
            return
        local = self._local
        prev = local.ctx
        local.ctx = ctx
        try:
            yield ctx
        finally:
            local.ctx = prev

    # -- completed-prompt retention -----------------------------------------

    def retain_prompt(self, prompt_id: str | None) -> int:
        """Snapshot every event stamped with ``prompt_id`` into the bounded
        completed-prompt retention ring, so the fleet trace collector can
        stitch a finished prompt's timeline even after its recording
        threads' ring buffers have wrapped (high-throughput hosts wrap in
        seconds). LRU-bounded at :data:`PROMPT_RETENTION` prompts; evictions
        are counted (reason ``"prompt-retention"``). Returns the number of
        rows retained."""
        if not self.enabled or not prompt_id:
            return 0
        evicted = 0
        with self._lock:
            rows = []
            for _tid, (_name, ev) in self._buffers.items():
                rows.extend(r for r in ev if r[5].get("prompt_id") == prompt_id)
            for _name, ev in self._retired:
                rows.extend(r for r in ev if r[5].get("prompt_id") == prompt_id)
            if not rows:
                return 0
            self._retained[prompt_id] = rows
            self._retained.move_to_end(prompt_id)
            while len(self._retained) > PROMPT_RETENTION:
                _pid, old = self._retained.popitem(last=False)
                evicted += len(old)
            if evicted:
                self.dropped["prompt-retention"] = (
                    self.dropped.get("prompt-retention", 0) + evicted
                )
        if evicted:
            self._count_dropped("prompt-retention", evicted)
        return len(rows)

    # -- export -------------------------------------------------------------

    def export(self, prompt_id: str | None = None) -> dict:
        """Chrome/Perfetto trace-event JSON (the ``chrome://tracing`` /
        ui.perfetto.dev format): complete ``X`` events with ``ts``/``dur`` in
        microseconds, plus thread-name metadata. ``prompt_id`` filters to one
        prompt's timeline (spans stamped with that prompt_id)."""
        pid = os.getpid()
        with self._lock:
            snap = [(tid, name, list(ev))
                    for tid, (name, ev) in self._buffers.items()]
            # Retired buffers (dead threads whose ident was recycled): their
            # rows carry their own tids, so they render identically.
            snap.extend(
                (0, name, list(ev)) for name, ev in self._retired
            )
            # Completed-prompt retention: rows may duplicate live-buffer rows
            # (retention snapshots, it does not move) — deduped by span_id
            # below, since span ids are process-unique.
            if prompt_id is not None:
                retained = list(self._retained.get(prompt_id, ()))
            else:
                retained = [r for rows in self._retained.values()
                            for r in rows]
            epoch_wall = self._epoch_wall_s
        snap.append((0, "retained", retained))
        events: list[dict] = []
        tids_seen: set[int] = set()
        span_ids_seen: set[int] = set()
        for _rec_tid, _tname, recs in snap:
            for name, ts, dur, cat, tid, attrs, span_id in recs:
                if prompt_id is not None and attrs.get("prompt_id") != prompt_id:
                    continue
                if span_id in span_ids_seen:
                    continue
                span_ids_seen.add(span_id)
                args = dict(attrs)
                args["span_id"] = span_id
                events.append({
                    "ph": "X", "name": name, "cat": cat,
                    "ts": round(ts - self._epoch_us, 3),
                    "dur": round(dur, 3),
                    "pid": pid, "tid": tid, "args": args,
                })
                tids_seen.add(tid)
        events.sort(key=lambda e: (e["tid"], e["ts"], -e["dur"]))
        thread_names = {tid: tname for tid, tname, _ in snap}
        meta = [{
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": thread_names.get(tid, f"thread-{tid}")},
        } for tid in sorted(tids_seen)]
        meta.insert(0, {
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": "parallel_anything_tpu"},
        })
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            # Wall-clock anchor of ts==0 (taken with the monotonic epoch):
            # the cross-host stitcher's clock-domain alignment key.
            "epoch_wall_s": epoch_wall,
        }


# The process-wide tracer every instrumentation site records into and the
# server's GET /trace renders. Tests may enable()/disable() it.
tracer = Tracer()


def on() -> bool:
    """The hot-path enabled check — guard attribute computation with this."""
    return tracer.enabled


def enable(capacity: int | None = None) -> None:
    tracer.enable(capacity)


def disable() -> None:
    tracer.disable()


def span(name: str, cat: str = "host", prompt_id: str | None = None, **attrs):
    return tracer.span(name, cat=cat, prompt_id=prompt_id, **attrs)


def record(name: str, ts: float, dur: float, cat: str = "host",
           tid: int | None = None, prompt_id: str | None = None, **attrs):
    tracer.record(name, ts, dur, cat=cat, tid=tid, prompt_id=prompt_id,
                  **attrs)


def export(prompt_id: str | None = None) -> dict:
    return tracer.export(prompt_id)


def current_prompt_id() -> Optional[str]:
    return tracer.current_prompt_id()


def current_span_id() -> Optional[int]:
    return tracer.current_span_id()


def trace_context(traceparent):
    return tracer.trace_context(traceparent)


def current_trace_id() -> Optional[str]:
    return tracer.current_trace_id()


def retain_prompt(prompt_id: str | None) -> int:
    return tracer.retain_prompt(prompt_id)


def epoch_wall_s() -> float:
    """Wall-clock instant of the tracer's ts==0 origin (stitcher anchor)."""
    return tracer._epoch_wall_s


@contextlib.contextmanager
def hardware_trace(log_dir: str = "/tmp/parallelanything-trace"):
    """Bracket a span subtree with ``jax.profiler.trace`` so the XProf device
    timeline lines up with the host spans recorded inside the block: open the
    trace in Perfetto alongside the ``GET /trace`` export and the
    ``hardware-trace`` host span marks the profiled window."""
    import jax

    with span("hardware-trace", cat="profiler", log_dir=log_dir):
        jax.profiler.start_trace(log_dir)
        try:
            yield log_dir
        finally:
            jax.profiler.stop_trace()


# -- trace-derived aggregates ------------------------------------------------
#
# Shared by bench.py (every JSON line), __graft_entry__.dryrun_multichip, and
# scripts/trace_summary.py (which re-implements the same math stdlib-only; a
# tier-1 test pins the two against each other on the same fixture).


def _percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile (scripts/loadgen.py convention)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = max(0, min(len(s) - 1, round(q / 100.0 * (len(s) - 1))))
    return s[k]


def _x_events(events) -> list[dict]:
    if isinstance(events, dict):
        events = events.get("traceEvents", [])
    return [e for e in events if e.get("ph") == "X"]


def stream_overlap_efficiency(events) -> float | None:
    """Fraction of each ``stream-run`` span's wall time occupied by
    ``stream-stage-compute`` spans, averaged over runs; in (0, 1] by
    construction (compute spans are non-overlapping and contained in their
    run). Exposed transfer/backpressure time — the part double-buffering
    exists to hide — is exactly what pushes this below 1; it is the
    overlap-efficiency number the flux_stream live-window measurement needs.
    None when the trace holds no streamed runs."""
    xs = _x_events(events)
    runs = [e for e in xs if e["name"] == "stream-run" and e.get("dur", 0) > 0]
    if not runs:
        return None
    comps = [e for e in xs if e["name"] == "stream-stage-compute"]
    effs = []
    for r in runs:
        r0, r1 = r["ts"], r["ts"] + r["dur"]
        busy = sum(
            c["dur"] for c in comps
            if c["tid"] == r["tid"] and c["ts"] >= r0
            and c["ts"] + c["dur"] <= r1 + 1.0  # float-rounding slack (µs)
        )
        effs.append(min(1.0, busy / r["dur"]))
    return sum(effs) / len(effs)


def lane_wait_p95_s(events) -> float | None:
    """p95 of serving ``lane-wait`` spans (submit → seated), seconds."""
    waits = [e["dur"] / 1e6 for e in _x_events(events)
             if e["name"] == "lane-wait"]
    return _percentile(waits, 95) if waits else None


def host_gap_ms(events) -> float | None:
    """Mean host-side gap between consecutive ``step`` spans on each thread —
    the per-step scheduling overhead the device cannot see. None with fewer
    than two steps anywhere."""
    steps: dict[int, list[dict]] = {}
    for e in _x_events(events):
        if e["name"] == "step":
            steps.setdefault(e["tid"], []).append(e)
    gaps = []
    for evs in steps.values():
        evs.sort(key=lambda e: e["ts"])
        for a, b in zip(evs, evs[1:]):
            gaps.append(max(0.0, b["ts"] - (a["ts"] + a["dur"])) / 1e3)
    return sum(gaps) / len(gaps) if gaps else None


def fleet_hop_p95_ms(events) -> float | None:
    """p95 of the router's ``fleet-hop`` spans (place → backend accepted),
    milliseconds — the fleet tier's own overhead per dispatch, distinct from
    the backend-side prompt time. The hop span and the backend's prompt span
    share ``origin_prompt_id``/``prompt_id``, so one Perfetto export shows
    the prompt's timeline across the hop. None when no fleet routing ran
    inside the traced window (kept out of :func:`trace_aggregates`, whose
    key set is pinned against scripts/trace_summary.py)."""
    hops = [e["dur"] / 1e3 for e in _x_events(events)
            if e["name"] == "fleet-hop"]
    return round(_percentile(hops, 95), 4) if hops else None


def trace_aggregates(events) -> dict:
    """The trace-derived aggregate fields every bench.py JSON line carries."""
    eff = stream_overlap_efficiency(events)
    p95 = lane_wait_p95_s(events)
    gap = host_gap_ms(events)
    return {
        "stream_overlap_efficiency": None if eff is None else round(eff, 4),
        "lane_wait_p95": None if p95 is None else round(p95, 6),
        "host_gap_ms": None if gap is None else round(gap, 4),
    }
