"""Tokenization: prompts → the int32 id arrays models/text_encoders.py consumes.

The reference never tokenizes (conditioning arrives pre-encoded at its forward
boundary, any_device_parallel.py:1287); a standalone framework needs prompt → ids.
This image ships no tokenizer tables and has no egress, so everything here loads
from user-supplied files:

- ``CLIPBPETokenizer`` — a from-scratch implementation of CLIP's byte-BPE scheme
  (bytes→unicode alphabet, end-of-word ``</w>`` marker, lowercasing, merge ranks)
  reading the standard ``vocab.json`` + ``merges.txt`` pair.
- ``load_tokenizer_json`` — wraps the HF ``tokenizers`` runtime (present in this
  image) for ``tokenizer.json`` files (T5 and modern CLIP exports).

Output convention matches the SD ecosystem: fixed ``max_len`` windows, BOS/EOS
framing for CLIP, right-padding with a configurable pad id (CLIP-L pads with EOS,
OpenCLIP-G with 0), plus a 0/1 mask for T5-style encoders.
"""

from __future__ import annotations

import functools
import json
import os

import numpy as np


@functools.cache
def _bytes_to_unicode() -> dict[int, str]:
    """CLIP/GPT-2's reversible byte→printable-unicode table: printable ASCII and
    latin-1 map to themselves, the rest shift into 256+."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _word_pairs(word: tuple[str, ...]) -> set[tuple[str, str]]:
    return set(zip(word[:-1], word[1:]))


class CLIPBPETokenizer:
    """CLIP's byte-BPE with ``</w>`` word suffix, built from vocab.json+merges.txt.

    ``__call__`` returns (ids, mask): ids is (B, max_len) int32 with
    BOS ... EOS padding, mask marks BOS..EOS inclusive.
    """

    def __init__(
        self,
        vocab: dict[str, int],
        merges: list[tuple[str, str]],
        max_len: int = 77,
        bos: str = "<|startoftext|>",
        eos: str = "<|endoftext|>",
        pad_id: int | None = None,
    ):
        self.vocab = vocab
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self.max_len = max_len
        self.bos_id = vocab[bos]
        self.eos_id = vocab[eos]
        self.pad_id = self.eos_id if pad_id is None else pad_id
        self.byte_map = _bytes_to_unicode()
        try:
            import regex
        except ImportError as e:  # pragma: no cover - present in this image
            raise ImportError(
                "CLIPBPETokenizer needs the 'regex' package (unicode categories in "
                "the CLIP split pattern) — pip install "
                "comfyui-parallelanything-tpu[text]"
            ) from e

        # CLIP's pattern: contractions, letter runs, digit runs, other symbols.
        self._pat = regex.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+",
            regex.IGNORECASE,
        )
        self._cache: dict[str, list[int]] = {}

    @classmethod
    def from_files(cls, vocab_path: str, merges_path: str, **kw) -> "CLIPBPETokenizer":
        with open(vocab_path, encoding="utf-8") as f:
            vocab = json.load(f)
        merges: list[tuple[str, str]] = []
        with open(merges_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#version"):
                    continue
                a, b = line.split()
                merges.append((a, b))
        return cls(vocab, merges, **kw)

    def _bpe(self, token: str) -> list[str]:
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        pairs = _word_pairs(word)
        if not pairs:
            return [token + "</w>"]
        while True:
            pair = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if pair not in self.ranks:
                break
            first, second = pair
            out: list[str] = []
            i = 0
            while i < len(word):
                if (
                    i < len(word) - 1
                    and word[i] == first
                    and word[i + 1] == second
                ):
                    out.append(first + second)
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            word = tuple(out)
            if len(word) == 1:
                break
            pairs = _word_pairs(word)
        return list(word)

    def encode(self, text: str) -> list[int]:
        """Text → token ids, unframed/unpadded."""
        ids: list[int] = []
        text = " ".join(text.lower().strip().split())
        for tok in self._pat.findall(text):
            key = tok
            cached = self._cache.get(key)
            if cached is None:
                mapped = "".join(self.byte_map[b] for b in tok.encode("utf-8"))
                try:
                    cached = [self.vocab[piece] for piece in self._bpe(mapped)]
                except KeyError as e:
                    # Silently dropping pieces would condition the model on a
                    # different prompt than the user wrote.
                    raise KeyError(
                        f"BPE piece {e.args[0]!r} (from token {tok!r}) missing from "
                        "the vocab — vocab.json/merges.txt pair mismatch?"
                    ) from e
                self._cache[key] = cached
            ids.extend(cached)
        return ids

    def __call__(self, texts: str | list[str]) -> tuple[np.ndarray, np.ndarray]:
        if isinstance(texts, str):
            texts = [texts]
        ids = np.full((len(texts), self.max_len), self.pad_id, np.int32)
        mask = np.zeros((len(texts), self.max_len), np.int32)
        for r, text in enumerate(texts):
            body = self.encode(text)[: self.max_len - 2]
            row = [self.bos_id, *body, self.eos_id]
            ids[r, : len(row)] = row
            mask[r, : len(row)] = 1
        return ids, mask


class JsonTokenizer:
    """tokenizer.json (HF fast format) wrapper — covers T5/modern-CLIP exports.
    Pads/truncates to ``max_len``; appends ``eos_id`` when set (T5 convention)."""

    def __init__(self, tok, max_len: int, eos_id: int | None = None, pad_id: int = 0):
        self._tok = tok
        self.max_len = max_len
        self.eos_id = eos_id
        self.pad_id = pad_id

    def __call__(self, texts: str | list[str]) -> tuple[np.ndarray, np.ndarray]:
        if isinstance(texts, str):
            texts = [texts]
        ids = np.full((len(texts), self.max_len), self.pad_id, np.int32)
        mask = np.zeros((len(texts), self.max_len), np.int32)
        for r, text in enumerate(texts):
            row = self._tok.encode(text).ids
            if self.eos_id is not None:
                # HF T5 tokenizer.json files append </s> via their post-processor
                # already — strip it first so EOS appears exactly once.
                while row and row[-1] == self.eos_id:
                    row = row[:-1]
                row = row[: self.max_len - 1] + [self.eos_id]
            else:
                row = row[: self.max_len]
            ids[r, : len(row)] = row
            mask[r, : len(row)] = 1
        return ids, mask


def load_tokenizer_json(
    path: str | os.PathLike, max_len: int = 512, eos_id: int | None = None,
    pad_id: int = 0,
) -> JsonTokenizer:
    try:
        from tokenizers import Tokenizer
    except ImportError as e:  # pragma: no cover - present in this image
        raise ImportError(
            "tokenizer.json loading needs the 'tokenizers' package; "
            "use CLIPBPETokenizer.from_files for vocab.json+merges.txt"
        ) from e
    return JsonTokenizer(
        Tokenizer.from_file(os.fspath(path)), max_len, eos_id, pad_id
    )
