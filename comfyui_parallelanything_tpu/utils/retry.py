"""Shared bounded-retry / backoff policy (stdlib-only, standalone-loadable).

Before round 14 every cross-host interaction hand-rolled its own retry shape:
the fleet scoreboard doubled a poll interval inline, ``HeartbeatClient``
re-beat a dead router at a fixed cadence (a hot loop when the interval is
short), the router's monitor re-dispatched queued prompts on every sweep,
and ``scripts/loadgen.py`` polled ``/history`` at a flat 50 ms. One policy
object replaces all of them:

- **bounded exponential backoff** — ``base_s * multiplier**attempt`` capped
  at ``cap_s`` (never unbounded: a dead peer costs one socket timeout per
  window, not per scheduling decision);
- **deterministic jitter** — the jitter fraction comes from
  ``md5(key, attempt)``, not ``random``: two runs of one seeded chaos
  schedule retry at identical instants (the reproducibility contract
  scripts/chaos.py gates on), while distinct keys still de-synchronize so a
  fleet of backends never thunders the router in lockstep;
- **deadline cap** — ``give up at`` an absolute budget regardless of the
  attempt count, so a retry loop can never outlive the request it serves.

Module level is stdlib-only and free of package-relative imports by the
``utils/roofline.py`` contract: jax-free scripts (loadgen, chaos) load this
file standalone by path over a wedged TPU tunnel.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time


def deterministic_jitter(key: str, attempt: int) -> float:
    """A stable value in [0, 1) from (key, attempt) — the jitter source.
    md5, not ``hash()``: process-salted hashes would make two runs of one
    seeded schedule back off at different instants."""
    digest = hashlib.md5(f"{key}:{attempt}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff + deterministic jitter + deadline cap.

    ``backoff_s(attempt, key)`` is the pure schedule (attempt 0 = the wait
    after the FIRST failure); ``attempts()`` iterates it with sleeping;
    ``call()`` wraps a callable. ``jitter`` is the fraction of each window
    that jitters DOWNWARD (full windows stay the worst case, so caps and
    deadline math read literally)."""

    max_attempts: int = 4
    base_s: float = 0.1
    cap_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: float | None = None

    def backoff_s(self, attempt: int, key: str = "") -> float:
        # Exponent clamped: float ** raises OverflowError past ~2**1024, and
        # callers legitimately pass unbounded consecutive-failure counts (a
        # heartbeat client surviving hours of router downtime must not have
        # its loop die computing its own sleep). 64 doublings exceed any cap.
        raw = min(self.cap_s,
                  self.base_s * self.multiplier ** min(max(0, attempt), 64))
        if not self.jitter:
            return raw
        return raw * (1.0 - self.jitter * deterministic_jitter(key, attempt))

    def attempts(self, key: str = "", sleep=time.sleep, now=time.monotonic):
        """Yield attempt indices 0..max_attempts-1, sleeping the backoff
        between attempts and stopping early at the deadline. The caller
        ``break``s on success; exhausting the generator means giving up."""
        t0 = now()
        for attempt in range(self.max_attempts):
            yield attempt
            if attempt + 1 >= self.max_attempts:
                return
            wait = self.backoff_s(attempt, key)
            if self.deadline_s is not None:
                remaining = self.deadline_s - (now() - t0)
                if remaining <= 0:
                    return
                wait = min(wait, remaining)
            sleep(wait)

    def call(self, fn, *, retry_on=(OSError,), key: str = "",
             sleep=time.sleep, now=time.monotonic):
        """Run ``fn()`` under the policy; returns its first successful value
        or re-raises the LAST failure once the budget (attempts or deadline)
        is spent. Only ``retry_on`` exception types are retried — anything
        else propagates immediately (a 400 is not a transient)."""
        last: BaseException | None = None
        for _attempt in self.attempts(key=key, sleep=sleep, now=now):
            try:
                return fn()
            except retry_on as e:  # noqa: PERF203 — the retry loop is the point
                last = e
        if last is None:  # max_attempts <= 0: nothing ever ran
            raise ValueError(f"retry budget empty ({self.max_attempts} attempts)")
        raise last


# Shared instances: ONE place the fleet's retry shapes are defined, so an
# operator reasons about one table instead of five hand-rolled loops.
# (Callers needing different bounds derive with dataclasses.replace.)
HEARTBEAT = RetryPolicy(max_attempts=1_000_000, base_s=0.5, cap_s=30.0)
POLL = RetryPolicy(max_attempts=1_000_000, base_s=0.05, cap_s=0.5, jitter=0.25)
DISPATCH = RetryPolicy(max_attempts=4, base_s=0.1, cap_s=5.0)
