"""Bounded in-process metric history: the continuous-telemetry ring.

Every observability surface before round 22 is point-in-time (``GET
/metrics``, ``/health``, ``/fleet/slo``) or per-run (the perf ledger):
nobody can answer "what was this host doing over the last ten minutes"
— the reference can't either, its ``[ParallelAnything]`` prints scroll
away and ``any_device_parallel.py`` retains nothing. This module keeps a
byte-bounded ring of periodic snapshots of every ``pa_*`` family
(counters/gauges as values, histograms as their raw cumulative bucket
accumulators) so trajectories — step-time creep, queue growth,
cache-hit collapse — are readable while they happen:

- :class:`HistoryRing` — per-family point series with monotone
  timestamps, bounded in bytes (``PA_HISTORY_BYTES``; ``0`` disables the
  whole layer, a tier-1-tested no-op). On byte pressure the FATTEST
  family downsamples (every second interior point dropped, first/last
  kept) so the window SPAN survives at lower resolution instead of the
  oldest history falling off a cliff.
- **counter-reset-aware readers**: :meth:`HistoryRing.delta` /
  :meth:`HistoryRing.rate` sum only non-negative inter-point deltas (a
  restarted process's counter restarting from 0 contributes its new
  value, not a huge negative step); :meth:`HistoryRing.quantile_at`
  reads a quantile off histogram BUCKET DELTAS across the window — the
  windowed twin of ``MetricsRegistry.quantile``'s lifetime view.
- **phase marks**: :meth:`HistoryRing.mark_phase` stamps declared load
  phases (scripts/loadgen.py open-loop rung boundaries, chaos phases)
  into the window so the anomaly sentinel (utils/anomaly.py) can
  attribute a rate ramp to a declared phase instead of paging on it.
- ``pa-history/v1`` export (:meth:`HistoryRing.window`) — the
  ``GET /metrics/history?window=&family=`` body server.py serves and the
  router's ``GET /fleet/history`` merges host-labeled.
- :class:`HistorySampler` — the seeded-cadence daemon thread
  (``PA_HISTORY_INTERVAL_S``): its first tick is offset by a stable hash
  of the host id so a fleet's samplers de-synchronize, and every tick
  runs OFF the hot step path (the MemoryMonitor discipline — palint's
  host-sync pass never sees it).

Flag discipline: ``PA_HISTORY_BYTES=0`` disables snapshots, readers and
the sampler entirely (the tracer/sentinel null-path rule — the disabled
path is one env read). Import discipline: module level is stdlib-only
and free of package-relative imports (the utils/roofline.py standalone
contract) so scripts/console.py and tests load this file over a wedged
TPU tunnel; the metrics read is a lazy best-effort import.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

HISTORY_SCHEMA = "pa-history/v1"

# Default ring budget: ~2 MiB holds hours of 5 s-cadence snapshots for a
# serving host's typical family count; small enough to be invisible next
# to one compiled program.
DEFAULT_BYTES = 2 << 20
DEFAULT_INTERVAL_S = 5.0
MAX_PHASES = 256


def max_bytes(env=os.environ) -> int:
    """The ``PA_HISTORY_BYTES`` ring budget (0 disables the layer)."""
    raw = env.get("PA_HISTORY_BYTES")
    if raw in (None, ""):
        return DEFAULT_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_BYTES


def enabled(env=os.environ) -> bool:
    return max_bytes(env) > 0


def interval_s(env=os.environ) -> float:
    raw = env.get("PA_HISTORY_INTERVAL_S")
    try:
        return max(0.1, float(raw)) if raw else DEFAULT_INTERVAL_S
    except ValueError:
        return DEFAULT_INTERVAL_S


def cadence_offset_s(key: str, interval: float) -> float:
    """Deterministic per-host phase offset in ``[0, interval)`` — the
    seeded cadence: a fleet's samplers (and two runs of one host id)
    tick at stable, de-synchronized instants."""
    u = int.from_bytes(hashlib.md5(str(key).encode()).digest()[:8], "big")
    return (u % 10_000) / 10_000.0 * float(interval)


def _point_bytes(values: dict) -> int:
    """Deterministic byte estimate for one sample point: timestamp + per
    series key + payload floats (8 B each, JSON-ish overhead folded into
    the constants). An estimate, not an accounting — the bound only needs
    to hold within a small constant factor, identically on every host."""
    n = 24
    for lbl, v in values.items():
        n += len(lbl) + 16
        n += 8 * (len(v) if isinstance(v, list) else 1)
    return n


def _match(lbl: str, labels: dict | None) -> bool:
    if not labels:
        return True
    return all(f'{k}="{v}"' in lbl for k, v in labels.items())


class HistoryRing:
    """Byte-bounded per-family time series over the metrics registry.

    Thread-safe: the sampler thread snapshots, HTTP handler threads read
    windows, loadgen stamps phases over HTTP. Timestamps are wall-clock
    (the one clock a fleet's windows can align on) and forced strictly
    monotone per ring — a stepped NTP clock never produces an
    out-of-order window."""

    def __init__(self, budget: int | None = None):
        self._budget = budget  # None → read PA_HISTORY_BYTES per snapshot
        self._lock = threading.Lock()
        # name → {"type", "bounds", "points": [(ts, {label: v})], "bytes"}
        self._families: dict[str, dict] = {}  # guarded-by: _lock
        self._phases: list[dict] = []         # guarded-by: _lock
        self._bytes = 0                       # guarded-by: _lock
        self._snapshots = 0                   # guarded-by: _lock
        self._downsampled = 0                 # guarded-by: _lock
        self._last_ts = 0.0                   # guarded-by: _lock
        self._first_ts = 0.0                  # guarded-by: _lock

    def budget(self) -> int:
        return self._budget if self._budget is not None else max_bytes()

    # -- write side ----------------------------------------------------------

    def record(self, sample: dict, ts: float | None = None) -> int:
        """Append one snapshot (``MetricsRegistry.dump()`` shape). Returns
        the families recorded (0 when the layer is disabled)."""
        budget = self.budget()
        if budget <= 0 or not sample:
            return 0
        if ts is None:
            # palint: allow[observability] history STAMP — the wall clock is
            # the one clock fleet windows align on (monotonic is per-process)
            ts = time.time()
        n = 0
        with self._lock:
            # Strictly monotone per ring, even under a stepped wall clock.
            ts = max(float(ts), self._last_ts + 1e-6)
            self._last_ts = ts
            if not self._first_ts:
                self._first_ts = ts
            self._snapshots += 1
            for name, m in sample.items():
                values = m.get("values") or {}
                if not values:
                    continue
                fam = self._families.get(name)
                if fam is None:
                    fam = self._families[name] = {
                        "type": m.get("type"),
                        "bounds": m.get("bounds"),
                        "points": [],
                        "bytes": 0,
                    }
                pb = _point_bytes(values)
                fam["points"].append((ts, values))
                fam["bytes"] += pb
                self._bytes += pb
                n += 1
            self._downsample_locked(budget)
        return n

    def snapshot(self, ts: float | None = None) -> int:
        """Sample the process-wide metrics registry into the ring and
        publish the ring's own occupancy gauges. Best-effort: absent
        metrics (standalone load) is a clean no-op."""
        if self.budget() <= 0:
            return 0
        try:
            from .metrics import registry as _metrics

            sample = _metrics.dump(prefix="pa_")
        except Exception:
            return 0
        n = self.record(sample, ts=ts)
        st = self.stats()
        try:
            _metrics.gauge("pa_history_bytes", st["bytes"],
                           help="metric-history ring occupancy (bytes)")
            _metrics.gauge("pa_history_points", st["points"],
                           help="metric-history ring sample points")
            _metrics.gauge("pa_history_span_seconds", st["span_s"],
                           help="metric-history window span (seconds)")
        except Exception:
            pass
        return n

    def _downsample_locked(self, budget: int) -> None:  # palint: holds _lock
        """While over budget, thin the fattest family: drop every second
        INTERIOR point (first and last kept) so the window span survives
        at halved resolution — per-family, so one chatty family never
        evicts a quiet family's history."""
        guard = 64
        while self._bytes > budget and guard > 0:
            guard -= 1
            fat = None
            for name, fam in self._families.items():
                if len(fam["points"]) > 2 and (
                        fat is None
                        or fam["bytes"] > self._families[fat]["bytes"]):
                    fat = name
            if fat is None:
                # Nothing left to thin: drop whole 2-point families oldest-
                # first rather than busy-loop (a budget smaller than two
                # snapshots of every family).
                for name, fam in list(self._families.items()):
                    if self._bytes <= budget:
                        break
                    self._bytes -= fam["bytes"]
                    del self._families[name]
                return
            fam = self._families[fat]
            pts = fam["points"]
            kept = [pts[0]] + pts[1:-1][1::2] + [pts[-1]]
            freed = sum(_point_bytes(v) for _, v in pts) - sum(
                _point_bytes(v) for _, v in kept)
            fam["points"] = kept
            fam["bytes"] -= freed
            self._bytes -= freed
            self._downsampled += 1

    def mark_phase(self, label: str, state: str = "begin",
                   ts: float | None = None, detail: str | None = None) -> None:
        """Stamp a declared load-phase boundary (state ``begin``/``end``)
        into the window — loadgen's open-loop rungs and chaos phases
        declare themselves here so the sentinel attributes, not pages."""
        if self.budget() <= 0:
            return
        if ts is None:
            # palint: allow[observability] phase STAMP, same clock as points
            ts = time.time()
        mark = {"ts": float(ts), "label": str(label), "state": str(state)}
        if detail:
            mark["detail"] = str(detail)
        with self._lock:
            self._phases.append(mark)
            del self._phases[:-MAX_PHASES]

    def phase_at(self, ts: float | None = None) -> str | None:
        """The innermost declared phase open at ``ts`` (default: now), or
        None — replayed from the begin/end marks."""
        with self._lock:
            marks = list(self._phases)
            if ts is None:
                ts = self._last_ts or float("inf")
        open_phases: list[str] = []
        for m in marks:
            if m["ts"] > ts:
                break
            if m["state"] == "begin":
                open_phases.append(m["label"])
            elif m["label"] in open_phases:
                open_phases.remove(m["label"])
        return open_phases[-1] if open_phases else None

    def reset(self) -> None:
        with self._lock:
            self._families.clear()
            self._phases.clear()
            self._bytes = 0
            self._snapshots = 0
            self._downsampled = 0
            self._last_ts = 0.0
            self._first_ts = 0.0

    # -- read side -----------------------------------------------------------

    def _points(self, name: str, window_s: float | None,
                labels: dict | None, fill_empty: bool = False):
        """Matching series values per point inside the window (a list of
        payloads per point — one entry per matching label set).
        ``fill_empty`` keeps points where the family was sampled but no
        label matched, as empty lists — the counter-delta read needs them
        so a label set BORN mid-window contributes its first value (born
        at 0, not born invisible)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return [], None, None
            pts = list(fam["points"])
            ftype, bounds = fam["type"], fam["bounds"]
        if window_s is not None and pts:
            cutoff = pts[-1][0] - float(window_s)
            pts = [p for p in pts if p[0] >= cutoff]
        out = []
        for ts, values in pts:
            vs = [v for lbl, v in values.items() if _match(lbl, labels)]
            if vs or fill_empty:
                out.append((ts, vs))
        return out, ftype, bounds

    def latest(self, name: str, labels: dict | None = None,
               agg: str = "sum") -> float | None:
        """Last sampled scalar value, aggregated (``sum``/``max``/``mean``)
        across matching label sets — the gauge read."""
        pts, _, _ = self._points(name, None, labels)
        if not pts:
            return None
        vs = [float(v) for v in pts[-1][1] if not isinstance(v, list)]
        if not vs:
            return None
        if agg == "max":
            return max(vs)
        if agg == "mean":
            return sum(vs) / len(vs)
        return sum(vs)

    def label_values(self, name: str, key: str) -> list[str]:
        """Distinct values of one label key across the family's latest
        point — how the sentinel enumerates fault sites / hosts without
        knowing them a priori."""
        pts, _, _ = self._points(name, None, None)
        if not pts:
            return []
        out: set[str] = set()
        with self._lock:
            fam = self._families.get(name)
            if fam is None or not fam["points"]:
                return []
            values = fam["points"][-1][1]
        needle = f'{key}="'
        for lbl in values:
            i = lbl.find(needle)
            if i >= 0:
                j = lbl.index('"', i + len(needle))
                out.add(lbl[i + len(needle):j])
        return sorted(out)

    def delta(self, name: str, window_s: float | None = None,
              labels: dict | None = None) -> float | None:
        """Counter increase over the window, reset-aware: only non-negative
        inter-point deltas count, and a reset (value dropping) contributes
        the post-reset value — a restarted backend never reads as a giant
        negative rate."""
        pts, _, _ = self._points(name, window_s, labels, fill_empty=True)
        if not pts:
            return None
        with self._lock:
            first_ring = self._first_ts
            fam = self._families.get(name)
            first_fam = (fam["points"][0][0]
                         if fam and fam["points"] else None)
        totals = [sum(float(v) for v in vs if not isinstance(v, list))
                  for _, vs in pts]
        d = 0.0
        # Birth credit: a family first sampled AFTER the ring started (and
        # whose birth point is inside this window) counted from 0 — its
        # first value IS growth, not pre-existing history.
        if (first_ring and first_fam is not None
                and first_fam > first_ring + 1e-9
                and pts[0][0] <= first_fam + 1e-9):
            d += totals[0]
        elif len(pts) < 2:
            return None
        for prev, cur in zip(totals, totals[1:]):
            step = cur - prev
            d += step if step >= 0 else cur
        return d

    def rate(self, name: str, window_s: float | None = None,
             labels: dict | None = None) -> float | None:
        """Reset-aware counter rate (per second) over the window."""
        pts, _, _ = self._points(name, window_s, labels)
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        d = self.delta(name, window_s, labels)
        return None if d is None else d / span

    def quantile_at(self, name: str, q: float,
                    window_s: float | None = None,
                    labels: dict | None = None) -> float | None:
        """Histogram quantile (0-100) over the WINDOW's observations:
        bucket-count deltas between the window's first and last points
        (reset-aware — a shrunken cumulative count reads as post-reset),
        interpolated exactly like ``MetricsRegistry.quantile``."""
        pts, ftype, bounds = self._points(name, window_s, labels)
        if ftype != "histogram" or not bounds or len(pts) < 2:
            return None
        nb = len(bounds)

        def bucket_sum(vs):
            counts = [0.0] * (nb + 1)
            for v in vs:
                if isinstance(v, list) and len(v) >= nb + 3:
                    for i in range(nb + 1):
                        counts[i] += v[i]
            return counts

        first, last = bucket_sum(pts[0][1]), bucket_sum(pts[-1][1])
        counts = []
        for f, l in zip(first, last):
            d = l - f
            counts.append(d if d >= 0 else l)
        total = sum(counts)
        if total <= 0:
            return None
        target = q / 100.0 * total
        cum, lo = 0.0, 0.0
        for i, c in enumerate(counts):
            hi = bounds[i] if i < nb else bounds[-1]
            if cum + c >= target and c > 0:
                frac = (target - cum) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            cum += c
            lo = hi
        return lo

    # -- surfaces ------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            points = sum(len(f["points"]) for f in self._families.values())
            span = 0.0
            for f in self._families.values():
                if len(f["points"]) >= 2:
                    span = max(span,
                               f["points"][-1][0] - f["points"][0][0])
            return {
                "bytes": self._bytes,
                "max_bytes": self.budget(),
                "families": len(self._families),
                "points": points,
                "span_s": round(span, 3),
                "snapshots": self._snapshots,
                "downsampled": self._downsampled,
            }

    def window(self, window_s: float | None = None,
               families=None) -> dict:
        """The ``pa-history/v1`` document (``GET /metrics/history``).
        ``families`` filters by name prefix (string or iterable)."""
        if isinstance(families, str):
            families = [f for f in families.split(",") if f]
        prefixes = list(families) if families else None
        with self._lock:
            fams = {}
            for name, fam in self._families.items():
                if prefixes is not None and not any(
                        name.startswith(p) for p in prefixes):
                    continue
                pts = fam["points"]
                if window_s is not None and pts:
                    cutoff = pts[-1][0] - float(window_s)
                    pts = [p for p in pts if p[0] >= cutoff]
                fams[name] = {
                    "type": fam["type"],
                    "bounds": fam["bounds"],
                    "points": [
                        {"ts": round(ts, 6), "values": values}
                        for ts, values in pts
                    ],
                }
            phases = list(self._phases)
        if window_s is not None and phases:
            last = self._last_ts
            phases = [p for p in phases if p["ts"] >= last - float(window_s)]
        return {
            "schema": HISTORY_SCHEMA,
            "enabled": self.budget() > 0,
            "interval_hint_s": interval_s(),
            "families": fams,
            "phases": phases,
            # Nested, NOT merged: stats() reuses the "families"/"points"
            # keys as counts and would clobber the series dict above.
            "stats": self.stats(),
        }


# The process-wide ring server.py samples into and GET /metrics/history
# serves. Tests may reset() it.
ring = HistoryRing()


class HistorySampler:
    """Seeded-cadence snapshot thread (the MemoryMonitor shape): every
    ``PA_HISTORY_INTERVAL_S`` it samples the registry into :data:`ring`
    and feeds the anomaly sentinel — a daemon thread entirely off the
    hot step path. The first tick is phase-offset by a stable hash of
    the host id so fleet samplers de-synchronize deterministically."""

    def __init__(self, host: str = "", interval: float | None = None,
                 target: HistoryRing | None = None):
        self.host = str(host)
        self.interval = float(interval) if interval else interval_s()
        self.ring = target or ring
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="pa-history-sampler", daemon=True
        )

    def start(self) -> "HistorySampler":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def tick(self) -> int:
        """One snapshot + sentinel pass (the loop body, callable directly
        by tests and chaos phases for deterministic cadence)."""
        n = self.ring.snapshot()
        try:
            from . import anomaly

            anomaly.observe(self.ring, host=self.host)
        except Exception:
            pass
        return n

    def _loop(self) -> None:
        if self._stop.wait(cadence_offset_s(self.host, self.interval)):
            return
        while True:
            try:
                self.tick()
            except Exception:
                pass
            if self._stop.wait(self.interval):
                return
