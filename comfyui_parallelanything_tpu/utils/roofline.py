"""Roofline attribution: calibrated predicted-vs-actual accounting for every
compiled program.

The banked sd15_16 MFU of 0.086 against the 1.11 s analytic roofline
(BASELINE.md "MFU budget") says 91% of the step goes somewhere we cannot yet
name. Rounds 8-11 collected every raw input — per-program HLO
``cost_analysis`` FLOPs/bytes (utils/telemetry.py), span timings
(utils/tracing.py), step/HBM history (ledger/perf_ledger.jsonl), mesh
topology (parallel/mesh.py) — and this module is the join:

- **Analytic cost model** (:func:`predict_time_s`): compute time from FLOPs
  vs platform peak, memory time from bytes vs HBM bandwidth, collective time
  from an ICI/DCN link model over the mesh width, combined as
  ``max(compute, memory) + comms`` — the same roofline scripts/mfu_budget.py
  projects per op class, here per *program* and per *step*.
- **Per-program predictions** (:data:`programs`): ``instrument_jit``
  (utils/telemetry.py) feeds every named program's first-compile cost
  analysis through :func:`observe_program`, so the registry carries
  ``predicted_s`` alongside the compile registry's FLOPs/bytes for the loop
  programs (``loop:k:euler``), stage programs (``stream-stage[0:3)``,
  ``pipeline-stage[..)``), ``parallel-apply`` and ``model-apply:*`` — the
  cost table the ROADMAP's auto-parallel planner scores candidate plans
  with. Surfaced as ``pa_roofline_predicted_s`` gauges, the ``roofline``
  section of ``GET /health``, and per-program rows in the perf ledger.
- **Measured-side attribution** (:func:`attribution_from_trace`): each
  traced window decomposes into compute / exposed-transfer / host-gap /
  comms buckets from the existing span vocabulary — streaming's
  ``stream-prefetch-wait`` discipline generalized. Exactly one bucket per
  window is the residual (whatever the host-side spans cannot directly
  measure): streamed windows measure compute (``stream-stage-compute`` is
  device-accurate — the backpressure blocks) and leave host-gap residual;
  async dispatch windows (bench's chained loop — ``step`` spans are
  dispatch windows, nothing blocks per step) measure the host gaps
  (inter-step gaps net of comms) and leave compute residual — the opaque
  readback the host waits in IS the device working. Buckets are
  non-negative and sum to the wall by construction.
- **Calibration store** (``ledger/roofline_calib.json``): per
  (program, platform, shape-bucket) scale factors fitted from ledger
  history — ``scale = median(actual / predicted_raw)`` —
  so predictions self-correct as evidence banks
  (``scripts/roofline_report.py --bank``), the same stdlib-only
  bank-and-gate handshake as scripts/numerics_audit.py.

Flag discipline: ``PA_ROOFLINE=0`` disables observation and gauge
publication entirely (the tracer/sentinel pattern — a tier-1-tested no-op).
Import discipline: module level is stdlib-only and free of package-relative
imports, so ``scripts/roofline_report.py`` loads this file standalone (no
jax, runs over a wedged tunnel); jax/metrics/tracing load lazily inside
functions and every side channel is best-effort.

Reference parity note: the reference places work by a *static* VRAM
heuristic — ``get_free_vram`` scoring plus a fixed 0.7/0.3 memory blend
(any_device_parallel.py:724-766, 1317-1322). This layer replaces that with
a measured-history-calibrated cost model: placement consumers (the fleet
ring's capacity weights, the planned auto-parallel search) read speed the
hardware actually demonstrated, not a capacity proxy.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import threading

CALIB_SCHEMA = "pa-roofline-calib/v1"
CALIB_FILENAME = "roofline_calib.json"

# Platform roofline specs by device_kind substring: peak dense bf16 FLOP/s
# per chip (the bench._PEAK_BF16 table), HBM bytes/s, the per-chip ICI /
# DCN link bandwidths the collective model divides by (public spec sheets;
# ICI is the aggregate per-chip interconnect, DCN a conservative per-host
# 100 Gb/s), and ``h2d_bw`` — the host→HBM DMA rate the weight-streaming
# cost model (parallel/planner.py stream candidates) divides weight bytes
# by (PCIe-class, deliberately conservative: calibration corrects upward,
# a too-fast guess would make the planner pick stream over placements that
# actually win). Matched in order, first substring hit wins.
PLATFORM_SPECS: tuple[tuple[str, dict], ...] = (
    ("v6", {"peak_flops": 918e12, "hbm_bw": 1640e9, "ici_bw": 448e9,
            "dcn_bw": 12.5e9, "h2d_bw": 32e9}),
    ("v5p", {"peak_flops": 459e12, "hbm_bw": 2765e9, "ici_bw": 600e9,
             "dcn_bw": 12.5e9, "h2d_bw": 32e9}),
    ("v5e", {"peak_flops": 197e12, "hbm_bw": 819e9, "ici_bw": 200e9,
             "dcn_bw": 12.5e9, "h2d_bw": 16e9}),
    ("v5 lite", {"peak_flops": 197e12, "hbm_bw": 819e9, "ici_bw": 200e9,
                 "dcn_bw": 12.5e9, "h2d_bw": 16e9}),
    ("v4", {"peak_flops": 275e12, "hbm_bw": 1228e9, "ici_bw": 300e9,
            "dcn_bw": 12.5e9, "h2d_bw": 16e9}),
    ("v3", {"peak_flops": 123e12, "hbm_bw": 900e9, "ici_bw": 200e9,
            "dcn_bw": 12.5e9, "h2d_bw": 8e9}),
)

# Deterministic pseudo-spec for CPU / unknown backends — the same
# off-hardware philosophy as devices/memory.py's fallback accounting: the
# numbers are optimistic (XLA CPU never hits them), so uncalibrated
# predictions land well *under* measured time and roofline_ratio stays in
# its sane (0, 1.2] band until the calibration store learns the host.
CPU_SPEC = {"peak_flops": 2e12, "hbm_bw": 50e9, "ici_bw": 10e9,
            "dcn_bw": 1e9, "h2d_bw": 10e9, "generation": "cpu-pseudo"}


def enabled() -> bool:
    """The PA_ROOFLINE flag (default on; the observation itself is one dict
    write per program per process — the heavy lowering is telemetry's and
    already happened)."""
    return os.environ.get("PA_ROOFLINE", "") not in ("0", "false")


def platform_spec(device_kind: str = "", platform: str = "cpu") -> dict:
    """Roofline spec for a chip: ``device_kind`` substring match over
    :data:`PLATFORM_SPECS` (falling back to ``$PALLAS_AXON_TPU_GEN`` — the
    tunneled device_kind string often doesn't name the generation, the
    bench._peak_bf16 lesson), else the deterministic CPU pseudo-spec."""
    for kind in (str(device_kind or "").lower(),
                 os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()):
        if not kind:
            continue
        for key, spec in PLATFORM_SPECS:
            if key in kind:
                return {**spec, "generation": key, "platform": platform}
    return {**CPU_SPEC, "platform": platform}


# The speed-blend's reference workload (ROADMAP "speed-aware hybrid
# blending"): roughly one sd15 batch-16 1024² denoise step — the absolute
# numbers cancel in the share normalization, but the flops:bytes ratio
# decides which wall (compute vs memory) each platform's nominal time sits
# against, so it is pinned here rather than left to callers.
NOMINAL_STEP_FLOPS = 2e12
NOMINAL_STEP_BYTES = 4e10


def nominal_step_time_s(device_kind: str = "", platform: str = "cpu",
                        flops: float = NOMINAL_STEP_FLOPS,
                        bytes_accessed: float = NOMINAL_STEP_BYTES) -> float:
    """Per-platform nominal step time from the roofline spec alone — the
    SPEED signal ``parallel/split.blend_speed_weights`` blends into
    heterogeneous-chain workload weights the way free memory is blended
    today (the banked hybrid_sd15 showed a VRAM-only split makes a tpu+cpu
    chain a de-optimization: the CPU's share must reflect that it is ~40x
    slower, not that it has spare RAM)."""
    spec = platform_spec(device_kind, platform)
    return max(flops / spec["peak_flops"], bytes_accessed / spec["hbm_bw"])


# ---------------------------------------------------------------------------
# the analytic cost model
# ---------------------------------------------------------------------------


def collective_time_s(nbytes: float, n_devices: int, spec: dict,
                      link: str = "ici") -> float:
    """Ring all-gather/all-reduce time for ``nbytes`` over ``n_devices``:
    each chip moves ``(n-1)/n`` of the payload over its link
    (the standard alpha-free ring model; alpha is folded into calibration).
    Zero on a single device — no collective runs at all."""
    n = max(1, int(n_devices))
    if n <= 1 or not nbytes:
        return 0.0
    bw = spec.get(f"{link}_bw") or spec.get("ici_bw") or 1.0
    return (n - 1) / n * float(nbytes) / bw


def predict_time_s(flops: float | None, bytes_accessed: float | None,
                   spec: dict, n_devices: int = 1,
                   collective_bytes: float = 0.0,
                   link: str = "ici") -> dict:
    """One program/step roofline: SPMD divides FLOPs and bytes over the mesh
    width, compute and memory overlap (``max``), collectives serialize on
    top (``+``) — the shape the MPMD/auto-parallel papers' cost models share
    (PAPERS.md arxiv 2606.17566, 2412.14374). Returns the full decomposition
    so consumers can see *which* wall the prediction sits against."""
    n = max(1, int(n_devices))
    f = float(flops or 0.0) / n
    b = float(bytes_accessed or 0.0) / n
    compute_s = f / spec["peak_flops"]
    memory_s = b / spec["hbm_bw"]
    comms_s = collective_time_s(collective_bytes, n, spec, link=link)
    return {
        "compute_s": compute_s,
        "memory_s": memory_s,
        "comms_s": comms_s,
        "predicted_s": max(compute_s, memory_s) + comms_s,
        "bound": ("comms" if comms_s > max(compute_s, memory_s)
                  else "memory" if memory_s > compute_s else "compute"),
    }


def shape_bucket(flops: float | None) -> str:
    """Coarse work-size bucket for the calibration key: the power-of-two
    exponent of the FLOP count (programs within 2x of each other share a
    scale factor; a lane-width or depth change moves buckets)."""
    f = float(flops or 0.0)
    if f <= 0:
        return "2^0"
    return f"2^{int(math.log2(f))}"


# ---------------------------------------------------------------------------
# calibration store (ledger/roofline_calib.json)
# ---------------------------------------------------------------------------


def _ledger_dir() -> str:
    """Mirror of utils/telemetry.ledger_dir — duplicated because this module
    must stay loadable standalone (no package-relative imports) for the
    stdlib-only scripts."""
    override = os.environ.get("PA_LEDGER_DIR")
    if override:
        return override
    evidence = os.environ.get("PA_EVIDENCE_DIR")
    if evidence:
        return os.path.join(evidence, "ledger")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(repo, "ledger")


def calib_path(ledger_dir: str | None = None) -> str:
    return os.path.join(ledger_dir or _ledger_dir(), CALIB_FILENAME)


# (path → (mtime, scales)) memo: the planner prices candidates on every
# parallelize call, and an uncached open+parse per wrap is avoidable I/O —
# a changed mtime (re-bank, test write) invalidates naturally.
_calib_cache: dict = {}
_calib_cache_lock = threading.Lock()


def load_calibration(path: str | None = None) -> dict:
    """The banked scale factors, ``{}`` when nothing is banked yet (fresh
    checkouts predict uncalibrated — scale 1.0 everywhere). Memoized by
    file mtime (one stat per call, parse only on change)."""
    p = path or calib_path()
    try:
        mtime = os.path.getmtime(p)
    except OSError:
        return {}
    with _calib_cache_lock:
        cached = _calib_cache.get(p)
        if cached is not None and cached[0] == mtime:
            return cached[1]
    try:
        with open(p) as f:
            data = json.load(f)
        scales = data.get("scales") if isinstance(data, dict) else None
        scales = scales if isinstance(scales, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}
    with _calib_cache_lock:
        _calib_cache[p] = (mtime, scales)
    return scales


def save_calibration(scales: dict, path: str | None = None) -> str | None:
    """Persist the fitted scales (best-effort — a read-only checkout must
    not fail the run that fitted them). Returns the path or None."""
    import time

    p = path or calib_path()
    try:
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        with open(p, "w") as f:
            # palint: allow[observability] calibration-bank epoch STAMP
            json.dump({"schema": CALIB_SCHEMA, "ts": time.time(),
                       "scales": scales}, f, indent=1, sort_keys=True)
        return p
    except OSError:
        return None


def calib_key(program: str, platform: str, bucket: str) -> str:
    return f"{program}|{platform}|{bucket}"


def calibration_scale(calib: dict, program: str, platform: str,
                      bucket: str) -> float:
    """Most-specific banked scale: exact (program, platform, bucket) →
    (program, platform, any bucket) → (platform-wide) → 1.0 (uncalibrated).
    The hierarchy means one banked rung already improves every same-platform
    prediction — a new program starts from the platform's learned optimism
    instead of from spec-sheet peaks."""
    for key in (calib_key(program, platform, bucket),
                calib_key(program, platform, "*"),
                calib_key("*", platform, "*")):
        entry = calib.get(key)
        if isinstance(entry, dict) and entry.get("scale"):
            return float(entry["scale"])
    return 1.0


def _quantile(vals: list[float], q: float) -> float:
    """Nearest-rank quantile (the scripts/loadgen.py percentile
    convention)."""
    s = sorted(vals)
    k = max(0, min(len(s) - 1, round(q * (len(s) - 1))))
    return s[k]


# Calibration fits the 25th-percentile measured/predicted ratio, not the
# median: the gate's sane band is (0, 1.2] — fixed — so a median-centered
# scale would red-flag any run >20% faster than banked history (ordinary
# host-load variance, or an honest optimization). The conservative quantile
# keeps calibrated predictions below typical measurements; a deliberate
# perf change still re-banks, exactly like the perf/numerics baselines.
_FIT_QUANTILE = 0.25


def fit_calibration(records: list[dict]) -> dict:
    """Fit per-(program, platform, shape-bucket) scales from ledger history.

    Input: perf-ledger records. Two row sources, both always fitted against
    the RAW (uncalibrated) prediction so repeated re-banking converges
    instead of compounding:

    - rung-level: bench records carrying ``predicted_step_raw_s`` +
      ``value`` (measured s/it), keyed ``rung:<rung>``;
    - program-level: any record whose ``roofline_programs`` rows carry a
      ``measured_s`` alongside ``predicted_raw_s`` (bench attaches the DP
      step program's per-dispatch wall);
    - plan-level: ``kind="plan"`` decisions (parallel/planner.py, appended
      by bench/dryrun with the measured step) carrying
      ``plan_predicted_raw_s`` + ``plan_actual_s``, keyed
      ``plan:<rung>`` — the feedback loop that sharpens the planner's
      candidate scores per platform as its decisions get measured.

    The fitted scale is the conservative :data:`_FIT_QUANTILE` of the
    measured/raw ratios (see above). Each key additionally rolls up into
    the ``(program, platform, *)`` and platform-wide ``(*, platform, *)``
    fallbacks. Stale re-emits, ``kind=dryrun``/``dryrun``-marked, and error
    records are never fitted (the perf-gate comparability discipline —
    virtual-mesh CPU timings must not calibrate real predictions)."""
    by_key: dict[str, list[float]] = {}

    def feed(program: str, platform: str, bucket: str,
             predicted: float, actual: float) -> None:
        if predicted <= 0 or actual <= 0:
            return
        ratio = actual / predicted
        for key in (calib_key(program, platform, bucket),
                    calib_key(program, platform, "*"),
                    calib_key("*", platform, "*")):
            by_key.setdefault(key, []).append(ratio)

    for rec in records:
        if rec.get("stale") or rec.get("dryrun") or rec.get("invalid"):
            continue
        if rec.get("kind") not in ("bench", "loadgen", "plan"):
            continue  # error records and virtual-mesh dryruns never fit
        platform = rec.get("platform") or "?"
        if rec.get("kind") == "plan":
            pred = rec.get("plan_predicted_raw_s")
            act = rec.get("plan_actual_s")
            if isinstance(pred, (int, float)) and isinstance(act, (int, float)):
                feed(f"plan:{rec.get('rung') or '?'}", platform,
                     shape_bucket(rec.get("plan_flops")),
                     float(pred), float(act))
            continue
        pred_raw = rec.get("predicted_step_raw_s")
        value = rec.get("value")
        if (rec.get("kind") == "bench"
                and isinstance(pred_raw, (int, float))
                and isinstance(value, (int, float))):
            feed(f"rung:{rec.get('rung') or '?'}", platform,
                 shape_bucket(rec.get("model_flops_per_step")),
                 float(pred_raw), float(value))
        progs = rec.get("roofline_programs")
        if isinstance(progs, dict):
            for name, row in progs.items():
                if not isinstance(row, dict):
                    continue
                p = row.get("predicted_raw_s")
                m = row.get("measured_s")
                if isinstance(p, (int, float)) and isinstance(m, (int, float)):
                    feed(name, row.get("platform") or platform,
                         shape_bucket(row.get("flops")), float(p), float(m))
    return {
        key: {"scale": round(_quantile(ratios, _FIT_QUANTILE), 6),
              "n": len(ratios)}
        for key, ratios in by_key.items()
    }


def load_jsonl(path: str) -> list[dict]:
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def ledger_records(path: str | None = None) -> list[dict]:
    return load_jsonl(path or os.path.join(_ledger_dir(),
                                           "perf_ledger.jsonl"))


# ---------------------------------------------------------------------------
# per-program prediction registry (fed by utils/telemetry.instrument_jit)
# ---------------------------------------------------------------------------


class ProgramRegistry:
    """Per-program roofline rows: one entry per instrumented program name,
    written once at the program's first compile (when telemetry's cost
    analysis runs) and re-priced lazily when the calibration store is
    reloaded. Thread-safe; read by ``GET /health``, the ledger writers, and
    the dryrun's assertions."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rows: dict[str, dict] = {}  # guarded-by: _lock
        self._calib: dict | None = None

    def _calibration(self) -> dict:
        if self._calib is None:
            self._calib = load_calibration()
        return self._calib

    def refresh_calibration(self) -> None:
        """Drop the cached store (next record/reprice reloads from disk) —
        called after ``roofline_report.py --bank`` rewrites the file."""
        with self._lock:
            self._calib = None
            for row in self._rows.values():
                self._price(row)

    def _price(self, row: dict) -> None:
        spec = platform_spec(row.get("device_kind") or "",
                             row.get("platform") or "cpu")
        pred = predict_time_s(
            row.get("flops"), row.get("bytes_accessed"), spec,
            n_devices=row.get("n_devices") or 1,
            collective_bytes=row.get("collective_bytes") or 0.0,
        )
        bucket = shape_bucket(row.get("flops"))
        scale = calibration_scale(
            self._calibration(), row["program"],
            row.get("platform") or "cpu", bucket,
        )
        row.update(
            predicted_raw_s=pred["predicted_s"],
            predicted_s=pred["predicted_s"] * scale,
            compute_s=pred["compute_s"],
            memory_s=pred["memory_s"],
            comms_s=pred["comms_s"],
            bound=pred["bound"],
            shape_bucket=bucket,
            calib_scale=scale,
        )

    def record(self, program: str, *, flops=None, bytes_accessed=None,
               n_devices: int = 1, platform: str = "cpu",
               device_kind: str = "", collective_bytes: float = 0.0) -> dict:
        row = {
            "program": program,
            "flops": float(flops) if flops else None,
            "bytes_accessed": float(bytes_accessed) if bytes_accessed
            else None,
            "n_devices": max(1, int(n_devices)),
            "platform": platform,
            "device_kind": device_kind,
            "collective_bytes": float(collective_bytes or 0.0),
        }
        with self._lock:
            self._price(row)
            self._rows[program] = row
        _publish_predicted(program, row["predicted_s"])
        return row

    def rows(self) -> dict[str, dict]:
        with self._lock:
            return {n: dict(r) for n, r in sorted(self._rows.items())}

    def snapshot(self) -> dict:
        """The ``roofline`` section of ``GET /health``."""
        rows = self.rows()
        return {
            "enabled": enabled(),
            "programs": rows,
            "calibrated": sum(
                1 for r in rows.values() if r.get("calib_scale") != 1.0
            ),
        }

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()
            self._calib = None


programs = ProgramRegistry()


def _publish_predicted(program: str, value: float) -> None:
    """The one ``pa_roofline_predicted_s`` emission point (record-time and
    scrape-time both go through here). No-op standalone / when metrics is
    absent."""
    try:
        from .metrics import registry as _metrics

        _metrics.gauge(
            "pa_roofline_predicted_s", value,
            labels={"program": program},
            help="calibrated analytic roofline prediction per compiled "
                 "program (utils/roofline.py)",
        )
    except Exception:
        pass


def observe_program(program: str, *, flops=None, bytes_accessed=None,
                    args=None) -> None:
    """telemetry._InstrumentedJit's hook: turn a program's first-compile
    cost analysis into a roofline row. ``args`` are the CONCRETE call
    arguments — mesh width and platform are read off their shardings (an
    SPMD program's per-device work is total/N), and the collective term is
    fed the total bytes of every NON-replicated argument leaf: on a
    multi-device mesh those are the values XLA must gather/scatter at use
    sites (FSDP/TP weight all-gathers dominate; batch-sharded activations
    that need no gather are small against them — a first-order link-model
    estimate, refined per platform by the calibration store). Best-effort
    by contract: accounting must never break the program it accounts."""
    if not enabled():
        return
    n_devices = 1
    platform = "cpu"
    device_kind = ""
    sharded_bytes = 0
    try:
        import jax

        dev = None
        for leaf in jax.tree.leaves(args):
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                continue
            try:
                dset = sharding.device_set
                if len(dset) > n_devices:
                    n_devices = len(dset)
                if dev is None:
                    dev = next(iter(dset))
                if len(dset) > 1 and not sharding.is_fully_replicated:
                    sharded_bytes += int(getattr(leaf, "nbytes", 0))
            except Exception:
                pass
        if dev is None:
            dev = jax.devices()[0]
        platform = dev.platform
        device_kind = getattr(dev, "device_kind", "") or ""
    except Exception:
        pass
    try:
        programs.record(
            program, flops=flops, bytes_accessed=bytes_accessed,
            n_devices=n_devices, platform=platform, device_kind=device_kind,
            collective_bytes=sharded_bytes if n_devices > 1 else 0.0,
        )
    except Exception:
        pass


def program_rows_for_ledger() -> dict[str, dict] | None:
    """Compact per-program rows for a perf-ledger record (the fields
    fit_calibration reads back, minus the registry's internals)."""
    rows = programs.rows()
    if not rows:
        return None
    out = {}
    for name, r in rows.items():
        out[name] = {
            "predicted_s": round(r["predicted_s"], 6),
            "predicted_raw_s": round(r["predicted_raw_s"], 6),
            "flops": r["flops"],
            "bytes_accessed": r["bytes_accessed"],
            "n_devices": r["n_devices"],
            "platform": r["platform"],
            "bound": r["bound"],
        }
    return out


# ---------------------------------------------------------------------------
# measured-side attribution (trace spans → compute/transfer/host-gap/comms)
# ---------------------------------------------------------------------------


def _x_events(events) -> list[dict]:
    if isinstance(events, dict):
        events = events.get("traceEvents", [])
    return [e for e in events if e.get("ph") == "X"]


def attribution_from_trace(events, wall_s: float | None = None,
                           last_steps: int | None = None) -> dict | None:
    """Decompose a traced window into the four dispatch buckets —
    ``compute_s`` / ``exposed_transfer_s`` / ``comms_s`` / ``host_gap_s``,
    non-negative and summing to the wall. One bucket per window is the
    RESIDUAL (whatever the host-side spans cannot directly measure); which
    one depends on the window's sync discipline:

    - **streamed window** (``stream-stage-compute`` spans present — the
      backpressure blocks make them device-accurate): compute is the
      measured Σ stage-compute, exposed transfer the measured
      Σ ``stream-prefetch-wait`` (what double-buffering failed to hide),
      comms the Σ ``fleet-hop``/comms-cat spans, and HOST-GAP is the
      residual — scheduling/dispatch time the device cannot see.
    - **dispatch window** (only ``step`` spans — async dispatch, nothing
      blocks per step; bench's chained loop, eager runs): the directly
      measurable part is the HOST side — per-thread gaps *between*
      consecutive step spans (the ``host_gap_ms`` discipline) net of any
      comms spans filling them — and COMPUTE is the residual: dispatch +
      device execution + the blocking readback the host observed as one
      opaque wait. Booking that wait as "host gap" would claim the device
      was idle while it was doing all the work.

    ``wall_s`` pins the wall to an externally measured clock (bench's
    ``sec_it * iters`` — which extends past the last dispatch to the final
    readback); default is the window spanned by the selected spans.
    ``last_steps`` restricts to the last N ``step`` spans — how bench drops
    its warmup steps. None when the trace holds nothing attributable."""
    xs = _x_events(events)
    steps = sorted((e for e in xs if e["name"] == "step"),
                   key=lambda e: e["ts"])
    if last_steps:
        steps = steps[-int(last_steps):]
    if steps:
        w0 = steps[0]["ts"]
        w1 = max(e["ts"] + e.get("dur", 0.0) for e in steps)
    else:
        runs = [e for e in xs if e["name"] == "stream-run"]
        if not runs:
            return None
        w0 = min(e["ts"] for e in runs)
        w1 = max(e["ts"] + e.get("dur", 0.0) for e in runs)
    window_s = max(0.0, (w1 - w0) / 1e6)
    wall = float(wall_s) if wall_s else window_s
    if wall <= 0:
        return None

    def total(pred) -> float:
        return sum(
            e.get("dur", 0.0) for e in xs
            if pred(e) and e["ts"] >= w0 - 1.0
            and e["ts"] + e.get("dur", 0.0) <= w1 + 1.0
        ) / 1e6

    stream_compute = total(lambda e: e["name"] == "stream-stage-compute")
    transfer = total(lambda e: e["name"] == "stream-prefetch-wait")
    comms = total(lambda e: e["name"] == "fleet-hop"
                  or e.get("cat") == "comms")
    if stream_compute > 0:
        # Sync-disciplined window: compute/transfer measured, host-gap
        # residual. Clamp in measurement-priority order — concurrent
        # threads can overlap spans past the wall clock.
        compute = min(stream_compute, wall)
        transfer = min(transfer, max(0.0, wall - compute))
        comms = min(comms, max(0.0, wall - compute - transfer))
        host_gap = max(0.0, wall - compute - transfer - comms)
    else:
        # Dispatch window: host gaps measured (per-thread inter-step gaps,
        # net of comms spans that fill them), compute residual.
        by_tid: dict = {}
        for e in steps:
            by_tid.setdefault(e.get("tid"), []).append(e)
        gaps = 0.0
        for evs in by_tid.values():
            for a, b in zip(evs, evs[1:]):
                gaps += max(
                    0.0, b["ts"] - (a["ts"] + a.get("dur", 0.0))
                ) / 1e6
        comms = min(comms, wall)
        host_gap = min(max(0.0, gaps - comms), max(0.0, wall - comms))
        transfer = min(transfer, max(0.0, wall - comms - host_gap))
        compute = max(0.0, wall - transfer - comms - host_gap)
    return {
        "compute_s": round(compute, 6),
        "exposed_transfer_s": round(transfer, 6),
        "comms_s": round(comms, 6),
        "host_gap_s": round(host_gap, 6),
        "wall_s": round(wall, 6),
    }


def attribution_fractions(attr: dict | None) -> dict | None:
    """The bucket fractions of wall time (what trace_summary/loadgen print);
    None in, None out."""
    if not attr or not attr.get("wall_s"):
        return None
    w = attr["wall_s"]
    return {
        "compute_fraction": round(attr["compute_s"] / w, 4),
        "exposed_transfer_fraction": round(attr["exposed_transfer_s"] / w, 4),
        "comms_fraction": round(attr["comms_s"] / w, 4),
        "host_gap_fraction": round(attr["host_gap_s"] / w, 4),
    }


def publish_gauges() -> None:
    """Scrape-time refresh (the server's ``GET /metrics``): per-program
    predictions plus — when tracing is live — the attribution fractions of
    the current trace window as ``pa_roofline_*_fraction`` gauges. No-op
    standalone or with PA_ROOFLINE=0."""
    if not enabled():
        return
    try:
        from .metrics import registry as _metrics
    except Exception:
        return
    for name, row in programs.rows().items():
        _publish_predicted(name, row["predicted_s"])
    try:
        from . import tracing

        if not tracing.on():
            return
        fracs = attribution_fractions(
            attribution_from_trace(tracing.export())
        )
        if not fracs:
            return
        for key, val in fracs.items():
            _metrics.gauge(
                f"pa_roofline_{key}", val,
                help="measured-side roofline attribution over the live "
                     "trace window (utils/roofline.py buckets)",
            )
    except Exception:
        pass


# ---------------------------------------------------------------------------
# unified step-FLOPs accessor (satellite: mfu_budget vs telemetry sources)
# ---------------------------------------------------------------------------
#
# The jaxpr walk below is the exact per-equation count scripts/mfu_budget.py
# buckets per op class; it lives here so bench.py, mfu_budget, and the
# roofline all read ONE implementation — MFU and roofline_ratio can no
# longer silently disagree about what a step costs.


def _aval_nbytes(aval) -> int:
    return (math.prod(aval.shape) * aval.dtype.itemsize if aval.shape
            else aval.dtype.itemsize)


def _dot_flops(eqn):
    """Exact dot_general FLOPs (2·M·N·K over batch dims) + the lane-padded
    variant (contraction and output dims rounded up to the 128-lane MXU
    granularity)."""
    lane = 128
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    k = math.prod(lhs.shape[d] for d in lc)
    b = math.prod(lhs.shape[d] for d in lb)
    m = math.prod(
        lhs.shape[d] for d in range(len(lhs.shape)) if d not in (*lc, *lb)
    )
    n = math.prod(
        rhs.shape[d] for d in range(len(rhs.shape)) if d not in (*rc, *rb)
    )
    pad = lambda v: -(-v // lane) * lane  # noqa: E731
    return 2 * b * m * n * k, 2 * b * pad(m) * pad(n) * pad(k), (m, n, k, b)


def _conv_flops(eqn):
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval  # kernel (spatial..., in/feature, out) per dnums
    # 2 · out_elements · (kernel elements per output) — feature_group_count
    # divides the per-output kernel work.
    groups = eqn.params.get("feature_group_count", 1)
    kernel_per_out = math.prod(rhs.shape[:-1]) // max(groups, 1)
    flops = 2 * math.prod(out.shape) * kernel_per_out
    return flops, flops  # convs lower through MXU-shaped patches; no pad model


def _subjaxprs(eqn):
    """Inner jaxprs of one equation (pjit/scan/cond/custom-call params)."""
    from jax.extend import core as jex_core

    closed = getattr(jex_core, "ClosedJaxpr", None)
    bare = getattr(jex_core, "Jaxpr", None)
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if closed is not None and isinstance(x, closed):
                yield x.jaxpr
            elif bare is not None and isinstance(x, bare):
                yield x


def walk_jaxpr(jaxpr, acc, seq_lens) -> None:
    """Bucket every equation's FLOPs/bytes by op class into ``acc`` —
    scripts/mfu_budget.py's per-class walk (conv / matmul / attention /
    elementwise), shared verbatim so the budget and the roofline count the
    same ops."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        for sub in _subjaxprs(eqn):  # recurse into pjit/scan/cond
            walk_jaxpr(sub, acc, seq_lens)
        if name == "dot_general":
            f, fpad, (m, n, k, b) = _dot_flops(eqn)
            cls = "matmul"
            # Attention score/value products: QK^T contracts the head dim
            # (k ≤ 256) against a full sequence (m or n ∈ seq_lens — the
            # chunked path keeps full length only on the K side); PV
            # contracts the sequence itself (k ∈ seq_lens).
            if (k in seq_lens) or (
                (m in seq_lens or n in seq_lens) and k <= 256
            ):
                cls = "attention"
            acc[cls]["flops"] += f
            acc[cls]["flops_padded"] += fpad
            acc[cls]["bytes"] += sum(
                _aval_nbytes(v.aval) for v in eqn.invars
            )
            acc[cls]["bytes"] += sum(
                _aval_nbytes(v.aval) for v in eqn.outvars
            )
            acc[cls]["count"] += 1
        elif name == "conv_general_dilated":
            f, fpad = _conv_flops(eqn)
            acc["conv"]["flops"] += f
            acc["conv"]["flops_padded"] += fpad
            acc["conv"]["bytes"] += sum(
                _aval_nbytes(v.aval) for v in eqn.invars
            )
            acc["conv"]["bytes"] += sum(
                _aval_nbytes(v.aval) for v in eqn.outvars
            )
            acc["conv"]["count"] += 1
        elif not eqn.primitive.multiple_results or name in ("scan", "while"):
            byts = sum(
                _aval_nbytes(v.aval) for v in eqn.invars
                if hasattr(v, "aval")
            )
            byts += sum(_aval_nbytes(v.aval) for v in eqn.outvars)
            acc["elementwise"]["flops"] += math.prod(
                eqn.outvars[0].aval.shape
            ) if eqn.outvars and eqn.outvars[0].aval.shape else 0
            acc["elementwise"]["bytes"] += byts
            acc["elementwise"]["count"] += 1
            acc.setdefault("_by_prim", {}).setdefault(name, [0, 0])
            acc["_by_prim"][name][0] += 1
            acc["_by_prim"][name][1] += byts


def empty_acc() -> dict:
    return {
        c: {"flops": 0, "flops_padded": 0, "bytes": 0, "count": 0}
        for c in ("conv", "matmul", "attention", "elementwise")
    }


def analytic_flops(apply, params, x, t, ctx, kwargs=None):
    """Total model FLOPs of ONE forward step from the exact jaxpr walk —
    the fallback when XLA HLO cost analysis returns nothing (VERDICT r5
    next-6: the QuantTensor int8 rungs banked ``mfu: null``). Pure tracing —
    nothing executes, CPU-safe."""
    import jax as _jax

    kw = dict(kwargs or {})
    jaxpr = _jax.make_jaxpr(
        lambda p, x_, t_, c_: apply(p, x_, t_, c_, **kw)
    )(params, x, t, ctx)
    acc = empty_acc()
    walk_jaxpr(jaxpr.jaxpr, acc, set())
    acc.pop("_by_prim", None)
    total = float(sum(c["flops"] for c in acc.values()))
    return total if total > 0 else None


def step_cost(apply, params, x, t, ctx, kwargs=None) -> dict:
    """THE shared step-FLOPs accessor (one source for MFU and for the
    roofline): XLA HLO ``cost_analysis`` of a CPU lowering (FLOPs AND bytes
    accessed — dot/conv counts are backend-independent, and the axon
    tunnel's PJRT client implements no cost analysis) with the jaxpr walk
    as fallback and cross-check. Returns::

        {flops, bytes_accessed, flops_hlo, flops_jaxpr,
         flops_source: "hlo"|"jaxpr"|None, flops_discrepancy_ratio}

    ``flops_discrepancy_ratio`` (hlo/jaxpr, when both resolved) is logged
    and recorded so the two counters can never silently disagree — a ratio
    far from 1 means one of them stopped counting something real."""
    flops_hlo = bytes_hlo = None
    try:
        import jax

        abstract = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
            (params, x, t, ctx, dict(kwargs or {})),
        )
        with jax.default_device(jax.devices("cpu")[0]):
            cost = jax.jit(apply).lower(
                abstract[0], abstract[1], abstract[2], abstract[3],
                **abstract[4],
            ).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        cost = cost or {}
        f = cost.get("flops")
        b = cost.get("bytes accessed")
        flops_hlo = float(f) if f and f > 0 else None
        bytes_hlo = float(b) if b and b > 0 else None
    except Exception:
        pass
    flops_jaxpr = None
    try:
        flops_jaxpr = analytic_flops(apply, params, x, t, ctx, kwargs)
    except Exception:
        pass
    flops = flops_hlo or flops_jaxpr
    source = ("hlo" if flops_hlo else "jaxpr" if flops_jaxpr else None)
    discrepancy = (
        round(flops_hlo / flops_jaxpr, 4)
        if flops_hlo and flops_jaxpr else None
    )
    if discrepancy is not None and not 0.5 <= discrepancy <= 2.0:
        try:
            from .logging import get_logger

            get_logger().warning(
                "step-FLOPs sources disagree %.2fx (hlo %.3g vs jaxpr "
                "%.3g) — one counter stopped counting something real",
                discrepancy, flops_hlo, flops_jaxpr,
            )
        except Exception:
            pass
    return {
        "flops": flops,
        "bytes_accessed": bytes_hlo,
        "flops_hlo": flops_hlo,
        "flops_jaxpr": flops_jaxpr,
        "flops_source": source,
        "flops_discrepancy_ratio": discrepancy,
    }


# ---------------------------------------------------------------------------
# ledger-history capacity weights (the fleet ring's consumer)
# ---------------------------------------------------------------------------


def host_step_weights(records: list[dict],
                      clamp: tuple[float, float] = (0.25, 4.0)) -> dict:
    """Per-host capacity weights from banked step-time history: weight ∝
    1 / median(step seconds), normalized to mean 1.0 and clamped (a single
    wild record must not hand one host the whole ring).

    Sources are TIERED, never mixed — a 1/median comparison is only
    meaningful over one metric measured on one workload shape, so only the
    fleet's OWN measurements qualify (a loadgen run drives every host with
    the same prompt mix in the same window; bench s/it is rung-dependent
    and would compare a host that benched ``smoke`` against one that
    benched ``flux_16`` as if 80x apart):

    1. loadgen per-host ``server_step_p50_s`` (per-dispatch step seconds,
       same workload across hosts by construction) — used when ANY host
       has them;
    2. loadgen per-host client latency p50 — only when NO host has
       server-side step history (older loadgen records).

    ``{}`` when no usable history — the ring then weights every host
    equally, exactly as before calibration existed."""
    step_times: dict[str, list[float]] = {}
    lat_times: dict[str, list[float]] = {}

    def feed(into, host, t) -> None:
        if host and isinstance(t, (int, float)) and t > 0:
            into.setdefault(str(host), []).append(float(t))

    for rec in records:
        if rec.get("stale") or rec.get("invalid") or rec.get("kind") == "error":
            continue
        # loadgen AND openloop records qualify: both drive every host with
        # the same prompt mix in the same window (the same-workload rule).
        if (rec.get("kind") in ("loadgen", "openloop")
                and isinstance(rec.get("hosts"), dict)):
            for hid, row in rec["hosts"].items():
                if isinstance(row, dict):
                    feed(step_times, hid, row.get("server_step_p50_s"))
                    feed(lat_times, hid, row.get("latency_p50_s"))
    times = step_times or lat_times
    if not times:
        return {}
    speeds = {h: 1.0 / statistics.median(ts) for h, ts in times.items()}
    mean = sum(speeds.values()) / len(speeds)
    lo, hi = clamp
    return {
        h: round(min(hi, max(lo, s / mean)), 4) for h, s in speeds.items()
    }
