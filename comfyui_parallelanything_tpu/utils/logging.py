"""Structured logging with the reference's event vocabulary.

The reference logs ~40 ``[ParallelAnything]``-prefixed prints (SURVEY §5.5): setup
summary with device/percentage table (any_device_parallel.py:1029), per-device clone
progress + free-VRAM readings (1088-1094), success/safe-mode/LoRA status (1103-1108),
OOM/degradation warnings (1116, 1426, 1437). This module keeps that event vocabulary on
stdlib ``logging`` — levels, structure, and counters instead of prints.

Correlation (round 8): with several prompt workers and a serving dispatcher
in flight at once, the old flat format left records unattributable. Every
record now passes through :class:`ContextFilter`, which stamps ``prompt_id``
and ``span_id`` from the calling thread's active trace/progress context
(utils/tracing.py span stack, falling back to the utils/progress.py scope),
so a grep for one prompt's id yields its complete log *and* its ``/trace``
timeline — the same key correlates both.
"""

from __future__ import annotations

import collections
import logging
from collections.abc import Sequence

_LOGGER_NAME = "parallel_anything_tpu"

# Flight-recorder depth: the "last K log records" a postmortem bundle
# (utils/telemetry.write_postmortem) captures.
_RECENT_CAPACITY = 256


class _RecentHandler(logging.Handler):
    """Bounded in-memory ring of formatted records — the log half of the
    flight recorder. Always installed (a deque append per record is free);
    read via :func:`recent_log_records` at postmortem time."""

    def __init__(self, capacity: int = _RECENT_CAPACITY):
        super().__init__()
        self.records: collections.deque[str] = collections.deque(
            maxlen=capacity
        )

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.records.append(self.format(record))
        except Exception:  # noqa: BLE001 — the recorder must never raise
            pass


_recent: _RecentHandler | None = None


def recent_log_records() -> list[str]:
    """The last K formatted log records (oldest first) — what
    ``write_postmortem`` dumps as ``logs.txt``."""
    return list(_recent.records) if _recent is not None else []


class ContextFilter(logging.Filter):
    """Stamp the calling thread's prompt/span context into every record.

    Lazy imports keep this module importable standalone and make the filter
    unconditionally safe: a tracing/progress hiccup degrades to ``-`` fields,
    never to a lost log line."""

    def filter(self, record: logging.LogRecord) -> bool:
        prompt_id = span_id = None
        try:
            from . import tracing

            prompt_id = tracing.current_prompt_id()
            span_id = tracing.current_span_id()
        except Exception:
            pass
        record.prompt_id = prompt_id if prompt_id is not None else "-"
        record.span_id = span_id if span_id is not None else "-"
        return True


def get_logger() -> logging.Logger:
    global _recent
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        fmt = logging.Formatter(
            "[ParallelAnything] %(levelname)s "
            "prompt=%(prompt_id)s span=%(span_id)s %(message)s"
        )
        handler = logging.StreamHandler()
        handler.setFormatter(fmt)
        handler.addFilter(ContextFilter())
        logger.addHandler(handler)
        _recent = _RecentHandler()
        _recent.setFormatter(logging.Formatter(
            "%(asctime)s " + fmt._fmt
        ))
        _recent.addFilter(ContextFilter())
        logger.addHandler(_recent)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def log_setup_summary(
    devices: Sequence[str], weights: Sequence[float], mode: str
) -> None:
    """Setup summary — parity with the device/percentage table print at 1029."""
    table = ", ".join(
        f"{d}={w * 100:.1f}%" for d, w in zip(devices, weights)
    )
    get_logger().info("parallel setup (%s): %s", mode, table)


def log_placement(device: str, what: str) -> None:
    """Per-device placement — parity with per-device clone progress prints 1088-1094."""
    get_logger().info("placed %s on %s", what, device)


def log_degradation(event: str, detail: str) -> None:
    """Degradation events (device drop / single-device fallback) — parity with the OOM
    warnings at 1116 ('Reducing to N devices due to OOM') and 1437."""
    get_logger().warning("degradation [%s]: %s", event, detail)
