"""In-graph numeric assertions (NaN/Inf) via jax.experimental.checkify.

The reference's answer to silent numeric corruption is defensive try/except and
print-and-continue (SURVEY §4, §5.2); SPMD has no user-visible threads to race, so
the TPU-native hazard is NaN/Inf propagating through a jitted program. ``checked``
wraps a forward so every call verifies its output is finite *inside* the compiled
program and raises a clear host-side error instead of emitting black images.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental import checkify


def assert_finite(tree: Any, name: str = "output") -> None:
    """In-graph assertion that every array leaf is finite (trace-time usable)."""
    for i, leaf in enumerate(jax.tree.leaves(tree)):
        if isinstance(leaf, jax.Array) or hasattr(leaf, "dtype"):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                checkify.check(
                    jnp.all(jnp.isfinite(leaf)),
                    f"{name}[leaf {i}] contains NaN/Inf",
                )


def checked(fn: Callable[..., Any], name: str = "forward") -> Callable[..., Any]:
    """Wrap ``fn`` so its outputs are finite-checked inside jit; raises ValueError
    on the host when the check trips.

    Usage::

        model_checked = checked(model.apply, "flux forward")
        out = model_checked(params, x, t, ctx)   # raises on NaN/Inf output
    """

    def inner(*args, **kwargs):
        out = fn(*args, **kwargs)
        assert_finite(out, name)
        return out

    checked_fn = checkify.checkify(inner)

    def wrapper(*args, **kwargs):
        err, out = checked_fn(*args, **kwargs)
        err.throw()
        return out

    return wrapper
