from .logging import get_logger, log_setup_summary, log_placement, log_degradation
from .cleanup import aggressive_cleanup
from .compile_cache import enable_compilation_cache
from .metrics import StepTimer, StepStats, trace
from .checks import assert_finite, checked
from . import degrade, faults, numerics, retry, roofline, telemetry, tracing

__all__ = [
    "degrade",
    "faults",
    "numerics",
    "retry",
    "roofline",
    "enable_compilation_cache",
    "get_logger",
    "log_setup_summary",
    "log_placement",
    "log_degradation",
    "aggressive_cleanup",
    "StepTimer",
    "StepStats",
    "trace",
    "tracing",
    "telemetry",
    "assert_finite",
    "checked",
]
