"""Online anomaly sentinel over the metric-history ring.

The regression gates (scripts/perf_ledger.py and friends) only fire at
CI time; a live incident — step-time creep, queue growth, an embed-hit
collapse, a disk going slow — used to be invisible until a crash wrote a
postmortem. This module watches the history ring (utils/timeseries.py)
ONLINE and makes a live incident leave the same evidence a crash does:

- **watch list** (:data:`WATCHLIST`): step-time p95, lane wait, queue
  depth, SLO burn rate, embed/compile cache hit rates, HBM watermark,
  heartbeat staleness, per-role stage p95s, journal/ledger disk-append
  p95 — every signal read off the ring's windowed readers, never off a
  hot step path.
- **robust online detectors**: :class:`BandDetector` keeps an EWMA
  baseline and an EWMA absolute deviation (the online MAD proxy) and
  fires on a banded z-score (|z| > z_max, direction-aware, baseline
  FROZEN while firing so the anomaly can't teach the detector that
  broken is normal); :class:`TrendDetector` fires on monotone growth
  (queue depth — a queue that only ever grows is saturation long before
  any absolute bound trips). Both are pure functions of the sample
  series: same seed + same series = same firings, so chaos runs
  (scripts/chaos.py) assert EXACT attribution instead of flaky noise.
- **a firing emits everything at once**: the
  ``pa_anomaly_active{signal=,host=}`` gauge,
  ``pa_anomaly_events_total{signal=}`` (and ``_unattributed_total`` when
  nothing declared explains it), an ``anomaly``-category instant span, a
  ``kind="anomaly"`` perf-ledger record naming
  signal/baseline/observed/window, and — rate-limited per signal
  (``PA_ANOMALY_POSTMORTEM_S``) — a ``write_postmortem`` forensics
  bundle carrying the history window.
- **attribution**: a firing inside a declared load phase
  (``HistoryRing.mark_phase``) or overlapping a fired fault site
  (``pa_fault_injected_total{site=}`` window delta) is ATTRIBUTED —
  fault-injection phases become labeled anomalies, not pages;
  scripts/anomaly_report.py ``--check`` gates on zero unattributed
  firings.

Flag discipline: ``PA_ANOMALY=0`` disables observation, emission and
gauges entirely (the tracer's null-path rule — a tier-1-tested no-op;
the disabled path is one env read). Import discipline: module level is
stdlib-only and free of package-relative imports (the standalone
contract) — metrics/tracing/telemetry emission is lazy best-effort, so
scripts/anomaly_report.py and tests load this file over a wedged tunnel.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

ANOMALY_SCHEMA = "pa-anomaly/v1"


def enabled(env=os.environ) -> bool:
    """The PA_ANOMALY flag (default on — observation is a handful of ring
    reads per sampler tick, never on a step path)."""
    return env.get("PA_ANOMALY", "") not in ("0", "false")


def postmortem_interval_s(env=os.environ) -> float:
    """Min seconds between auto-forensics bundles PER SIGNAL
    (``PA_ANOMALY_POSTMORTEM_S``; 0 disables capture, not detection)."""
    raw = env.get("PA_ANOMALY_POSTMORTEM_S")
    try:
        return float(raw) if raw not in (None, "") else 300.0
    except ValueError:
        return 300.0


@dataclasses.dataclass(frozen=True)
class Watch:
    """One watched signal: how to read it off the ring and how to judge it.

    ``kind``: ``gauge`` (latest value, ``agg`` across label sets),
    ``rate``/``delta`` (reset-aware counter readers), ``quantile``
    (windowed histogram quantile ``q``), ``ratio`` (windowed
    hit/(hit+miss) of two cumulative series — cache hit rates).
    ``detector``: ``band`` (EWMA + MAD z-score, ``direction``-aware) or
    ``trend`` (monotone growth over ``trend_k`` points ≥ ``min_rise``).
    ``min_sigma`` floors the deviation scale so μs-level jitter on a
    quiet host can never manufacture a huge z."""

    name: str
    metric: str
    kind: str = "gauge"
    labels: tuple = ()            # (("k","v"),...) — hashable dict twin
    agg: str = "sum"
    q: float = 95.0
    miss_metric: str | None = None
    window_s: float | None = 600.0
    detector: str = "band"
    direction: str = "high"
    z_max: float = 8.0
    warmup: int = 5
    min_sigma: float = 0.01
    trend_k: int = 4
    min_rise: float = 4.0


WATCHLIST: tuple[Watch, ...] = (
    Watch("step_time_p95", "pa_serving_step_seconds", kind="quantile",
          min_sigma=0.005),
    Watch("lane_wait_p95", "pa_slo_stage_seconds", kind="quantile",
          labels=(("stage", "lane_wait"),), min_sigma=0.01),
    Watch("queue_depth", "pa_server_queue_pending", kind="gauge",
          detector="trend", trend_k=4, min_rise=6.0),
    Watch("burn_rate", "pa_slo_burn_rate", kind="gauge", agg="max",
          min_sigma=0.25),
    Watch("embed_hit_rate", "pa_embed_cache_hits", kind="ratio",
          miss_metric="pa_embed_cache_misses", direction="low",
          min_sigma=0.15, z_max=6.0),
    Watch("compile_hit_rate", "pa_compile_cache_hits_total", kind="ratio",
          miss_metric="pa_compile_cache_misses_total", direction="low",
          min_sigma=0.15, z_max=6.0),
    Watch("hbm_watermark", "pa_hbm_utilization", kind="gauge", agg="max",
          min_sigma=0.05, z_max=6.0),
    Watch("heartbeat_staleness", "pa_fleet_host_health_age_s", kind="gauge",
          agg="max", min_sigma=2.0),
    Watch("stage_p95_encode", "pa_role_stage_seconds", kind="quantile",
          labels=(("role", "encode"),), min_sigma=0.01),
    Watch("stage_p95_denoise", "pa_role_stage_seconds", kind="quantile",
          labels=(("role", "denoise"),), min_sigma=0.01),
    Watch("stage_p95_decode", "pa_role_stage_seconds", kind="quantile",
          labels=(("role", "decode"),), min_sigma=0.01),
    Watch("disk_append_p95", "pa_disk_append_seconds", kind="quantile",
          min_sigma=0.005),
)


class BandDetector:
    """EWMA baseline + EWMA absolute deviation (online MAD proxy), banded
    z-score. Deterministic: state is a pure fold over the value series.
    The baseline FREEZES while firing (anomalous samples must not teach
    the detector that broken is normal); ``clear_k`` consecutive in-band
    samples clear the firing and resume adaptation."""

    MAD_TO_SIGMA = 1.4826  # normal-consistency constant

    def __init__(self, z_max: float = 8.0, warmup: int = 5,
                 alpha: float = 0.3, min_sigma: float = 0.01,
                 direction: str = "high", clear_k: int = 2):
        self.z_max = float(z_max)
        self.warmup = int(warmup)
        self.alpha = float(alpha)
        self.min_sigma = float(min_sigma)
        self.direction = direction
        self.clear_k = int(clear_k)
        self.mean: float | None = None
        self.dev = 0.0
        self.n = 0
        self.firing = False
        self.z = 0.0
        self._calm = 0

    def update(self, x: float) -> bool:
        """Feed one sample; returns the post-sample firing state."""
        x = float(x)
        if self.mean is None:
            self.mean, self.n = x, 1
            return False
        sigma = max(self.MAD_TO_SIGMA * self.dev, self.min_sigma)
        z = (x - self.mean) / sigma
        self.z = z
        out_of_band = (
            z > self.z_max if self.direction == "high"
            else z < -self.z_max if self.direction == "low"
            else abs(z) > self.z_max
        )
        if self.n < self.warmup:
            out_of_band = False
        if out_of_band:
            self.firing = True
            self._calm = 0
            return True  # baseline frozen while firing
        if self.firing:
            self._calm += 1
            if self._calm >= self.clear_k:
                self.firing = False
        self.n += 1
        self.mean += self.alpha * (x - self.mean)
        self.dev += self.alpha * (abs(x - self.mean) - self.dev)
        return self.firing

    def baseline(self) -> float | None:
        return self.mean


class TrendDetector:
    """Monotone-growth detector (queue depth): fires when the last
    ``k`` inter-sample deltas are all positive and the total rise is at
    least ``min_rise`` — saturation shows as a queue that only grows,
    long before any absolute threshold trips. Clears on the first
    non-increasing sample."""

    def __init__(self, k: int = 4, min_rise: float = 4.0):
        self.k = int(k)
        self.min_rise = float(min_rise)
        self.window: list[float] = []
        self.firing = False
        self.z = 0.0

    def update(self, x: float) -> bool:
        self.window.append(float(x))
        del self.window[:-(self.k + 1)]
        if len(self.window) < self.k + 1:
            self.firing = False
            return False
        deltas = [b - a for a, b in zip(self.window, self.window[1:])]
        rise = self.window[-1] - self.window[0]
        self.firing = all(d > 0 for d in deltas) and rise >= self.min_rise
        self.z = rise / max(self.min_rise, 1e-9)
        return self.firing

    def baseline(self) -> float | None:
        return self.window[0] if self.window else None


def _make_detector(w: Watch):
    if w.detector == "trend":
        return TrendDetector(k=w.trend_k, min_rise=w.min_rise)
    return BandDetector(z_max=w.z_max, warmup=w.warmup,
                        min_sigma=w.min_sigma, direction=w.direction)


def _read(ring, w: Watch) -> float | None:
    """One watched value off the ring's reset-aware readers."""
    labels = dict(w.labels) or None
    if w.kind == "quantile":
        return ring.quantile_at(w.metric, w.q, window_s=w.window_s,
                                labels=labels)
    if w.kind == "rate":
        return ring.rate(w.metric, window_s=w.window_s, labels=labels)
    if w.kind == "delta":
        return ring.delta(w.metric, window_s=w.window_s, labels=labels)
    if w.kind == "ratio":
        hits = ring.delta(w.metric, window_s=w.window_s, labels=labels)
        misses = ring.delta(w.miss_metric, window_s=w.window_s,
                            labels=labels)
        if hits is None and misses is None:
            return None
        hits, misses = hits or 0.0, misses or 0.0
        total = hits + misses
        return None if total <= 0 else hits / total
    return ring.latest(w.metric, labels=labels, agg=w.agg)


class AnomalySentinel:
    """Watch-list evaluation + the ``pa_anomaly_*`` emission points.

    Driven by the history sampler's tick (utils/timeseries.HistorySampler)
    — one :meth:`observe` per snapshot, entirely off the step path.
    Thread-safe: ticks and /metrics publishes interleave."""

    def __init__(self, watchlist: tuple[Watch, ...] | None = None,
                 seed: int = 0):
        self.watchlist = tuple(watchlist if watchlist is not None
                               else WATCHLIST)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._detectors = {}          # name → detector — guarded-by: _lock
        self._active: dict[str, dict] = {}   # guarded-by: _lock
        self._events = 0                     # guarded-by: _lock
        self._unattributed = 0               # guarded-by: _lock
        self._last_event: dict | None = None  # guarded-by: _lock
        self._last_pm: dict[str, float] = {}  # guarded-by: _lock
        self._host = ""                      # guarded-by: _lock

    def reset(self, watchlist: tuple[Watch, ...] | None = None,
              seed: int | None = None) -> None:
        with self._lock:
            if watchlist is not None:
                self.watchlist = tuple(watchlist)
            if seed is not None:
                self.seed = int(seed)
            self._detectors.clear()
            self._active.clear()
            self._events = 0
            self._unattributed = 0
            self._last_event = None
            self._last_pm.clear()

    # -- observation ---------------------------------------------------------

    def observe(self, ring, host: str | None = None,
                ts: float | None = None) -> list[dict]:
        """Evaluate every watched signal against the ring; returns the
        NEW firings (empty on a quiet tick). Disabled path: one env read
        in the module-level hook."""
        if ts is None:
            # palint: allow[observability] anomaly-event STAMP — ledger
            # records and phase marks share the wall clock
            ts = time.time()
        fired: list[dict] = []
        with self._lock:
            if host:
                self._host = str(host)
            host = self._host
        for w in self.watchlist:
            value = _read(ring, w)
            if value is None:
                continue
            with self._lock:
                det = self._detectors.get(w.name)
                if det is None:
                    det = self._detectors[w.name] = _make_detector(w)
                was = det.firing
                firing = det.update(value)
                newly = firing and not was
                cleared = was and not firing
                if newly:
                    event = {
                        "signal": w.name,
                        "metric": w.metric,
                        "host": host,
                        "observed": round(float(value), 6),
                        "baseline": (None if det.baseline() is None
                                     else round(det.baseline(), 6)),
                        "z": round(getattr(det, "z", 0.0), 3),
                        "window_s": w.window_s,
                        "detector": w.detector,
                        "seed": self.seed,
                        "ts": ts,
                    }
                    self._active[w.name] = event
                elif firing:
                    self._active.get(w.name, {}).update(
                        observed=round(float(value), 6))
                elif cleared:
                    self._active.pop(w.name, None)
            if cleared:
                self._set_active_gauge(w.name, host, 0.0)
            if not newly:
                continue
            event["attributed_to"] = self._attribute(ring, w)
            event["attributed"] = bool(event["attributed_to"]["faults"]
                                       or event["attributed_to"]["phase"])
            with self._lock:
                self._events += 1
                if not event["attributed"]:
                    self._unattributed += 1
                self._last_event = event
            fired.append(event)
            self._emit(event, ring)
        return fired

    def _attribute(self, ring, w: Watch) -> dict:
        """What declared cause overlaps this firing: fault sites whose
        injection counter moved inside the signal's window, and the
        innermost open declared load phase."""
        sites = []
        try:
            for site in ring.label_values("pa_fault_injected_total", "site"):
                d = ring.delta("pa_fault_injected_total",
                               window_s=w.window_s,
                               labels={"site": site})
                if d is not None and d > 0:
                    sites.append(site)
        except Exception:
            pass
        phase = None
        try:
            phase = ring.phase_at()
        except Exception:
            pass
        return {"faults": sites, "phase": phase}

    # -- emission (lazy, best-effort — the standalone contract) --------------

    def _set_active_gauge(self, signal: str, host: str, v: float) -> None:
        try:
            from .metrics import registry

            registry.gauge("pa_anomaly_active", v,
                           labels={"signal": signal, "host": host},
                           help="1 while the sentinel's detector for this "
                                "signal is firing")
        except Exception:
            pass

    def _emit(self, event: dict, ring) -> None:
        signal, host = event["signal"], event["host"]
        self._set_active_gauge(signal, host, 1.0)
        try:
            from .metrics import registry

            registry.counter("pa_anomaly_events_total",
                             labels={"signal": signal},
                             help="anomaly firings (utils/anomaly.py)")
            if not event["attributed"]:
                registry.counter(
                    "pa_anomaly_unattributed_total",
                    labels={"signal": signal},
                    help="firings with no declared fault/phase cause — "
                         "scripts/anomaly_report.py gates on zero",
                )
        except Exception:
            pass
        try:
            from . import tracing

            if tracing.on():
                tracing.record(
                    "anomaly", tracing.now_us(), 0.0, cat="anomaly",
                    signal=signal, observed=event["observed"],
                    baseline=event["baseline"], z=event["z"],
                    attributed=event["attributed"],
                )
        except Exception:
            pass
        try:
            from . import telemetry

            telemetry.append_ledger_record(dict(event), kind="anomaly")
        except Exception:
            pass
        self._maybe_postmortem(event, ring)
        try:
            from .logging import get_logger

            get_logger().warning(
                "anomaly fired [%s] observed=%s baseline=%s z=%s "
                "attributed=%s",
                signal, event["observed"], event["baseline"], event["z"],
                event["attributed_to"],
            )
        except Exception:
            pass

    def _maybe_postmortem(self, event: dict, ring) -> None:
        """Auto-forensics, rate-limited per signal: the bundle carries the
        history window (and, when tracing is live, write_postmortem's
        trace.json already holds every in-flight prompt's spans — the
        worst one is whichever the stitched view shows still open)."""
        interval = postmortem_interval_s()
        if interval <= 0:
            return
        now = time.monotonic()
        with self._lock:
            last = self._last_pm.get(event["signal"])
            if last is not None and now - last < interval:
                return
            self._last_pm[event["signal"]] = now
        try:
            from . import telemetry

            path = telemetry.write_postmortem(
                f"anomaly-{event['signal']}",
                extra={"anomaly": event, "history": ring.window()},
            )
            if path:
                event["postmortem"] = path
        except Exception:
            pass

    # -- surfaces ------------------------------------------------------------

    def publish_gauges(self) -> None:
        """Scrape-time gauges: explicit zeros for every quiet watched
        signal (absent series read as 'never watched', not 'healthy')."""
        if not enabled():
            return
        with self._lock:
            active = set(self._active)
            host = self._host
            names = [w.name for w in self.watchlist]
        for name in names:
            self._set_active_gauge(name, host,
                                   1.0 if name in active else 0.0)

    def snapshot(self) -> dict:
        """The ``GET /health`` anomaly section."""
        with self._lock:
            out = {
                "schema": ANOMALY_SCHEMA,
                "enabled": enabled(),
                "watchlist": [w.name for w in self.watchlist],
                "active": {k: dict(v) for k, v in self._active.items()},
                "events_total": self._events,
                "unattributed_total": self._unattributed,
                "last_event": (dict(self._last_event)
                               if self._last_event else None),
            }
        try:
            from . import timeseries

            out["ring"] = timeseries.ring.stats()
        except Exception:
            out["ring"] = None
        return out


# The process-wide sentinel the history sampler ticks and /metrics
# publishes. Tests may reset() it.
sentinel = AnomalySentinel()


def observe(ring=None, host: str | None = None) -> list[dict]:
    """Module-level hook (the sampler tick): disabled path is one env
    read; ``ring`` defaults to the process-wide history ring."""
    if not enabled():
        return []
    if ring is None:
        try:
            from . import timeseries

            ring = timeseries.ring
        except Exception:
            return []
    return sentinel.observe(ring, host=host)
