"""SLO plane: declared latency objectives, window accounting, burn rates.

The reference has zero load observability — "read s/it off the progress bar"
(SURVEY §5.1) — and until round 15 this repo's loadgen was closed-loop, the
one regime where queues never blow up. The open-loop work (scripts/loadgen.py
arrival processes, fleet/twin.py) needs a vocabulary for "are we meeting our
latency objectives under real traffic"; this module is that vocabulary:

- **objective registry** (:class:`Objective` / :class:`SloRegistry`): declared
  latency objectives — "``target`` fraction of requests complete under
  ``threshold_s``, judged over ``window_s``" — from ``PA_SLO_OBJECTIVES``
  (JSON list) or :data:`DEFAULT_OBJECTIVES`. Google-SRE shaped: the error
  budget of an objective is ``1 - target``; the **burn rate** is the bad
  fraction observed in the window divided by that budget (1.0 = consuming
  budget exactly as fast as allowed; > 1 = burning toward violation).
- **stage decomposition**: every request's end-to-end latency decomposes into
  ``admission`` (HTTP ingress → worker pickup, server.py), ``lane_wait``
  (serving submit → seated, serving/bucket.py), ``eval`` (sampler-node wall,
  host.py), ``decode`` (decode-node wall, host.py) and — client-side only —
  ``collect`` (the residual: history polling + HTTP + everything the server
  cannot see; scripts/loadgen.py computes it against its own clocks). Stages
  ride the SAME measurement points the existing span vocabulary records
  (lane-wait span, workflow-node spans, the worker pickup) — one clock, two
  views, the tracing/metrics consistency rule.
- **``pa_slo_*`` metrics**: ``pa_slo_request_seconds`` (server-side request
  residency, bucket bounds aligned to the declared thresholds so verdicts
  read exactly off bucket edges — the round-15 explicit-bounds histogram),
  ``pa_slo_stage_seconds{stage=}``, and scrape-time gauges
  ``pa_slo_burn_rate{objective=}`` / ``pa_slo_budget_remaining{objective=}``
  / ``pa_slo_objective_ok{objective=}``.
- **exposition readers** (:func:`histogram_quantile`, :func:`fraction_under`,
  :func:`verdicts_from_text`): stdlib parsers over Prometheus text, so the
  fleet router can judge objectives over a MERGED multi-host scrape
  (``GET /fleet/slo``) and loadgen can read server-side stage quantiles —
  the scraped twins of the in-process reads.

Flag discipline: ``PA_SLO=0`` disables observation and gauge publication
entirely (the tracer/sentinel/roofline pattern — a tier-1-tested no-op; the
disabled path is one env read per call site).
Import discipline: module level is stdlib-only and free of package-relative
imports, so ``scripts/loadgen.py`` and ``scripts/twin_report.py`` load this
file standalone (no jax, runs over a wedged tunnel); utils/metrics.py loads
lazily inside functions and every metrics write is best-effort.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
from collections import deque

SLO_SCHEMA = "pa-slo/v1"

# The stages of a request's end-to-end latency (ISSUE 11 decomposition;
# round 17 adds "encode" — the text-encode node wall the embed cache
# collapses — and "decode_wait", the batched-decode queue wait, a sub-stage
# of the decode node wall). "collect" is client-side residual only — servers
# never observe it directly.
STAGES = ("admission", "encode", "lane_wait", "eval", "decode_wait",
          "decode", "collect")

# Stage histograms keep sub-millisecond resolution at the bottom (a healthy
# admission wait on an idle host is ~0) and minutes at the top (a saturated
# open-loop queue) — the metrics.py default ladder, restated here so the
# standalone loaders agree with the in-process registry.
STAGE_BOUNDS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
)


def enabled() -> bool:
    """The PA_SLO flag (default on; observation is one histogram write and
    one bounded-deque append per request — the tracer's cheap-path rule)."""
    return os.environ.get("PA_SLO", "") not in ("0", "false")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declared latency objective: ``target`` fraction of requests must
    complete under ``threshold_s``, judged over a sliding ``window_s``."""

    name: str
    threshold_s: float
    target: float = 0.95
    window_s: float = 3600.0

    @property
    def budget(self) -> float:
        """The error budget: the bad fraction the objective tolerates."""
        return max(1e-9, 1.0 - float(self.target))


# The default objective set: conservative enough that an unconfigured CPU
# smoke run doesn't page anyone, tight enough that a saturated open-loop
# queue (p95 blowing past half a minute) reads as burning.
DEFAULT_OBJECTIVES: tuple[Objective, ...] = (
    Objective(name="request_under_30s", threshold_s=30.0, target=0.95),
)


def parse_objectives(raw) -> list[Objective]:
    """Objectives from the ``PA_SLO_OBJECTIVES`` JSON value (a list of
    ``{"name", "threshold_s", "target", "window_s"}`` objects). Malformed
    input raises ValueError at parse — a typo'd objective must fail loudly,
    never silently observe nothing (the faults.py plan rule)."""
    if isinstance(raw, str):
        try:
            raw = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(f"PA_SLO_OBJECTIVES is not JSON: {e}") from e
    if not isinstance(raw, list):
        raise ValueError(
            f"PA_SLO_OBJECTIVES must be a JSON list, got {type(raw).__name__}"
        )
    out: list[Objective] = []
    for i, e in enumerate(raw):
        if not isinstance(e, dict) or "name" not in e or "threshold_s" not in e:
            raise ValueError(
                f"objective {i} must be an object with 'name' and "
                f"'threshold_s': {e!r}"
            )
        out.append(Objective(
            name=str(e["name"]),
            threshold_s=float(e["threshold_s"]),
            target=float(e.get("target", 0.95)),
            window_s=float(e.get("window_s", 3600.0)),
        ))
    return out


def objectives_from_env(env=os.environ) -> list[Objective]:
    raw = env.get("PA_SLO_OBJECTIVES")
    if not raw:
        return list(DEFAULT_OBJECTIVES)
    return parse_objectives(raw)


def request_bounds(objectives) -> tuple[float, ...]:
    """The ``pa_slo_request_seconds`` bucket ladder: the default log-spaced
    bounds with every declared threshold inserted as an exact bucket edge —
    so ``fraction_under(threshold)`` is a bucket read, not an interpolation
    (the round-15 explicit-bounds histogram satellite's reason to exist)."""
    bounds = set(STAGE_BOUNDS)
    for o in objectives:
        bounds.add(float(o.threshold_s))
    return tuple(sorted(bounds))


class SloRegistry:
    """Objective accounting + the ``pa_slo_*`` emission points. Thread-safe:
    server workers observe concurrently; /metrics scrapes publish gauges.

    Window accounting is a bounded per-objective deque of
    ``(monotonic_ts, ok)`` events — O(1) per observation, trimmed lazily at
    read time; the bound (:data:`MAX_EVENTS`) caps memory on a busy host at
    the cost of the window shrinking to the last N requests (noted in the
    verdict as ``window_clipped``)."""

    MAX_EVENTS = 65536

    def __init__(self, objectives: list[Objective] | None = None):
        self._lock = threading.Lock()
        # guarded-by: _lock (both: replaced/extended wholesale under it)
        self._objectives = list(
            objectives if objectives is not None else objectives_from_env()
        )
        self._events: dict[str, deque] = {  # guarded-by: _lock
            o.name: deque(maxlen=self.MAX_EVENTS) for o in self._objectives
        }
        # The threshold-aligned ladder, computed once per objective set —
        # the histogram only reads bounds at its first touch anyway, and
        # the hot path must not rebuild/sort it per request under the lock.
        self._bounds = request_bounds(self._objectives)

    # -- declaration ---------------------------------------------------------

    def objectives(self) -> list[Objective]:
        with self._lock:
            return list(self._objectives)

    def declare(self, objective: Objective) -> None:
        """Add/replace one objective (tests, programmatic config)."""
        with self._lock:
            self._objectives = [
                o for o in self._objectives if o.name != objective.name
            ] + [objective]
            self._events.setdefault(
                objective.name, deque(maxlen=self.MAX_EVENTS)
            )
            self._bounds = request_bounds(self._objectives)

    def reset(self, objectives: list[Objective] | None = None) -> None:
        with self._lock:
            self._objectives = list(
                objectives if objectives is not None else objectives_from_env()
            )
            self._events = {
                o.name: deque(maxlen=self.MAX_EVENTS)
                for o in self._objectives
            }
            self._bounds = request_bounds(self._objectives)

    # -- observation ---------------------------------------------------------

    def observe_request(self, seconds: float) -> None:
        """One request's server-side end-to-end residency (admission wait +
        execution): feeds the threshold-aligned histogram and every
        objective's window."""
        s = float(seconds)
        now = time.monotonic()
        with self._lock:
            bounds = self._bounds
            for o in self._objectives:
                self._events[o.name].append((now, s <= o.threshold_s))
        _histogram("pa_slo_request_seconds", s, bounds=bounds,
                   help="server-side request residency (admission + exec) — "
                        "bucket edges aligned to declared SLO thresholds")

    def observe_stage(self, stage: str, seconds: float) -> None:
        """One stage sample of a request's latency decomposition."""
        _histogram("pa_slo_stage_seconds", float(seconds),
                   labels={"stage": str(stage)}, bounds=STAGE_BOUNDS,
                   help="per-stage latency decomposition (admission/encode/"
                        "lane_wait/eval/decode_wait/decode)")

    # -- window math ---------------------------------------------------------

    def _window(self, o: Objective, now: float) -> tuple[int, int, bool]:
        """(n, bad, clipped) over the objective's window. Caller holds the
        lock; expired events are trimmed from the left."""
        ev = self._events.get(o.name)
        if ev is None:
            return 0, 0, False
        clipped = len(ev) == ev.maxlen
        cutoff = now - o.window_s
        while ev and ev[0][0] < cutoff:
            ev.popleft()
        n = len(ev)
        bad = sum(1 for _, ok in ev if not ok)
        return n, bad, clipped

    def verdicts(self) -> list[dict]:
        """One verdict per objective: the window's bad fraction, burn rate
        (bad fraction / error budget), remaining budget fraction, and the
        ok bit (burn rate ≤ 1 — within budget). An empty window is vacuously
        ok with burn rate 0 (no traffic burns no budget)."""
        now = time.monotonic()
        out: list[dict] = []
        with self._lock:
            for o in self._objectives:
                n, bad, clipped = self._window(o, now)
                bad_fraction = bad / n if n else 0.0
                # Rounded before the ok comparison: 1 - 0.9 is 0.0999…8 in
                # floats, and "burning exactly at the allowed rate" must
                # read as ok, not as a 1e-16 violation.
                burn = round(bad_fraction / o.budget, 9)
                out.append({
                    "name": o.name,
                    "threshold_s": o.threshold_s,
                    "target": o.target,
                    "window_s": o.window_s,
                    "requests": n,
                    "bad": bad,
                    "bad_fraction": round(bad_fraction, 6),
                    "burn_rate": round(burn, 4),
                    "budget_remaining": round(max(0.0, 1.0 - burn), 4),
                    "ok": burn <= 1.0,
                    "window_clipped": clipped,
                })
        return out

    def burn_rate(self, name: str) -> float | None:
        for v in self.verdicts():
            if v["name"] == name:
                return v["burn_rate"]
        return None

    # -- surfaces ------------------------------------------------------------

    def publish_gauges(self) -> None:
        """Scrape-time gauges (the server's ``GET /metrics``): burn rate,
        remaining budget, and the ok bit per objective. No-op when PA_SLO=0
        or metrics is absent (standalone load)."""
        if not enabled():
            return
        for v in self.verdicts():
            labels = {"objective": v["name"]}
            _gauge("pa_slo_burn_rate", v["burn_rate"], labels,
                   help="window bad-fraction / error budget (1.0 = burning "
                        "exactly at the allowed rate)")
            _gauge("pa_slo_budget_remaining", v["budget_remaining"], labels,
                   help="fraction of the error budget left in the window")
            _gauge("pa_slo_objective_ok", 1.0 if v["ok"] else 0.0, labels,
                   help="1 = the objective is within budget over its window")

    def snapshot(self) -> dict:
        return {"schema": SLO_SCHEMA, "enabled": enabled(),
                "objectives": self.verdicts()}


# The process-wide registry every instrumentation site writes to. Tests may
# reset() it (objectives re-read from the env).
registry = SloRegistry()


def observe_request(seconds: float) -> None:
    """Module-level hook (server.py worker): disabled path is one env read."""
    if not enabled():
        return
    registry.observe_request(seconds)


def observe_stage(stage: str, seconds: float) -> None:
    """Module-level hook (server/bucket/host stage sites)."""
    if not enabled():
        return
    registry.observe_stage(stage, seconds)


# ---------------------------------------------------------------------------
# best-effort metrics emission (lazy — this module must load standalone)
# ---------------------------------------------------------------------------


def _histogram(name, value, labels=None, bounds=None, help="") -> None:
    try:
        from .metrics import registry as _metrics
    except Exception:
        return
    try:
        _metrics.histogram(name, value, labels=labels, bounds=bounds,
                           help=help)
    except Exception:
        pass


def _gauge(name, value, labels=None, help="") -> None:
    try:
        from .metrics import registry as _metrics
    except Exception:
        return
    try:
        _metrics.gauge(name, value, labels=labels, help=help)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Prometheus-text readers (the scraped twins — loadgen, router /fleet/slo)
# ---------------------------------------------------------------------------


def _series_bucket_counts(text: str, name: str,
                          labels: dict | None = None) -> list[dict[str, float]]:
    """Per-SERIES cumulative ``_bucket`` counts by ``le``, one dict per
    distinct non-``le`` label set matching ``labels`` (each k="v" pair must
    appear in the line's label block). Kept per series so readers can
    handle mixed bucket ladders (two hosts with different declared
    objectives) correctly — summing cumulative counts across different
    ladders produces non-monotone garbage at edges only one host has."""
    need = [f'{k}="{v}"' for k, v in (labels or {}).items()]
    series: dict[str, dict[str, float]] = {}
    for m in re.finditer(
        rf'^{re.escape(name)}_bucket\{{([^}}]*)\}} ([0-9.eE+-]+)$',
        text, re.M,
    ):
        lbl = m.group(1)
        if any(pair not in lbl for pair in need):
            continue
        le = re.search(r'le="([^"]+)"', lbl)
        if le is None:
            continue
        key = re.sub(r'(^|,)le="[^"]*"', "", lbl)
        by_le = series.setdefault(key, {})
        by_le[le.group(1)] = by_le.get(le.group(1), 0.0) + float(m.group(2))
    return list(series.values())


def _bucket_counts(text: str, name: str,
                   labels: dict | None = None) -> dict[str, float]:
    """Cumulative ``_bucket`` counts by ``le``, merged across every label set
    matching ``labels``. Sound when the matching series share one bucket
    ladder (cumulative counts add per ``le``) — which every
    MetricsRegistry histogram of one metric name guarantees within a
    process, and fleets sharing one objective config guarantee across
    hosts; mixed-ladder readers must use :func:`_series_bucket_counts`."""
    by_le: dict[str, float] = {}
    for s in _series_bucket_counts(text, name, labels):
        for le, c in s.items():
            by_le[le] = by_le.get(le, 0.0) + c
    return by_le


def histogram_quantile(text: str, name: str, q: float,
                       labels: dict | None = None) -> float | None:
    """Quantile from a histogram's exposition, merged across matching label
    sets — linear interpolation within the target bucket (the same estimate
    ``MetricsRegistry.quantile`` computes in-process)."""
    by_le = _bucket_counts(text, name, labels)
    if not by_le:
        return None
    finite = sorted(
        (float(le), c) for le, c in by_le.items() if le != "+Inf"
    )
    total = by_le.get("+Inf", finite[-1][1] if finite else 0.0)
    if total <= 0:
        return None
    target = q / 100.0 * total
    lo = 0.0
    prev_cum = 0.0
    for le, cum in finite:
        if cum >= target and cum > prev_cum:
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + (le - lo) * min(1.0, max(0.0, frac))
        lo, prev_cum = le, cum
    return lo  # +Inf bucket: clamp to the last finite bound


def _series_under(by_le: dict[str, float],
                  threshold_s: float) -> tuple[float, float] | None:
    """(count ≤ threshold, total) for ONE series' cumulative buckets.
    Exact when the threshold is a bucket edge (the :func:`request_bounds`
    alignment); linear interpolation within the covering bucket otherwise
    (a mixed-version host with the default ladder)."""
    finite = sorted(
        (float(le), c) for le, c in by_le.items() if le != "+Inf"
    )
    total = by_le.get("+Inf", finite[-1][1] if finite else 0.0)
    if total <= 0:
        return None
    t = float(threshold_s)
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in finite:
        if t < le:
            if le > prev_le:
                frac_in = (t - prev_le) / (le - prev_le)
                est = prev_cum + (cum - prev_cum) * max(0.0, min(1.0, frac_in))
            else:
                est = cum
            return min(total, est), total
        prev_le, prev_cum = le, cum
        if t == le:
            return min(total, cum), total
    return min(total, prev_cum), total


def fraction_under(text: str, name: str, threshold_s: float,
                   labels: dict | None = None) -> tuple[float, float] | None:
    """(fraction of observations ≤ threshold, total count) from a
    histogram's exposition. Evaluated PER SERIES and aggregated by count —
    each series interpolates on its OWN bucket ladder, so a merged
    multi-host scrape with heterogeneous ladders (hosts declaring
    different objectives) still answers correctly. None when the histogram
    is absent or empty."""
    under_total = 0.0
    count_total = 0.0
    for by_le in _series_bucket_counts(text, name, labels):
        got = _series_under(by_le, threshold_s)
        if got is None:
            continue
        under, total = got
        under_total += under
        count_total += total
    if count_total <= 0:
        return None
    return min(1.0, under_total / count_total), count_total


def verdicts_from_text(text: str, objectives: list[Objective],
                       labels: dict | None = None) -> list[dict]:
    """Objective verdicts judged over a (possibly multi-host merged)
    Prometheus scrape's ``pa_slo_request_seconds`` — the router's
    ``GET /fleet/slo`` view. Exposition histograms are cumulative (process
    lifetime), so these verdicts judge ALL observed traffic, not a sliding
    window — the burn-rate gauges carry the windowed view; the merged
    fraction is the fleet-lifetime achievement."""
    out: list[dict] = []
    for o in objectives:
        got = fraction_under(text, "pa_slo_request_seconds", o.threshold_s,
                             labels=labels)
        if got is None:
            out.append({
                "name": o.name, "threshold_s": o.threshold_s,
                "target": o.target, "requests": 0,
                "achieved_fraction": None, "ok": None,
            })
            continue
        fraction, total = got
        bad_fraction = 1.0 - fraction
        burn = bad_fraction / o.budget
        out.append({
            "name": o.name,
            "threshold_s": o.threshold_s,
            "target": o.target,
            "requests": int(total),
            "achieved_fraction": round(fraction, 6),
            "burn_rate": round(burn, 4),
            "ok": fraction >= o.target,
        })
    return out
