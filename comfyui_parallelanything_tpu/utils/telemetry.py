"""Resource telemetry: compile accounting, HBM watermarks, the perf ledger,
and failure postmortem bundles.

PR 3's span tracing answered "where did the time go"; this module answers the
other two production questions — "where did the bytes and compiles go" and
"did we regress":

- **Compile observability**: :class:`CompileRegistry` accounts every XLA
  compile in the process, per *program* (a stable human-readable name each
  instrumented jit site declares — ``loop:k:euler``, ``stream-stage[0:3)``,
  ``parallel-apply``). :func:`watch_compiles` registers ``jax.monitoring``
  listeners for backend-compile durations and persistent-cache hit/miss
  events; :func:`instrument_jit` wraps ``jax.jit`` so compiles occurring
  inside a program's calls attribute to that program, records a ``compile``
  span (utils/tracing.py) per compile, feeds ``pa_compile_*`` metrics, and —
  on a program's first compile — runs HLO ``cost_analysis()`` on the lowered
  program so the registry carries FLOPs/bytes-accessed per executable, and
  feeds the same analysis through ``utils/roofline.observe_program`` so every
  named program also carries a calibrated analytic time prediction
  (``pa_roofline_predicted_s``, the ``roofline`` health section).
- **Device memory telemetry**: :class:`HbmWatermark` (peak
  ``bytes_in_use`` across snapshots — the ``peak_hbm_bytes`` every bench
  line and ledger record carries) and :class:`MemoryMonitor` (the server's
  periodic sampler) over ``devices.memory.memory_snapshot``, whose CPU
  fallback is deterministic so off-hardware tests can assert the math.
- **Perf ledger**: every bench/dryrun/loadgen run appends one
  schema-versioned JSONL record to ``ledger/perf_ledger.jsonl``
  (:func:`append_ledger_record`); ``scripts/perf_ledger.py`` diffs the latest
  record per (rung, platform) against the banked evidence and exits nonzero
  on a step-time or peak-HBM regression — the CI regression gate.
- **Failure forensics**: :func:`write_postmortem` dumps a bundle (trace ring
  export, metrics snapshot, per-device memory stats, recent log records,
  error + traceback) into ``ledger/postmortem/<stamp>-<tag>/`` so the next
  flux_stream OOM over the flaky tunnel is diagnosable after the fact.

Import discipline: this module imports only stdlib at module level — jax,
metrics, tracing, and devices.memory all load lazily inside functions — so
outer/driver processes can reason about the schema without touching jax
(they still must not import it through the package ``__init__``; bench.py's
outer process carries its own stdlib ledger-append twin for that reason).
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
import traceback as _traceback

LEDGER_SCHEMA = "pa-perf-ledger/v1"
# v2 (fleet tier): adds top-level host_id / accepting / inflight_prompts —
# the fields a fleet router's scoreboard needs for placement and drain
# decisions without any extra endpoint. v1 consumers are unaffected: the
# additions are top-level keys, every v1 field is unchanged.
HEALTH_SCHEMA = "pa-health/v3"  # v3 adds host warm_keys; every v2 field intact
LEDGER_FILENAME = "perf_ledger.jsonl"

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "Resource exhausted")


def looks_like_oom(err) -> bool:
    """Heuristic OOM classifier over an exception (or its string) — the same
    marker set scripts/tpu_watchdog.py matches on failure records."""
    text = f"{type(err).__name__}: {err}" if isinstance(err, BaseException) \
        else str(err)
    return any(m in text for m in _OOM_MARKERS)


def _loadavg_1m() -> float | None:
    try:
        return round(os.getloadavg()[0], 2)
    except (AttributeError, OSError):
        return None


# ---------------------------------------------------------------------------
# compile observability
# ---------------------------------------------------------------------------


class CompileRegistry:
    """Process-wide per-program compile accounting.

    Attribution is thread-local: an :class:`instrument_jit` wrapper pushes its
    program name around each call, and the jax.monitoring listeners charge
    whatever compile/cache events fire during that call to the innermost
    program on the calling thread's stack (``(unattributed)`` otherwise —
    library-internal jits like ``device_put`` land there)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._local = threading.local()
        # program name -> {"compiles", "compile_time_s", "cache_hits",
        #                  "cache_misses", "flops", "bytes_accessed"}
        self._programs: dict[str, dict] = {}  # guarded-by: _lock
        self._totals = {  # guarded-by: _lock
            "compiles": 0, "compile_time_s": 0.0,
            "cache_hits": 0, "cache_misses": 0,
        }

    # -- attribution --------------------------------------------------------

    def _stack(self) -> list:
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
        return s

    def push_program(self, name: str) -> None:
        self._stack().append(name)

    def pop_program(self) -> None:
        s = self._stack()
        if s:
            s.pop()

    def current_program(self) -> str | None:
        s = self._stack()
        return s[-1] if s else None

    def _prog(self, name: str) -> dict:  # palint: holds _lock
        p = self._programs.get(name)
        if p is None:
            p = self._programs[name] = {
                "compiles": 0, "compile_time_s": 0.0,
                "cache_hits": 0, "cache_misses": 0,
                "flops": None, "bytes_accessed": None,
            }
        return p

    # -- event sinks (called from the jax.monitoring listeners) -------------

    def on_compile(self, dur_s: float) -> None:
        name = self.current_program() or "(unattributed)"
        with self._lock:
            self._totals["compiles"] += 1
            self._totals["compile_time_s"] += dur_s
            p = self._prog(name)
            p["compiles"] += 1
            p["compile_time_s"] += dur_s
        # Side channels outside the lock; both are no-ops when their layer is
        # off, and neither may ever break a compiling caller.
        try:
            from .metrics import registry

            registry.counter("pa_compile_total", labels={"program": name},
                             help="XLA backend compiles per program")
            registry.observe("pa_compile_seconds", dur_s,
                             labels={"program": name},
                             help="XLA backend compile wall time")
        except Exception:
            pass
        try:
            from . import tracing

            tracing.record(
                "compile", tracing.now_us() - dur_s * 1e6, dur_s * 1e6,
                cat="compile", program=name,
            )
        except Exception:
            pass

    def on_cache_event(self, hit: bool) -> None:
        key = "cache_hits" if hit else "cache_misses"
        name = self.current_program() or "(unattributed)"
        with self._lock:
            self._totals[key] += 1
            self._prog(name)[key] += 1
        try:
            from .metrics import registry

            registry.counter(f"pa_compile_{key}_total",
                             labels={"program": name},
                             help="persistent compilation cache "
                                  + ("hits" if hit else "misses"))
        except Exception:
            pass

    def record_cost(self, name: str, flops: float | None,
                    bytes_accessed: float | None) -> None:
        with self._lock:
            p = self._prog(name)
            if flops:
                p["flops"] = float(flops)
            if bytes_accessed:
                p["bytes_accessed"] = float(bytes_accessed)

    # -- read side ----------------------------------------------------------

    def compiles_of(self, name: str) -> int:
        with self._lock:
            p = self._programs.get(name)
            return p["compiles"] if p else 0

    def snapshot(self) -> dict:
        """Totals + per-program breakdown — the ``compile`` section of
        ``GET /health`` and the source of every bench line's
        ``compile_time_s`` / ``compile_cache_hits`` / ``compile_cache_misses``
        fields."""
        with self._lock:
            return {
                "compiles": self._totals["compiles"],
                "compile_time_s": round(self._totals["compile_time_s"], 4),
                "cache_hits": self._totals["cache_hits"],
                "cache_misses": self._totals["cache_misses"],
                "programs": {
                    n: dict(p) for n, p in sorted(self._programs.items())
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()
            self._totals = {
                "compiles": 0, "compile_time_s": 0.0,
                "cache_hits": 0, "cache_misses": 0,
            }


compile_registry = CompileRegistry()

_watch_installed = False
_watch_lock = threading.Lock()


def _on_event_duration(event: str, duration: float, **_kw) -> None:
    # jax 0.4.x: '/jax/core/compile/backend_compile_duration'. Substring
    # match keeps this robust across the key's historical renames.
    if "backend_compile" in event:
        compile_registry.on_compile(float(duration))


def _on_event(event: str, **_kw) -> None:
    if event.endswith("/cache_hits"):
        compile_registry.on_cache_event(True)
    elif event.endswith("/cache_misses"):
        compile_registry.on_cache_event(False)


def watch_compiles() -> None:
    """Idempotently register the jax.monitoring listeners that feed
    :data:`compile_registry`. Listeners are process-global and permanent
    (jax offers no per-listener removal) but do nothing beyond dict updates,
    so installing them once at startup is free."""
    global _watch_installed
    if _watch_installed:  # lock-free fast path: called per instrumented jit
        return            # dispatch, so the mutex must not be in the hot path
    with _watch_lock:
        if _watch_installed:
            return
        import jax.monitoring as monitoring

        monitoring.register_event_duration_secs_listener(_on_event_duration)
        monitoring.register_event_listener(_on_event)
        _watch_installed = True


def compile_snapshot() -> dict:
    return compile_registry.snapshot()


class _InstrumentedJit:
    """``jax.jit`` plus per-program compile attribution. Call-compatible with
    the jitted callable it wraps; the per-call overhead when nothing compiles
    is two thread-local list ops and one dict read."""

    __slots__ = ("name", "_jit", "_cost_done")

    def __init__(self, fn, name: str, **jit_kwargs):
        import jax

        self.name = name
        self._jit = jax.jit(fn, **jit_kwargs)
        self._cost_done = False

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        watch_compiles()
        reg = compile_registry
        if not self._cost_done:
            # Fault site (utils/faults.py): an injected compile failure fires
            # before this program's FIRST observed compile, so the
            # compile→eager degradation rung (utils/degrade.py) is rehearsed
            # against the same callers a real XLA lowering error would hit.
            from . import faults

            act = faults.check("compile-fail", key=self.name)
            if act is not None:
                raise RuntimeError(
                    f"injected compile failure (program={self.name}, "
                    f"hit={act.hit})"
                )
        n0 = reg.compiles_of(self.name) if not self._cost_done else 0
        reg.push_program(self.name)
        try:
            out = self._jit(*args, **kwargs)
        finally:
            reg.pop_program()
        if not self._cost_done and reg.compiles_of(self.name) > n0:
            # First observed compile for this program: attach HLO cost
            # analysis (FLOPs / bytes accessed) from a lowering over abstract
            # avals — never the concrete buffers, which a donating program
            # may already have invalidated.
            self._cost_done = True
            self._analyze_cost(args, kwargs)
        return out

    def _analyze_cost(self, args, kwargs) -> None:
        if os.environ.get("PA_TELEMETRY_COST") == "0":
            return
        try:
            import jax

            def leaf(l):
                if isinstance(l, jax.core.Tracer):
                    raise _SkipCost  # nested trace: avals aren't concrete
                if hasattr(l, "shape") and hasattr(l, "dtype"):
                    return jax.ShapeDtypeStruct(l.shape, l.dtype)
                return l

            abs_args, abs_kwargs = jax.tree.map(leaf, (args, kwargs))
            cost = self._jit.lower(*abs_args, **abs_kwargs).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            cost = cost or {}
            compile_registry.record_cost(
                self.name, cost.get("flops"), cost.get("bytes accessed")
            )
            # Roofline prediction (utils/roofline.py): the same first-compile
            # cost analysis priced against the platform's analytic roofline —
            # mesh width/platform read off the CONCRETE args' shardings. Its
            # own flag (PA_ROOFLINE) and its own try/except: a broken
            # prediction must not cost the compile registry its FLOPs row.
            try:
                from . import roofline

                roofline.observe_program(
                    self.name, flops=cost.get("flops"),
                    bytes_accessed=cost.get("bytes accessed"),
                    args=(args, kwargs),
                )
            except Exception:
                pass
        except Exception:
            pass  # accounting must never break the program it accounts


class _SkipCost(Exception):
    pass


def instrument_jit(fn, name: str, **jit_kwargs) -> _InstrumentedJit:
    """The drop-in replacement for ``jax.jit`` at the repo's program-cache
    sites (sampling/compiled.py, parallel/{pipeline,streaming,orchestrator},
    models/api.py): same callable contract, compiles attributed to ``name``
    in :data:`compile_registry`."""
    return _InstrumentedJit(fn, name, **jit_kwargs)


# ---------------------------------------------------------------------------
# device memory telemetry
# ---------------------------------------------------------------------------


class HbmWatermark:
    """Peak device-memory watermark over explicit samples.

    ``sample()`` snapshots every device (``devices.memory.memory_snapshot``
    — deterministic CPU fallback included) and folds the max per-device
    ``bytes_in_use`` into ``peak_bytes``. bench.py samples per timed
    iteration, the streaming runner per stage (traced runs), the server's
    :class:`MemoryMonitor` periodically."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.peak_bytes = 0
        self.samples = 0
        self.last: list[dict] | None = None

    def sample(self, devices=None) -> list[dict]:
        from ..devices.memory import memory_snapshot

        snap = memory_snapshot(devices)
        # Fold in the backend's own peak_bytes_in_use where it exposes one:
        # transient within-step spikes (activation peaks between our samples)
        # are exactly what the watermark exists to catch, and the allocator's
        # running peak sees them when instantaneous bytes_in_use cannot. It
        # is process-lifetime monotone, so reset() cannot lower it — fresh
        # bench children start clean, which is where the number is banked.
        peak = max(
            (max(s["bytes_in_use"], s.get("peak_bytes_in_use") or 0)
             for s in snap),
            default=0,
        )
        with self._lock:
            self.peak_bytes = max(self.peak_bytes, peak)
            self.samples += 1
            self.last = snap
        try:
            from .metrics import registry

            registry.gauge("pa_hbm_peak_bytes", self.peak_bytes,
                           help="max per-device bytes_in_use observed this "
                                "run (the peak_hbm_bytes watermark)")
        except Exception:
            pass
        return snap

    def reset(self) -> None:
        with self._lock:
            self.peak_bytes = 0
            self.samples = 0
            self.last = None


watermark = HbmWatermark()


class MemoryMonitor:
    """Periodic HBM sampler (daemon thread): feeds the watermark and the
    ``pa_hbm_*`` gauges so ``GET /health`` / ``GET /metrics`` stay fresh
    between requests. Errors are swallowed — a flapping tunnel device must
    never take the serving host down with it."""

    def __init__(self, interval_s: float = 60.0):
        self.interval_s = max(1.0, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="pa-memory-monitor", daemon=True
        )

    def start(self) -> "MemoryMonitor":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                from ..devices.memory import publish_memory_gauges

                publish_memory_gauges()
                watermark.sample()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# perf ledger
# ---------------------------------------------------------------------------


def ledger_dir() -> str:
    """``$PA_LEDGER_DIR`` > ``$PA_EVIDENCE_DIR/ledger`` (so mocked/dry runs
    redirect their ledger with their evidence) > ``<repo>/ledger`` — the repo
    root, never cwd: every reader (scripts/perf_ledger.py, the watchdog,
    bench's outer append) resolves there, and a record written to whatever
    directory the operator launched the server from would be invisible to
    the gate."""
    override = os.environ.get("PA_LEDGER_DIR")
    if override:
        return override
    evidence = os.environ.get("PA_EVIDENCE_DIR")
    if evidence:
        return os.path.join(evidence, "ledger")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    return os.path.join(repo, "ledger")


def ledger_path() -> str:
    return os.path.join(ledger_dir(), LEDGER_FILENAME)


def append_ledger_record(record: dict, kind: str) -> str | None:
    """Append one schema-versioned record to the perf ledger; returns the
    ledger file path, or None when the append failed (best-effort by
    contract — a full disk must not kill the run it accounts).

    ``kind``: ``bench`` (a measured bench.py line), ``dryrun``
    (dryrun_multichip), ``loadgen`` (scripts/loadgen.py summary), ``error``
    (a failed attempt — never compared by the regression gate)."""
    rec = dict(record)
    rec["schema"] = LEDGER_SCHEMA
    rec["kind"] = kind
    # palint: allow[observability] ledger epoch STAMP, not a duration
    rec.setdefault("ts", time.time())
    try:
        rec.setdefault("host", socket.gethostname())
    except OSError:
        pass
    rec.setdefault("pid", os.getpid())
    path = ledger_path()
    # Slow-disk fault site (utils/faults.py): the sleep sits inside the
    # timed region so an injected fsync stall lands in
    # pa_disk_append_seconds{target=ledger} — the anomaly sentinel's
    # disk_append_p95 watch reads exactly this histogram.
    try:
        from . import faults
        slow = faults.check("slow-disk", key="ledger")
    except Exception:
        slow = None
    t0 = time.perf_counter()
    try:
        if slow is not None:
            slow.sleep()
        os.makedirs(ledger_dir(), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        path = None
    try:
        from .metrics import registry
        registry.histogram("pa_disk_append_seconds",
                           time.perf_counter() - t0,
                           labels={"target": "ledger"},
                           help="journal/ledger append wall time")
    except Exception:
        pass
    return path


# ---------------------------------------------------------------------------
# health snapshot (GET /health)
# ---------------------------------------------------------------------------


def health_snapshot(queue: dict | None = None,
                    host: dict | None = None) -> dict:
    """One JSON-able view of the process's resource state: devices, per-device
    HBM (+ utilization), peak watermark, compile/cache accounting, load
    average — the fields the watchdog attaches to failed-attempt notes and
    ``GET /health`` serves. Every section degrades to None independently (a
    wedged device backend must not blank the host-side sections). ``host``
    merges the pa-health/v3 fleet fields (host_id, accepting,
    inflight_prompts) top-level — the server passes its own identity/drain
    state; standalone callers (watchdog notes) omit it."""
    out: dict = {
        "schema": HEALTH_SCHEMA,
        # palint: allow[observability] health-document epoch STAMP
        "ts": time.time(),
        "loadavg_1m": _loadavg_1m(),
    }
    if host:
        out.update(host)
    try:
        from ..devices.discovery import available_devices

        out["devices"] = available_devices()
    except Exception:
        out["devices"] = None
    try:
        from ..devices.memory import memory_snapshot

        hbm = memory_snapshot()
        out["hbm"] = hbm
        utils = [s["utilization"] for s in hbm if s.get("utilization") is not None]
        out["hbm_utilization_max"] = max(utils) if utils else None
    except Exception:
        out["hbm"] = None
        out["hbm_utilization_max"] = None
    out["peak_hbm_bytes"] = watermark.peak_bytes or None
    out["compile"] = compile_snapshot()
    try:
        # Roofline attribution (utils/roofline.py): per-program calibrated
        # predictions priced from the compile registry's cost analysis —
        # the cost table the auto-parallel planner reads.
        from . import roofline

        out["roofline"] = roofline.programs.snapshot()
    except Exception:
        out["roofline"] = None
    try:
        # Auto-parallel planner (parallel/planner.py, round 18): the
        # process's last plan decision — chosen vs shadow hand plan,
        # divergence/win counters — the /health section the acceptance
        # gate and a capacity planner read routing decisions from.
        from ..parallel import planner

        out["plan"] = planner.snapshot()
    except Exception:
        out["plan"] = None
    try:
        # Numerics sentinel (utils/numerics.py): flag state, non-finite
        # event / quarantined-lane totals, last event, and the fingerprint
        # gate's last verdict (scripts/numerics_audit.py).
        from . import numerics

        out["numerics"] = numerics.sentinel.snapshot()
    except Exception:
        out["numerics"] = None
    try:
        # Cross-request compute reuse (round 17): the content-addressed
        # embed cache's hit/byte accounting (models/embed_cache.py) and the
        # batched decode tail's occupancy (serving/decode.py) — the /health
        # section a capacity planner reads the redundancy win from.
        from ..models.embed_cache import cache as _embed_cache
        from ..serving.decode import get_decode_queue as _get_dq
        from ..serving.scheduler import get_scheduler as _get_sched

        dq = _get_dq()
        sched = _get_sched()
        out["reuse"] = {
            "embed_cache": _embed_cache.stats(),
            "decode": dq.stats() if dq is not None else None,
            "serving": sched.reuse_stats() if sched is not None else None,
        }
    except Exception:
        out["reuse"] = None
    try:
        # Anomaly sentinel (utils/anomaly.py, round 22): active/fired
        # signal counts, the last event, and the history ring's budget —
        # the /health section the ops console and chaos verdicts read.
        from . import anomaly

        out["anomaly"] = anomaly.sentinel.snapshot()
    except Exception:
        out["anomaly"] = None
    if queue is not None:
        out["queue"] = queue
    return out


# ---------------------------------------------------------------------------
# failure postmortem bundles (the flight recorder's dump)
# ---------------------------------------------------------------------------


def write_postmortem(tag: str, error: BaseException | None = None,
                     extra: dict | None = None,
                     out_dir: str | None = None) -> str | None:
    """Dump a postmortem bundle and return its directory, or None when even
    creating the directory failed. Each artifact writes independently — a
    dead device backend loses ``memory.json``, never the trace or the logs.

    Layout (``<ledger>/postmortem/<UTC stamp>-<tag>/``):

    - ``error.json``   — tag, error type/message, traceback, loadavg, the
      compile snapshot, peak watermark, caller extras
    - ``trace.json``   — the span tracer's Chrome/Perfetto export (whatever
      the ring buffers still hold)
    - ``metrics.prom`` — the full Prometheus exposition at failure time
    - ``memory.json``  — per-device memory stats + watermark
    - ``logs.txt``     — the last K log records (utils/logging.py ring)
    """
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", tag)[:80] or "failure"
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
    base = out_dir or os.path.join(ledger_dir(), "postmortem")
    path = os.path.join(base, f"{stamp}-{safe}")
    try:
        suffix = 1
        while os.path.exists(path):
            suffix += 1
            path = os.path.join(base, f"{stamp}-{safe}-{suffix}")
        os.makedirs(path)
    except OSError:
        return None

    def dump(filename: str, producer) -> None:
        try:
            payload = producer()
            with open(os.path.join(path, filename), "w") as f:
                if isinstance(payload, str):
                    f.write(payload)
                else:
                    json.dump(payload, f, indent=1, default=str)
        except Exception:
            pass

    def error_payload():
        info: dict = {
            "tag": tag,
            # palint: allow[observability] postmortem epoch STAMP
            "ts": time.time(),
            "loadavg_1m": _loadavg_1m(),
            "compile": compile_snapshot(),
            "peak_hbm_bytes": watermark.peak_bytes or None,
        }
        if error is not None:
            info["error_type"] = type(error).__name__
            info["error"] = str(error)[:4000]
            info["oom"] = looks_like_oom(error)
            info["traceback"] = "".join(
                _traceback.format_exception(
                    type(error), error, error.__traceback__
                )
            )[-16000:]
        if extra:
            info["extra"] = extra
        return info

    dump("error.json", error_payload)

    def trace_payload():
        from . import tracing

        return tracing.export()

    dump("trace.json", trace_payload)

    def metrics_payload():
        from .metrics import registry

        return registry.render()

    dump("metrics.prom", metrics_payload)

    def memory_payload():
        from ..devices.memory import memory_snapshot

        return {
            "devices": memory_snapshot(),
            "peak_hbm_bytes": watermark.peak_bytes or None,
            "samples": watermark.samples,
        }

    dump("memory.json", memory_payload)

    def logs_payload():
        from .logging import recent_log_records

        return "\n".join(recent_log_records()) + "\n"

    dump("logs.txt", logs_payload)
    return path
