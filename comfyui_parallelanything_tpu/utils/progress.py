"""Cooperative per-step progress + interrupt plumbing.

Inside ComfyUI, the reference gets progress bars and the Cancel button for
free: the host's sampler loop reports each denoise step and polls
``comfy.model_management`` for an interrupt between steps. Standalone, this
module is that machinery: the eager sampler loops call ``report_progress``
once per step (sampling/runner.py), the graph host reports node boundaries
(host.run_workflow ``on_node``), and the HTTP server translates both into the
``progress`` / ``executing`` WebSocket events a stock ComfyUI client renders —
and sets the interrupt flag from ``POST /interrupt`` so the *running* prompt
stops between steps, not just the pending ones.

The hook is a process-wide single slot (one accelerator, one serial prompt
worker — the server's original execution model); ``set_progress_hook`` returns
the previous hook so scoped installs nest correctly.

Concurrent serving (round 7, serving/) outgrew the single slot: with several
prompt workers in flight at once, one prompt's Cancel must not kill its
neighbor, and each prompt's ``progress`` events must carry its own hook. The
``progress_scope`` context manager installs a PER-THREAD (hook, preview,
interrupt-event) triple that shadows the process-wide slots for code running
on that thread; the continuous-batching scheduler captures the submitting
thread's scope at admission and drives the per-lane hooks/cancel from its
dispatcher thread. The process-wide flag keeps its original semantics (any
thread's ``request_interrupt`` stops any running loop at its next boundary)
so existing single-worker callers are untouched.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Optional

_hook: Optional[Callable[[int, int], None]] = None
_preview_hook: Optional[Callable[[object], None]] = None
_interrupt = threading.Event()
_scope_local = threading.local()


class Interrupted(RuntimeError):
    """Raised between sampler steps after ``request_interrupt()`` — the
    cooperative analogue of ComfyUI's InterruptProcessingException."""


class ProgressScope:
    """One thread's (hook, preview, interrupt-event) triple — the per-prompt
    analogue of the process-wide slots. ``interrupt_event`` is a one-shot
    per-prompt Cancel: fresh per scope, so the stale-flag races the global
    Event needs clear_interrupt choreography for cannot exist here.
    ``prompt_id`` names the prompt the scope serves — the correlation key
    utils/tracing.py spans and utils/logging.py records inherit on this
    thread (and the serving scheduler captures at admission)."""

    __slots__ = ("hook", "preview_hook", "interrupt_event", "prompt_id")

    def __init__(self, hook=None, preview_hook=None, interrupt_event=None,
                 prompt_id=None):
        self.hook = hook
        self.preview_hook = preview_hook
        self.interrupt_event = interrupt_event
        self.prompt_id = prompt_id


@contextlib.contextmanager
def progress_scope(hook=None, preview_hook=None, interrupt_event=None,
                   prompt_id=None):
    """Install a per-thread ProgressScope for the duration of the block
    (shadowing the process-wide slots on THIS thread only); nests — the
    previous scope is restored on exit."""
    prev = getattr(_scope_local, "scope", None)
    if prompt_id is None and prev is not None:
        prompt_id = prev.prompt_id  # nested scopes stay on the same prompt
    scope = ProgressScope(hook, preview_hook, interrupt_event, prompt_id)
    _scope_local.scope = scope
    try:
        yield scope
    finally:
        _scope_local.scope = prev


def current_scope() -> Optional[ProgressScope]:
    """The calling thread's active ProgressScope, or None (global-slot mode).
    The serving scheduler captures this at submit time so its dispatcher
    thread can drive the submitting prompt's hooks and honor its Cancel."""
    return getattr(_scope_local, "scope", None)


def current_progress_hook() -> Optional[Callable[[int, int], None]]:
    """The hook ``report_progress`` would fire on this thread right now
    (scope hook if one is installed, else the process-wide slot)."""
    scope = current_scope()
    if scope is not None and scope.hook is not None:
        return scope.hook
    return _hook


def current_preview_hook() -> Optional[Callable[[object], None]]:
    """The preview hook active on this thread (scope first, then the
    process-wide slot) — the serving scheduler keeps preview-enabled work
    inline, since only the inline loops carry the preview channel."""
    scope = current_scope()
    if scope is not None and scope.preview_hook is not None:
        return scope.preview_hook
    return _preview_hook


def set_progress_hook(fn: Optional[Callable[[int, int], None]]):
    """Install ``fn(value, max_value)`` as the step hook; returns the previous
    hook (restore it when the scope ends)."""
    global _hook
    prev, _hook = _hook, fn
    return prev


def set_preview_hook(fn: Optional[Callable[[object], None]]):
    """Install ``fn(latent)`` to receive the CURRENT latent once per eager
    sampler step (the WS latent-preview source; None latent steps — e.g.
    samplers that only report counters — are skipped). Returns the previous
    hook. Like the progress hook this is a process-wide single slot; the
    compiled whole-loop path has no step boundaries and emits no previews."""
    global _preview_hook
    prev, _preview_hook = _preview_hook, fn
    return prev


def request_interrupt() -> None:
    """Ask the running sampler loop to stop at the next step boundary."""
    _interrupt.set()


def clear_interrupt() -> None:
    """Reset the flag — call before starting a prompt so a stale interrupt
    aimed at a previous (possibly already-finished) prompt can't kill it."""
    _interrupt.clear()


def interrupt_requested() -> bool:
    return _interrupt.is_set()


def check_interrupt(where: str = "between nodes") -> None:
    """Honor a pending interrupt (the flag is consumed so the next prompt
    starts clean). Called at every cooperative boundary: sampler steps
    (``report_progress``) and graph-node starts (``host.run_workflow``) — the
    latter so a Cancel landing inside a non-sampler node (VAE decode, a slow
    checkpoint load) still stops the prompt, matching ComfyUI's per-node
    interrupt check."""
    scope = current_scope()
    if (scope is not None and scope.interrupt_event is not None
            and scope.interrupt_event.is_set()):
        # Per-prompt Cancel (not consumed: the event is one-shot per scope,
        # and the serving scheduler watches the same event for its lanes).
        raise Interrupted(f"interrupted {where}")
    if _interrupt.is_set():
        _interrupt.clear()
        raise Interrupted(f"interrupted {where}")


def report_progress(value: int, max_value: int, latent=None) -> None:
    """One sampler step completed: notify the hook (and the preview hook with
    the current latent, when both are present), then honor a pending
    interrupt. A per-thread scope shadows the process-wide slots."""
    scope = current_scope()
    hook = scope.hook if scope is not None and scope.hook is not None else _hook
    preview = (
        scope.preview_hook
        if scope is not None and scope.preview_hook is not None
        else _preview_hook
    )
    if hook is not None:
        hook(value, max_value)
    if preview is not None and latent is not None:
        preview(latent)
    check_interrupt(f"at step {value}/{max_value}")
