"""Cooperative per-step progress + interrupt plumbing.

Inside ComfyUI, the reference gets progress bars and the Cancel button for
free: the host's sampler loop reports each denoise step and polls
``comfy.model_management`` for an interrupt between steps. Standalone, this
module is that machinery: the eager sampler loops call ``report_progress``
once per step (sampling/runner.py), the graph host reports node boundaries
(host.run_workflow ``on_node``), and the HTTP server translates both into the
``progress`` / ``executing`` WebSocket events a stock ComfyUI client renders —
and sets the interrupt flag from ``POST /interrupt`` so the *running* prompt
stops between steps, not just the pending ones.

The hook is a process-wide single slot (one accelerator, one serial prompt
worker — the server's execution model); ``set_progress_hook`` returns the
previous hook so scoped installs nest correctly.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

_hook: Optional[Callable[[int, int], None]] = None
_preview_hook: Optional[Callable[[object], None]] = None
_interrupt = threading.Event()


class Interrupted(RuntimeError):
    """Raised between sampler steps after ``request_interrupt()`` — the
    cooperative analogue of ComfyUI's InterruptProcessingException."""


def set_progress_hook(fn: Optional[Callable[[int, int], None]]):
    """Install ``fn(value, max_value)`` as the step hook; returns the previous
    hook (restore it when the scope ends)."""
    global _hook
    prev, _hook = _hook, fn
    return prev


def set_preview_hook(fn: Optional[Callable[[object], None]]):
    """Install ``fn(latent)`` to receive the CURRENT latent once per eager
    sampler step (the WS latent-preview source; None latent steps — e.g.
    samplers that only report counters — are skipped). Returns the previous
    hook. Like the progress hook this is a process-wide single slot; the
    compiled whole-loop path has no step boundaries and emits no previews."""
    global _preview_hook
    prev, _preview_hook = _preview_hook, fn
    return prev


def request_interrupt() -> None:
    """Ask the running sampler loop to stop at the next step boundary."""
    _interrupt.set()


def clear_interrupt() -> None:
    """Reset the flag — call before starting a prompt so a stale interrupt
    aimed at a previous (possibly already-finished) prompt can't kill it."""
    _interrupt.clear()


def interrupt_requested() -> bool:
    return _interrupt.is_set()


def check_interrupt(where: str = "between nodes") -> None:
    """Honor a pending interrupt (the flag is consumed so the next prompt
    starts clean). Called at every cooperative boundary: sampler steps
    (``report_progress``) and graph-node starts (``host.run_workflow``) — the
    latter so a Cancel landing inside a non-sampler node (VAE decode, a slow
    checkpoint load) still stops the prompt, matching ComfyUI's per-node
    interrupt check."""
    if _interrupt.is_set():
        _interrupt.clear()
        raise Interrupted(f"interrupted {where}")


def report_progress(value: int, max_value: int, latent=None) -> None:
    """One sampler step completed: notify the hook (and the preview hook with
    the current latent, when both are present), then honor a pending
    interrupt."""
    if _hook is not None:
        _hook(value, max_value)
    if _preview_hook is not None and latent is not None:
        _preview_hook(latent)
    check_interrupt(f"at step {value}/{max_value}")
