"""Unified fault-injection registry: one syntax, one arming rule, every site.

Before round 14, three ad-hoc ``PA_FAIL_INJECT`` parsers injected faults in
three places with three grammars (bench.py's raise-at-step-3, the serving
bucket's ``nan:<lane>`` one-shot via utils/numerics.py, and nothing at all
for the fleet tier). This module is the chaos tier's single entry point:

- **named sites** (:data:`FAULT_SITES`) across the stack — stream-prefetch
  OOM, compile failure, backend HTTP drop/delay/5xx, heartbeat loss,
  slow-host, mid-step crash, per-lane NaN. A call site asks
  ``faults.check("<site>", key=...)`` at the exact point the real failure
  would occur; the disabled path is a single flag check (the tracer/sentinel
  discipline — tier-1-tested no-op).
- **a deterministic seeded fault plan**: ``PA_FAULT_PLAN`` is JSON —
  ``{"seed": N, "faults": [{"site": ..., "match": ..., "nth": ...,
  "count": ..., "delay_s": ..., "mode": ...}]}`` (or a bare list; seed 0).
  ``match`` substring-filters the call site's ``key`` (a URL path, a stage
  index, a program name); ``nth`` fires on the nth eligible hit (1-based —
  omitted, it derives deterministically from the plan seed, so two runs of
  one seed fire at identical points); ``count`` is how many consecutive
  hits fire (``null`` = every hit from ``nth`` on); ``delay_s`` rides the
  action for delay-type faults.
- **one arming rule**: a plan (or the legacy ``PA_FAIL_INJECT`` alias) arms
  ONLY under an explicit evidence/ledger redirect (``PA_EVIDENCE_DIR`` /
  ``PA_LEDGER_DIR``) — an injected failure's postmortems, ledger records,
  and chaos artifacts must never land in the repo's real evidence (the
  round-9 rule, now centralized).
- **attribution**: every fired fault emits an instant ``faults``-category
  span (``fault-injected``) and a ``pa_fault_injected_total{site=}``
  counter, so a chaos postmortem PROVES what was injected where — a failure
  that can't be told apart from a real one is a useless rehearsal.

Legacy aliases (kept so round-9/11 tests and docs don't break):
``PA_FAIL_INJECT=nan:<lane>`` ≡ a one-shot ``lane-nan`` fault;
any other value (``oom``) ≡ ``mid-step-crash`` firing from hit 3 onward
(bench.py's historical raise-at-step-3 contract).

Module level is stdlib-only and free of package-relative imports (the
``utils/roofline.py`` contract): scripts/chaos.py and tests load it either
as part of the package or standalone by path; the span/counter emission
degrades gracefully when the package isn't importable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time

# Site vocabulary: name → where it injects (the call site owns the failure
# shape; this table is the operator-facing contract, README "Fault
# tolerance"). check() accepts only these names so a typo'd plan fails
# loudly at parse instead of silently never firing.
FAULT_SITES = {
    "stream-prefetch-oom": "parallel/streaming.py stage prefetch — raises "
                           "RESOURCE_EXHAUSTED so the re-carve ladder runs",
    "compile-fail": "utils/telemetry.instrument_jit first compile — raises "
                    "so the compile→eager degradation rung runs",
    "backend-http": "server.py HTTP ingress — mode drop/delay/5xx per "
                    "request path (key = METHOD /path)",
    "heartbeat-loss": "fleet HeartbeatClient — the beat is silently skipped "
                      "(the router sees the host go dark)",
    "slow-host": "server.py prompt worker — sleeps delay_s before the "
                 "prompt executes (straggler rehearsal)",
    "mid-step-crash": "bench.py / chaos denoise step — raises an "
                      "OOM-shaped RuntimeError mid-run",
    "lane-nan": "serving lane eval input (via utils/numerics.take_injection) "
                "— match is the lane index to poison",
    "journal-corrupt": "fleet PromptJournal.append — the record's line is "
                       "written torn (mode=truncate: half the bytes, no "
                       "newline) or garbled (mode=garble: NULs mid-line), "
                       "rehearsing a router crash mid-write; match filters "
                       "the event name (submit/dispatch/resolve)",
    "slow-disk": "fleet PromptJournal.append + utils/telemetry ledger "
                 "writes — sleeps delay_s inside the append (the fsync "
                 "stall rehearsal: journal/ledger latency shows up in "
                 "pa_disk_append_seconds and the anomaly sentinel's "
                 "disk_append_p95 watch); match filters the target "
                 "(journal event name, or 'ledger')",
    "network-partition": "fleet router↔backend link — BOTH directions of "
                         "one host's traffic drop while each side stays "
                         "alive: router _post/_get raises a refused-socket "
                         "OSError (key = 'router-><base>') and the host's "
                         "HeartbeatClient silently skips its beat (key = "
                         "'<host_id>->router'); match filters the key, so "
                         "one spec partitions one host, two specs cut both "
                         "directions",
}


def _stable_u64(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


@dataclasses.dataclass
class FaultSpec:
    """One parsed plan entry. ``nth`` None → derived from the plan seed."""

    site: str
    match: str | None = None
    nth: int | None = None
    count: int | None = 1          # None = every hit from nth on
    delay_s: float = 0.0
    mode: str | None = None

    def resolved_nth(self, seed: int) -> int:
        if self.nth is not None:
            return max(1, int(self.nth))
        # Deterministic in (plan seed, site, match): same seed → same firing
        # schedule, different sites de-correlate. Band [1, 4] keeps derived
        # faults inside short CI workloads.
        return 1 + _stable_u64(f"{seed}:{self.site}:{self.match}") % 4


@dataclasses.dataclass
class FaultAction:
    """What a call site receives when its fault fires."""

    site: str
    mode: str | None
    delay_s: float
    key: str
    hit: int            # which eligible hit this was (1-based)
    spec: FaultSpec

    def sleep(self) -> None:
        if self.delay_s > 0:
            time.sleep(self.delay_s)


class FaultPlanError(ValueError):
    """Malformed PA_FAULT_PLAN — raised at parse, never silently ignored."""


def parse_plan(raw) -> tuple[int, list[FaultSpec]]:
    """(seed, specs) from the PA_FAULT_PLAN JSON value (dict or bare list)."""
    if isinstance(raw, str):
        try:
            raw = json.loads(raw)
        except json.JSONDecodeError as e:
            raise FaultPlanError(f"PA_FAULT_PLAN is not JSON: {e}") from e
    if isinstance(raw, list):
        seed, entries = 0, raw
    elif isinstance(raw, dict):
        seed = int(raw.get("seed", 0))
        entries = raw.get("faults", [])
    else:
        raise FaultPlanError(f"PA_FAULT_PLAN must be a dict or list, "
                             f"got {type(raw).__name__}")
    specs = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or "site" not in e:
            raise FaultPlanError(f"fault entry {i} must be an object with "
                                 f"a 'site': {e!r}")
        site = str(e["site"])
        if site not in FAULT_SITES:
            raise FaultPlanError(
                f"unknown fault site {site!r} (have: "
                f"{', '.join(sorted(FAULT_SITES))})"
            )
        count = e.get("count", 1)
        specs.append(FaultSpec(
            site=site,
            match=None if e.get("match") is None else str(e["match"]),
            nth=None if e.get("nth") is None else int(e["nth"]),
            count=None if count is None else int(count),
            delay_s=float(e.get("delay_s", 0.0)),
            mode=None if e.get("mode") is None else str(e["mode"]),
        ))
    return seed, specs


def _legacy_specs(value: str) -> list[FaultSpec]:
    """The PA_FAIL_INJECT alias, kept verbatim-compatible with rounds 9/11."""
    if value.startswith("nan:"):
        try:
            lane = int(value.split(":", 1)[1])
        except ValueError:
            return []
        return [FaultSpec(site="lane-nan", match=str(lane), nth=1, count=1)]
    # bench.py's historical contract: the third step (and every one after,
    # though the first raise ends the run) fails with an OOM-shaped error.
    return [FaultSpec(site="mid-step-crash", mode="oom", nth=3, count=None)]


class FaultRegistry:
    """Hit counting + firing decisions for one parsed plan. Thread-safe —
    sites fire from HTTP handler threads, the serving dispatcher, and the
    streaming runner concurrently."""

    def __init__(self, seed: int = 0, specs: list[FaultSpec] | None = None,
                 armed: bool = True):
        self.seed = int(seed)
        # unguarded: write-once at construction (refresh() swaps the
        # whole REGISTRY object, never this list), read-only afterwards
        self.specs = list(specs or ())
        self.armed = bool(armed) and bool(self.specs)
        self.env_sig: tuple | None = None   # what from_env parsed, for refresh()
        self._hits: dict[tuple[int, str], int] = {}   # (spec idx, key-class) — guarded-by: _lock
        self._fired: dict[str, int] = {}              # site → fired count — guarded-by: _lock
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env=os.environ) -> "FaultRegistry":
        plan = env.get("PA_FAULT_PLAN")
        legacy = env.get("PA_FAIL_INJECT")
        redirected = bool(env.get("PA_EVIDENCE_DIR") or env.get("PA_LEDGER_DIR"))
        if plan:
            seed, specs = parse_plan(plan)
        elif legacy:
            seed, specs = 0, _legacy_specs(legacy)
        else:
            reg = cls(armed=False)
            reg.env_sig = _env_sig(env)
            return reg
        # The one arming rule: no evidence/ledger redirect → the plan parses
        # (typos still fail loudly) but never fires.
        reg = cls(seed=seed, specs=specs, armed=redirected)
        reg.env_sig = _env_sig(env)
        return reg

    def check(self, site: str, key: str = "") -> FaultAction | None:
        """The per-site hook. Counts one eligible hit per matching spec and
        returns the first spec whose firing window covers it (else None).
        Fired faults are recorded (span + counter) before returning."""
        if not self.armed:
            return None
        action = None
        with self._lock:
            for idx, spec in enumerate(self.specs):
                if spec.site != site:
                    continue
                if spec.match is not None and spec.match not in key:
                    continue
                hkey = (idx, "")
                self._hits[hkey] = hit = self._hits.get(hkey, 0) + 1
                nth = spec.resolved_nth(self.seed)
                in_window = hit >= nth and (
                    spec.count is None or hit < nth + spec.count
                )
                if in_window and action is None:
                    action = FaultAction(site=site, mode=spec.mode,
                                         delay_s=spec.delay_s, key=key,
                                         hit=hit, spec=spec)
            if action is not None:
                self._fired[site] = self._fired.get(site, 0) + 1
        if action is not None:
            self._record_fired(action)
        return action

    def record_external(self, site: str, key: str = "", mode=None) -> None:
        """Attribution for a fault the plan armed but a SUBSYSTEM executes
        (the lane-nan poke lives in utils/numerics.take_injection, which owns
        the one-shot/seating semantics) — same span + counter as check()."""
        with self._lock:
            self._fired[site] = self._fired.get(site, 0) + 1
        self._record_fired(FaultAction(site=site, mode=mode, delay_s=0.0,
                                       key=key, hit=0,
                                       spec=FaultSpec(site=site)))

    @staticmethod
    def _record_fired(action: FaultAction) -> None:
        """Span + counter + log — every injected fault is attributable.
        Package imports are lazy and best-effort: this module stays
        standalone-loadable, and attribution must never mask the fault."""
        try:
            from . import tracing

            if tracing.on():
                now = tracing.now_us()
                tracing.record(
                    "fault-injected", now, 0.0, cat="faults",
                    site=action.site, mode=action.mode, key=action.key,
                    hit=action.hit,
                )
        except Exception:  # noqa: BLE001 — standalone load / tracing hiccup
            pass
        try:
            from .metrics import registry

            registry.counter(
                "pa_fault_injected_total", labels={"site": action.site},
                help="faults fired by the injection registry (utils/faults.py)"
                     " — chaos runs prove their injections here",
            )
        except Exception:  # noqa: BLE001
            pass
        try:
            from .logging import get_logger

            get_logger().warning(
                "fault injected [%s] mode=%s key=%s hit=%d",
                action.site, action.mode, action.key, action.hit,
            )
        except Exception:  # noqa: BLE001
            pass

    def lane_nan_target(self) -> int | None:
        """The lane index of the first un-exhausted ``lane-nan`` spec, or
        None. Does NOT consume a hit — utils/numerics.take_injection owns
        the one-shot/seated semantics; it reports consumption back through
        :meth:`record_external`."""
        if not self.armed:
            return None
        with self._lock:
            for spec in self.specs:
                if spec.site != "lane-nan":
                    continue
                try:
                    return int(spec.match or "0")
                except ValueError:
                    continue
        return None

    def fired(self) -> dict[str, int]:
        with self._lock:
            return dict(self._fired)

    def reset(self) -> None:
        """Clear hit/fired counters (re-arm) — tests and the dryrun's
        repeated injection sections."""
        with self._lock:
            self._hits.clear()
            self._fired.clear()


def _env_sig(env=os.environ) -> tuple:
    return (env.get("PA_FAULT_PLAN"), env.get("PA_FAIL_INJECT"),
            bool(env.get("PA_EVIDENCE_DIR") or env.get("PA_LEDGER_DIR")))


# Process-wide registry, parsed from the env at import (bench/server set the
# env before the package loads). reload() re-reads unconditionally;
# refresh() re-reads only when the relevant env vars changed since the parse
# — the sites that must honor env set mid-process (utils/numerics.py's
# lane-nan path, guarded by its own sentinel flag) call refresh().
registry = FaultRegistry.from_env()


def active() -> bool:
    """The hot-path flag — True only when an armed plan exists."""
    return registry.armed


def check(site: str, key: str = "") -> FaultAction | None:
    """Module-level hook every instrumented site calls. Disabled path is
    this one attribute read."""
    if not registry.armed:
        return None
    return registry.check(site, key)


def fired() -> dict[str, int]:
    return registry.fired()


def reset() -> None:
    registry.reset()


def reload() -> FaultRegistry:
    global registry
    registry = FaultRegistry.from_env()
    return registry


def refresh() -> FaultRegistry:
    """Re-parse the env ONLY when the fault-relevant vars changed — cheap
    enough for sites whose callers set the env after package import."""
    if registry.env_sig != _env_sig():
        return reload()
    return registry


def oom_error(action: FaultAction) -> RuntimeError:
    """The OOM-shaped injected error (matches utils/telemetry._OOM_MARKERS,
    so looks_like_oom and the degradation ladders treat it as the real
    thing)."""
    return RuntimeError(
        f"RESOURCE_EXHAUSTED: injected failure "
        f"(site={action.site}, hit={action.hit})"
    )
