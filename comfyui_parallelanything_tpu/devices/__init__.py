from .discovery import available_devices, get_device, device_platform, default_device
from .memory import free_memory_bytes, total_memory_bytes

__all__ = [
    "available_devices",
    "get_device",
    "device_platform",
    "default_device",
    "free_memory_bytes",
    "total_memory_bytes",
]
