"""Device discovery for the device-chain API.

Reference behavior (any_device_parallel.py:770-786, ParallelDevice.get_available_devices):
the dropdown enumerates ``cpu`` always, then ``cuda:i`` / ``mps`` / ``xpu:i`` /
DirectML ``privateuseone:i`` as available. The TPU-native equivalent enumerates ``cpu``
always, then ``tpu:i`` from ``jax.devices('tpu')``. Device identifiers are strings of the
form ``"<platform>"`` or ``"<platform>:<index>"`` (e.g. ``"tpu:3"``, ``"cpu"``), matching
the reference's string-keyed chain entries (any_device_parallel.py:823-832).
"""

from __future__ import annotations

import functools

import jax

# Platform names that mean "a TPU chip". The tunneled TPU registers as the
# experimental 'axon' PJRT plugin, whose devices report platform 'axon' — treat
# it as TPU everywhere (device strings, backend dispatch, default device).
TPU_PLATFORMS = ("tpu", "axon")


def is_tpu_device(d: jax.Device) -> bool:
    return d.platform in TPU_PLATFORMS


def _platform_devices(platform: str) -> list[jax.Device]:
    """All jax devices for a platform, or [] when that backend is absent."""
    try:
        return list(jax.devices(platform))
    except RuntimeError:
        return []


def _tpu_class_devices() -> list[jax.Device]:
    """Devices of the first present TPU-class platform ('tpu', else 'axon')."""
    for plat in TPU_PLATFORMS:
        devs = _platform_devices(plat)
        if devs:
            return devs
    return []


@functools.cache
def available_devices() -> list[str]:
    """Enumerate selectable device strings, accelerators first, ``cpu`` always present.

    Mirrors ParallelDevice.get_available_devices (any_device_parallel.py:770-786), with
    ``tpu:i`` taking the role of ``cuda:i``. Any other accelerator platform JAX exposes
    (e.g. ``gpu``) is listed too, so the chain API is backend-agnostic.
    """
    out: list[str] = []
    seen_platforms: set[str] = set()
    for dev in jax.devices():
        plat = dev.platform
        if plat == "cpu":
            continue
        # Canonical spelling: TPU-class devices (incl. the tunneled 'axon'
        # plugin) are always listed as tpu:N, so saved chains stay portable and
        # dedup/grouping sees one platform per chip.
        if plat in TPU_PLATFORMS:
            plat = "tpu"
        seen_platforms.add(plat)
        out.append(f"{plat}:{dev.id}")
    # Non-default accelerator backends (e.g. tpu present but cpu is default platform).
    if "tpu" not in seen_platforms:
        for dev in _tpu_class_devices():
            out.append(f"tpu:{dev.id}")
    if "gpu" not in seen_platforms:
        for dev in _platform_devices("gpu"):
            out.append(f"gpu:{dev.id}")
    out.append("cpu")
    return out


def device_platform(device_str: str) -> str:
    """``"tpu:3"`` -> ``"tpu"``; ``"cpu"`` -> ``"cpu"``."""
    return device_str.split(":", 1)[0].lower()


def get_device(device_str: str) -> jax.Device:
    """Resolve a device string to a live ``jax.Device``.

    Raises ``ValueError`` for unknown platforms or out-of-range indices — the analogue
    of the reference's per-device validation in the replica loop
    (any_device_parallel.py:1037-1042), which skips invalid chain entries.
    """
    plat = device_platform(device_str)
    idx = 0
    if ":" in device_str:
        try:
            idx = int(device_str.split(":", 1)[1])
        except ValueError as e:
            raise ValueError(f"Malformed device string {device_str!r}") from e
    # 'tpu:N' resolves against whichever TPU-class platform is present, so user
    # chains written as tpu:0 work when the chip registers as 'axon'.
    devs = _tpu_class_devices() if plat == "tpu" else _platform_devices(plat)
    if not devs:
        raise ValueError(f"No devices available for platform {plat!r} (from {device_str!r})")
    for d in devs:
        if d.id == idx:
            return d
    raise ValueError(
        f"Device index {idx} out of range for platform {plat!r} "
        f"({len(devs)} device(s) available)"
    )


def default_device() -> jax.Device:
    """The canonical compute device — analogue of
    comfy.model_management.get_torch_device() (consumed at any_device_parallel.py:952)."""
    devs = _tpu_class_devices()
    if devs:
        return devs[0]
    devs = _platform_devices("gpu")
    if devs:
        return devs[0]
    return jax.devices("cpu")[0]
