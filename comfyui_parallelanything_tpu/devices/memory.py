"""Device memory introspection — the HBM analogue of get_free_vram.

Reference (any_device_parallel.py:724-735): free MB on a CUDA device via
``total_memory - memory_allocated``, and 0 for any non-CUDA device. Here the probe reads
``jax.Device.memory_stats()`` (``bytes_limit`` / ``bytes_in_use``), returning 0 for
devices that expose no stats (host CPU), so CPU-only chains fall back to pure
user weights exactly like the reference (any_device_parallel.py:738-739).

Beyond the reference: ``ResidencyTracker`` — live-buffer accounting for the
weight-streaming executor (parallel/streaming.py). The streamed path's whole
contract is a bound on device-resident weight bytes (≈ 2 stages + activations);
the tracker records every stage placement/retirement so tests can assert that
bound off-hardware, where ``memory_stats()`` reports nothing.
"""

from __future__ import annotations

import dataclasses
import os

import jax


def _stats(device: jax.Device) -> dict | None:
    try:
        return device.memory_stats()
    except Exception:
        return None


def total_memory_bytes(device: jax.Device) -> int:
    """Device memory capacity in bytes; 0 when the backend exposes no stats."""
    stats = _stats(device)
    if not stats:
        return 0
    return int(stats.get("bytes_limit", 0))


def free_memory_bytes(device: jax.Device) -> int:
    """Free HBM in bytes (limit - in_use); 0 when unavailable.

    Parity: get_free_vram (any_device_parallel.py:724-735) returns
    ``total_memory - memory_allocated`` in MB for CUDA and 0 otherwise.
    """
    stats = _stats(device)
    if not stats:
        return 0
    limit = int(stats.get("bytes_limit", 0))
    in_use = int(stats.get("bytes_in_use", 0))
    return max(0, limit - in_use)


def usable_hbm_bytes(device: jax.Device) -> int:
    """The HBM budget the weights-don't-fit routing compares against: the
    ``PA_HBM_BUDGET_BYTES`` override when set (round-5 finding: the tunnel
    chip's *usable* HBM sits below the reported ``bytes_limit`` — the measured
    ceiling from scripts/probe_hbm.py belongs in the env, not hardcoded),
    otherwise 90% of the device's reported capacity (runtime/framework
    reservations come off the top before any weight lands). 0 when the backend
    exposes no stats (host CPU) — the caller must then budget explicitly."""
    override = os.environ.get("PA_HBM_BUDGET_BYTES")
    if override:
        return int(override)
    total = total_memory_bytes(device)
    return int(total * 0.9)


@dataclasses.dataclass
class ResidencyTracker:
    """Accounting of live *streamed-weight* bytes on a device.

    The streaming scheduler (parallel/streaming.py) calls ``place(tag, n)``
    when it dispatches a stage's host→HBM transfer and ``retire(tag)`` once
    that stage's compute has completed AND its buffers have been released —
    so ``live_bytes`` tracks the scheduler's weight footprint and
    ``peak_bytes`` is the number the 2-stage bound is asserted on.
    ``resident_bytes`` counts the permanently-placed remainder (prepare/
    finalize params), reported separately because it is not part of the
    double-buffer ring."""

    live_bytes: int = 0
    peak_bytes: int = 0
    resident_bytes: int = 0
    _tags: dict = dataclasses.field(default_factory=dict)

    def place(self, tag, nbytes: int) -> None:
        if tag in self._tags:
            raise ValueError(f"stage {tag!r} placed twice without retirement")
        self._tags[tag] = int(nbytes)
        self.live_bytes += int(nbytes)
        self.peak_bytes = max(self.peak_bytes, self.live_bytes)

    def retire(self, tag) -> None:
        self.live_bytes -= self._tags.pop(tag)

    def add_resident(self, nbytes: int) -> None:
        self.resident_bytes += int(nbytes)

    @property
    def live_tags(self) -> tuple:
        return tuple(self._tags)
