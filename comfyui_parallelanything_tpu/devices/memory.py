"""Device memory introspection — the HBM analogue of get_free_vram.

Reference (any_device_parallel.py:724-735): free MB on a CUDA device via
``total_memory - memory_allocated``, and 0 for any non-CUDA device. Here the probe reads
``jax.Device.memory_stats()`` (``bytes_limit`` / ``bytes_in_use``), returning 0 for
devices that expose no stats (host CPU), so CPU-only chains fall back to pure
user weights exactly like the reference (any_device_parallel.py:738-739).
"""

from __future__ import annotations

import jax


def _stats(device: jax.Device) -> dict | None:
    try:
        return device.memory_stats()
    except Exception:
        return None


def total_memory_bytes(device: jax.Device) -> int:
    """Device memory capacity in bytes; 0 when the backend exposes no stats."""
    stats = _stats(device)
    if not stats:
        return 0
    return int(stats.get("bytes_limit", 0))


def free_memory_bytes(device: jax.Device) -> int:
    """Free HBM in bytes (limit - in_use); 0 when unavailable.

    Parity: get_free_vram (any_device_parallel.py:724-735) returns
    ``total_memory - memory_allocated`` in MB for CUDA and 0 otherwise.
    """
    stats = _stats(device)
    if not stats:
        return 0
    limit = int(stats.get("bytes_limit", 0))
    in_use = int(stats.get("bytes_in_use", 0))
    return max(0, limit - in_use)
