"""Device memory introspection — the HBM analogue of get_free_vram.

Reference (any_device_parallel.py:724-735): free MB on a CUDA device via
``total_memory - memory_allocated``, and 0 for any non-CUDA device. Here the probe reads
``jax.Device.memory_stats()`` (``bytes_limit`` / ``bytes_in_use``), returning 0 for
devices that expose no stats (host CPU), so CPU-only chains fall back to pure
user weights exactly like the reference (any_device_parallel.py:738-739).

Beyond the reference: ``ResidencyTracker`` — live-buffer accounting for the
weight-streaming executor (parallel/streaming.py). The streamed path's whole
contract is a bound on device-resident weight bytes (≈ 2 stages + activations);
the tracker records every stage placement/retirement so tests can assert that
bound off-hardware, where ``memory_stats()`` reports nothing.

Telemetry surface (round 9): ``device_memory_stats`` / ``memory_snapshot`` /
``publish_memory_gauges`` feed the ``pa_hbm_*`` gauges, ``GET /health``, and
the perf ledger's ``peak_hbm_bytes`` watermark. Where the backend exposes no
``memory_stats()`` (host CPU, the axon tunnel), the snapshot reports a
DETERMINISTIC pseudo-limit (``PA_CPU_FAKE_HBM_BYTES``, default 8 GiB) with
``bytes_in_use`` summed from the process's live jax arrays on that device —
so off-hardware tests can assert the utilization math instead of skipping it.
The parity probes above (``total_memory_bytes``/``free_memory_bytes``) keep
returning 0 off-hardware on purpose: the hybrid chain's weighting fallback
(any_device_parallel.py:738-739) is routing behavior, not telemetry, and must
not start believing a fake limit.
"""

from __future__ import annotations

import dataclasses
import os

import jax

# Deterministic pseudo-capacity reported for devices without memory_stats().
CPU_FALLBACK_LIMIT_BYTES = 8 * 2**30


def _stats(device: jax.Device) -> dict | None:
    try:
        return device.memory_stats()
    except Exception:
        return None


def total_memory_bytes(device: jax.Device) -> int:
    """Device memory capacity in bytes; 0 when the backend exposes no stats."""
    stats = _stats(device)
    if not stats:
        return 0
    return int(stats.get("bytes_limit", 0))


def free_memory_bytes(device: jax.Device) -> int:
    """Free HBM in bytes (limit - in_use); 0 when unavailable.

    Parity: get_free_vram (any_device_parallel.py:724-735) returns
    ``total_memory - memory_allocated`` in MB for CUDA and 0 otherwise.
    """
    stats = _stats(device)
    if not stats:
        return 0
    limit = int(stats.get("bytes_limit", 0))
    in_use = int(stats.get("bytes_in_use", 0))
    return max(0, limit - in_use)


def usable_hbm_bytes(device: jax.Device) -> int:
    """The HBM budget the weights-don't-fit routing compares against: the
    ``PA_HBM_BUDGET_BYTES`` override when set (round-5 finding: the tunnel
    chip's *usable* HBM sits below the reported ``bytes_limit`` — the measured
    ceiling from scripts/probe_hbm.py belongs in the env, not hardcoded),
    otherwise 90% of the device's reported capacity (runtime/framework
    reservations come off the top before any weight lands). 0 when the backend
    exposes no stats (host CPU) — the caller must then budget explicitly."""
    override = os.environ.get("PA_HBM_BUDGET_BYTES")
    if override:
        return int(override)
    total = total_memory_bytes(device)
    return int(total * 0.9)


def _device_label(device: jax.Device) -> str:
    return f"{device.platform}:{device.id}"


def _fallback_in_use(devices) -> dict:
    """ONE pass over the process's live jax arrays, bucketing per-shard bytes
    by device — the deterministic ``bytes_in_use`` stand-in where the backend
    reports nothing. A sharded array contributes its per-shard slice
    (nbytes / device count) to each of its devices."""
    wanted = {d: 0 for d in devices}
    for arr in jax.live_arrays():
        try:
            devs = arr.sharding.device_set
        except Exception:
            continue
        per_shard = arr.nbytes // max(1, len(devs))
        for d in devs:
            if d in wanted:
                wanted[d] += per_shard
    return wanted


def _device_backed_stats(device: jax.Device) -> dict | None:
    stats = _stats(device)
    if not stats or int(stats.get("bytes_limit", 0)) <= 0:
        return None
    return {
        "device": _device_label(device),
        "bytes_limit": int(stats.get("bytes_limit", 0)),
        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
        "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)) or None,
        "source": "device",
    }


def _fallback_stats(device: jax.Device, in_use: int) -> dict:
    limit = int(os.environ.get("PA_CPU_FAKE_HBM_BYTES",
                               str(CPU_FALLBACK_LIMIT_BYTES)))
    return {
        "device": _device_label(device),
        "bytes_limit": limit,
        "bytes_in_use": in_use,
        "peak_bytes_in_use": None,
        "source": "fallback",
    }


def device_memory_stats(device: jax.Device) -> dict:
    """Telemetry stats for one device: real ``memory_stats()`` where exposed
    (``source: "device"``), else the deterministic fallback
    (``source: "fallback"`` — pseudo-limit ``$PA_CPU_FAKE_HBM_BYTES`` or
    8 GiB, in-use from live arrays)."""
    s = _device_backed_stats(device)
    if s is not None:
        return s
    return _fallback_stats(device, _fallback_in_use([device])[device])


def memory_snapshot(devices=None) -> list[dict]:
    """Per-device stats + utilization for every (or the given) device — the
    body of ``GET /health``'s ``hbm`` section and the postmortem bundle's
    ``memory.json``. Fallback accounting is a single live-array pass shared
    by all devices, not one walk per device — the snapshot runs per bench
    warmup step and per traced streaming stage."""
    devices = list(devices) if devices is not None else list(jax.devices())
    stats = [(d, _device_backed_stats(d)) for d in devices]
    fallback_in_use = None
    out = []
    for d, s in stats:
        if s is None:
            if fallback_in_use is None:
                fallback_in_use = _fallback_in_use(
                    [dd for dd, ss in stats if ss is None]
                )
            s = _fallback_stats(d, fallback_in_use[d])
        limit = s["bytes_limit"]
        s["utilization"] = (
            round(s["bytes_in_use"] / limit, 6) if limit > 0 else None
        )
        out.append(s)
    return out


def publish_memory_gauges(devices=None) -> list[dict]:
    """Export per-device ``pa_hbm_bytes_limit`` / ``pa_hbm_bytes_in_use`` /
    ``pa_hbm_utilization`` gauges (the Prometheus view of the snapshot);
    returns the snapshot so callers need only one pass."""
    from ..utils.metrics import registry

    snap = memory_snapshot(devices)
    for s in snap:
        lbl = {"device": s["device"]}
        registry.gauge("pa_hbm_bytes_limit", s["bytes_limit"], labels=lbl,
                       help="device memory capacity (deterministic pseudo-"
                            "limit where the backend exposes no stats)")
        registry.gauge("pa_hbm_bytes_in_use", s["bytes_in_use"], labels=lbl,
                       help="device memory in use (live-array fallback "
                            "off-hardware)")
        if s["utilization"] is not None:
            registry.gauge("pa_hbm_utilization", s["utilization"], labels=lbl,
                           help="bytes_in_use / bytes_limit")
    return snap


@dataclasses.dataclass
class ResidencyTracker:
    """Accounting of live *streamed-weight* bytes on a device.

    The streaming scheduler (parallel/streaming.py) calls ``place(tag, n)``
    when it dispatches a stage's host→HBM transfer and ``retire(tag)`` once
    that stage's compute has completed AND its buffers have been released —
    so ``live_bytes`` tracks the scheduler's weight footprint and
    ``peak_bytes`` is the number the 2-stage bound is asserted on.
    ``resident_bytes`` counts the permanently-placed remainder (prepare/
    finalize params), reported separately because it is not part of the
    double-buffer ring."""

    live_bytes: int = 0
    peak_bytes: int = 0
    resident_bytes: int = 0
    _tags: dict = dataclasses.field(default_factory=dict)

    def place(self, tag, nbytes: int) -> None:
        if tag in self._tags:
            raise ValueError(f"stage {tag!r} placed twice without retirement")
        self._tags[tag] = int(nbytes)
        self.live_bytes += int(nbytes)
        self.peak_bytes = max(self.peak_bytes, self.live_bytes)

    def retire(self, tag) -> None:
        self.live_bytes -= self._tags.pop(tag)

    def add_resident(self, nbytes: int) -> None:
        self.resident_bytes += int(nbytes)

    @property
    def live_tags(self) -> tuple:
        return tuple(self._tags)

    def publish_gauges(self, device: str, bound_bytes: int | None = None
                       ) -> None:
        """Export the tracker's accounting as ``pa_hbm_stream_*`` gauges —
        the streamed-weight residency view of HBM, next to the raw
        ``pa_hbm_bytes_*`` device gauges. ``bound_bytes`` is the budget the
        scheduler promises to stay under (2 × max stage)."""
        from ..utils.metrics import registry

        lbl = {"device": device}
        registry.gauge("pa_hbm_stream_live_bytes", self.live_bytes,
                       labels=lbl,
                       help="streamed-weight bytes currently resident")
        registry.gauge("pa_hbm_stream_peak_bytes", self.peak_bytes,
                       labels=lbl,
                       help="peak streamed-weight residency this process")
        registry.gauge("pa_hbm_stream_resident_bytes", self.resident_bytes,
                       labels=lbl,
                       help="permanently-placed prepare/finalize bytes")
        if bound_bytes:
            registry.gauge("pa_hbm_stream_bound_bytes", bound_bytes,
                           labels=lbl,
                           help="the 2-stage residency bound the scheduler "
                                "is held to")
