"""Minimal ComfyUI-compatible HTTP API over the workflow host.

The reference pack's graphs are driven through ComfyUI's HTTP server (the
frontend and every scripting client POST API-format JSON to ``/prompt``).
This module is that surface for the standalone host: stdlib-only
(``http.server``), a configurable pool of worker threads executing prompts
(default ONE — the reference's serial schedule; ``workers>1`` or
``PA_SERVER_WORKERS`` turns on concurrent execution and installs the
continuous-batching scheduler, serving/, so concurrent prompts' sampler runs
share compiled step dispatches), and a persistent ``host.WorkflowCache``
shared across prompts so a model loaded by one prompt stays resident for the
next (the reference's keep-loaded behavior, which its
``cleanup_parallel_model``/finalizer pair defends, any_device_parallel.py
211-282).

Endpoints (the ComfyUI client-protocol subset that makes scripts work):

- ``POST /prompt``            ``{"prompt": {...graph...}}`` → ``{"prompt_id"}``;
                              ``extra_data.priority`` / ``extra_data.deadline_s``
                              feed the serving policy layer; 429 when the
                              bounded queue (``max_pending`` /
                              $PA_MAX_PENDING) is full — explicit
                              backpressure instead of silent latency
- ``GET  /history``           all completed prompts
- ``GET  /history/{id}``      one prompt's status + outputs
- ``GET  /view?filename=``    serve a saved image (``subfolder=`` honored)
- ``GET  /queue``             running + pending prompt ids
- ``POST /queue``             stock per-prompt cancel:
                              ``{"delete": [prompt_id, ...]}`` drops queued
                              prompts and stops running ones at their next
                              step boundary (per-lane cancel — co-batched
                              neighbors keep running); ``{"clear": true}``
                              drops every pending prompt
- ``GET  /metrics``           Prometheus text: serving per-bucket occupancy,
                              lane-wait/step-time histograms (server-side
                              p50/p95), dispatch counts (utils/metrics.py
                              registry) + queue gauges + per-device
                              ``pa_hbm_*`` memory gauges (refreshed per
                              scrape and by the periodic memory monitor)
- ``GET  /health``            one JSON health document
                              (utils/telemetry.health_snapshot,
                              ``pa-health/v3``): devices, per-device HBM +
                              utilization (deterministic pseudo-accounting
                              off-hardware), peak watermark, compile/cache
                              accounting, queue depth/workers, 1-minute
                              load average, a ``numerics`` section
                              (utils/numerics.py: sentinel flag, last
                              non-finite event, quarantined-lane total,
                              fingerprint-gate verdict; enable with
                              $PA_NUMERICS=1), and the fleet identity/
                              admission fields a router's scoreboard reads
                              (``host_id``, ``accepting``,
                              ``inflight_prompts`` — fleet/scoreboard.py
                              needs no extra endpoint)
- ``POST /drain``             fleet drain: stop seating new prompts
                              (``POST /prompt`` → 503 while draining),
                              finish running lanes; body
                              ``{"resume": true}`` re-opens admission
                              (elastic rejoin). A router mirrors the state
                              from /health's ``accepting``
- ``GET  /trace``             Chrome/Perfetto trace-event JSON of the span
                              tracer (utils/tracing.py) — per-prompt
                              timelines from HTTP ingress to device step;
                              ``?prompt_id=`` filters to one prompt. Enable
                              with ``--trace`` / $PA_TRACE=1 (off by
                              default: the tracer's disabled path is a
                              single flag check)
- ``POST /interrupt``         drop all *pending* prompts and stop every
                              *running* one at its next sampler-step boundary
                              (per-prompt cooperative scope,
                              utils/progress.py; a single compiled step
                              cannot be preempted mid-dispatch)
- ``POST /upload/image``      multipart input upload into $PA_INPUT_DIR
                              (stock dedupe suffixing; ``overwrite`` honored)
- ``GET  /object_info[/cls]`` node-registry introspection (INPUT_TYPES etc.)
- ``GET  /system_stats``      devices from devices.discovery
- ``GET  /ws``                WebSocket progress events (RFC 6455, stdlib):
                              ``status`` on queue changes,
                              ``execution_start`` when a prompt begins,
                              ``execution_cached`` with the cache-served node
                              ids, ``executing`` per node as it runs,
                              ``progress`` per sampler step (what frontends
                              render progress bars from), ``executed`` per
                              output node with its images,
                              ``execution_interrupted`` on Cancel, and the
                              canonical completion signal API clients wait
                              for — ``executing`` with ``node: null`` and the
                              ``prompt_id``. Opt-in (``extra_data.preview``
                              on POST /prompt): per-step latent previews as
                              stock binary frames (>II event-type 1 + format
                              2 (PNG) + PNG bytes; utils/latent_preview.py).

Run:  ``python -m comfyui_parallelanything_tpu.server [--port 8188]``
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import queue
import struct
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .host import WorkflowCache, run_workflow
from .utils import faults, slo, tracing
from .utils.progress import Interrupted, progress_scope

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"  # RFC 6455 §1.3


def _ws_frame(payload: bytes, opcode: int = 0x1) -> bytes:
    """One server→client frame (FIN set, unmasked — RFC 6455 §5.2)."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        head += bytes([n])
    elif n < 1 << 16:
        head += bytes([126]) + struct.pack(">H", n)
    else:
        head += bytes([127]) + struct.pack(">Q", n)
    return head + payload


def _ws_read_frame(rfile) -> tuple[int, bytes] | None:
    """(opcode, payload) of one client frame, or None on EOF — including an
    abrupt disconnect mid-header (a truncated read must not raise out of the
    handler as struct.error). Client frames are masked (RFC 6455 §5.3)."""

    def need(k: int) -> bytes | None:
        data = rfile.read(k)
        return data if len(data) == k else None

    hdr = need(2)
    if hdr is None:
        return None
    opcode = hdr[0] & 0x0F
    masked, n = hdr[1] & 0x80, hdr[1] & 0x7F
    if n == 126:
        ext = need(2)
        if ext is None:
            return None
        n = struct.unpack(">H", ext)[0]
    elif n == 127:
        ext = need(8)
        if ext is None:
            return None
        n = struct.unpack(">Q", ext)[0]
    mask = need(4) if masked else b"\x00" * 4
    if mask is None:
        return None
    data = need(n)
    if data is None:
        return None
    if masked:
        data = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
    return opcode, data


def _jsonable(v):
    """INPUT_TYPES trees hold tuples/dicts/strings and the odd non-JSON leaf
    (a type, a float('inf') bound) — degrade those to strings."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, float):
        return v if v == v and abs(v) != float("inf") else str(v)
    return str(v)


class _WsListener:
    """One /ws client: a dedicated writer thread drains a bounded frame
    queue. All writes (events AND pongs) go through the single writer, so
    frames can never interleave mid-stream; ``send`` never blocks, and a
    stalled client simply fills its queue and is evicted — the socket close
    then unblocks any in-flight ``sendall``."""

    def __init__(self, sock):
        self.sock = sock
        self.frames: "queue.Queue[bytes | None]" = queue.Queue(maxsize=64)
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()

    def _write_loop(self) -> None:
        while True:
            frame = self.frames.get()
            if frame is None:
                return
            try:
                self.sock.sendall(frame)
            except OSError:
                return

    def send(self, frame: bytes) -> bool:
        """False → the queue is full (stalled client): caller should evict."""
        try:
            self.frames.put_nowait(frame)
            return True
        except queue.Full:
            return False

    def close(self) -> None:
        try:
            self.frames.put_nowait(None)
        except queue.Full:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class QueueFullError(RuntimeError):
    """Bounded prompt queue is full — surfaced as HTTP 429 (backpressure)."""


class DrainingError(RuntimeError):
    """Host is draining (POST /drain): no new prompts are seated — surfaced
    as HTTP 503 so a fleet router places the prompt elsewhere."""


def default_host_id() -> str:
    """Stable-ish per-process host identity for the fleet tier: explicit
    $PA_HOST_ID wins (operators name their hosts); otherwise hostname+pid —
    unique across a fleet of processes, including several on one machine."""
    hid = os.environ.get("PA_HOST_ID")
    if hid:
        return hid
    import socket

    try:
        name = socket.gethostname()
    except OSError:
        name = "host"
    return f"{name}-{os.getpid()}"


class PromptQueue:
    """Prompt executor with ComfyUI-shaped bookkeeping.

    Default is the reference's schedule: ONE worker thread, prompts strictly
    serial. ``workers > 1`` runs that many prompt workers concurrently and
    installs a ``serving.ContinuousBatchingScheduler`` so the overlapping
    sampler runs share compiled step dispatches (per-bucket batching); each
    prompt executes under its own ``progress_scope`` — per-prompt progress
    hooks and a per-prompt cooperative Cancel event that doubles as the
    serving layer's per-lane cancel."""

    def __init__(self, class_mappings=None, output_dir: str | None = None,
                 workers: int | None = None, max_pending: int | None = None,
                 serving: bool | None = None, trace: bool | None = None,
                 host_id: str | None = None, role: str | None = None):
        if trace is None:
            trace = os.environ.get("PA_TRACE", "") not in ("", "0", "false")
        if trace:
            tracing.enable()
        if os.environ.get("PA_NUMERICS", "") not in ("", "0", "false"):
            # Numerics sentinel (utils/numerics.py): per-lane non-finite
            # quarantine + latent fingerprints on the serving path; off by
            # default (single flag check, zero overhead).
            from .utils import numerics

            numerics.enable()
        self.class_mappings = class_mappings
        self.output_dir = output_dir or os.environ.get("PA_OUTPUT_DIR", "output")
        # Fleet identity + drain state (pa-health/v3): host_id names this
        # process on a router's scoreboard; accepting=False (POST /drain)
        # stops seating new prompts while running lanes finish.
        self.host_id = host_id or default_host_id()
        # Role-pool membership (fleet/roles.py): which stage tier this host
        # serves — "all" (the default) keeps the pre-role single-pool
        # behavior bitwise; a specific role rides the registration
        # heartbeat and /health so the router pools it.
        from .fleet.roles import normalize_role

        self.role = normalize_role(role or os.environ.get("PA_ROLE"))
        self.accepting = True
        self._drain_source = None
        # Residency advertisement (pa-health/v3): model keys this host has
        # served — its warm compiled programs / pinned weights, in the same
        # fleet/router.model_key space the ring places on. A router replaying
        # a dead sibling's prompts prefers a host whose warm set covers the
        # key over a cold primary. LRU-bounded: insertion-ordered dict,
        # oldest evicted past the cap.
        self.warm_keys: dict[str, float] = {}  # guarded-by: _lock
        self._warm_cap = 64
        self.cache = WorkflowCache()
        self.pending: "queue.Queue[tuple | None]" = queue.Queue()
        self.pending_ids: list[str] = []  # guarded-by: _lock
        # pid → its per-prompt cooperative Cancel event (progress_scope).
        self.running: dict[str, threading.Event] = {}  # guarded-by: _lock
        self.history: dict[str, dict] = {}  # guarded-by: _lock
        self.counter = 0
        self._lock = threading.Lock()
        self._listeners: dict = {}  # socket → _WsListener — guarded-by: _lock
        self.workers = max(
            1, int(workers if workers is not None
                   else os.environ.get("PA_SERVER_WORKERS", "1"))
        )
        if max_pending is None:
            max_pending = int(os.environ.get("PA_MAX_PENDING", "0"))
        # 0 means unbounded on BOTH spellings (param/CLI and env var).
        self.max_pending = max_pending or None
        self.scheduler = None
        self.decode_queue = None
        enable_serving = self.workers > 1 if serving is None else serving
        if enable_serving:
            from .serving import ContinuousBatchingScheduler, DecodeQueue

            self.scheduler = ContinuousBatchingScheduler().install()
            # Batched tail decode (serving/decode.py): concurrent prompts'
            # VAE decodes batch into shared compiled dispatches instead of
            # serializing inline behind each other's denoise.
            self.decode_queue = DecodeQueue().install()
        elif self.role == "decode":
            # A dedicated DECODE-tier host is the width-bucketed batching
            # target even single-worker: the router funnels every pool
            # member's decode stages here, so cross-prompt batching is the
            # point of the role (serving/decode.py lingers for siblings).
            from .serving import DecodeQueue

            self.decode_queue = DecodeQueue().install()
        # Periodic HBM sampling (utils/telemetry.py): keeps the pa_hbm_*
        # gauges and the peak watermark fresh between /metrics scrapes so
        # GET /health reflects memory state even while a prompt is wedged.
        self._mem_monitor = None
        try:
            from .utils.telemetry import MemoryMonitor, watch_compiles

            watch_compiles()  # /health's compile section needs the listeners
            self._mem_monitor = MemoryMonitor(
                float(os.environ.get("PA_MEM_SAMPLE_S", "60"))
            ).start()
        except Exception:
            pass
        # Continuous telemetry (utils/timeseries.py + utils/anomaly.py):
        # the seeded-cadence history sampler snapshots every pa_* family
        # into the bounded ring and ticks the anomaly sentinel — a daemon
        # thread entirely off the hot step path. PA_HISTORY_BYTES=0
        # disables the whole layer (bitwise no-op).
        self._history_sampler = None
        try:
            from .utils import timeseries

            if timeseries.enabled():
                self._history_sampler = timeseries.HistorySampler(
                    host=self.host_id
                ).start()
        except Exception:
            pass
        # unguarded: written once here before the threads start, only
        # iterated afterwards (shutdown joins a snapshot-stable list)
        self._workers = [
            threading.Thread(target=self._run, daemon=True)
            for _ in range(self.workers)
        ]
        for t in self._workers:
            t.start()

    def add_listener(self, sock) -> "_WsListener":
        listener = _WsListener(sock)
        with self._lock:
            self._listeners[sock] = listener
        return listener

    def remove_listener(self, sock) -> None:
        with self._lock:
            listener = self._listeners.pop(sock, None)
        if listener is not None:
            listener.close()

    def _emit(self, event: dict) -> None:
        """Queue one JSON event to every /ws client — never blocks the
        caller (the worker thread must not wedge on a stalled client); a
        client whose bounded queue fills is evicted."""
        frame = _ws_frame(json.dumps(event).encode())
        with self._lock:
            listeners = list(self._listeners.items())
        for sock, listener in listeners:
            if not listener.send(frame):
                self.remove_listener(sock)

    def _emit_binary(self, payload: bytes) -> None:
        """Queue one binary event (the stock preview-frame channel: a 4-byte
        big-endian event type + event payload, sent as a binary WS frame)."""
        frame = _ws_frame(payload, opcode=0x2)
        with self._lock:
            listeners = list(self._listeners.items())
        for sock, listener in listeners:
            if not listener.send(frame):
                self.remove_listener(sock)

    def _emit_status(self) -> None:
        with self._lock:
            remaining = len(self.pending_ids)
        self._emit({
            "type": "status",
            "data": {"status": {"exec_info": {"queue_remaining": remaining}}},
        })

    def submit(self, prompt: dict, preview: bool = False,
               priority: int = 0, deadline_s: float | None = None,
               fleet: dict | None = None,
               stage: dict | None = None) -> tuple[str, int]:
        pid = uuid.uuid4().hex
        # Bookkeeping AND enqueue under one lock: interrupt() drains under the
        # same lock, so a submit racing an interrupt either lands wholly
        # before (and is dropped with a history entry) or wholly after (and
        # survives) — never half-registered.
        with self._lock:
            if not self.accepting:
                raise DrainingError(
                    f"host {self.host_id} is draining (no new prompts)"
                )
            if (self.max_pending is not None
                    and len(self.pending_ids) - len(self.running)
                    >= self.max_pending):
                from .utils.metrics import registry

                registry.counter("pa_server_rejected_total",
                                 help="prompts refused with 429 (queue full)")
                raise QueueFullError(
                    f"queue full ({self.max_pending} pending)"
                )
            self.counter += 1
            number = self.counter
            self.pending_ids.append(pid)
            # The enqueue clock rides the item: the worker's pickup delta is
            # the ADMISSION stage of the SLO latency decomposition.
            self.pending.put((pid, prompt, bool(preview), int(priority),
                              deadline_s, fleet, stage, time.monotonic()))
        self._emit_status()
        return pid, number

    def inflight_prompts(self) -> int:
        """Queued + running — the pa-health/v3 field a fleet scoreboard
        reads for saturation decisions (caller need not hold the lock)."""
        with self._lock:
            return len(self.pending_ids)

    def _mark_warm(self, prompt: dict) -> None:
        """Record the executed prompt's model key as warm (pa-health/v3).
        Best-effort: residency advertisement must never fail a prompt."""
        try:
            from .fleet.router import model_key

            key = model_key(prompt)
            with self._lock:
                self.warm_keys.pop(key, None)
                # palint: allow[observability] epoch STAMP on an advertised
                # surface (pa-health/v3 warm-key recency), not a duration
                self.warm_keys[key] = time.time()
                while len(self.warm_keys) > self._warm_cap:
                    self.warm_keys.pop(next(iter(self.warm_keys)))
        except Exception:  # noqa: BLE001
            pass

    def drain(self, source: str = "operator") -> dict:
        """Stop seating new prompts (POST /prompt → 503); running prompts
        and their serving lanes finish normally — the fleet drain state a
        router observes via /health ``accepting``. ``source`` records WHO
        drained (operator via POST /drain vs an automatic policy): only
        non-operator drains may be auto-resumed by the rejoin hook below.
        Returns the drain view."""
        with self._lock:
            self.accepting = False
            self._drain_source = source
            state = {"host_id": self.host_id, "accepting": False,
                     "pending": len(self.pending_ids) - len(self.running),
                     "running": len(self.running)}
        return state

    def resume(self) -> dict:
        """Re-open admission after a drain (elastic rejoin)."""
        with self._lock:
            self.accepting = True
            self._drain_source = None
            return {"host_id": self.host_id, "accepting": True}

    def resume_if_auto_drained(self) -> None:
        """The heartbeat rejoin hook: re-open admission ONLY when the drain
        was not operator-initiated — a router restart mid-maintenance must
        not silently cancel the operator's POST /drain (chaos-review
        finding, round 14). A host that fell off the ring while serving has
        accepting=True already, so this is a no-op for it."""
        with self._lock:
            if self.accepting or getattr(self, "_drain_source", None) == "operator":
                return
            self.accepting = True
            self._drain_source = None

    def _drop_pending(self, pid: str) -> None:  # palint: holds _lock
        """history + bookkeeping for a prompt cancelled before it ran
        (caller holds the lock)."""
        self.pending_ids.remove(pid)
        self.history[pid] = {
            "status": {"status_str": "interrupted", "completed": False,
                       "host_id": self.host_id},
            "outputs": {},
        }

    def interrupt(self) -> int:
        """Drop every pending prompt AND ask every running one to stop at its
        next boundary (per-prompt cooperative scope events — the ComfyUI
        Cancel semantics; a single compiled step still cannot be preempted
        mid-dispatch). Anything a worker popped before this drain counts as
        running."""
        dropped = 0
        with self._lock:
            while True:
                try:
                    item = self.pending.get_nowait()
                except queue.Empty:
                    break
                if item is None:  # preserve the shutdown sentinel
                    self.pending.put(None)
                    break
                if item[0] in self.pending_ids:  # not already cancel()ed
                    dropped += 1
                    self._drop_pending(item[0])
            # An id still pending but not running is an in-flight pop (the
            # worker took it off the queue but hasn't published running yet):
            # removing it here makes the worker's pending_ids check drop it —
            # the Cancel wins the race instead of losing it.
            for pid in [p for p in self.pending_ids if p not in self.running]:
                dropped += 1
                self._drop_pending(pid)
            # Each running prompt's own scope event: set under the SAME lock
            # the worker registers it under, so a Cancel can never land in
            # the window between pop and registration. Fresh event per prompt
            # — no stale-flag choreography needed.
            for evt in self.running.values():
                evt.set()
        if self.scheduler is not None:
            self.scheduler.kick()  # lanes notice the events at this boundary
        if dropped:
            self._emit_status()  # ws clients must see the queue shrink
        return dropped

    def clear_pending(self) -> int:
        """Drop every PENDING prompt atomically (running ones finish) — the
        stock ``POST /queue {"clear": true}`` semantics. One lock hold, so a
        prompt a worker picks up concurrently is never misclassified as
        pending-then-cancelled-running."""
        dropped = 0
        with self._lock:
            for pid in [p for p in self.pending_ids if p not in self.running]:
                self._drop_pending(pid)
                dropped += 1
        if dropped:
            self._emit_status()
        return dropped

    def cancel(self, pids) -> int:
        """Per-prompt Cancel (stock ``POST /queue {"delete": [...]}``):
        pending prompts drop with an interrupted history entry; running ones
        get their scope event set — the cooperative boundary check stops the
        graph, and the serving scheduler frees the prompt's lane at the next
        step boundary without perturbing co-batched neighbors."""
        acted = 0
        with self._lock:
            targets = set(str(p) for p in pids)
            running_hits = [p for p in targets if p in self.running]
            pending_hits = [
                p for p in targets
                if p in self.pending_ids and p not in self.running
            ]
            for pid in pending_hits:
                self._drop_pending(pid)
                acted += 1
            for pid in running_hits:
                self.running[pid].set()
                acted += 1
        if running_hits and self.scheduler is not None:
            self.scheduler.kick()
        if pending_hits:
            self._emit_status()
        return acted

    def shutdown(self) -> None:
        self.pending.put(None)  # workers cascade the sentinel to siblings
        for t in self._workers:
            t.join(timeout=30)
        if self._mem_monitor is not None:
            self._mem_monitor.stop()
        if self._history_sampler is not None:
            self._history_sampler.stop()
        if self.scheduler is not None:
            self.scheduler.uninstall()
            self.scheduler.shutdown()
        if self.decode_queue is not None:
            self.decode_queue.shutdown()

    def _run(self) -> None:
        while True:
            item = self.pending.get()
            if item is None:
                self.pending.put(None)  # cascade to sibling workers
                return
            pid, prompt, preview, priority, deadline_s, fleet, stage, enq_ts = item
            cancel_evt = threading.Event()
            with self._lock:
                if pid not in self.pending_ids:
                    continue  # interrupted while queued
                # Publish under the same lock interrupt()/cancel() set events
                # under; the event is fresh per prompt, so a stale Cancel
                # aimed at a previous prompt cannot exist by construction.
                self.running[pid] = cancel_evt
            self._emit({"type": "execution_start", "data": {"prompt_id": pid}})
            t0 = time.monotonic()
            # SLO admission stage: ingress → worker pickup — the queue wait
            # a closed-loop client never inflates and an open-loop one does.
            admission_s = max(0.0, t0 - enq_ts)
            slo.observe_stage("admission", admission_s)
            if tracing.on():
                now_us = tracing.now_us()
                tracing.record("admission-wait", now_us - admission_s * 1e6,
                               admission_s * 1e6, cat="server",
                               prompt_id=pid)
            # Per-node `executing` + per-step `progress` events — the pair a
            # stock ComfyUI frontend renders its progress bars from. The node
            # id rides a cell so the progress hook can tag its events with
            # whichever node is currently executing.
            current: dict = {"node": None}

            def on_node(nid, _pid=pid, _cur=current):
                _cur["node"] = nid
                self._emit({
                    "type": "executing",
                    "data": {"node": nid, "prompt_id": _pid},
                })

            def hook(value, max_value, _pid=pid, _cur=current):
                self._emit({
                    "type": "progress",
                    "data": {"value": value, "max": max_value,
                             "prompt_id": _pid, "node": _cur["node"]},
                })

            def on_cached(nids, _pid=pid):
                self._emit({
                    "type": "execution_cached",
                    "data": {"nodes": list(nids), "prompt_id": _pid},
                })

            def preview_hook(latent):
                # Stock preview frame: >II event-type 1 (PREVIEW_IMAGE) +
                # image format 2 (PNG), then the PNG bytes. Never let a
                # preview failure (odd latent rank, PIL hiccup) kill the
                # prompt — previews are best-effort by contract.
                import struct

                try:
                    from .utils.latent_preview import preview_png

                    png = preview_png(latent)
                except Exception:  # noqa: BLE001 — preview is best-effort
                    return
                self._emit_binary(struct.pack(">II", 1, 2) + png)

            from .serving.scheduler import serving_hints

            # Fault site (utils/faults.py): the straggler rehearsal — an
            # injected slow-host stalls the prompt worker, not the HTTP
            # surface, so health polls stay green while latency inflates
            # (exactly the failure the router's saturation spill must absorb).
            _slow = faults.check("slow-host", key=pid)
            if _slow is not None:
                _slow.sleep()
            # Role-pool staged dispatch (fleet/roles.py): a router hop
            # carrying extra_data.pa_stage executes ONE carved stage — the
            # stage's upstream-closure subgraph with the previous stage's
            # content-addressed outputs preseeded. A failed carve or handle
            # resolution degrades to executing the closure (or the whole
            # graph) locally — bitwise by the fold_in contract, never an
            # error.
            exec_graph, preseed, stage_entry = self._stage_setup(prompt, stage)
            # Inbound distributed-trace context (W3C traceparent shape,
            # injected by the fleet router into extra_data.fleet): parsed
            # here so this host's whole span subtree — prompt, node, lane,
            # step, decode — joins the router's cross-host trace under one
            # trace_id. Malformed/absent context degrades to local-only.
            tp = (tracing.parse_traceparent(fleet.get("traceparent"))
                  if fleet and tracing.on() else None)
            try:
                # The prompt span is the root of this prompt's trace
                # timeline; prompt_id on the scope correlates log records and
                # spans recorded anywhere on (or on behalf of) this thread.
                with progress_scope(
                    hook=hook,
                    preview_hook=preview_hook if preview else None,
                    interrupt_event=cancel_evt,
                    prompt_id=pid,
                ), serving_hints(priority=priority, deadline_s=deadline_s), \
                        tracing.trace_context(tp), \
                        tracing.span(
                            "prompt", cat="server", prompt_id=pid,
                            # Every span names its host + role: the stitched
                            # fleet timeline's per-tier filter keys.
                            host_id=self.host_id, role=self.role,
                            # Cross-hop correlation: a fleet router stamps
                            # its own prompt id into extra_data.fleet, so
                            # this backend-side timeline joins the router's
                            # fleet-prompt/fleet-hop spans in one export.
                            **({"origin_prompt_id": fleet.get("origin"),
                                "router": fleet.get("router")}
                               if fleet else {}),
                            **({"trace_id": tp["trace_id"],
                                "parent_span_id": tp["parent_span_id"]}
                               if tp else {}),
                            **({"stage": stage_entry["stage"]}
                               if stage_entry is not None else {}),
                        ):
                    if stage_entry is not None:
                        # Denoise hosts may pull conds straight off the
                        # encode tier (models/embed_cache.py remote tier).
                        from .models.embed_cache import set_remote_sources

                        set_remote_sources(
                            (stage or {}).get("sources") or ())
                    try:
                        results = run_workflow(
                            exec_graph, class_mappings=self.class_mappings,
                            outputs=self.cache, on_node=on_node,
                            on_cached=on_cached, preseed=preseed,
                        )
                    finally:
                        if stage_entry is not None:
                            from .models.embed_cache import set_remote_sources

                            set_remote_sources(None)
                entry = {
                    "status": {"status_str": "success", "completed": True,
                               "exec_s": round(time.monotonic() - t0, 3)},
                    "outputs": self._image_outputs(prompt, results),
                }
                if stage_entry is not None:
                    # The stage hand-off: exported boundary outputs banked
                    # content-addressed; the router journals these handles
                    # as the prompt's stage lineage and preseeds them into
                    # the NEXT stage's dispatch.
                    entry["status"]["pa_stage"] = {
                        "stage": stage_entry["stage"],
                        "handles": self._stage_export(stage_entry, results),
                    }
                    from .utils.metrics import registry as _metrics

                    _metrics.histogram(
                        "pa_role_stage_seconds",
                        time.monotonic() - t0,
                        labels={"role": stage_entry["stage"]},
                        help="wall seconds of one carved stage execution "
                             "on a role-pool host")
                # This host now holds the prompt's model warm (compiled
                # programs + pinned weights) — advertise it (pa-health/v3).
                self._mark_warm(prompt)
                # Per-output-node `executed` events (what API clients collect
                # result images from without polling /history).
                for nid, out in entry["outputs"].items():
                    self._emit({
                        "type": "executed",
                        "data": {"node": nid, "output": out,
                                 "prompt_id": pid},
                    })
            except Interrupted:
                entry = {
                    "status": {"status_str": "interrupted", "completed": False},
                    "outputs": {},
                }
                self._emit({
                    "type": "execution_interrupted",
                    "data": {"prompt_id": pid, "node_id": current["node"]},
                })
            except Exception as e:  # noqa: BLE001 — failures land in history
                entry = {
                    "status": {"status_str": "error", "completed": False,
                               "message": f"{type(e).__name__}: {e}"},
                    "outputs": {},
                }
                # Flight recorder: an OOM (or any error under
                # PA_POSTMORTEM=always) dumps a forensics bundle and hands
                # the client its path in the history entry — the next
                # serving-on-hardware failure is diagnosable after the fact.
                try:
                    from .utils.telemetry import (
                        looks_like_oom,
                        write_postmortem,
                    )

                    if (looks_like_oom(e)
                            or os.environ.get("PA_POSTMORTEM") == "always"):
                        bundle = write_postmortem(f"prompt-{pid}", error=e)
                        if bundle:
                            entry["status"]["postmortem"] = bundle
                except Exception:  # noqa: BLE001 — forensics is best-effort
                    pass
            # Every history entry names the host that produced it — the
            # fleet tier's per-host latency attribution rides this field
            # (scripts/loadgen.py groups client latencies by it).
            entry["status"]["host_id"] = self.host_id
            # SLO request residency: admission wait + execution — the
            # server-observable part of the client's end-to-end latency
            # (the client-side remainder is loadgen's "collect" residual).
            slo.observe_request(admission_s + (time.monotonic() - t0))
            if tracing.on():
                # Completed-prompt retention: the fleet stitcher may collect
                # this prompt's spans long after the live rings wrapped.
                tracing.retain_prompt(pid)
            with self._lock:
                self.history[pid] = entry
                if pid in self.pending_ids:
                    self.pending_ids.remove(pid)
                # The per-prompt Cancel event retires with the prompt: a
                # Cancel that landed after the last cooperative checkpoint
                # dies with this entry instead of leaking into the next
                # prompt (the fresh-event-per-prompt discipline).
                self.running.pop(pid, None)
            # The canonical completion signal ComfyUI API clients block on.
            self._emit({
                "type": "executing", "data": {"node": None, "prompt_id": pid},
            })
            self._emit_status()

    def _stage_setup(self, prompt: dict, stage) -> tuple:
        """(exec_graph, preseed, stage_entry) for one staged dispatch.

        Re-derives the carve locally (host.carve_stages is deterministic, so
        router and backend always agree on the cut) and resolves the
        dispatch's handles: local stage store first, then the peer hosts the
        router listed. An unresolvable handle is simply not preseeded — the
        stage's upstream-closure graph recomputes that prefix locally,
        bitwise by fold_in. Unstaged prompts (or a carve the backend can't
        reproduce) fall back to the whole graph."""
        if not isinstance(stage, dict) or not stage.get("stage"):
            return prompt, None, None
        try:
            from .host import carve_stages

            plan = carve_stages(prompt)
        except Exception:
            plan = None
        stage_entry = None
        for st in (plan or {}).get("stages", ()):
            if st["stage"] == stage.get("stage"):
                stage_entry = st
                break
        if stage_entry is None:
            return prompt, None, None
        from .fleet import roles as fleet_roles
        from .utils.metrics import registry as _metrics

        handles = {str(k): v for k, v in (stage.get("handles") or {}).items()}
        sources = [str(b).rstrip("/") for b in (stage.get("sources") or ())]
        preseed: dict[str, tuple] = {}
        needs = {str(n) for n in stage_entry["needs"]}
        # Every carried handle that names a node in this closure preseeds,
        # not just the declared needs: the closure includes the whole
        # upstream prefix, and any resolved boundary inside it
        # short-circuits its subtree (a decode host must not re-run the
        # encoder class because the closure names the encode node). A miss
        # only counts for a NEEDS node — those are the ones whose absence
        # forces a prefix recompute.
        for nid in sorted(set(handles) | needs):
            if nid not in stage_entry["graph"]:
                continue
            key = handles.get(nid)
            value = fleet_roles.store.get_value(key) if key else None
            if value is None and key:
                value = self._fetch_stage_value(key, sources)
            if value is None:
                if nid in needs:
                    _metrics.counter(
                        "pa_role_handle_misses",
                        help="stage hand-off handles that resolved nowhere "
                             "(prefix recomputed locally)")
                continue
            _metrics.counter(
                "pa_role_handle_hits",
                help="stage hand-off handles resolved from the local or "
                     "peer stage store")
            preseed[nid] = tuple(value)
        return stage_entry["graph"], preseed, stage_entry

    def _fetch_stage_value(self, key: str, sources):
        """One handle off a peer's ``GET /stage/{key}``; the blob is banked
        in the local store too (this host serves it onward — takeover
        re-dispatches can land anywhere in the pool). None on any failure."""
        if not sources:
            return None
        import urllib.request

        from .fleet import roles as fleet_roles

        for base in sources:
            try:
                with urllib.request.urlopen(
                    f"{base}/stage/{key}", timeout=10
                ) as r:
                    blob = r.read()
                value = fleet_roles.deserialize_value(blob)
            except Exception:
                continue
            fleet_roles.store.put(blob)
            return value
        return None

    def _stage_export(self, stage_entry: dict, results: dict) -> dict:
        """Bank this stage's boundary outputs content-addressed; returns
        ``{node_id: content_key}`` — the handles the history entry carries
        and the journal's stage lineage records. Unserializable outputs are
        skipped (the next stage recomputes them), never an error."""
        from .fleet import roles as fleet_roles

        handles: dict[str, str] = {}
        for nid in stage_entry["exports"]:
            out = results.get(nid)
            if out is None:
                continue
            key = fleet_roles.store.put_value(out)
            if key:
                handles[nid] = key
        return handles

    def _image_outputs(self, prompt: dict, results: dict) -> dict:
        """ComfyUI history shape: per save-node ``{"images": [{filename,
        subfolder, type}]}`` — detected as outputs whose first element is a
        list of existing file paths (what the SaveImage family returns)."""
        out: dict[str, dict] = {}
        for nid in prompt:
            vals = results.get(str(nid))
            if not vals or not isinstance(vals[0], (list, tuple)):
                continue
            paths = [p for p in vals[0]
                     if isinstance(p, str) and os.path.exists(p)]
            if not paths:
                continue
            images = []
            for p in paths:
                rel = os.path.relpath(p, self.output_dir)
                sub, fname = os.path.split(rel)
                if sub.startswith(".."):
                    # Saved outside output_dir: /view's escape check would 403
                    # exactly this path, so advertising it would hand clients
                    # an unfetchable record — omit it from the history.
                    continue
                images.append(
                    {"filename": fname, "subfolder": sub, "type": "output"}
                )
            if images:
                out[str(nid)] = {"images": images}
        return out


class _Handler(BaseHTTPRequestHandler):
    q: PromptQueue  # injected by make_server
    # RFC 6455 §4 handshakes require an HTTP/1.1 status line — browsers and
    # strict WS clients reject 'HTTP/1.0 101'. (Every response sets
    # Content-Length, which HTTP/1.1 keep-alive needs.)
    protocol_version = "HTTP/1.1"
    # Every response is two small writes (buffered headers, then body);
    # with Nagle on, the body write can stall ~40ms behind the peer's
    # delayed ACK — tens of ms on every /history poll and /prompt hop,
    # which the fleet router pays per prompt. TCP_NODELAY it.
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code: int, payload, content_type="application/json"):
        body = (json.dumps(payload).encode()
                if content_type == "application/json" else payload)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _http_fault(self) -> bool:
        """Fault site (utils/faults.py ``backend-http``): per-request
        drop/delay/5xx keyed on ``METHOD /path``. Returns True when the
        request was consumed (the caller must not answer it) — the chaos
        rehearsal for half-dead backends whose sockets misbehave while the
        process lives. No-op (one flag read) when no plan is armed."""
        act = faults.check("backend-http", key=f"{self.command} {self.path}")
        if act is None:
            return False
        if act.mode == "delay":
            act.sleep()
            return False
        if act.mode == "drop":
            # Vanish mid-request: the peer sees a reset/EOF, exactly like a
            # crashed host — the router's OSError handling must absorb it.
            import socket as _socket

            self.close_connection = True
            try:
                self.connection.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            return True
        act.sleep()  # 5xx (default): alive but failing
        self._send(500, {"error": f"injected fault (site=backend-http, "
                                  f"hit={act.hit})"})
        return True

    def do_GET(self):  # noqa: N802 — http.server API
        if self._http_fault():
            return
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        if url.path == "/ws":
            return self._serve_websocket()
        if url.path == "/queue":
            with self.q._lock:
                running = list(self.q.running)
                pend = [p for p in self.q.pending_ids if p not in self.q.running]
            return self._send(
                200, {"queue_running": running, "queue_pending": pend}
            )
        if url.path == "/metrics":
            from .utils.metrics import registry

            with self.q._lock:
                registry.gauge("pa_server_queue_pending",
                               len(self.q.pending_ids) - len(self.q.running),
                               help="prompts queued, not yet running")
                registry.gauge("pa_server_running", len(self.q.running),
                               help="prompts executing right now")
            try:
                # Scrape-time refresh of the pa_hbm_* device gauges (the
                # periodic monitor keeps them warm between scrapes; a dead
                # device backend degrades to the last published values).
                from .devices.memory import publish_memory_gauges

                publish_memory_gauges()
            except Exception:
                pass
            try:
                # pa_numerics_* gauges (utils/numerics.py): published at
                # scrape time so a healthy server exposes explicit zeros,
                # not absent series.
                from .utils import numerics

                numerics.sentinel.publish_gauges()
            except Exception:
                pass
            try:
                # pa_roofline_* gauges (utils/roofline.py): per-program
                # calibrated predictions, plus the live trace window's
                # attribution fractions (comms / host-gap / compute /
                # exposed-transfer) when tracing is on — what
                # scripts/loadgen.py surfaces in its summary.
                from .utils import roofline

                roofline.publish_gauges()
            except Exception:
                pass
            try:
                # pa_slo_* burn-rate/budget gauges (utils/slo.py): windowed
                # objective verdicts published at scrape time — the
                # histograms carry lifetime counts, the gauges the window.
                slo.registry.publish_gauges()
            except Exception:
                pass
            try:
                # pa_embed_cache_* gauges (models/embed_cache.py): published
                # at scrape time so a fresh server exposes explicit zeros —
                # loadgen diffs them into embed_cache_hit_rate.
                from .models.embed_cache import cache as _embed_cache

                _embed_cache.publish_gauges()
            except Exception:
                pass
            try:
                # pa_role_stage_store_* gauges (fleet/roles.py): the
                # content-addressed stage hand-off store's residency.
                from .fleet.roles import store as _stage_store

                _stage_store.publish_gauges()
            except Exception:
                pass
            try:
                # pa_anomaly_* gauges (utils/anomaly.py): explicit zeros
                # for every quiet watched signal, 1 while firing — the
                # other families' scrape-time publish discipline.
                from .utils import anomaly

                anomaly.sentinel.publish_gauges()
            except Exception:
                pass
            return self._send(
                200, registry.render().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        if url.path == "/metrics/history":
            # The continuous-telemetry window (pa-history/v1): the bounded
            # ring's per-family points, readable while an incident is
            # happening — ?window= (seconds) and ?family= (comma name
            # prefixes) subset it. Disabled (PA_HISTORY_BYTES=0) serves an
            # empty, explicitly-disabled document rather than 404ing.
            from .utils import timeseries

            qs = parse_qs(url.query)
            try:
                window = qs.get("window", [None])[0]
                window = None if window in (None, "") else float(window)
            except ValueError:
                return self._send(400, {"error": "window must be seconds"})
            doc = timeseries.ring.window(
                window_s=window, families=qs.get("family", [None])[0]
            )
            doc["host"] = self.q.host_id
            return self._send(200, doc)
        if url.path == "/health":
            from .serving.bucket import batched_fraction
            from .utils.telemetry import health_snapshot

            with self.q._lock:
                queue = {
                    "pending": len(self.q.pending_ids) - len(self.q.running),
                    "running": len(self.q.running),
                    "workers": self.q.workers,
                    "max_pending": self.q.max_pending,
                    "completed": len(self.q.history),
                    "serving": self.q.scheduler is not None,
                    # Lane-steps served via shared dispatch / total — how
                    # much of the step traffic actually co-batched.
                    "serving_batched_fraction": round(batched_fraction(), 4),
                }
                # pa-health/v3 (fleet tier): identity + admission state a
                # router's scoreboard reads straight off this document — no
                # extra endpoint. v3 adds ``warm_keys`` (model residency:
                # which placement keys this host serves warm — the router's
                # failover re-dispatch prefers a warm sibling over a cold
                # primary); every v2 field is unchanged.
                host = {
                    "host_id": self.q.host_id,
                    "accepting": self.q.accepting,
                    "inflight_prompts": len(self.q.pending_ids),
                    "warm_keys": list(self.q.warm_keys),
                    # Role-pool membership (fleet/roles.py) — the scoreboard
                    # reads it so statically configured --backends hosts
                    # pool correctly without ever heartbeating.
                    "role": self.q.role,
                }
            return self._send(200, health_snapshot(queue=queue, host=host))
        if url.path == "/trace":
            # Chrome/Perfetto trace-event JSON (open at ui.perfetto.dev).
            # With tracing disabled the export is empty — the body says so
            # instead of 404ing, so a client can tell "off" from "no spans".
            qs = parse_qs(url.query)
            prompt_id = qs.get("prompt_id", [None])[0]
            trace = tracing.export(prompt_id=prompt_id)
            trace["enabled"] = tracing.on()
            # Stitch metadata (round 21): who this export belongs to — the
            # fleet collector labels the track and aligns the clock domain
            # off these (epoch_wall_s rides tracing.export itself).
            trace["host_id"] = self.q.host_id
            trace["role"] = self.q.role
            return self._send(200, trace)
        if parts and parts[0] == "history":
            # Snapshot under the queue lock: the worker thread inserts entries
            # under it, and json.dumps over a dict mutated mid-iteration raises
            # RuntimeError and aborts the connection. (Entries are written once
            # at insert, so a shallow copy is a consistent view.)
            with self.q._lock:
                snap = dict(self.q.history)
            if len(parts) == 2:
                entry = snap.get(parts[1])
                return self._send(200, {parts[1]: entry} if entry else {})
            return self._send(200, snap)
        if url.path == "/view":
            qs = parse_qs(url.query)
            fname = qs.get("filename", [""])[0]
            sub = qs.get("subfolder", [""])[0]
            path = os.path.normpath(os.path.join(self.q.output_dir, sub, fname))
            base = os.path.abspath(self.q.output_dir)
            if not os.path.abspath(path).startswith(base + os.sep):
                return self._send(403, {"error": "path escapes output dir"})
            if not os.path.exists(path):
                return self._send(404, {"error": "not found"})
            with open(path, "rb") as f:
                return self._send(200, f.read(), content_type="image/png")
        if parts and parts[0] == "object_info":
            from .nodes import NODE_CLASS_MAPPINGS, NODE_DISPLAY_NAME_MAPPINGS

            classes = dict(NODE_CLASS_MAPPINGS)
            classes.update(self.q.class_mappings or {})
            names = [parts[1]] if len(parts) == 2 else list(classes)
            info = {}
            for name in names:
                cls = classes.get(name)
                if cls is None:
                    continue
                info[name] = {
                    "input": _jsonable(cls.INPUT_TYPES()),
                    "output": _jsonable(list(cls.RETURN_TYPES)),
                    "output_name": _jsonable(
                        list(getattr(cls, "RETURN_NAMES", None)
                             or cls.RETURN_TYPES)
                    ),
                    "name": name,
                    "display_name": NODE_DISPLAY_NAME_MAPPINGS.get(name, name),
                    "description": getattr(cls, "DESCRIPTION", ""),
                    "category": getattr(cls, "CATEGORY", ""),
                }
            if len(parts) == 2 and not info:
                return self._send(404, {"error": f"unknown node {parts[1]!r}"})
            return self._send(200, info)
        if url.path == "/system_stats":
            from .devices.discovery import available_devices

            return self._send(200, {"devices": available_devices()})
        if parts and parts[0] == "embed" and len(parts) == 2:
            # Remote embed tier (models/embed_cache.py): an encode host
            # serves its content-addressed encoder outputs to denoise-pool
            # peers. 404 is a MISS, not an error — the peer encodes locally.
            from .models.embed_cache import export_blob

            blob = export_blob(parts[1])
            if blob is None:
                return self._send(404, {"error": "no such embed key"})
            return self._send(200, blob,
                              content_type="application/octet-stream")
        if parts and parts[0] == "stage" and len(parts) == 2:
            # Stage hand-off store (fleet/roles.py): serve one boundary
            # value (conds out of encode, latents out of denoise) to the
            # host running the next stage. 404 = miss = peer recomputes.
            from .fleet.roles import store as _stage_store

            blob = _stage_store.get(parts[1])
            if blob is None:
                return self._send(404, {"error": "no such stage key"})
            return self._send(200, blob,
                              content_type="application/octet-stream")
        return self._send(404, {"error": f"no route {url.path}"})

    def _serve_websocket(self):
        """RFC 6455 upgrade + event push. The thread parks reading client
        frames (ping → pong, close → exit) while PromptQueue._emit writes
        events to the raw socket from the worker thread."""
        key = self.headers.get("Sec-WebSocket-Key")
        if self.headers.get("Upgrade", "").lower() != "websocket" or not key:
            return self._send(400, {"error": "expected a WebSocket upgrade"})
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_GUID).encode()).digest()
        ).decode()
        sock = self.connection
        # Register BEFORE the 101 goes out: a client that POSTs /prompt the
        # instant its handshake completes must not race past an unregistered
        # listener and miss the prompt's events (TCP buffers anything queued
        # before the client starts reading).
        listener = self.q.add_listener(sock)
        self.send_response(101, "Switching Protocols")
        self.send_header("Upgrade", "websocket")
        self.send_header("Connection", "Upgrade")
        self.send_header("Sec-WebSocket-Accept", accept)
        self.end_headers()
        self.wfile.flush()
        self.close_connection = True
        try:
            while True:
                frame = _ws_read_frame(self.rfile)
                if frame is None or frame[0] == 0x8:  # EOF / close
                    return
                if frame[0] == 0x9:  # ping → pong, via the single writer
                    listener.send(_ws_frame(frame[1], opcode=0xA))
        except OSError:
            return
        finally:
            self.q.remove_listener(sock)

    def do_POST(self):  # noqa: N802 — http.server API
        if self._http_fault():
            return
        url = urlparse(self.path)
        if url.path == "/interrupt":
            return self._send(200, {"dropped": self.q.interrupt()})
        if url.path == "/drain":
            # Fleet drain: stop seating (POST /prompt → 503), finish running
            # lanes; {"resume": true} re-opens admission (elastic rejoin).
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad JSON: {e}"})
            if payload.get("resume"):
                return self._send(200, self.q.resume())
            return self._send(200, self.q.drain())
        if url.path == "/queue":
            # Stock per-prompt cancel: {"delete": [prompt_id, ...]} — routed
            # through the per-prompt scope event, which the serving layer's
            # lanes also watch ({"clear": true} drops every pending prompt).
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad JSON: {e}"})
            deleted = 0
            if payload.get("clear"):
                # Stock clear: every PENDING prompt drops; running ones finish.
                deleted += self.q.clear_pending()
            targets = payload.get("delete")
            if targets is not None:
                if not isinstance(targets, (list, tuple)):
                    return self._send(
                        400, {"error": '"delete" must be a list of prompt ids'}
                    )
                deleted += self.q.cancel(targets)
            return self._send(200, {"deleted": deleted})
        if url.path == "/prompt":
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                prompt = payload.get("prompt")
                if not isinstance(prompt, dict) or not prompt:
                    return self._send(
                        400, {"error": "body must carry a non-empty "
                                       '{"prompt": {...}} graph'}
                    )
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad JSON: {e}"})
            extra = payload.get("extra_data") or {}
            preview = bool(extra.get("preview") or payload.get("preview"))
            try:
                deadline_s = extra.get("deadline_s")
                fleet = extra.get("fleet")
                stage = extra.get("pa_stage")
                pid, number = self.q.submit(
                    prompt, preview=preview,
                    priority=int(extra.get("priority") or 0),
                    deadline_s=None if deadline_s is None else float(deadline_s),
                    fleet=fleet if isinstance(fleet, dict) else None,
                    stage=stage if isinstance(stage, dict) else None,
                )
            except DrainingError as e:
                return self._send(503, {"error": str(e)})
            except QueueFullError as e:
                return self._send(429, {"error": str(e)})
            except (TypeError, ValueError) as e:
                return self._send(400, {"error": f"bad extra_data: {e}"})
            return self._send(200, {"prompt_id": pid, "number": number})
        if url.path == "/history/phase":
            # Declared load-phase stamp (utils/timeseries.py): loadgen's
            # open-loop rungs announce themselves so the anomaly sentinel
            # attributes the rate ramp instead of paging on it.
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad JSON: {e}"})
            label = payload.get("label")
            if not label:
                return self._send(400, {"error": "label required"})
            from .utils import timeseries

            timeseries.ring.mark_phase(
                str(label), state=str(payload.get("state") or "begin"),
                detail=payload.get("detail"),
            )
            return self._send(200, {"ok": True})
        if url.path == "/upload/image":
            return self._upload_image()
        return self._send(404, {"error": f"no route {url.path}"})

    def _upload_image(self):
        """Stock ``POST /upload/image``: multipart form with an ``image``
        file part (+ optional ``overwrite``) saved into the input directory
        ($PA_INPUT_DIR — the folder LoadImage resolves against), response
        ``{"name", "subfolder", "type"}`` exactly as API clients expect."""
        import email
        import email.policy
        import os
        import re

        ctype = self.headers.get("Content-Type", "")
        if "multipart/form-data" not in ctype:
            return self._send(400, {"error": "multipart/form-data required"})
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        # Stock image uploads are MBs; a tight cap bounds the per-thread
        # buffering (body + parsed copy) on a host that also serves models.
        if length <= 0 or length > 64 * 1024 * 1024:
            return self._send(400, {"error": "bad Content-Length"})
        body = self.rfile.read(length)
        msg = email.message_from_bytes(
            b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + body,
            policy=email.policy.HTTP,
        )
        image_part = None
        overwrite = False
        for part in msg.iter_parts():
            name = part.get_param("name", header="content-disposition")
            if name == "image":
                image_part = part
            elif name == "overwrite":
                overwrite = (part.get_content() or "").strip().lower() in (
                    "1", "true", "yes")
        if image_part is None:
            return self._send(400, {"error": "no 'image' file part"})
        filename = image_part.get_filename() or "upload.png"
        # Flatten any path the client sent; keep a safe basename only, and
        # never a dot-name/empty result (open("input/..") would explode).
        filename = re.sub(r"[^A-Za-z0-9._-]", "_", os.path.basename(filename))
        if filename.strip("._") == "":
            filename = "upload.png"
        payload = image_part.get_payload(decode=True)
        if not payload:
            return self._send(400, {"error": "empty image payload"})
        in_dir = os.environ.get("PA_INPUT_DIR", "input")
        os.makedirs(in_dir, exist_ok=True)
        stem, ext = os.path.splitext(filename)
        path = os.path.join(in_dir, filename)
        if overwrite:
            with open(path, "wb") as f:
                f.write(payload)
        else:
            # Stock dedupe: suffix (1), (2), …; O_EXCL ("xb") makes the
            # pick-and-write atomic under the threaded server.
            i = 0
            while True:
                try:
                    with open(path, "xb") as f:
                        f.write(payload)
                    break
                except FileExistsError:
                    i += 1
                    filename = f"{stem} ({i}){ext}"
                    path = os.path.join(in_dir, filename)
        return self._send(200, {"name": filename, "subfolder": "",
                                "type": "input"})


class _HTTPServer(ThreadingHTTPServer):
    # http.server's default listen backlog is 5 — a fleet router's poll
    # traffic (history proxies + health polls + heartbeats, each a fresh
    # connection) overflows that in bursts and dispatch POSTs get
    # connection-reset, costing spurious failover retries.
    request_queue_size = 128


def make_server(
    host: str = "127.0.0.1",
    port: int = 8188,
    class_mappings=None,
    output_dir: str | None = None,
    workers: int | None = None,
    max_pending: int | None = None,
    serving: bool | None = None,
    trace: bool | None = None,
    host_id: str | None = None,
    role: str | None = None,
) -> tuple[ThreadingHTTPServer, PromptQueue]:
    """Build (but don't start) the HTTP server + its prompt queue. Port 0
    picks an ephemeral port (tests); ``server.server_address`` has the real
    one. ``workers > 1`` (or $PA_SERVER_WORKERS) executes prompts
    concurrently and installs the continuous-batching scheduler;
    ``max_pending`` (or $PA_MAX_PENDING) bounds the queue (429 beyond it);
    ``trace`` (or $PA_TRACE=1) turns the span tracer on so ``GET /trace``
    serves per-prompt timelines; ``host_id`` (or $PA_HOST_ID) names this
    process on a fleet router's scoreboard (pa-health/v3)."""
    q = PromptQueue(class_mappings=class_mappings, output_dir=output_dir,
                    workers=workers, max_pending=max_pending, serving=serving,
                    trace=trace, host_id=host_id, role=role)
    handler = type("Handler", (_Handler,), {"q": q})
    srv = _HTTPServer((host, port), handler)
    return srv, q


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8188)
    ap.add_argument("--output-dir", default=None)
    ap.add_argument("--workers", type=int, default=None,
                    help="concurrent prompt workers (>1 enables continuous "
                         "batching; default $PA_SERVER_WORKERS or 1)")
    ap.add_argument("--max-pending", type=int, default=None,
                    help="bounded queue depth — 429 beyond it "
                         "(default $PA_MAX_PENDING or unbounded)")
    ap.add_argument("--trace", action="store_true", default=None,
                    help="enable span tracing (GET /trace serves "
                         "Chrome/Perfetto trace JSON; default $PA_TRACE)")
    ap.add_argument("--host-id", default=None,
                    help="fleet identity on a router's scoreboard "
                         "(default $PA_HOST_ID or hostname-pid)")
    ap.add_argument("--role", default=None,
                    choices=["all", "encode", "denoise", "decode"],
                    help="role-pool membership (fleet/roles.py): which "
                         "stage tier this host serves — rides the "
                         "registration heartbeat and /health (default "
                         "$PA_ROLE or 'all', every pool)")
    ap.add_argument("--fleet-router", default=None,
                    help="router base URL(s), comma-separated (or "
                         "$PA_FLEET_ROUTER): register this host via "
                         "heartbeats so it joins the ring elastically and "
                         "drops out when it dies. List EVERY router of an "
                         "HA pair (primary + standby): a standby that takes "
                         "over must already know the fleet's membership")
    ap.add_argument("--advertise", default=None,
                    help="base URL the ROUTER should reach this host at "
                         "(default http://<host>:<port>)")
    args = ap.parse_args()
    srv, q = make_server(args.host, args.port, output_dir=args.output_dir,
                         workers=args.workers, max_pending=args.max_pending,
                         trace=args.trace, host_id=args.host_id,
                         role=args.role)
    heartbeats = []
    router_base = args.fleet_router or os.environ.get("PA_FLEET_ROUTER")
    if router_base:
        from .fleet.registry import HeartbeatClient

        # A wildcard bind is not a reachable address — advertise the host's
        # name instead (or let --advertise override for NAT/containers).
        reach = args.host
        if reach in ("0.0.0.0", "::", ""):
            import socket

            try:
                reach = socket.gethostname()
            except OSError:
                reach = "127.0.0.1"
        advertise = args.advertise or (
            f"http://{reach}:{srv.server_address[1]}"
        )
        # One heartbeat client PER router: an HA pair's standby must hold
        # live membership BEFORE its takeover (round-14 chaos finding: a
        # promoted standby that only ever heard of backends through the dead
        # primary has an empty ring and 503s everything).
        for rb in (b for b in router_base.split(",") if b):
            heartbeats.append(HeartbeatClient(
                rb, q.host_id, advertise,
                interval_s=float(os.environ.get("PA_FLEET_HEARTBEAT_S", "2")),
                # Rejoin after falling off the ring (router restart /
                # standby takeover / our own heartbeats lost): re-open
                # admission so the returning host takes traffic again — a
                # host that expired off the ring mid-drain would otherwise
                # rejoin refusing forever.
                on_rejoin=q.resume_if_auto_drained,
                role=q.role,
            ).start())
    # palint: allow[observability] server startup banner (CLI surface)
    print(f"ParallelAnything workflow server on http://{args.host}:{args.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        for hb in heartbeats:
            hb.stop()
        q.shutdown()


if __name__ == "__main__":
    main()
