"""Minimal ComfyUI-compatible HTTP API over the workflow host.

The reference pack's graphs are driven through ComfyUI's HTTP server (the
frontend and every scripting client POST API-format JSON to ``/prompt``).
This module is that surface for the standalone host: stdlib-only
(``http.server``), one worker thread executing prompts serially (one
accelerator — serial is the correct schedule), and a persistent
``host.WorkflowCache`` shared across prompts so a model loaded by one prompt
stays resident for the next (the reference's keep-loaded behavior, which its
``cleanup_parallel_model``/finalizer pair defends, any_device_parallel.py
211-282).

Endpoints (the ComfyUI client-protocol subset that makes scripts work):

- ``POST /prompt``            ``{"prompt": {...graph...}}`` → ``{"prompt_id"}``
- ``GET  /history``           all completed prompts
- ``GET  /history/{id}``      one prompt's status + outputs
- ``GET  /view?filename=``    serve a saved image (``subfolder=`` honored)
- ``GET  /queue``             running + pending prompt ids
- ``POST /interrupt``         drop all *pending* prompts (a compiled step
                              cannot be preempted mid-dispatch)
- ``GET  /object_info[/cls]`` node-registry introspection (INPUT_TYPES etc.)
- ``GET  /system_stats``      devices from devices.discovery

Run:  ``python -m comfyui_parallelanything_tpu.server [--port 8188]``
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .host import WorkflowCache, run_workflow


def _jsonable(v):
    """INPUT_TYPES trees hold tuples/dicts/strings and the odd non-JSON leaf
    (a type, a float('inf') bound) — degrade those to strings."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, float):
        return v if v == v and abs(v) != float("inf") else str(v)
    return str(v)


class PromptQueue:
    """Serial prompt executor with ComfyUI-shaped bookkeeping."""

    def __init__(self, class_mappings=None, output_dir: str | None = None):
        self.class_mappings = class_mappings
        self.output_dir = output_dir or os.environ.get("PA_OUTPUT_DIR", "output")
        self.cache = WorkflowCache()
        self.pending: "queue.Queue[tuple[str, dict] | None]" = queue.Queue()
        self.pending_ids: list[str] = []
        self.running: str | None = None
        self.history: dict[str, dict] = {}
        self.counter = 0
        self._lock = threading.Lock()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, prompt: dict) -> tuple[str, int]:
        pid = uuid.uuid4().hex
        # Bookkeeping AND enqueue under one lock: interrupt() drains under the
        # same lock, so a submit racing an interrupt either lands wholly
        # before (and is dropped with a history entry) or wholly after (and
        # survives) — never half-registered.
        with self._lock:
            self.counter += 1
            number = self.counter
            self.pending_ids.append(pid)
            self.pending.put((pid, prompt))
        return pid, number

    def interrupt(self) -> int:
        """Drop every pending prompt (the running one finishes — a compiled
        step cannot be preempted). Anything the worker popped before this
        drain counts as running."""
        dropped = 0
        with self._lock:
            while True:
                try:
                    item = self.pending.get_nowait()
                except queue.Empty:
                    break
                if item is None:  # preserve the shutdown sentinel
                    self.pending.put(None)
                    break
                pid = item[0]
                dropped += 1
                self.pending_ids.remove(pid)
                self.history[pid] = {
                    "status": {"status_str": "interrupted", "completed": False},
                    "outputs": {},
                }
        return dropped

    def shutdown(self) -> None:
        self.pending.put(None)
        self._worker.join(timeout=30)

    def _run(self) -> None:
        while True:
            item = self.pending.get()
            if item is None:
                return
            pid, prompt = item
            with self._lock:
                if pid not in self.pending_ids:
                    continue  # interrupted while queued
                self.running = pid
            t0 = time.time()
            try:
                results = run_workflow(
                    prompt, class_mappings=self.class_mappings,
                    outputs=self.cache,
                )
                entry = {
                    "status": {"status_str": "success", "completed": True,
                               "exec_s": round(time.time() - t0, 3)},
                    "outputs": self._image_outputs(prompt, results),
                }
            except Exception as e:  # noqa: BLE001 — failures land in history
                entry = {
                    "status": {"status_str": "error", "completed": False,
                               "message": f"{type(e).__name__}: {e}"},
                    "outputs": {},
                }
            with self._lock:
                self.history[pid] = entry
                self.pending_ids.remove(pid)
                self.running = None

    def _image_outputs(self, prompt: dict, results: dict) -> dict:
        """ComfyUI history shape: per save-node ``{"images": [{filename,
        subfolder, type}]}`` — detected as outputs whose first element is a
        list of existing file paths (what the SaveImage family returns)."""
        out: dict[str, dict] = {}
        for nid in prompt:
            vals = results.get(str(nid))
            if not vals or not isinstance(vals[0], (list, tuple)):
                continue
            paths = [p for p in vals[0]
                     if isinstance(p, str) and os.path.exists(p)]
            if not paths:
                continue
            images = []
            for p in paths:
                rel = os.path.relpath(p, self.output_dir)
                sub, fname = os.path.split(rel)
                if sub.startswith(".."):
                    sub, fname = "", p  # saved outside output_dir: absolute
                images.append(
                    {"filename": fname, "subfolder": sub, "type": "output"}
                )
            out[str(nid)] = {"images": images}
        return out


class _Handler(BaseHTTPRequestHandler):
    q: PromptQueue  # injected by make_server

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code: int, payload, content_type="application/json"):
        body = (json.dumps(payload).encode()
                if content_type == "application/json" else payload)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        if url.path == "/queue":
            with self.q._lock:
                running = [self.q.running] if self.q.running else []
                pend = [p for p in self.q.pending_ids if p != self.q.running]
            return self._send(
                200, {"queue_running": running, "queue_pending": pend}
            )
        if parts and parts[0] == "history":
            if len(parts) == 2:
                entry = self.q.history.get(parts[1])
                return self._send(200, {parts[1]: entry} if entry else {})
            return self._send(200, self.q.history)
        if url.path == "/view":
            qs = parse_qs(url.query)
            fname = qs.get("filename", [""])[0]
            sub = qs.get("subfolder", [""])[0]
            path = os.path.normpath(os.path.join(self.q.output_dir, sub, fname))
            base = os.path.abspath(self.q.output_dir)
            if not os.path.abspath(path).startswith(base + os.sep):
                return self._send(403, {"error": "path escapes output dir"})
            if not os.path.exists(path):
                return self._send(404, {"error": "not found"})
            with open(path, "rb") as f:
                return self._send(200, f.read(), content_type="image/png")
        if parts and parts[0] == "object_info":
            from .nodes import NODE_CLASS_MAPPINGS, NODE_DISPLAY_NAME_MAPPINGS

            classes = dict(NODE_CLASS_MAPPINGS)
            classes.update(self.q.class_mappings or {})
            names = [parts[1]] if len(parts) == 2 else list(classes)
            info = {}
            for name in names:
                cls = classes.get(name)
                if cls is None:
                    continue
                info[name] = {
                    "input": _jsonable(cls.INPUT_TYPES()),
                    "output": _jsonable(list(cls.RETURN_TYPES)),
                    "output_name": _jsonable(
                        list(getattr(cls, "RETURN_NAMES", None)
                             or cls.RETURN_TYPES)
                    ),
                    "name": name,
                    "display_name": NODE_DISPLAY_NAME_MAPPINGS.get(name, name),
                    "description": getattr(cls, "DESCRIPTION", ""),
                    "category": getattr(cls, "CATEGORY", ""),
                }
            if len(parts) == 2 and not info:
                return self._send(404, {"error": f"unknown node {parts[1]!r}"})
            return self._send(200, info)
        if url.path == "/system_stats":
            from .devices.discovery import available_devices

            return self._send(200, {"devices": available_devices()})
        return self._send(404, {"error": f"no route {url.path}"})

    def do_POST(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        if url.path == "/interrupt":
            return self._send(200, {"dropped": self.q.interrupt()})
        if url.path == "/prompt":
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                prompt = payload.get("prompt")
                if not isinstance(prompt, dict) or not prompt:
                    return self._send(
                        400, {"error": "body must carry a non-empty "
                                       '{"prompt": {...}} graph'}
                    )
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad JSON: {e}"})
            pid, number = self.q.submit(prompt)
            return self._send(200, {"prompt_id": pid, "number": number})
        return self._send(404, {"error": f"no route {url.path}"})


def make_server(
    host: str = "127.0.0.1",
    port: int = 8188,
    class_mappings=None,
    output_dir: str | None = None,
) -> tuple[ThreadingHTTPServer, PromptQueue]:
    """Build (but don't start) the HTTP server + its prompt queue. Port 0
    picks an ephemeral port (tests); ``server.server_address`` has the real
    one."""
    q = PromptQueue(class_mappings=class_mappings, output_dir=output_dir)
    handler = type("Handler", (_Handler,), {"q": q})
    srv = ThreadingHTTPServer((host, port), handler)
    return srv, q


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8188)
    ap.add_argument("--output-dir", default=None)
    args = ap.parse_args()
    srv, q = make_server(args.host, args.port, output_dir=args.output_dir)
    print(f"ParallelAnything workflow server on http://{args.host}:{args.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        q.shutdown()


if __name__ == "__main__":
    main()
