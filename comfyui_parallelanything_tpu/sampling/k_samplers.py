"""k-diffusion-family samplers: Euler, Euler-ancestral, Heun, DPM++ 2M.

The reference is driven by its host's KSampler — every sampler in that menu calls the
(monkey-patched) ``diffusion_model.forward`` once or twice per step
(any_device_parallel.py:1287). To stand alone, this framework carries the standard
sigma-space sampler set itself. Host-side step loops like ddim.py/flow.py: each model
call routes through the (possibly parallelized) forward, so the DP/pipeline scheduler
sees exactly the per-step batched calls it is designed for.

Conventions (eps-prediction SD family, k-diffusion/EDM parameterization):
``sigma_t = sqrt((1-ᾱ_t)/ᾱ_t)``; model input is ``x/sqrt(sigma²+1)`` at the discrete
timestep nearest in log-sigma; denoised prediction ``x0 = x - sigma·eps``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .cfg import apply_callback, double_kwargs, rescale_guidance
from .schedules import scaled_linear_schedule


def broadcast_cond_batch(arr, batch: int):
    """ComfyUI conditioning-batch semantics: one encoded prompt (or any even
    divisor) tiles to the latent batch; a non-divisor batch is a user error
    surfaced here rather than as a downstream XLA shape mismatch. Shared by
    the node boundary (nodes._prepare_sampling_inputs) and the denoiser's
    extra-cond path so direct ``run_sampler(extra_conds=...)`` callers get the
    same contract."""
    if arr is not None and arr.shape[0] != batch:
        if batch % arr.shape[0]:
            raise ValueError(
                f"conditioning batch {arr.shape[0]} does not divide "
                f"latent batch {batch}"
            )
        arr = jnp.repeat(arr, batch // arr.shape[0], axis=0)
    return arr


def model_sigmas(alphas_cumprod: jnp.ndarray) -> jnp.ndarray:
    """Per-trained-timestep sigma table, ascending with t."""
    return jnp.sqrt((1.0 - alphas_cumprod) / alphas_cumprod)


def sampling_sigmas(
    n_steps: int,
    alphas_cumprod: jnp.ndarray | None = None,
    sigma_table: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(n_steps+1,) descending sigmas over the model's range, ending at 0."""
    table = _sigma_table(alphas_cumprod, sigma_table)
    idx = jnp.linspace(len(table) - 1, 0, n_steps, dtype=jnp.float32)
    sig = jnp.interp(idx, jnp.arange(len(table), dtype=jnp.float32), table)
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


def karras_sigmas(
    n_steps: int,
    sigma_min: float = 0.0292,
    sigma_max: float = 14.6146,
    rho: float = 7.0,
) -> jnp.ndarray:
    """Karras et al. (2022) spacing — denser near sigma_min; (n_steps+1,), ends at 0."""
    ramp = jnp.linspace(0.0, 1.0, n_steps, dtype=jnp.float32)
    min_inv, max_inv = sigma_min ** (1 / rho), sigma_max ** (1 / rho)
    sig = (max_inv + ramp * (min_inv - max_inv)) ** rho
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


def exponential_sigmas(
    n_steps: int, sigma_min: float = 0.0292, sigma_max: float = 14.6146
) -> jnp.ndarray:
    """Log-uniform spacing (k-diffusion ``get_sigmas_exponential``); ends at 0."""
    sig = jnp.exp(
        jnp.linspace(
            jnp.log(jnp.float32(sigma_max)), jnp.log(jnp.float32(sigma_min)), n_steps
        )
    )
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


def _sigma_table(
    alphas_cumprod: jnp.ndarray | None, sigma_table: jnp.ndarray | None = None
) -> jnp.ndarray:
    if sigma_table is not None:
        return sigma_table
    if alphas_cumprod is None:
        alphas_cumprod = scaled_linear_schedule()
    return model_sigmas(alphas_cumprod)


def flow_sigma_table(shift: float = 1.0, n: int = 1000) -> jnp.ndarray:
    """The CONST (rectified-flow) model sigma table: sigma(t) = t with the
    resolution shift applied, ascending over n trained timesteps — the host's
    ModelSamplingDiscreteFlow table, which its scheduler menu samples for flow
    models. sigma_max = 1, sigma_min = shifted(1/n) (~1e-3)."""
    from .flow import apply_flow_shift

    return apply_flow_shift(
        jnp.linspace(1.0 / n, 1.0, n, dtype=jnp.float32), shift
    )


def sgm_uniform_sigmas(
    n_steps: int,
    alphas_cumprod: jnp.ndarray | None = None,
    sigma_table: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """SGM/EDM "trailing" uniform-timestep spacing (ComfyUI ``sgm_uniform``):
    n+1 uniform timesteps, last dropped, so the final nonzero sigma sits one
    uniform stride above 0 instead of at sigma_min."""
    table = _sigma_table(alphas_cumprod, sigma_table)
    idx = jnp.linspace(len(table) - 1, 0, n_steps + 1, dtype=jnp.float32)[:-1]
    sig = jnp.interp(idx, jnp.arange(len(table), dtype=jnp.float32), table)
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


def simple_sigmas(
    n_steps: int,
    alphas_cumprod: jnp.ndarray | None = None,
    sigma_table: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """ComfyUI ``simple``: raw table entries at equal index strides (no interp)."""
    table = _sigma_table(alphas_cumprod, sigma_table)
    stride = len(table) / n_steps
    idx = [len(table) - 1 - int(i * stride) for i in range(n_steps)]
    sig = table[jnp.asarray(idx, jnp.int32)]
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


def _beta_ppf(q: np.ndarray, a: float, b: float, grid_points: int = 65537) -> np.ndarray:
    """Beta quantile function by numeric CDF inversion (jax betainc + interp) —
    keeps the beta scheduler dependency-free (scipy is not a package dep)."""
    from jax.scipy.special import betainc

    grid = np.linspace(0.0, 1.0, grid_points, dtype=np.float64)
    cdf = np.asarray(betainc(a, b, jnp.asarray(grid)), np.float64)
    return np.interp(q, cdf, grid)


def beta_sigmas(
    n_steps: int,
    alphas_cumprod: jnp.ndarray | None = None,
    alpha: float = 0.6,
    beta: float = 0.6,
    sigma_table: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """ComfyUI ``beta`` (arXiv:2407.12173): timesteps at Beta(0.6, 0.6) quantiles —
    denser at both schedule ends. Duplicate timesteps (quantiles collide after
    rounding at high step counts) are skipped like the reference implementation,
    so the result may be shorter than ``n_steps + 1`` — a repeated sigma would
    divide-by-zero the multistep samplers (lms, dpm++ sde)."""
    table = _sigma_table(alphas_cumprod, sigma_table)
    # endpoint=False matches the reference scheduler: quantiles stop one stride
    # above q=0, so the last nonzero sigma sits above sigma_min.
    ts = 1.0 - np.linspace(0.0, 1.0, n_steps, endpoint=False, dtype=np.float64)
    idx = np.rint(_beta_ppf(ts, alpha, beta) * (len(table) - 1)).astype(np.int64)
    keep = np.concatenate([[True], np.diff(idx) != 0])
    sig = table[jnp.asarray(idx[keep], jnp.int32)]
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


def ddim_uniform_sigmas(
    n_steps: int,
    alphas_cumprod: jnp.ndarray | None = None,
    sigma_table: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """ComfyUI ``ddim_uniform``: the DDIM stride — table entries at indices
    ``1, 1+T//n, 1+2·T//n, … (< T)`` (integer stride, so the realized step count
    can differ slightly from ``n_steps``), descending."""
    table = _sigma_table(alphas_cumprod, sigma_table)
    T = len(table)
    stride = T // n_steps
    if stride <= 1:
        # Stride 1 would enumerate (nearly) the whole table regardless of the
        # request. Uniform trailing spacing is the exact limit of the stride
        # scheme as stride→1, and it honors the requested count — so the
        # degenerate regime hands off to sgm_uniform.
        return sgm_uniform_sigmas(n_steps, alphas_cumprod, sigma_table)
    idx = list(range(1, T, stride))
    sig = table[jnp.asarray(list(reversed(idx)), jnp.int32)]
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


def kl_optimal_sigmas(
    n_steps: int,
    alphas_cumprod: jnp.ndarray | None = None,
    sigma_table: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """"Align Your Steps" KL-optimal spacing (arXiv:2404.14507):
    σᵢ = tan((1−i/(n−1))·atan(σ_max) + (i/(n−1))·atan(σ_min)) — inclusive
    interpolation, so the last nonzero sigma is exactly σ_min."""
    table = _sigma_table(alphas_cumprod, sigma_table)
    sigma_min, sigma_max = jnp.float32(table[0]), jnp.float32(table[-1])
    frac = jnp.linspace(0.0, 1.0, n_steps, dtype=jnp.float32)
    sig = jnp.tan((1.0 - frac) * jnp.arctan(sigma_max) + frac * jnp.arctan(sigma_min))
    return jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)])


SCHEDULER_NAMES = (
    "karras", "normal", "exponential", "sgm_uniform", "simple", "ddim_uniform",
    "beta", "kl_optimal",
)


def make_sigmas(
    scheduler: str,
    n_steps: int,
    alphas_cumprod: jnp.ndarray | None = None,
    sigma_table: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """The KSampler scheduler menu: named spacing → (n_steps+1,) descending sigmas
    ending at 0, ranged over the model's sigma table when one is supplied.
    ``sigma_table`` overrides the eps alpha-bar derivation — flow models pass
    ``flow_sigma_table(shift)`` so every scheduler ranges over flow time,
    exactly as the host menu does for CONST model sampling."""
    if scheduler in ("karras", "exponential"):
        fn = karras_sigmas if scheduler == "karras" else exponential_sigmas
        if alphas_cumprod is None and sigma_table is None:
            return fn(n_steps)
        table = _sigma_table(alphas_cumprod, sigma_table)
        return fn(n_steps, sigma_min=float(table[0]), sigma_max=float(table[-1]))
    if scheduler == "normal":
        return sampling_sigmas(n_steps, alphas_cumprod, sigma_table)
    if scheduler == "sgm_uniform":
        return sgm_uniform_sigmas(n_steps, alphas_cumprod, sigma_table)
    if scheduler == "simple":
        return simple_sigmas(n_steps, alphas_cumprod, sigma_table)
    if scheduler == "ddim_uniform":
        return ddim_uniform_sigmas(n_steps, alphas_cumprod, sigma_table)
    if scheduler == "beta":
        return beta_sigmas(n_steps, alphas_cumprod, sigma_table=sigma_table)
    if scheduler == "kl_optimal":
        return kl_optimal_sigmas(n_steps, alphas_cumprod, sigma_table)
    raise ValueError(
        f"unknown scheduler {scheduler!r} (have {', '.join(SCHEDULER_NAMES)})"
    )


def area_weight(area, strength: float, shape, mask=None,
                mask_strength: float = 1.0, area_pct=None):
    """Per-pixel weight for one cond: ``strength`` everywhere (no
    scoping), strength inside the (h, w, y, x) latent-unit box (SetArea),
    or a pixel-space MASK resized to the latent grid (SetMask — stock's
    mask conditioning; "mask bounds" and "default" produce the same
    weights, the bounds only being stock's compute-crop optimization).
    Non-2D latents (video) use the full frame — stock scoping is 2D.

    Module-level (round 16) so the serving bucket composes the SAME weight
    maps host-side at seat time for the lane program's per-lane ``mc_w0`` /
    ``mc_w`` stacks; EpsDenoiser._area_mask delegates here."""
    weight = jnp.float32(strength)
    if area_pct is not None and area is None and len(shape) == 4:
        # Fractional box (ConditioningSetAreaPercentage): resolve against
        # the LATENT frame at weight time, when its shape is known.
        fh, fw, fy, fx = (float(v) for v in area_pct)
        area = (max(1, round(fh * shape[1])), max(1, round(fw * shape[2])),
                round(fy * shape[1]), round(fx * shape[2]))
    if area is not None and len(shape) == 4:
        h, w, y, x0 = (int(v) for v in area)
        box = jnp.zeros((1, shape[1], shape[2], 1), jnp.float32)
        weight = weight * box.at[:, y:y + h, x0:x0 + w, :].set(1.0)
    if mask is not None and len(shape) == 4:
        from ..models.vae import normalize_mask

        m = normalize_mask(mask, (shape[1], shape[2]))
        if m.shape[0] not in (1, shape[0]):
            m = m[:1]
        # Both present (SetMask then SetArea): stock composes — the area
        # crop times the mask weight inside it (get_area_and_mult), with
        # the mask's OWN strength multiplier kept separate from the
        # area's (stock's strength × mask_strength).
        weight = weight * m * jnp.float32(mask_strength)
    return weight


class EpsDenoiser:
    """Wraps a model forward into ``denoise(x, sigma) -> x0`` with batched CFG
    (cond ‖ uncond in one call — what feeds the DP path its batch, ddim.py).

    ``prediction`` selects the parameterization:

    - ``"eps"`` — noise prediction (SD1.5/SDXL family); x0 = x − σ·eps with
      the 1/√(σ²+1) input scaling and log-interp σ→timestep table.
    - ``"v"``   — SD2.x-768 v-param (x0 = c_skip·x + c_out·v with
      c_skip = 1/(σ²+1), c_out = −σ/√(σ²+1)).
    - ``"flow"`` — rectified-flow velocity (FLUX/WAN family). Here σ IS the
      flow time t ∈ (0, 1]: the model takes x unscaled and t directly, and
      x0 = x − σ·v. This is exact, not an approximation: under the flow
      forward x_t = (1−t)·x0 + t·n, the k-diffusion ODE d = (x − x0)/σ equals
      the velocity n − x0, so the whole sigma-space sampler family integrates
      the same probability-flow ODE ``flow_euler`` does — any k-sampler works
      on a flow model given a flow-time schedule (the host KSampler's CONST
      model-sampling wrapper, reproduced TPU-side)."""

    def __init__(
        self,
        model,
        context=None,
        *,
        cfg_scale: float = 1.0,
        uncond_context=None,
        uncond_kwargs: dict | None = None,
        alphas_cumprod: jnp.ndarray | None = None,
        prediction: str = "eps",
        cfg_rescale: float = 0.0,
        extra_conds: tuple | list | None = None,
        cond_area: tuple | None = None,
        cond_area_pct: tuple | None = None,
        cond_mask=None,
        cond_strength: float = 1.0,
        cond_mask_strength: float = 1.0,
        **model_kwargs,
    ):
        if alphas_cumprod is None:
            alphas_cumprod = scaled_linear_schedule()
        if prediction not in ("eps", "v", "flow"):
            raise ValueError(
                f"prediction must be 'eps', 'v' or 'flow', got {prediction!r}"
            )
        self.prediction = prediction
        self.model = model
        self.context = context
        self.cfg_scale = cfg_scale
        self.cfg_rescale = cfg_rescale
        self.uncond_context = uncond_context
        self.uncond_kwargs = uncond_kwargs
        # Multi-cond (stock ConditioningCombine/SetArea): extra positive conds,
        # each {"context", "pooled"?, "strength"?, "area"? (h, w, y, x) in
        # latent units}. Predictions are area-weight-normalized per pixel —
        # ComfyUI's calc_cond_batch combination rule, minus its crop-run
        # optimization (each cond here sees the full latent; documented
        # divergence). ``cond_area``/``cond_strength`` scope the PRIMARY cond
        # the same way when SetArea was applied to it directly.
        self.extra_conds = tuple(extra_conds or ())
        self.cond_area = cond_area
        self.cond_area_pct = cond_area_pct  # fractional SetAreaPercentage box
        self.cond_mask = cond_mask  # pixel-space MASK (ConditioningSetMask)
        self.cond_strength = cond_strength
        self.cond_mask_strength = cond_mask_strength
        self.kwargs = model_kwargs
        self.sigma_table = model_sigmas(alphas_cumprod)
        self.log_sigmas = jnp.log(self.sigma_table)

    def _area_mask(self, area, strength: float, shape, mask=None,
                   mask_strength: float = 1.0, area_pct=None):
        return area_weight(area, strength, shape, mask=mask,
                           mask_strength=mask_strength, area_pct=area_pct)

    def _combine_conds(self, eps_c, x_in, t_vec, batch):
        """Area-weight-normalized blend of the primary cond's prediction with
        every extra cond's (one model call each — token lengths differ, so
        they cannot batch into one call without padding). An extra carrying
        ``timestep_range`` (start, end) contributes only while sampling
        progress is inside the window (the stock ConditioningSetTimestepRange
        + Combine multi-stage pattern)."""
        m0 = self._area_mask(self.cond_area, self.cond_strength, x_in.shape,
                             mask=self.cond_mask,
                             mask_strength=self.cond_mask_strength,
                             area_pct=self.cond_area_pct)
        num = m0 * eps_c
        den = m0 * jnp.ones_like(eps_c[..., :1])
        for e in self.extra_conds:
            ctx = broadcast_cond_batch(e["context"], batch)
            kw = dict(self.kwargs)
            pooled = e.get("pooled")
            if pooled is not None:
                kw["y"] = broadcast_cond_batch(pooled, batch)
            eps_e = self.model(x_in, t_vec, ctx, **kw)
            m = self._area_mask(
                e.get("area"), float(e.get("strength", 1.0)), x_in.shape,
                mask=e.get("mask"),
                mask_strength=float(e.get("mask_strength", 1.0)),
                area_pct=e.get("area_pct"),
            )
            rng_ = e.get("timestep_range")
            if rng_ is not None:
                from ..ops.basic import progress_window_gate

                m = m * progress_window_gate(
                    t_vec, rng_[0], rng_[1], x_in.ndim,
                    flow_time=(self.prediction == "flow"),
                )
            num = num + m * eps_e
            den = den + m * jnp.ones_like(eps_e[..., :1])
        # Uncovered pixels (every cond area-scoped away from them) fall back
        # to the primary prediction rather than dividing by zero.
        return jnp.where(den > 0, num / jnp.maximum(den, 1e-8), eps_c)

    def _timestep(self, sigma) -> jnp.ndarray:
        """Continuous timestep whose table sigma matches (log-space interpolation)."""
        return jnp.interp(
            jnp.log(sigma),
            self.log_sigmas,
            jnp.arange(len(self.log_sigmas), dtype=jnp.float32),
        )

    def __call__(self, x: jnp.ndarray, sigma: jnp.ndarray) -> jnp.ndarray:
        batch = x.shape[0]
        if self.prediction == "flow":
            # Flow time is the sigma: the model takes x raw and t = σ directly.
            scale = 1.0
            t_vec = jnp.full((batch,), sigma, jnp.float32)
            x_in = x
        else:
            scale = 1.0 / jnp.sqrt(sigma**2 + 1.0)
            t_vec = jnp.full((batch,), self._timestep(sigma), jnp.float32)
            x_in = x * scale
        use_cfg = self.cfg_scale != 1.0 and self.uncond_context is not None
        if use_cfg:
            # Every per-batch kwarg doubles with the batch; uncond variants (e.g.
            # SDXL's negative pooled y) ride the second half (sampling/cfg.py).
            kw = double_kwargs(self.kwargs, self.uncond_kwargs, batch)
            eps_both = self.model(
                jnp.concatenate([x_in, x_in], axis=0),
                jnp.concatenate([t_vec, t_vec], axis=0),
                jnp.concatenate([self.context, self.uncond_context], axis=0),
                **kw,
            )
            eps_c, eps_u = jnp.split(eps_both, 2, axis=0)
            if (self.extra_conds or self.cond_area is not None
                    or self.cond_area_pct is not None
                    or self.cond_mask is not None):
                eps_c = self._combine_conds(eps_c, x_in, t_vec, batch)
            eps = eps_u + self.cfg_scale * (eps_c - eps_u)
            eps = rescale_guidance(eps, eps_c, self.cfg_rescale)
        else:
            eps = self.model(x_in, t_vec, self.context, **self.kwargs)
            if (self.extra_conds or self.cond_area is not None
                    or self.cond_area_pct is not None
                    or self.cond_mask is not None):
                eps = self._combine_conds(eps, x_in, t_vec, batch)
        if self.prediction == "v":
            return x / (sigma**2 + 1.0) - eps * sigma * scale
        # eps: x0 = x − σ·eps. flow: x0 = x − σ·v — the same expression.
        return x - sigma * eps


def sample_euler(denoise, x, sigmas, callback=None):
    """Deterministic Euler over the sigma schedule."""
    for i in range(len(sigmas) - 1):
        x0 = denoise(x, sigmas[i])
        d = (x - x0) / sigmas[i]
        x = x + d * (sigmas[i + 1] - sigmas[i])
        x = apply_callback(callback, i, x)
    return x


def ancestral_steps(s, s_next, eta: float = 1.0):
    """(sigma_down, sigma_up) for an ancestral step from ``s`` to ``s_next``
    (k-diffusion's get_ancestral_step): deterministic integration runs to
    sigma_down, then sigma_up of fresh noise restores the s_next level."""
    sigma_up = jnp.minimum(
        s_next,
        eta * jnp.sqrt(jnp.maximum(s_next**2 * (s**2 - s_next**2) / s**2, 0.0)),
    )
    sigma_down = jnp.sqrt(jnp.maximum(s_next**2 - sigma_up**2, 0.0))
    return sigma_down, sigma_up


def sample_euler_ancestral(denoise, x, sigmas, rng, eta: float = 1.0, callback=None):
    """Euler with ancestral noise injection (stochastic).

    RNG discipline (shared by every stochastic sampler here, their compiled
    twins, and the serving lanes): the step-``i`` key is ``fold_in(rng, i)``
    — a pure function of (request rng, step index), never of how many draws
    preceded it — so output is bit-identical whether the run executes alone,
    inside a compiled loop, or co-batched in a serving lane (round 10)."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        sigma_down, sigma_up = ancestral_steps(s, s_next, eta)
        d = (x - x0) / s
        x = x + d * (sigma_down - s)
        if float(s_next) > 0:
            sub = jax.random.fold_in(rng, i)
            x = x + sigma_up * jax.random.normal(sub, x.shape, x.dtype)
        x = apply_callback(callback, i, x)
    return x


def sample_euler_ancestral_rf(denoise, x, sigmas, rng, eta: float = 1.0,
                              callback=None):
    """Euler ancestral for rectified-flow schedules (the host's
    ``sample_euler_ancestral_RF``): under x_t = (1−t)·x0 + t·n the VE renoise
    ``x += σ_up·n`` would leave the (1−t)·x0 component unscaled, so the RF form
    rescales by the interpolant's alpha ratio and injects the variance that
    exactly restores the t_next marginal."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        if float(s_next) == 0.0:
            x = x0
        else:
            downstep = 1.0 + (s_next / s - 1.0) * eta
            sd = s_next * downstep
            alpha_ip1 = 1.0 - s_next
            alpha_down = 1.0 - sd
            renoise = jnp.sqrt(jnp.maximum(
                s_next**2 - sd**2 * alpha_ip1**2 / alpha_down**2, 0.0
            ))
            ratio = sd / s
            x = ratio * x + (1.0 - ratio) * x0
            sub = jax.random.fold_in(rng, i)
            x = (alpha_ip1 / alpha_down) * x + renoise * jax.random.normal(
                sub, x.shape, x.dtype
            )
        x = apply_callback(callback, i, x)
    return x


def sample_dpmpp_2s_ancestral_rf(denoise, x, sigmas, rng, eta: float = 1.0,
                                 callback=None):
    """DPM-Solver++(2S) ancestral for rectified-flow schedules (the host's
    ``sample_dpmpp_2s_ancestral_RF``): the exponential-integrator time is the
    flow log-SNR λ = log((1−σ)/σ), the midpoint sits at λ + h/2 (pinned to
    σ = 0.9999 when σ = 1, where λ diverges), and the renoise rescales by the
    interpolant's alpha ratio like the RF Euler-ancestral form."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        downstep = 1.0 + (s_next / s - 1.0) * eta
        sd = s_next * downstep
        alpha_ip1 = 1.0 - s_next
        alpha_down = 1.0 - sd
        renoise = jnp.sqrt(jnp.maximum(
            s_next**2 - sd**2 * alpha_ip1**2 / alpha_down**2, 0.0
        ))
        if float(s_next) == 0.0:
            d = (x - x0) / s
            x = x + d * (sd - s)
        else:
            if float(s) >= 1.0:
                sigma_mid = jnp.float32(0.9999)
            else:
                t_i = jnp.log((1.0 - s) / s)
                t_down = jnp.log((1.0 - sd) / sd)
                h = t_down - t_i
                sigma_mid = 1.0 / (jnp.exp(t_i + 0.5 * h) + 1.0)
            u = (sigma_mid / s) * x + (1.0 - sigma_mid / s) * x0
            x0_2 = denoise(u, sigma_mid)
            x = (sd / s) * x + (1.0 - sd / s) * x0_2
        if float(s_next) > 0:
            sub = jax.random.fold_in(rng, i)
            x = (alpha_ip1 / alpha_down) * x + renoise * jax.random.normal(
                sub, x.shape, x.dtype
            )
        x = apply_callback(callback, i, x)
    return x


def sample_lcm_rf(denoise, x, sigmas, rng, callback=None):
    """LCM on rectified-flow schedules: re-noising uses the flow interpolant
    ``x = t·n + (1−t)·x0`` (the host's CONST ``noise_scaling``) instead of the
    VE ``x0 + σ·n``."""
    for i in range(len(sigmas) - 1):
        x0 = denoise(x, sigmas[i])
        x = x0
        if float(sigmas[i + 1]) > 0:
            sub = jax.random.fold_in(rng, i)
            t = sigmas[i + 1]
            x = t * jax.random.normal(sub, x.shape, x.dtype) + (1.0 - t) * x0
        x = apply_callback(callback, i, x)
    return x


def sample_heun(denoise, x, sigmas, callback=None):
    """Heun's 2nd-order method (two model calls per step except the last)."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        d = (x - x0) / s
        x_pred = x + d * (s_next - s)
        if float(s_next) == 0.0:
            x = x_pred
        else:
            x0_2 = denoise(x_pred, s_next)
            d2 = (x_pred - x0_2) / s_next
            x = x + 0.5 * (d + d2) * (s_next - s)
        x = apply_callback(callback, i, x)
    return x


def sample_dpm_2(denoise, x, sigmas, callback=None):
    """DPM2 (k-diffusion ``sample_dpm_2``): explicit midpoint method — the
    second model call sits at the geometric mean of the step's sigmas."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        d = (x - x0) / s
        if float(s_next) == 0.0:
            x = x + d * (s_next - s)
        else:
            sigma_mid = jnp.exp(0.5 * (jnp.log(s) + jnp.log(s_next)))
            x_2 = x + d * (sigma_mid - s)
            x0_2 = denoise(x_2, sigma_mid)
            d_2 = (x_2 - x0_2) / sigma_mid
            x = x + d_2 * (s_next - s)
        x = apply_callback(callback, i, x)
    return x


def sample_dpm_2_ancestral(denoise, x, sigmas, rng, eta: float = 1.0, callback=None):
    """DPM2 ancestral (k-diffusion ``sample_dpm_2_ancestral``): the midpoint
    step runs to sigma_down, then sigma_up of fresh noise is injected."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        sigma_down, sigma_up = ancestral_steps(s, s_next, eta)
        d = (x - x0) / s
        if float(sigma_down) == 0.0:
            x = x + d * (sigma_down - s)
        else:
            sigma_mid = jnp.exp(0.5 * (jnp.log(s) + jnp.log(sigma_down)))
            x_2 = x + d * (sigma_mid - s)
            x0_2 = denoise(x_2, sigma_mid)
            d_2 = (x_2 - x0_2) / sigma_mid
            x = x + d_2 * (sigma_down - s)
        if float(s_next) > 0:
            sub = jax.random.fold_in(rng, i)
            x = x + sigma_up * jax.random.normal(sub, x.shape, x.dtype)
        x = apply_callback(callback, i, x)
    return x


def sample_dpmpp_2s_ancestral(denoise, x, sigmas, rng, eta: float = 1.0,
                              callback=None):
    """DPM-Solver++ (2S) ancestral (k-diffusion ``sample_dpmpp_2s_ancestral``):
    single-step 2nd order in exponential-integrator form (midpoint at
    r = 1/2 in log-sigma time), ancestral noise on every non-final step."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        sigma_down, sigma_up = ancestral_steps(s, s_next, eta)
        if float(sigma_down) == 0.0:
            d = (x - x0) / s
            x = x + d * (sigma_down - s)
        else:
            t, t_next = -jnp.log(s), -jnp.log(sigma_down)
            h = t_next - t
            sigma_mid = jnp.exp(-(t + 0.5 * h))
            x_2 = (sigma_mid / s) * x - jnp.expm1(-0.5 * h) * x0
            x0_2 = denoise(x_2, sigma_mid)
            x = (sigma_down / s) * x - jnp.expm1(-h) * x0_2
        if float(s_next) > 0:
            sub = jax.random.fold_in(rng, i)
            x = x + sigma_up * jax.random.normal(sub, x.shape, x.dtype)
        x = apply_callback(callback, i, x)
    return x


def sample_dpmpp_sde(denoise, x, sigmas, rng, eta: float = 1.0, callback=None):
    """DPM-Solver++ SDE (k-diffusion ``sample_dpmpp_sde``, r = 1/2): 2nd-order
    single-step with ancestral-style noise injected BOTH at the midpoint model
    call and at the step end — two model calls and two noise draws per step.
    Per-step keys: ``k_mid, k_end = split(fold_in(rng, i))`` — the fold_in
    discipline (see sample_euler_ancestral), with the two draws split from the
    step key (the compiled twin and the serving lanes consume the same)."""
    r = 0.5
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        if float(s_next) == 0.0:
            d = (x - x0) / s
            x = x + d * (s_next - s)
        else:
            sub = jax.random.fold_in(rng, i)
            k_mid, k_end = jax.random.split(sub)
            t, t_next = -jnp.log(s), -jnp.log(s_next)
            h = t_next - t
            sigma_mid = jnp.exp(-(t + r * h))
            fac = 1.0 / (2.0 * r)
            # Step 1: to the midpoint's sigma_down, + its sigma_up of noise.
            sd1, su1 = ancestral_steps(s, sigma_mid, eta)
            t_down1 = -jnp.log(jnp.maximum(sd1, 1e-10))
            x_2 = (sd1 / s) * x - jnp.expm1(t - t_down1) * x0
            x_2 = x_2 + su1 * jax.random.normal(k_mid, x.shape, x.dtype)
            x0_2 = denoise(x_2, sigma_mid)
            # Step 2: full step from the blended denoised estimate.
            sd2, su2 = ancestral_steps(s, s_next, eta)
            t_down2 = -jnp.log(jnp.maximum(sd2, 1e-10))
            x0_blend = (1.0 - fac) * x0 + fac * x0_2
            x = (sd2 / s) * x - jnp.expm1(t - t_down2) * x0_blend
            x = x + su2 * jax.random.normal(k_end, x.shape, x.dtype)
        x = apply_callback(callback, i, x)
    return x


def sample_dpmpp_2m(denoise, x, sigmas, callback=None):
    """DPM-Solver++ (2M): multistep 2nd order, one model call per step."""
    old_x0 = None
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        t, t_next = -jnp.log(s), -jnp.log(jnp.maximum(s_next, 1e-10))
        h = t_next - t
        if old_x0 is None or float(s_next) == 0.0:
            x = (s_next / s) * x - jnp.expm1(-h) * x0
        else:
            h_last = t - (-jnp.log(sigmas[i - 1]))
            r = h_last / h
            x0_prime = (1 + 1 / (2 * r)) * x0 - (1 / (2 * r)) * old_x0
            x = (s_next / s) * x - jnp.expm1(-h) * x0_prime
        old_x0 = x0
        x = apply_callback(callback, i, x)
    return x


def sample_dpmpp_2m_sde(denoise, x, sigmas, rng, eta: float = 1.0, callback=None):
    """DPM-Solver++ (2M) SDE: the stochastic 2M variant (k-diffusion's
    'dpmpp_2m_sde' with the default midpoint solver) — one model call per step,
    per-step noise injection scaled by the SDE's decay."""
    old_x0 = None
    h_last = None
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        if float(s_next) == 0.0:
            x = x0
        else:
            t, t_next = -jnp.log(s), -jnp.log(s_next)
            h = t_next - t
            eta_h = eta * h
            x = (
                (s_next / s) * jnp.exp(-eta_h) * x
                + (-jnp.expm1(-h - eta_h)) * x0
            )
            if old_x0 is not None:
                r = h_last / h
                # midpoint correction
                x = x + 0.5 * (-jnp.expm1(-h - eta_h)) * (1 / r) * (x0 - old_x0)
            if eta > 0:
                sub = jax.random.fold_in(rng, i)
                x = x + s_next * jnp.sqrt(
                    jnp.maximum(-jnp.expm1(-2 * eta_h), 0.0)
                ) * jax.random.normal(sub, x.shape, x.dtype)
            h_last = h
        old_x0 = x0
        x = apply_callback(callback, i, x)
    return x


def sample_dpmpp_3m_sde(denoise, x, sigmas, rng, eta: float = 1.0, callback=None):
    """DPM-Solver++ (3M) SDE (k-diffusion's 'dpmpp_3m_sde'): third-order
    multistep in exponential-integrator form — one model call per step, the two
    previous x0 estimates building 1st/2nd difference corrections, per-step
    noise injection scaled by the SDE decay."""
    x0_1 = x0_2 = None  # previous two denoised estimates
    h_1 = h_2 = None    # previous two log-sigma step sizes
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        if float(s_next) == 0.0:
            # Final (or interior-zero) step: no history update — a None h must
            # never enter the multistep state (k-diffusion updates history only
            # on non-zero steps).
            x = apply_callback(callback, i, x0)
            continue
        else:
            t, t_next = -jnp.log(s), -jnp.log(s_next)
            h = t_next - t
            h_eta = h * (eta + 1.0)
            x = jnp.exp(-h_eta) * x + (-jnp.expm1(-h_eta)) * x0
            if h_2 is not None:
                r0, r1 = h_1 / h, h_2 / h
                d1_0 = (x0 - x0_1) / r0
                d1_1 = (x0_1 - x0_2) / r1
                d1 = d1_0 + (d1_0 - d1_1) * r0 / (r0 + r1)
                d2 = (d1_0 - d1_1) / (r0 + r1)
                phi_2 = jnp.expm1(-h_eta) / h_eta + 1.0
                phi_3 = phi_2 / h_eta - 0.5
                x = x + phi_2 * d1 - phi_3 * d2
            elif h_1 is not None:
                r = h_1 / h
                d = (x0 - x0_1) / r
                phi_2 = jnp.expm1(-h_eta) / h_eta + 1.0
                x = x + phi_2 * d
            if eta > 0:
                sub = jax.random.fold_in(rng, i)
                x = x + s_next * jnp.sqrt(
                    jnp.maximum(-jnp.expm1(-2.0 * eta * h), 0.0)
                ) * jax.random.normal(sub, x.shape, x.dtype)
        x0_1, x0_2 = x0, x0_1
        h_1, h_2 = h, h_1
        x = apply_callback(callback, i, x)
    return x


def lms_coefficient_matrix(sigmas, order: int = 4):
    """Adams-Bashforth coefficients for LMS over a concrete sigma schedule:
    ``C[i, j]`` weights the j-steps-back derivative at step i (zero-padded past
    the running order ``min(i+1, order)``). Shared by the eager loop below and
    the whole-loop compiled sampler (compiled.py), which needs them as one
    host-precomputed array — they depend only on the schedule, not the latent."""
    sig = np.asarray(sigmas, np.float64)

    def lms_coeff(order_, i, j):
        # integral over [sigma_i, sigma_i+1] of the Lagrange basis poly for ds.
        def poly(tau):
            prod = 1.0
            for k in range(order_):
                if k == j:
                    continue
                prod *= (tau - sig[i - k]) / (sig[i - j] - sig[i - k])
            return prod

        from numpy.polynomial.legendre import leggauss

        nodes, weights = leggauss(16)
        a, b = sig[i], sig[i + 1]
        tau = 0.5 * (b - a) * nodes + 0.5 * (b + a)
        return float(0.5 * (b - a) * np.sum(weights * np.vectorize(poly)(tau)))

    n = len(sig) - 1
    C = np.zeros((n, order), np.float64)
    for i in range(n):
        cur = min(i + 1, order)
        for j in range(cur):
            C[i, j] = lms_coeff(cur, i, j)
    return C


def sample_lms(denoise, x, sigmas, order: int = 4, callback=None):
    """Linear multistep (Katherine Crowson's LMS): Adams-Bashforth over the
    sigma schedule with numerically integrated coefficients."""
    C = lms_coefficient_matrix(sigmas, order)
    ds = []
    for i in range(len(sigmas) - 1):
        x0 = denoise(x, sigmas[i])
        d = (x - x0) / sigmas[i]
        ds.append(d)
        if len(ds) > order:
            ds.pop(0)
        cur = min(i + 1, order)
        x = x + sum(C[i, j] * d_ for j, d_ in zip(range(cur), reversed(ds)))
        x = apply_callback(callback, i, x)
    return x


def sample_lcm(denoise, x, sigmas, rng, callback=None):
    """Latent Consistency Model sampling (the host KSampler's ``lcm`` entry):
    each step takes the model's x0 prediction directly and re-noises it to the
    next sigma with FRESH noise — one jump per step, no ODE integration."""
    for i in range(len(sigmas) - 1):
        x0 = denoise(x, sigmas[i])
        x = x0
        if float(sigmas[i + 1]) > 0:
            sub = jax.random.fold_in(rng, i)
            x = x + sigmas[i + 1] * jax.random.normal(sub, x.shape, x.dtype)
        x = apply_callback(callback, i, x)
    return x


def sample_ddpm(denoise, x, sigmas, rng, callback=None):
    """Ancestral DDPM in sigma space (k-diffusion's ``sample_ddpm`` /
    generic_step_sampler with the DDPM posterior step): the model's eps
    estimate drives the exact DDPM posterior mean in ᾱ-space, with posterior
    variance noise on every non-final step. x rides in k-diffusion's sigma
    scaling (x = √(1+σ²)·x_ᾱ) between steps."""
    for i in range(len(sigmas) - 1):
        s, s_next = sigmas[i], sigmas[i + 1]
        x0 = denoise(x, s)
        eps = (x - x0) / s
        acp = 1.0 / (s**2 + 1.0)          # ᾱ_t from sigma
        acp_prev = 1.0 / (s_next**2 + 1.0)
        alpha = acp / acp_prev
        x_a = x / jnp.sqrt(1.0 + s**2)     # ᾱ-space sample
        mu = jnp.sqrt(1.0 / alpha) * (
            x_a - (1.0 - alpha) * eps / jnp.sqrt(1.0 - acp)
        )
        if float(s_next) > 0:
            sub = jax.random.fold_in(rng, i)
            var = (1.0 - alpha) * (1.0 - acp_prev) / (1.0 - acp)
            mu = mu + jnp.sqrt(var) * jax.random.normal(sub, x.shape, x.dtype)
            x = mu * jnp.sqrt(1.0 + s_next**2)  # back to sigma scaling
        else:
            x = mu
        x = apply_callback(callback, i, x)
    return x


def unipc_coeff_table(sigmas, order: int = 3, variant: str = "bh1"):
    """Host-precomputed per-step UniPC quantities (float64) — the analogue of
    ``lms_coefficient_matrix``: they depend only on the concrete schedule, so
    the eager loop and the whole-loop compiled twin consume the same table.

    UniPC (Zhao et al. 2023) in k-diffusion sigma space: with λ = -log σ the
    VP-space α factors cancel and the exponential-integrator base step is
    exactly the dpmpp one, ``(σ_next/σ)·x - expm1(-h)·m0``. Row i holds
    ``[h_phi_1, B_h, rp0, rp1, rc0, rc1, rc_t, rki0, rki1]`` for the step
    σ_i→σ_{i+1} at running order p = min(order, i+1, n-i) (warm-up ramp and
    the official lower_order_final ramp-down): predictor weights ``rp*`` for
    the older-history differences, corrector weights ``rc*`` plus the fresh
    ``rc_t·(m_t − m0)`` term, and ``rki*`` the 1/r_k factors that form those
    differences. Unused slots are zero, so consumers need no order branches.
    ``B_h`` encodes the variant (bh1: hh; bh2: expm1(hh)) — the runtime update
    is variant-agnostic."""
    sig = np.asarray(sigmas, np.float64)
    lam = -np.log(np.maximum(sig, 1e-10))
    n = len(sig) - 1
    table = np.zeros((n, 9))
    for i in range(n):
        p = max(1, min(order, i + 1, n - i))
        h = lam[i + 1] - lam[i]
        hh = -h
        h_phi_1 = np.expm1(hh)
        B_h = hh if variant == "bh1" else np.expm1(hh)
        rks, rkinv = [], []
        for j in range(1, p):
            rk = (lam[i - j] - lam[i]) / h
            rks.append(rk)
            rkinv.append(1.0 / rk)
        rks.append(1.0)  # the D1_t column
        R = np.array([[rk**k for rk in rks] for k in range(p)])
        b = np.zeros(p)
        fact = 1.0
        h_phi_k = h_phi_1 / hh - 1.0
        for k in range(1, p + 1):
            b[k - 1] = h_phi_k * fact / B_h
            fact *= k + 1
            h_phi_k = h_phi_k / hh - 1.0 / fact
        # Order 2 predictor is hardcoded to 0.5 in the official UniPC (and the
        # host KSampler's port of it) — "for order 2, we use a simplified
        # version" — not the 1×1 solve, which differs by O(h).
        if p == 1:
            rhos_p = np.zeros(0)
        elif p == 2:
            rhos_p = np.array([0.5])
        else:
            rhos_p = np.linalg.solve(R[:-1, :-1], b[:-1])
        rhos_c = np.linalg.solve(R, b) if p > 1 else np.array([0.5])
        row = table[i]
        row[0], row[1] = h_phi_1, B_h
        row[2 : 2 + len(rhos_p)] = rhos_p
        row[4 : 4 + len(rhos_c) - 1] = rhos_c[:-1]
        row[6] = rhos_c[-1]
        row[7 : 7 + len(rkinv)] = rkinv
    return table


def _sample_unipc(denoise, x, sigmas, callback=None, variant="bh1", order=3):
    """UniPC multistep predictor-corrector (data-prediction form). One model
    call per step: the corrector reuses the evaluation at the predictor's
    point, which then becomes the next step's history entry — the official
    multistep flow. Final (σ→0) step returns m0 directly."""
    C = unipc_coeff_table(sigmas, order, variant)
    n = len(sigmas) - 1
    hist = [denoise(x, sigmas[0])]
    for i in range(n):
        s, s_next = sigmas[i], sigmas[i + 1]
        m0 = hist[-1]
        if float(s_next) == 0.0:
            x = apply_callback(callback, i, m0)
            continue
        hphi1, Bh, rp0, rp1, rc0, rc1, rct, rki0, rki1 = (float(v) for v in C[i])
        D1_1 = (hist[-2] - m0) * rki0 if len(hist) >= 2 else 0.0
        D1_2 = (hist[-3] - m0) * rki1 if len(hist) >= 3 else 0.0
        base = (s_next / s) * x - hphi1 * m0
        x_pred = base - Bh * (rp0 * D1_1 + rp1 * D1_2)
        m_t = denoise(x_pred, s_next)
        x = base - Bh * (rc0 * D1_1 + rc1 * D1_2 + rct * (m_t - m0))
        hist.append(m_t)
        if len(hist) > order:
            hist.pop(0)
        x = apply_callback(callback, i, x)
    return x


def sample_uni_pc(denoise, x, sigmas, callback=None):
    """UniPC, bh1 variant (the host KSampler's ``uni_pc`` entry)."""
    return _sample_unipc(denoise, x, sigmas, callback, variant="bh1")


def sample_uni_pc_bh2(denoise, x, sigmas, callback=None):
    """UniPC, bh2 variant (the host KSampler's ``uni_pc_bh2`` entry)."""
    return _sample_unipc(denoise, x, sigmas, callback, variant="bh2")


# One registry for the sigma-space samplers; stochastic ones (extra rng arg)
# are listed in RNG_SAMPLERS so dispatchers know the signature.
SAMPLERS = {
    "euler": sample_euler,
    "euler_ancestral": sample_euler_ancestral,
    "heun": sample_heun,
    "dpm_2": sample_dpm_2,
    "dpm_2_ancestral": sample_dpm_2_ancestral,
    "lms": sample_lms,
    "dpmpp_2s_ancestral": sample_dpmpp_2s_ancestral,
    "dpmpp_sde": sample_dpmpp_sde,
    "dpmpp_2m": sample_dpmpp_2m,
    "dpmpp_2m_sde": sample_dpmpp_2m_sde,
    "dpmpp_3m_sde": sample_dpmpp_3m_sde,
    "lcm": sample_lcm,
    "ddpm": sample_ddpm,
    "uni_pc": sample_uni_pc,
    "uni_pc_bh2": sample_uni_pc_bh2,
}
RNG_SAMPLERS = frozenset(
    {"euler_ancestral", "dpm_2_ancestral", "dpmpp_2s_ancestral", "dpmpp_sde",
     "dpmpp_2m_sde", "dpmpp_3m_sde", "lcm", "ddpm"}
)

# prediction="flow" renoising policy (host CONST-dispatch parity):
# - FLOW_VARIANTS: samplers the host swaps for an RF-specific form — we do too.
# - FLOW_REJECT: ddpm's alpha-bar posterior is an eps-schedule construction
#   with no flow meaning; reject loudly rather than produce garbage.
# - Everything else runs its generic form on the flow schedule (deterministic
#   samplers are exact there; the remaining SDE family keeps the generic
#   lambda-space noise the host also uses for them).
FLOW_VARIANTS = {
    "euler_ancestral": sample_euler_ancestral_rf,
    "dpmpp_2s_ancestral": sample_dpmpp_2s_ancestral_rf,
    "lcm": sample_lcm_rf,
}
FLOW_REJECT = frozenset({"ddpm"})
