"""Single sampler dispatch shared by pipelines.py and the TPUKSampler node.

One table, one CFG plumbing, one noise-scaling convention — so a sampler added
here is immediately available to both the Python pipeline API and the node graph
(and they cannot drift apart)."""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from .ddim import ddim_sample
from .flow import flow_euler_sample
from .k_samplers import (
    EpsDenoiser,
    karras_sigmas,
    sample_dpmpp_2m,
    sample_euler,
    sample_euler_ancestral,
    sample_heun,
    sampling_sigmas,
)

K_SAMPLERS: dict[str, Callable] = {
    "euler": sample_euler,
    "euler_ancestral": sample_euler_ancestral,
    "heun": sample_heun,
    "dpmpp_2m": sample_dpmpp_2m,
}

SAMPLER_NAMES = ("ddim", *K_SAMPLERS, "flow_euler")


def run_sampler(
    model,
    noise: jnp.ndarray,
    context,
    *,
    sampler: str,
    steps: int,
    cfg_scale: float = 1.0,
    uncond_context=None,
    uncond_kwargs: dict | None = None,
    rng=None,
    karras: bool = True,
    shift: float = 1.0,
    guidance: float | None = None,
    callback=None,
    **model_kwargs,
) -> jnp.ndarray:
    """Drive ``model`` from ``noise`` to a clean latent with the named sampler.

    ``noise`` is unit-variance N(0,1); eps-family samplers scale it to sigma_max
    internally. ``shift``/``guidance`` apply to ``flow_euler`` only."""
    use_cfg = cfg_scale != 1.0 and uncond_context is not None
    eff_cfg = cfg_scale if use_cfg else 1.0
    if sampler == "flow_euler":
        return flow_euler_sample(
            model, noise, context, steps=steps, shift=shift, guidance=guidance,
            cfg_scale=eff_cfg, uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs, callback=callback, **model_kwargs,
        )
    if sampler == "ddim":
        return ddim_sample(
            model, noise, context, steps=steps, cfg_scale=eff_cfg,
            uncond_context=uncond_context, uncond_kwargs=uncond_kwargs,
            callback=callback, **model_kwargs,
        )
    step_fn = K_SAMPLERS.get(sampler)
    if step_fn is None:
        raise ValueError(
            f"unknown sampler {sampler!r} (have {', '.join(SAMPLER_NAMES)})"
        )
    sigmas = karras_sigmas(steps) if karras else sampling_sigmas(steps)
    denoise = EpsDenoiser(
        model, context, cfg_scale=eff_cfg, uncond_context=uncond_context,
        uncond_kwargs=uncond_kwargs, **model_kwargs,
    )
    x = noise * sigmas[0]
    if sampler == "euler_ancestral":
        if rng is None:
            rng = jax.random.key(0)
        return step_fn(denoise, x, sigmas, jax.random.fold_in(rng, 1), callback=callback)
    return step_fn(denoise, x, sigmas, callback=callback)
