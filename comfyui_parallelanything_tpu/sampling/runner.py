"""Single sampler dispatch shared by pipelines.py and the TPUKSampler node.

One table, one CFG plumbing, one noise-scaling convention — so a sampler added
here is immediately available to both the Python pipeline API and the node graph
(and they cannot drift apart)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils import tracing
from ..utils.degrade import DegradedToInline

from .ddim import ddim_sample
from .flow import flow_euler_sample, flow_timesteps
from .k_samplers import (
    FLOW_REJECT,
    FLOW_VARIANTS,
    RNG_SAMPLERS,
    SAMPLERS as K_SAMPLERS,
    EpsDenoiser,
    flow_sigma_table,
    make_sigmas,
)

SAMPLER_NAMES = ("ddim", *K_SAMPLERS, "flow_euler")


def _compile_eager_rung(e: BaseException, sampler: str) -> None:
    """Compile-failure ladder (utils/degrade.py): a compile-side error on the
    whole-loop program falls back to the eager per-step loop — the rung is
    recorded and the caller's code FALLS THROUGH to the eager path. Runtime
    errors (incl. OOM, which has its own ladder) re-raise unchanged."""
    from ..utils.degrade import is_compile_failure, record_rung

    if not is_compile_failure(e):
        raise e
    record_rung("compile-eager",
                f"{sampler}: {type(e).__name__}: {e} — eager loop fallback",
                sampler=sampler)


def _compiled_spec(model, callback):
    """TraceSpec for the whole-loop compiled path, or None with a logged reason
    (the caller falls back to the eager per-step loops)."""
    from ..utils import get_logger
    from .compiled import trace_spec_of

    if callback is not None:
        get_logger().info(
            "compile_loop: user callback cannot trace into the loop; eager path"
        )
        return None
    if getattr(model, "is_streaming", False):
        # Weight-streaming models can never be one XLA program (the program
        # would close over the full weight pytree — the allocation streaming
        # exists to avoid). The eager loop is not a degradation here: each
        # denoise step drives the double-buffered per-stage programs
        # (parallel/streaming.py), so streaming survives the full sampler.
        get_logger().info(
            "compile_loop: weight-streaming model — per-stage programs run "
            "inside the eager denoise loop instead"
        )
        return None
    spec = trace_spec_of(model)
    if spec is None:
        get_logger().info(
            "compile_loop: model is not single-program traceable (hybrid chain "
            "or active sequence-parallel context); eager path"
        )
    return spec


def _merge_lora(model, factors):
    """Eager factor merge for inline legs. A ControlNet composition nests its
    base params under "base" while the factor paths address the BASE pytree,
    so recompose around the merged base via the serving delegate instead of
    patching the merged tree."""
    from ..models.lora import lora_model

    delegate = getattr(model, "control_delegate", None)
    if delegate is None:
        return lora_model(model, factors)
    from ..models.api import DiffusionModel
    from ..models.controlnet import apply_control

    return apply_control(
        lora_model(delegate["base"], factors),
        DiffusionModel(apply=delegate["ctrl_apply"],
                       params=delegate["ctrl_params"], name="ctrl"),
        delegate["hint"], delegate["strength"],
        delegate["start"], delegate["end"],
    )


def _traced_sampler_run(fn):
    """Wrap the whole dispatch in a ``sampler-run`` span (utils/tracing.py) —
    the per-prompt timeline node every step/lane-wait span nests under.
    Disabled tracing costs one flag check; ``sampler``/``steps`` are
    keyword-only on run_sampler, so the wrapper reads them from kwargs."""

    @functools.wraps(fn)
    def wrapped(model, noise, context=None, **kwargs):
        if not tracing.on():
            return fn(model, noise, context, **kwargs)
        with tracing.span(
            "sampler-run", cat="sampling",
            sampler=kwargs.get("sampler"), steps=kwargs.get("steps"),
            batch=int(noise.shape[0]) if hasattr(noise, "shape") else None,
        ):
            return fn(model, noise, context, **kwargs)

    return wrapped


@_traced_sampler_run
def run_sampler(
    model,
    noise: jnp.ndarray,
    context,
    *,
    sampler: str,
    steps: int,
    cfg_scale: float = 1.0,
    uncond_context=None,
    uncond_kwargs: dict | None = None,
    rng=None,
    karras: bool = True,
    scheduler: str | None = None,
    shift: float = 1.0,
    guidance: float | None = None,
    callback=None,
    init_latent: jnp.ndarray | None = None,
    denoise: float = 1.0,
    latent_mask: jnp.ndarray | None = None,
    prediction: str = "eps",
    cfg_rescale: float = 0.0,
    compile_loop: bool = False,
    sigmas: jnp.ndarray | None = None,
    extra_conds=None,
    cond_area=None,
    cond_area_pct=None,
    cond_mask=None,
    cond_strength: float = 1.0,
    cond_mask_strength: float = 1.0,
    lora: dict | None = None,
    **model_kwargs,
) -> jnp.ndarray:
    """Drive ``model`` from ``noise`` to a clean latent with the named sampler.

    ``noise`` is unit-variance N(0,1); eps-family samplers scale it to sigma_max
    internally. ``shift``/``guidance`` apply to the flow paths — ``flow_euler``
    AND any k-sampler running with ``prediction="flow"`` (shift warps the flow
    sigma table the scheduler menu ranges over; guidance feeds the FLUX-dev
    distilled-guidance kwarg).

    img2img: with ``init_latent`` + ``denoise < 1``, the schedule for
    ``steps/denoise`` total steps is truncated to its last ``steps`` entries and
    ``init_latent`` is noised to the truncated schedule's start (ComfyUI's
    KSampler denoise semantics: ``steps`` forwards always run — except when a
    scheduler realizes fewer than ``steps`` sigmas, where the truncation is
    rescaled to the realized length to preserve the requested strength).

    Inpainting: ``latent_mask`` (broadcastable to the latent; 1 = denoise this
    region, 0 = keep ``init_latent``) re-pins the keep region to the init noised
    to each step's level after every sampler step — the ComfyUI latent-noise-
    mask mechanism. Works at any ``denoise`` (requires ``init_latent``).

    ``compile_loop=True`` compiles the ENTIRE denoise loop into one XLA program
    (sampling/compiled.py): zero per-step dispatch, latent donated, inpaint mask
    traced in. Opt-in because it covers single-program models only (bare models
    and single-platform-group parallel chains) and trades away per-step OOM
    demotion; hybrid chains or a user ``callback`` silently fall back to the
    eager loops (logged).

    ``sigmas`` supplies an explicit descending schedule (the host's
    SamplerCustom/BasicScheduler split): schedule construction, ``scheduler``/
    ``steps``-based truncation, and the ``denoise`` math are all skipped, and
    noising follows the host's ``noise_scaling`` with ``init_latent`` as the
    base (``init + σ₀·noise`` eps; ``σ₀·noise + (1−σ₀)·init`` flow) — a
    truncated sigma ladder therefore gives img2img exactly as the host's
    custom-sampling graphs do. flow_euler treats it as its ``ts`` ladder; ddim
    (timestep-indexed, not sigma-driven) rejects it."""
    use_cfg = cfg_scale != 1.0 and uncond_context is not None
    eff_cfg = cfg_scale if use_cfg else 1.0
    # Per-request LoRA (round 16): ``lora`` maps param paths to low-rank
    # (a, b) factor pairs (models/lora.py extract_lora_factors). The inline
    # paths run the eagerly merged model; the serving path submits the BASE
    # model + factors so LoRA lanes co-batch with plain traffic (the lane
    # program applies W + b@a per lane). The merge is deferred past the
    # serving seam — a served request must never pay it.
    lora_factors = None
    if lora:
        lora_factors = dict(lora)
        if sampler in ("ddim", "flow_euler"):
            # TPU-native extras: not in the lane registry, always inline.
            model = _merge_lora(model, lora_factors)
            lora_factors = None
    # Model-level sampler preferences (patch nodes, e.g. RescaleCFG): defaults
    # only — an explicit caller value wins.
    prefs = getattr(model, "sampler_prefs", None) or {}
    if cfg_rescale == 0.0:
        cfg_rescale = float(prefs.get("cfg_rescale", 0.0))
    multi_cond = (bool(extra_conds) or cond_area is not None
                  or cond_area_pct is not None or cond_mask is not None)
    if multi_cond and sampler in ("ddim", "flow_euler"):
        # Multi-cond lives in EpsDenoiser (the k-sampler family — every stock
        # KSampler menu name). ddim/flow_euler are TPU-native extras with
        # their own model-call sites; combined/area conditioning there is out
        # of scope, and silence would mean silently dropping a prompt.
        raise ValueError(
            "combined/area conditioning (ConditioningCombine/SetArea) is "
            "supported on the k-sampler family only, not "
            f"{sampler!r} — pick any stock sampler name"
        )
    if multi_cond and compile_loop:
        from ..utils import get_logger

        get_logger().info(
            "compile_loop: multi-cond (Combine/SetArea) runs the eager path"
        )
        compile_loop = False
    if not 0.0 < denoise <= 1.0:
        raise ValueError(f"denoise must be in (0, 1], got {denoise}")
    if latent_mask is not None and init_latent is None:
        raise ValueError("latent_mask requires init_latent (the kept content)")
    if prediction == "v" and sampler == "flow_euler":
        raise ValueError("flow_euler is velocity-parameterized already; "
                         "prediction='v' applies to the eps-family samplers")
    if prediction == "flow" and sampler == "ddim":
        raise ValueError("ddim runs in alpha-bar space and has no flow form; "
                         "use flow_euler or any k-sampler for flow models")
    if sigmas is not None and sampler == "ddim":
        raise ValueError("ddim is timestep-indexed, not sigma-driven; explicit "
                         "sigmas apply to flow_euler and the k-samplers")
    img2img = init_latent is not None and denoise < 1.0
    total = max(steps, int(round(steps / denoise))) if img2img else steps
    # Shared by every compiled-loop dispatch below: the traced inpaint-mask
    # blend needs the init/noise references only when a mask is present.
    compiled_mask_kw = dict(
        mask=latent_mask,
        mask_init=init_latent if latent_mask is not None else None,
        mask_noise=noise if latent_mask is not None else None,
    )

    def masked_callback(keep_at):
        """Blend the keep-region back after each step; the user callback (which
        may itself replace x) runs on the blended latent."""
        if latent_mask is None:
            return callback
        m = latent_mask
        user = callback

        def cb(i, x):
            x = x * m + keep_at(i) * (1.0 - m)
            if user is not None:
                out = user(i, x)
                x = x if out is None else out
            return x

        return cb

    def with_progress(cb, n_steps):
        """Per-step progress + cooperative interrupt on the eager loops (the
        ComfyUI protocol's ``progress`` event source; utils/progress.py). The
        compiled path is one XLA program — no step boundaries to report or
        stop at, which run_sampler's docstring lists among its trade-offs.

        Tracing: each boundary-to-boundary interval is recorded as a ``step``
        span — the host-side dispatch window of one denoise step (the eager
        loops do not sync per step, and tracing must not add a sync; the
        serving bucket's step spans, which do block, carry the
        device-inclusive durations)."""
        from ..utils.progress import report_progress

        t_last = [tracing.now_us()] if tracing.on() else None

        def cb2(i, x):
            if t_last is not None and tracing.on():
                now = tracing.now_us()
                tracing.record(
                    "step", t_last[0], now - t_last[0], cat="sampling",
                    step=i + 1, of=n_steps,
                )
                t_last[0] = now
            # Raises Interrupted if requested; x feeds the WS latent-preview
            # hook (utils/progress.set_preview_hook) when one is installed.
            report_progress(i + 1, n_steps, latent=x)
            if cb is not None:
                return cb(i, x)
            return None

        return cb2

    if sampler == "flow_euler":
        if sigmas is not None:
            ts = jnp.asarray(sigmas, jnp.float32)
            x = ts[0] * noise
            if init_latent is not None:
                x = x + (1.0 - ts[0]) * init_latent
        else:
            ts = flow_timesteps(total, shift)
            x = noise
            if img2img:
                # x_t = t·noise + (1-t)·x0 under the v = noise - x0 flow.
                ts = ts[-(steps + 1) :]
                x = ts[0] * noise + (1.0 - ts[0]) * init_latent
        if compile_loop:
            spec = _compiled_spec(model, callback)
            if spec is not None:
                from .compiled import compiled_flow_sample

                if x is noise:
                    # The loop donates its latent; never donate the CALLER's
                    # noise array (plain txt2img passes it through unchanged).
                    x = jnp.copy(x)
                try:
                    return compiled_flow_sample(
                        spec, x, ts, context, cfg_scale=eff_cfg,
                        uncond_context=uncond_context,
                        uncond_kwargs=uncond_kwargs,
                        guidance=guidance, cfg_rescale=cfg_rescale,
                        **compiled_mask_kw, model_kwargs=model_kwargs,
                    )
                except Exception as e:  # noqa: BLE001 — classified below
                    _compile_eager_rung(e, "flow_euler")
        cb = with_progress(masked_callback(
            lambda i: (1.0 - ts[i + 1]) * init_latent + ts[i + 1] * noise
        ), len(ts) - 1)
        return flow_euler_sample(
            model, x, context, steps=steps, shift=shift, guidance=guidance,
            cfg_scale=eff_cfg, uncond_context=uncond_context,
            uncond_kwargs=uncond_kwargs, callback=cb, ts=ts,
            cfg_rescale=cfg_rescale, **model_kwargs,
        )
    if sampler == "ddim":
        # A caller-supplied schedule must drive BOTH the truncation/noising here
        # and the sampler itself, or the init is noised to a different level
        # than the sampler assumes.
        acp = model_kwargs.pop("alphas_cumprod", None)
        if acp is None:
            from .schedules import scaled_linear_schedule

            acp = scaled_linear_schedule()
        from .schedules import ddim_timesteps

        x = noise
        if img2img:
            # Exact-strength truncation: `steps` timesteps evenly spaced over
            # [0, denoise·T) descending (ddim_timesteps' integer stride can't
            # express this — 1000//n is 0 for n>1000 and quantizes badly above
            # 500).
            t_start = max(1, round(denoise * (acp.shape[0] - 1)))
            ts = jnp.linspace(t_start, 0, steps).round().astype(jnp.int32)
            a0 = acp[ts[0]]
            x = jnp.sqrt(a0) * init_latent + jnp.sqrt(1.0 - a0) * noise
        else:
            ts = ddim_timesteps(steps, acp.shape[0])

        if compile_loop:
            spec = _compiled_spec(model, callback)
            if spec is not None:
                from .compiled import compiled_ddim_sample

                if x is noise:
                    # See the flow branch: the donated latent must not be the
                    # caller's noise array.
                    x = jnp.copy(x)
                try:
                    return compiled_ddim_sample(
                        spec, x, ts, acp, context, cfg_scale=eff_cfg,
                        uncond_context=uncond_context,
                        uncond_kwargs=uncond_kwargs,
                        prediction=prediction, cfg_rescale=cfg_rescale,
                        **compiled_mask_kw, model_kwargs=model_kwargs,
                    )
                except Exception as e:  # noqa: BLE001 — classified below
                    _compile_eager_rung(e, "ddim")

        def ddim_keep(i):
            a = acp[ts[i + 1]] if i + 1 < len(ts) else jnp.float32(1.0)
            return jnp.sqrt(a) * init_latent + jnp.sqrt(1.0 - a) * noise

        return ddim_sample(
            model, x, context, steps=steps, cfg_scale=eff_cfg,
            uncond_context=uncond_context, uncond_kwargs=uncond_kwargs,
            callback=with_progress(masked_callback(ddim_keep), len(ts)),
            ts=ts, alphas_cumprod=acp,
            prediction=prediction, cfg_rescale=cfg_rescale, **model_kwargs,
        )
    step_fn = K_SAMPLERS.get(sampler)
    if step_fn is None:
        raise ValueError(
            f"unknown sampler {sampler!r} (have {', '.join(SAMPLER_NAMES)})"
        )
    is_flow = prediction == "flow"
    acp = model_kwargs.pop("alphas_cumprod", None)
    explicit_sigmas = sigmas is not None
    if is_flow:
        if acp is not None:
            # The coherence rule (one schedule drives sigmas, truncation, AND
            # the denoiser) makes silently ignoring this worse than rejecting:
            # flow schedules come from flow_sigma_table(shift), not alpha-bars.
            raise ValueError(
                "alphas_cumprod is an eps-schedule input with no flow meaning; "
                "flow schedules derive from the shift-warped flow sigma table"
            )
        if sampler in FLOW_REJECT:
            raise ValueError(
                f"{sampler} is an eps-schedule construction (alpha-bar "
                "posterior) with no rectified-flow form; pick any other "
                "k-sampler for flow models"
            )
        # Flow models sample over flow time (σ ≡ t): the scheduler menu
        # ranges over the CONST sigma table exactly like the host's
        # calculate_sigmas — "normal" is the shifted ladder; karras/beta/…
        # re-space it. FLUX-dev's distilled guidance rides a model kwarg as
        # in the flow_euler branch.
        if not explicit_sigmas:
            sched_name = scheduler if scheduler is not None else "normal"
            sigmas = make_sigmas(
                sched_name, total, sigma_table=flow_sigma_table(shift)
            )
        if guidance is not None:
            model_kwargs["guidance"] = jnp.full(
                (noise.shape[0],), guidance, jnp.float32
            )
    elif not explicit_sigmas:
        # Same coherence rule as the ddim branch: a caller-supplied schedule
        # must drive the sampling sigmas (and img2img truncation), not just
        # the denoiser's sigma→timestep table. ``scheduler`` names the full
        # KSampler menu (make_sigmas); the older ``karras`` boolean remains
        # as a fallback when no name is given.
        sched_name = (
            scheduler if scheduler is not None else ("karras" if karras else "normal")
        )
        sigmas = make_sigmas(sched_name, total, acp)
    if explicit_sigmas:
        # A supplied ladder IS the schedule: no construction, no denoise-based
        # truncation (the host's BasicScheduler already applied it).
        sigmas = jnp.asarray(sigmas, jnp.float32)
    if img2img and not explicit_sigmas:
        # The realized schedule can be shorter than requested (ddim_uniform's
        # integer stride; beta's duplicate-timestep dedup in make_sigmas).
        # While the fixed ComfyUI slice still truncates (realized > steps) use
        # it verbatim — ``steps`` forwards run, reference-faithful even when
        # the realized count is slightly off the request. Only when the fixed
        # slice would degenerate (realized <= steps keeps the WHOLE schedule,
        # i.e. effective denoise 1.0 regardless of the request — beta at high
        # step counts) rescale the truncation to the realized length so the
        # requested strength survives; documented divergence from the host
        # KSampler, which has no guard for this case.
        realized = len(sigmas) - 1
        if realized > steps:
            sigmas = sigmas[-(steps + 1) :]
        else:
            keep = min(realized, max(1, round(steps * realized / total)))
            sigmas = sigmas[-(keep + 1) :]
    # Noising: host noise_scaling semantics. With an explicit ladder any
    # supplied init is the base (the custom-sampling graphs' behavior — a
    # zero EmptyLatent base degenerates to pure noise); otherwise only
    # img2img mixes the init.
    mix_init = img2img or (explicit_sigmas and init_latent is not None)
    if is_flow:
        # Flow forward process: x_t = t·noise + (1−t)·x0.
        x = sigmas[0] * noise
        if mix_init:
            x = x + (1.0 - sigmas[0]) * init_latent
    else:
        x = noise * sigmas[0]
        if mix_init:
            x = init_latent + x
    if sampler in RNG_SAMPLERS and rng is None:
        rng = jax.random.key(0)
    # Continuous-batching seam (round 7, widened rounds 10 and 16, serving/):
    # when a scheduler is installed, route eligible work — any registered
    # LaneStepSpec sampler (stateful and stochastic included), no user
    # callback — into a shared step-boundary batch with whatever other
    # requests are in flight. Denoise-masked img2img/inpaint, multi-cond CFG
    # extras, delegated ControlNet compositions, and per-request LoRA all
    # ride the lane as per-lane state (round 16) instead of forcing inline.
    # Stochastic lanes are occupancy-deterministic because the per-step noise
    # key is fold_in(base, i) on BOTH paths (same base as the eager call
    # below). Ineligible or refused work falls through to the inline paths
    # unchanged; compile_loop callers asked for the whole-loop program and
    # are never hijacked.
    if not compile_loop and callback is None:
        from ..serving.scheduler import get_scheduler

        _sched = get_scheduler()
        if _sched is not None:
            from ..utils.metrics import registry as _registry

            ticket = _sched.maybe_submit(
                model=model,  # still the LoRA base — the merge is deferred
                x=x, sigmas=sigmas, context=context,
                sampler=sampler, cfg_scale=eff_cfg,
                uncond_context=uncond_context, uncond_kwargs=uncond_kwargs,
                alphas_cumprod=acp, prediction=prediction,
                cfg_rescale=cfg_rescale, model_kwargs=model_kwargs,
                rng=(
                    jax.random.fold_in(rng, 1)
                    if sampler in RNG_SAMPLERS else None
                ),
                latent_mask=latent_mask,
                mask_init=init_latent if latent_mask is not None else None,
                mask_noise=noise if latent_mask is not None else None,
                extra_conds=extra_conds, cond_area=cond_area,
                cond_area_pct=cond_area_pct, cond_mask=cond_mask,
                cond_strength=cond_strength,
                cond_mask_strength=cond_mask_strength,
                lora=lora_factors,
            )
            if ticket is not None:
                try:
                    return ticket.result()
                except DegradedToInline as e:
                    # The serving layer shed this request (its OOM ladder ran
                    # out of width/chunk to give): the inline eager path below
                    # is the final rung — the prompt still completes.
                    from ..utils.degrade import record_rung

                    record_rung("inline-fallback",
                                f"{sampler}: {e}", sampler=sampler)
                    _registry.counter(
                        "pa_serving_inline_fallback_total",
                        labels={"reason": "degraded", "sampler": sampler},
                        help="sampler runs that fell back to the inline "
                             "eager loop with a scheduler installed",
                    )
            else:
                # A scheduler was installed but could not take this request
                # (capability/shape/queue ineligibility): it runs inline.
                # Round 16's loadgen mixed-workload summary watches this
                # counter — eligible mixed traffic must NOT tick it.
                _registry.counter(
                    "pa_serving_inline_fallback_total",
                    labels={"reason": "ineligible", "sampler": sampler},
                    help="sampler runs that fell back to the inline eager "
                         "loop with a scheduler installed",
                )
    if lora_factors:
        # Inline (or shed-from-serving) leg: merge the factors eagerly. A
        # ControlNet composition nests its base params under "base" (the
        # factor paths address the BASE pytree), so recompose around the
        # merged base via the delegate instead of patching the merged tree.
        from ..models.lora import lora_model

        delegate = getattr(model, "control_delegate", None)
        if delegate is not None:
            from ..models.api import DiffusionModel
            from ..models.controlnet import apply_control

            model = apply_control(
                lora_model(delegate["base"], lora_factors),
                DiffusionModel(apply=delegate["ctrl_apply"],
                               params=delegate["ctrl_params"],
                               name="ctrl"),
                delegate["hint"], delegate["strength"],
                delegate["start"], delegate["end"],
            )
        else:
            model = lora_model(model, lora_factors)
    if compile_loop:
        spec = _compiled_spec(model, callback)
        if spec is not None:
            from .compiled import compiled_k_sample

            try:
                return compiled_k_sample(
                    spec, sampler, x, sigmas, context, cfg_scale=eff_cfg,
                    uncond_context=uncond_context, uncond_kwargs=uncond_kwargs,
                    acp=acp, prediction=prediction, cfg_rescale=cfg_rescale,
                    rng=rng, **compiled_mask_kw, model_kwargs=model_kwargs,
                )
            except Exception as e:  # noqa: BLE001 — classified below
                _compile_eager_rung(e, sampler)
    denoiser = EpsDenoiser(
        model, context, cfg_scale=eff_cfg, uncond_context=uncond_context,
        uncond_kwargs=uncond_kwargs, alphas_cumprod=acp, prediction=prediction,
        cfg_rescale=cfg_rescale, extra_conds=extra_conds, cond_area=cond_area,
        cond_area_pct=cond_area_pct,
        cond_mask=cond_mask, cond_strength=cond_strength,
        cond_mask_strength=cond_mask_strength, **model_kwargs,
    )
    if is_flow:
        # Host CONST-dispatch parity: samplers with an RF renoise form swap in.
        step_fn = FLOW_VARIANTS.get(sampler, step_fn)
        cb = masked_callback(
            lambda i: (1.0 - sigmas[i + 1]) * init_latent + sigmas[i + 1] * noise
        )
    else:
        cb = masked_callback(lambda i: init_latent + noise * sigmas[i + 1])
    cb = with_progress(cb, len(sigmas) - 1)
    if sampler in RNG_SAMPLERS:
        return step_fn(denoiser, x, sigmas, jax.random.fold_in(rng, 1), callback=cb)
    return step_fn(denoiser, x, sigmas, callback=cb)
