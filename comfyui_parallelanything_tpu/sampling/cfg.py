"""Classifier-free-guidance batching shared by every sampler.

CFG runs cond ‖ uncond in ONE forward (doubling dim0 — which is exactly what feeds
the data-parallel path its batch). Per-batch kwargs (pooled vectors, guidance
embeds) must double too; when the uncond half has its own value (e.g. SDXL's
negative-prompt pooled ``y``, matching ComfyUI/diffusers semantics) it rides the
second half of the concat."""

from __future__ import annotations

import jax.numpy as jnp


def double_kwargs(
    kwargs: dict, uncond_kwargs: dict | None, batch: int
) -> dict:
    """Concatenate cond ‖ uncond along dim0 for every kwarg whose leading dim is
    the batch; non-batch kwargs pass through. Missing uncond entries reuse the
    cond value. A key present ONLY in uncond_kwargs is an inconsistency (the cond
    half would run without it) — rejected loudly rather than silently dropped."""
    uncond = uncond_kwargs or {}
    extra = set(uncond) - set(kwargs)
    if extra:
        raise ValueError(
            f"uncond_kwargs keys {sorted(extra)} have no cond counterpart — "
            "cond and uncond conditioning must carry the same kwargs"
        )
    out = {}
    for k, v in kwargs.items():
        if hasattr(v, "shape") and v.shape[:1] == (batch,):
            out[k] = jnp.concatenate([v, uncond.get(k, v)], axis=0)
        else:
            out[k] = v
    return out


def rescale_guidance(guided: jnp.ndarray, cond: jnp.ndarray, phi: float) -> jnp.ndarray:
    """CFG rescale (Lin et al. 2023 §3.4; diffusers ``guidance_rescale``): match
    the guided prediction's per-sample std to the cond prediction's, blended by
    ``phi`` (0 = off). Tames high-cfg over-saturation, especially on
    v-prediction models."""
    if phi <= 0.0:
        return guided
    dims = tuple(range(1, guided.ndim))
    std_c = jnp.std(cond, axis=dims, keepdims=True)
    std_g = jnp.std(guided, axis=dims, keepdims=True)
    rescaled = guided * (std_c / jnp.maximum(std_g, 1e-8))
    return phi * rescaled + (1.0 - phi) * guided


def apply_callback(callback, i, x):
    """Invoke a sampler callback; a return that is an array of x's shape
    REPLACES the working latent (the hook latent-mask inpainting rides on).
    Any other return — None, a progress-bar bool, a logger's int — is ignored,
    so observer callbacks keep their fire-and-forget contract."""
    if callback is None:
        return x
    out = callback(i, x)
    if out is not None and getattr(out, "shape", None) == x.shape:
        return out
    return x
