"""DDIM sampler (eps-prediction, deterministic η=0) with batched CFG.

Host-side step loop, mirroring how the reference is driven: ComfyUI's KSampler calls
the (monkey-patched) ``diffusion_model.forward`` once per denoise step
(any_device_parallel.py:1287 — 'Called by ComfyUI's sampler every denoise step'). The
``model`` argument here is any forward callable — a bare ``DiffusionModel`` or the
``ParallelModel`` the orchestrator returns — so every step routes through the parallel
scheduler exactly like the reference's sampler steps do.

Classifier-free guidance doubles the batch (cond ‖ uncond in one forward), which is
also what feeds the data-parallel path its batch dimension.
"""

from __future__ import annotations

import jax.numpy as jnp

from .cfg import apply_callback, double_kwargs, rescale_guidance
from .schedules import ddim_timesteps, scaled_linear_schedule


def ddim_sample(
    model,
    x_init: jnp.ndarray,
    context: jnp.ndarray | None = None,
    *,
    steps: int = 20,
    cfg_scale: float = 1.0,
    uncond_context: jnp.ndarray | None = None,
    uncond_kwargs: dict | None = None,
    alphas_cumprod: jnp.ndarray | None = None,
    callback=None,
    ts: jnp.ndarray | None = None,
    prediction: str = "eps",
    cfg_rescale: float = 0.0,
    **model_kwargs,
) -> jnp.ndarray:
    """Denoise ``x_init`` (noise at t=ts[0]) over the DDIM steps. Returns x_0.
    ``ts`` overrides the timestep schedule (img2img passes a truncated one and
    pre-noises ``x_init`` to ts[0] itself). ``prediction="v"`` treats the model
    output as SD2.x v-parameterization (x0 = √ᾱ·x − √(1−ᾱ)·v)."""
    if prediction not in ("eps", "v"):
        raise ValueError(f"prediction must be 'eps' or 'v', got {prediction!r}")
    if alphas_cumprod is None:
        alphas_cumprod = scaled_linear_schedule()
    if ts is None:
        ts = ddim_timesteps(steps, alphas_cumprod.shape[0])
    batch = x_init.shape[0]
    use_cfg = cfg_scale != 1.0 and uncond_context is not None

    x = x_init
    for i, t in enumerate(ts):
        t_vec = jnp.full((batch,), t, jnp.float32)
        if use_cfg:
            x_in = jnp.concatenate([x, x], axis=0)
            t_in = jnp.concatenate([t_vec, t_vec], axis=0)
            c_in = jnp.concatenate([context, uncond_context], axis=0)
            kw = double_kwargs(model_kwargs, uncond_kwargs, batch)
            out_both = model(x_in, t_in, c_in, **kw)
            out_c, out_u = jnp.split(out_both, 2, axis=0)
            out = out_u + cfg_scale * (out_c - out_u)
            out = rescale_guidance(out, out_c, cfg_rescale)
        else:
            out = model(x, t_vec, context, **model_kwargs)

        a_t = alphas_cumprod[t]
        a_prev = alphas_cumprod[ts[i + 1]] if i + 1 < len(ts) else jnp.float32(1.0)
        if prediction == "v":
            x0 = jnp.sqrt(a_t) * x - jnp.sqrt(1.0 - a_t) * out
            eps = (x - jnp.sqrt(a_t) * x0) / jnp.sqrt(1.0 - a_t)
        else:
            eps = out
            x0 = (x - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
        x = jnp.sqrt(a_prev) * x0 + jnp.sqrt(1.0 - a_prev) * eps
        x = apply_callback(callback, i, x)
    return x
