"""Whole-loop compiled sampling: the entire denoise loop as ONE jitted program.

The reference's hot path re-enters the (monkey-patched) ``forward`` from Python
every denoise step (any_device_parallel.py:1287) — cheap on CUDA, but on TPU
each re-entry pays dispatch latency and re-allocates the latent in HBM. This
module compiles the *whole sampler loop* — schedule walk, CFG doubling, model
forward, latent update, optional inpaint-mask blend — into a single XLA program
via ``lax.scan``, with the input latent **donated** so every intermediate x_t
lives in the scan carry and the per-step host round-trip disappears.

Opt-in via ``run_sampler(..., compile_loop=True)``. The compiled path covers
the single-program cases (bare models; single-platform-group ParallelModel
chains, replicated or FSDP). It intentionally does NOT cover:

- heterogeneous chains (host-side scatter between per-platform programs cannot
  live inside one XLA program) — falls back to the eager loops;
- user callbacks (arbitrary Python per step) — falls back; the latent-mask
  inpainting hook IS supported, traced into the loop;
- step-level OOM demotion (parity 1435-1448): one program means one
  allocation decision at compile time. Elasticity stays with the eager path.

Each scan sampler mirrors its eager twin in ``k_samplers.py``/``ddim.py``/
``flow.py`` op-for-op (Python schedule branches become ``jnp.where`` on the
step index); ``tests/test_compiled.py`` pins eager/compiled equivalence for
the full sampler menu.
"""

from __future__ import annotations

import dataclasses
import weakref
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.split import (
    is_arraylike as _is_arraylike,
    pad_leaf as _pad_leaf,
    slice_padded as _slice_padded,
)
from ..utils import numerics
from .cfg import double_kwargs, rescale_guidance
from .k_samplers import (
    RNG_SAMPLERS,
    EpsDenoiser,
    ancestral_steps as _ancestral,
    lms_coefficient_matrix,
    unipc_coeff_table,
)

__all__ = [
    "TraceSpec",
    "trace_spec_of",
    "compiled_k_sample",
    "compiled_ddim_sample",
    "compiled_flow_sample",
    "lane_step_program",
]


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """A model reduced to what one XLA program needs: a pure apply + params
    (already placed/sharded), and the mesh to pin the batch axis to (None for
    single-device models)."""

    apply: Callable[..., Any]  # (params, x, t, context, **kwargs)
    params: Any
    mesh: Any = None
    data_axis: str | None = None


_plain_callable_specs: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def trace_spec_of(model) -> TraceSpec | None:
    """A TraceSpec for ``model``, or None when it cannot run as one program.

    ParallelModel exposes ``.traceable()`` (None for hybrid chains, active
    sequence-parallel contexts, and weight-streaming mode — a streamed
    model's full pytree must never be closed over by one program);
    DiffusionModel / ``(apply, params)`` are pure by construction; a bare
    callable is *assumed* pure — the documented contract of
    ``compile_loop=True``."""
    if getattr(model, "is_streaming", False):
        # Belt-and-braces for streaming wrappers that also quack
        # .apply/.params: the duck-typed branches below would trace the FULL
        # host pytree into the loop program and materialize it on-device.
        return None
    traceable = getattr(model, "traceable", None)
    if callable(traceable):
        return traceable()
    apply = getattr(model, "apply", None)
    params = getattr(model, "params", None)
    if callable(apply) and params is not None:
        return TraceSpec(apply=apply, params=params)
    if isinstance(model, tuple) and len(model) == 2 and callable(model[0]):
        return TraceSpec(apply=model[0], params=model[1])
    if callable(model):
        spec = _plain_callable_specs.get(model)
        if spec is None:

            def apply_plain(params, x, t, context=None, *, _m=model, **kwargs):
                return _m(x, t, context, **kwargs)

            spec = TraceSpec(apply=apply_plain, params=())
            _plain_callable_specs[model] = spec
        return spec
    return None


# ---------------------------------------------------------------------------
# placement: pad the batch to the data-axis width and shard (the compiled-path
# analogue of _dp_on_group's place(); orchestrator.py applies it per step, here
# it happens once at loop entry)
# ---------------------------------------------------------------------------


def _place_batch(tree, batch: int, padded: int, mesh, data_axis):
    """Pad+shard batch-dim leaves, replicate other array leaves (mesh case);
    pad only on single-device (mesh None)."""
    if mesh is None:
        if padded == batch:
            return tree
        return jax.tree.map(
            lambda l: _pad_leaf(l, padded - batch)
            if _is_arraylike(l) and l.ndim > 0 and l.shape[0] == batch
            else l,
            tree,
        )
    sharded = NamedSharding(mesh, P(data_axis))
    repl = NamedSharding(mesh, P())

    def leaf(l):
        if not _is_arraylike(l):
            return l
        if l.ndim > 0 and l.shape[0] == batch:
            return jax.device_put(_pad_leaf(l, padded - batch), sharded)
        return jax.device_put(l, repl)

    return jax.tree.map(leaf, tree)


def _constrain(x, mesh, data_axis):
    """Re-pin the carry's batch sharding each step so XLA's propagation can't
    drift it onto a replicated layout mid-loop."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(data_axis)))


def step_keys(rng, n: int) -> jnp.ndarray:
    """Per-step keys via the occupancy-independent ``fold_in(rng, i)``
    discipline (round 10): the key for step i depends only on (rng, i) — not
    on how many steps ran before or which other work shares a dispatch — so
    compiled noise == eager noise == serving-lane noise at any occupancy."""
    return jnp.stack([jax.random.fold_in(rng, i) for i in range(n)])


def _mask_blend(x, mask, keep):
    return x * mask + keep * (1.0 - mask)


# ---------------------------------------------------------------------------
# k-family scan loops (sigma-space). Each mirrors its eager twin; `denoise`
# is an EpsDenoiser built inside the jitted program.
# ---------------------------------------------------------------------------


def _scan_euler(denoise, x, sigmas, keys, post, constrain):
    def body(x, per):
        i, s, s_next = per
        x0 = denoise(x, s)
        d = (x - x0) / s
        x = x + d * (s_next - s)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:]))
    return x


def _scan_euler_ancestral(denoise, x, sigmas, keys, post, constrain, eta=1.0):
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)
        sigma_down, sigma_up = _ancestral(s, s_next, eta)
        d = (x - x0) / s
        x = x + d * (sigma_down - s)
        noise = jax.random.normal(key, x.shape, x.dtype)
        x = x + jnp.where(s_next > 0, sigma_up, 0.0) * noise
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


def _scan_dpm_2(denoise, x, sigmas, keys, post, constrain):
    # Interior steps have s_next > 0; the final step (s_next == 0) is plain
    # Euler — epilogue, same shape discipline as _scan_heun.
    def body(x, per):
        i, s, s_next = per
        x0 = denoise(x, s)
        d = (x - x0) / s
        sigma_mid = jnp.exp(0.5 * (jnp.log(s) + jnp.log(s_next)))
        x_2 = x + d * (sigma_mid - s)
        x0_2 = denoise(x_2, sigma_mid)
        d_2 = (x_2 - x0_2) / sigma_mid
        x = x + d_2 * (s_next - s)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n - 1), sigmas[:-2], sigmas[1:-1]))
    x0 = denoise(x, sigmas[n - 1])
    d = (x - x0) / sigmas[n - 1]
    x = x + d * (sigmas[n] - sigmas[n - 1])
    return constrain(post(n - 1, x))


def _scan_dpm_2_ancestral(denoise, x, sigmas, keys, post, constrain, eta=1.0):
    # The second-order branch sits under lax.cond, not jnp.where: the final
    # step (sigma_down == 0, Euler) must not execute — or pay for — the
    # midpoint model call its eager twin skips.
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)
        sd, su = _ancestral(s, s_next, eta)
        d = (x - x0) / s

        def euler_branch(x):
            return x + d * (sd - s)

        def midpoint_branch(x):
            sigma_mid = jnp.exp(0.5 * (jnp.log(s) + jnp.log(sd)))
            x_2 = x + d * (sigma_mid - s)
            x0_2 = denoise(x_2, sigma_mid)
            d_2 = (x_2 - x0_2) / sigma_mid
            return x + d_2 * (sd - s)

        x = jax.lax.cond(sd > 0, midpoint_branch, euler_branch, x)
        noise = jax.random.normal(key, x.shape, x.dtype)
        x = x + jnp.where(s_next > 0, su, 0.0) * noise
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


def _scan_dpmpp_2s_ancestral(denoise, x, sigmas, keys, post, constrain, eta=1.0):
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)
        sd, su = _ancestral(s, s_next, eta)

        def euler_branch(x):
            d = (x - x0) / s
            return x + d * (sd - s)

        def second_branch(x):
            t, t_next = -jnp.log(s), -jnp.log(sd)
            h = t_next - t
            sigma_mid = jnp.exp(-(t + 0.5 * h))
            x_2 = (sigma_mid / s) * x - jnp.expm1(-0.5 * h) * x0
            x0_2 = denoise(x_2, sigma_mid)
            return (sd / s) * x - jnp.expm1(-h) * x0_2

        x = jax.lax.cond(sd > 0, second_branch, euler_branch, x)
        noise = jax.random.normal(key, x.shape, x.dtype)
        x = x + jnp.where(s_next > 0, su, 0.0) * noise
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


def _scan_dpmpp_sde(denoise, x, sigmas, keys, post, constrain, eta=1.0):
    r = 0.5

    def body(x, per):
        i, s, s_next, key = per
        k_mid, k_end = jax.random.split(key)
        x0 = denoise(x, s)

        def euler_branch(x):
            d = (x - x0) / s
            return x + d * (s_next - s)

        def full_branch(x):
            t, t_next = -jnp.log(s), -jnp.log(s_next)
            h = t_next - t
            sigma_mid = jnp.exp(-(t + r * h))
            fac = 1.0 / (2.0 * r)
            sd1, su1 = _ancestral(s, sigma_mid, eta)
            t_down1 = -jnp.log(jnp.maximum(sd1, 1e-10))
            x_2 = (sd1 / s) * x - jnp.expm1(t - t_down1) * x0
            x_2 = x_2 + su1 * jax.random.normal(k_mid, x.shape, x.dtype)
            x0_2 = denoise(x_2, sigma_mid)
            sd2, su2 = _ancestral(s, s_next, eta)
            t_down2 = -jnp.log(jnp.maximum(sd2, 1e-10))
            x0_blend = (1.0 - fac) * x0 + fac * x0_2
            out = (sd2 / s) * x - jnp.expm1(t - t_down2) * x0_blend
            return out + su2 * jax.random.normal(k_end, x.shape, x.dtype)

        x = jax.lax.cond(s_next > 0, full_branch, euler_branch, x)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


def _scan_euler_ancestral_rf(denoise, x, sigmas, keys, post, constrain, eta=1.0):
    # Mirrors sample_euler_ancestral_rf (rectified-flow renoise form).
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)

        def final(x):
            return x0

        def step(x):
            downstep = 1.0 + (s_next / s - 1.0) * eta
            sd = s_next * downstep
            alpha_ip1 = 1.0 - s_next
            alpha_down = 1.0 - sd
            renoise = jnp.sqrt(jnp.maximum(
                s_next**2 - sd**2 * alpha_ip1**2 / alpha_down**2, 0.0
            ))
            xx = (sd / s) * x + (1.0 - sd / s) * x0
            return (alpha_ip1 / alpha_down) * xx + renoise * jax.random.normal(
                key, x.shape, x.dtype
            )

        x = jax.lax.cond(s_next > 0, step, final, x)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


def _scan_dpmpp_2s_ancestral_rf(denoise, x, sigmas, keys, post, constrain,
                                eta=1.0):
    # Mirrors sample_dpmpp_2s_ancestral_rf (flow log-SNR midpoint + RF renoise).
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)
        downstep = 1.0 + (s_next / s - 1.0) * eta
        sd = s_next * downstep
        a1 = 1.0 - s_next
        ad = 1.0 - sd
        renoise = jnp.sqrt(jnp.maximum(s_next**2 - sd**2 * a1**2 / ad**2, 0.0))

        def euler_branch(x):
            d = (x - x0) / s
            return x + d * (sd - s)

        def second_branch(x):
            # λ diverges at σ=1: clamp the formula's input and pin the result
            # to the host's fixed 0.9999 midpoint there (the clamped value
            # only feeds the discarded where-branch).
            s_c = jnp.minimum(s, 0.999999)
            t_i = jnp.log((1.0 - s_c) / s_c)
            t_down = jnp.log((1.0 - sd) / sd)
            sigma_mid = jnp.where(
                s >= 1.0,
                jnp.float32(0.9999),
                1.0 / (jnp.exp(t_i + 0.5 * (t_down - t_i)) + 1.0),
            )
            u = (sigma_mid / s) * x + (1.0 - sigma_mid / s) * x0
            x0_2 = denoise(u, sigma_mid)
            return (sd / s) * x + (1.0 - sd / s) * x0_2

        x = jax.lax.cond(s_next > 0, second_branch, euler_branch, x)
        noise = jax.random.normal(key, x.shape, x.dtype)
        x = jnp.where(s_next > 0, (a1 / ad) * x + renoise * noise, x)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


def _scan_lcm_rf(denoise, x, sigmas, keys, post, constrain):
    # Mirrors sample_lcm_rf: flow-interpolant renoise t·n + (1−t)·x0.
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)
        noise = jax.random.normal(key, x.shape, x.dtype)
        renoised = s_next * noise + (1.0 - s_next) * x0
        x = jnp.where(s_next > 0, renoised, x0)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


# prediction="flow" scan-twin swaps (host CONST-dispatch parity; mirrors
# k_samplers.FLOW_VARIANTS — runner rejects FLOW_REJECT before reaching here).
SCAN_FLOW_VARIANTS = {
    "euler_ancestral": _scan_euler_ancestral_rf,
    "dpmpp_2s_ancestral": _scan_dpmpp_2s_ancestral_rf,
    "lcm": _scan_lcm_rf,
}


def _scan_heun(denoise, x, sigmas, keys, post, constrain):
    # Interior steps have s_next > 0; the final step (s_next == 0) is Euler,
    # which collapses to x = denoise(x, s) — run it as an epilogue so the scan
    # body keeps the uniform two-call shape without dividing by zero.
    def body(x, per):
        i, s, s_next = per
        x0 = denoise(x, s)
        d = (x - x0) / s
        x_pred = x + d * (s_next - s)
        x0_2 = denoise(x_pred, s_next)
        d2 = (x_pred - x0_2) / s_next
        x = x + 0.5 * (d + d2) * (s_next - s)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n - 1), sigmas[:-2], sigmas[1:-1]))
    x = denoise(x, sigmas[n - 1])
    return constrain(post(n - 1, x))


def _scan_dpmpp_2m(denoise, x, sigmas, keys, post, constrain):
    s_prev = jnp.concatenate([sigmas[:1], sigmas[:-2]])  # dummy at i==0

    def body(carry, per):
        x, old_x0 = carry
        i, s, s_next, sp = per
        x0 = denoise(x, s)
        t, t_next = -jnp.log(s), -jnp.log(jnp.maximum(s_next, 1e-10))
        h = t_next - t
        simple = (s_next / s) * x - jnp.expm1(-h) * x0
        h_last = t - (-jnp.log(sp))
        r = jnp.where(i == 0, 1.0, h_last / h)
        x0_prime = (1 + 1 / (2 * r)) * x0 - (1 / (2 * r)) * old_x0
        multi = (s_next / s) * x - jnp.expm1(-h) * x0_prime
        x = jnp.where((i == 0) | (s_next == 0.0), simple, multi)
        x = constrain(post(i, x))
        return (x, x0), None

    n = len(sigmas) - 1
    (x, _), _ = jax.lax.scan(
        body, (x, jnp.zeros_like(x)), (jnp.arange(n), sigmas[:-1], sigmas[1:], s_prev)
    )
    return x


def _scan_dpmpp_2m_sde(denoise, x, sigmas, keys, post, constrain, eta=1.0):
    def body(carry, per):
        x, old_x0, h_last, have = carry
        i, s, s_next, key = per
        x0 = denoise(x, s)
        last = s_next == 0.0
        t, t_next = -jnp.log(s), -jnp.log(jnp.maximum(s_next, 1e-10))
        h = t_next - t
        eta_h = eta * h
        x_new = (s_next / s) * jnp.exp(-eta_h) * x + (-jnp.expm1(-h - eta_h)) * x0
        r_safe = jnp.where(have > 0, h_last / h, 1.0)
        x_new = x_new + have * (
            0.5 * (-jnp.expm1(-h - eta_h)) * (1 / r_safe) * (x0 - old_x0)
        )
        if eta > 0:
            x_new = x_new + s_next * jnp.sqrt(
                jnp.maximum(-jnp.expm1(-2 * eta_h), 0.0)
            ) * jax.random.normal(key, x.shape, x.dtype)
        x = jnp.where(last, x0, x_new)
        x = constrain(post(i, x))
        # History updates only on non-final steps (k-diffusion keeps h_last
        # untouched when s_next == 0); old_x0 updates unconditionally, matching
        # the eager loop's assignment outside the else-branch.
        return (x, x0, jnp.where(last, h_last, h), jnp.where(last, have, 1.0)), None

    n = len(sigmas) - 1
    (x, _, _, _), _ = jax.lax.scan(
        body,
        (x, jnp.zeros_like(x), jnp.float32(1.0), jnp.float32(0.0)),
        (jnp.arange(n), sigmas[:-1], sigmas[1:], keys),
    )
    return x


def _scan_dpmpp_3m_sde(denoise, x, sigmas, keys, post, constrain, eta=1.0):
    def body(carry, per):
        x, x0_1, x0_2, h_1, h_2, count = carry
        i, s, s_next, key = per
        x0 = denoise(x, s)
        last = s_next == 0.0
        t, t_next = -jnp.log(s), -jnp.log(jnp.maximum(s_next, 1e-10))
        h = t_next - t
        h_eta = h * (eta + 1.0)
        base = jnp.exp(-h_eta) * x + (-jnp.expm1(-h_eta)) * x0
        phi_2 = jnp.expm1(-h_eta) / h_eta + 1.0
        # 2nd-order correction (one history entry)
        r_2 = h_1 / h
        d_2 = (x0 - x0_1) / r_2
        second = base + phi_2 * d_2
        # 3rd-order correction (two history entries)
        r0, r1 = h_1 / h, h_2 / h
        d1_0 = (x0 - x0_1) / r0
        d1_1 = (x0_1 - x0_2) / r1
        d1 = d1_0 + (d1_0 - d1_1) * r0 / (r0 + r1)
        d2 = (d1_0 - d1_1) / (r0 + r1)
        phi_3 = phi_2 / h_eta - 0.5
        third = base + phi_2 * d1 - phi_3 * d2
        x_new = jnp.where(count >= 2, third, jnp.where(count == 1, second, base))
        if eta > 0:
            x_new = x_new + s_next * jnp.sqrt(
                jnp.maximum(-jnp.expm1(-2.0 * eta * h), 0.0)
            ) * jax.random.normal(key, x.shape, x.dtype)
        x = jnp.where(last, x0, x_new)
        x = constrain(post(i, x))
        # No history update on a zero step (eager `continue`).
        carry = (
            x,
            jnp.where(last, x0_1, x0),
            jnp.where(last, x0_2, x0_1),
            jnp.where(last, h_1, h),
            jnp.where(last, h_2, h_1),
            jnp.where(last, count, count + 1),
        )
        return carry, None

    n = len(sigmas) - 1
    z = jnp.zeros_like(x)
    (x, *_), _ = jax.lax.scan(
        body,
        (x, z, z, jnp.float32(1.0), jnp.float32(1.0), jnp.int32(0)),
        (jnp.arange(n), sigmas[:-1], sigmas[1:], keys),
    )
    return x


def _scan_lms(denoise, x, sigmas, keys, post, constrain, coeffs=None):
    # Coefficients depend only on the (concrete) schedule — precomputed on the
    # host by the entry point (sigmas is a tracer here), zero-padded per row to
    # the running order, so the scan body is a fixed-shape history contraction.
    order = coeffs.shape[1]

    def body(carry, per):
        x, hist = carry
        i, s = per
        x0 = denoise(x, s)
        d = (x - x0) / s
        hist = jnp.roll(hist, 1, axis=0).at[0].set(d)  # hist[j] = d_{i-j}
        x = x + jnp.tensordot(coeffs[i], hist, axes=([0], [0]))
        x = constrain(post(i, x))
        return (x, hist), None

    n = len(sigmas) - 1
    hist0 = jnp.zeros((order,) + x.shape, x.dtype)
    (x, _), _ = jax.lax.scan(body, (x, hist0), (jnp.arange(n), sigmas[:-1]))
    return x


def _scan_unipc(denoise, x, sigmas, keys, post, constrain, coeffs=None):
    # Variant-agnostic: the host-precomputed table (unipc_coeff_table) bakes
    # B_h/rho differences between bh1 and bh2 into the per-step rows. History
    # carry holds the last three model evaluations (zeros early — the
    # zero-padded rki/rho columns cancel them, mirroring the eager ramp-up).
    def body(carry, per):
        x, h1, h2, h3 = carry
        i, s, s_next, c = per
        hphi1, Bh, rp0, rp1, rc0, rc1, rct, rki0, rki1 = (c[k] for k in range(9))
        m0 = h1
        D1_1 = (h2 - m0) * rki0
        D1_2 = (h3 - m0) * rki1
        base = (s_next / s) * x - hphi1 * m0

        def step_branch(x):
            x_pred = base - Bh * (rp0 * D1_1 + rp1 * D1_2)
            m_t = denoise(x_pred, s_next)
            return (
                base - Bh * (rc0 * D1_1 + rc1 * D1_2 + rct * (m_t - m0)),
                m_t,
            )

        def terminal_branch(x):
            return m0, m0  # history entry is never consumed after a terminal step

        x, m_t = jax.lax.cond(s_next > 0, step_branch, terminal_branch, x)
        x = constrain(post(i, x))
        return (x, m_t, h1, h2), None

    n = len(sigmas) - 1
    m_init = denoise(x, sigmas[0])
    z = jnp.zeros_like(x)
    (x, *_), _ = jax.lax.scan(
        body, (x, m_init, z, z), (jnp.arange(n), sigmas[:-1], sigmas[1:], coeffs)
    )
    return x


def _scan_lcm(denoise, x, sigmas, keys, post, constrain):
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)
        noise = jax.random.normal(key, x.shape, x.dtype)
        x = x0 + jnp.where(s_next > 0, s_next, 0.0) * noise
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


def _scan_ddpm(denoise, x, sigmas, keys, post, constrain):
    def body(x, per):
        i, s, s_next, key = per
        x0 = denoise(x, s)
        eps = (x - x0) / s
        acp = 1.0 / (s**2 + 1.0)
        acp_prev = 1.0 / (s_next**2 + 1.0)
        alpha = acp / acp_prev
        x_a = x / jnp.sqrt(1.0 + s**2)
        mu = jnp.sqrt(1.0 / alpha) * (
            x_a - (1.0 - alpha) * eps / jnp.sqrt(1.0 - acp)
        )
        var = (1.0 - alpha) * (1.0 - acp_prev) / jnp.maximum(1.0 - acp, 1e-12)
        noisy = (
            mu + jnp.sqrt(jnp.maximum(var, 0.0))
            * jax.random.normal(key, x.shape, x.dtype)
        ) * jnp.sqrt(1.0 + s_next**2)
        x = jnp.where(s_next > 0, noisy, mu)
        return constrain(post(i, x)), None

    n = len(sigmas) - 1
    x, _ = jax.lax.scan(body, x, (jnp.arange(n), sigmas[:-1], sigmas[1:], keys))
    return x


SCAN_SAMPLERS = {
    "euler": _scan_euler,
    "euler_ancestral": _scan_euler_ancestral,
    "heun": _scan_heun,
    "dpm_2": _scan_dpm_2,
    "dpm_2_ancestral": _scan_dpm_2_ancestral,
    "lms": _scan_lms,
    "dpmpp_2s_ancestral": _scan_dpmpp_2s_ancestral,
    "dpmpp_sde": _scan_dpmpp_sde,
    "dpmpp_2m": _scan_dpmpp_2m,
    "dpmpp_2m_sde": _scan_dpmpp_2m_sde,
    "dpmpp_3m_sde": _scan_dpmpp_3m_sde,
    "lcm": _scan_lcm,
    "ddpm": _scan_ddpm,
    "uni_pc": _scan_unipc,
    "uni_pc_bh2": _scan_unipc,
}

# Samplers whose scan body consumes a host-precomputed schedule-derived table
# (built in compiled_k_sample; sigmas is a tracer inside the loop program).
_AUX_SAMPLERS = ("lms", "uni_pc", "uni_pc_bh2")


# ---------------------------------------------------------------------------
# the jitted loop programs. Unhashable static kwargs follow the orchestrator's
# pattern (orchestrator.py _jit_for): bake them into a closure and cache the
# jitted closure by static_kwargs_key, so repeated run_sampler calls with the
# same shapes/config hit the compile cache instead of re-tracing.
# ---------------------------------------------------------------------------

_loop_jits: dict[tuple, Callable] = {}
# Bounded FIFO: entries hold the spec's apply fn (strongly) and a compiled
# executable — a long-lived host cycling through many models must not grow
# without limit. aggressive_cleanup(clear_compile_cache=True) (the teardown /
# purge_cache path) empties it entirely via clear_compiled_loops().
_LOOP_CACHE_MAX = 32


def clear_compiled_loops() -> None:
    """Drop every cached loop program (called from aggressive_cleanup on the
    purge/teardown path, so ParallelModel.cleanup() reaches this cache too)."""
    _loop_jits.clear()


def _donate_for(spec: TraceSpec) -> bool:
    """Donate the input latent only off-CPU — the CPU backend doesn't implement
    donation and would warn on every call."""
    if spec.mesh is not None:
        return spec.mesh.devices.flat[0].platform != "cpu"
    leaves = jax.tree.leaves(spec.params)
    if leaves and hasattr(leaves[0], "devices"):
        return next(iter(leaves[0].devices())).platform != "cpu"
    return jax.default_backend() != "cpu"


def _get_loop_jit(kind: str, spec: TraceSpec, static: dict, meta: tuple, build,
                  donate: tuple = (1,)):
    """Cache key mirrors the repo's jit-cache discipline: the ambient
    sequence_parallel context is read at trace time inside ops.attention, so it
    must key the cache (ops/attention.py contract; orchestrator._jit_for does
    the same). ``build`` must close over (apply, mesh, data_axis) only — NOT
    the params pytree — so params always arrive as the first call argument
    (a bare callable's apply may still close over its own weights, which is why
    the cache is bounded and clearable above)."""
    from ..ops.attention import sequence_ctx_key
    from ..parallel.split import static_kwargs_key
    from ..utils.telemetry import instrument_jit

    key = (kind, spec.apply, static_kwargs_key(static), meta, spec.mesh,
           spec.data_axis, sequence_ctx_key())
    fn = _loop_jits.get(key)
    if fn is None:
        while len(_loop_jits) >= _LOOP_CACHE_MAX:
            _loop_jits.pop(next(iter(_loop_jits)))
        impl = build(dict(static))
        donate = donate if _donate_for(spec) else ()
        # Compile accounting (utils/telemetry.py): the k-family bakes the
        # sampler name into the program label; the other kinds are
        # one-program-per-kind.
        prog = f"loop:{kind}:{meta[0]}" if kind == "k" else f"loop:{kind}"
        fn = _loop_jits[key] = instrument_jit(
            impl, prog, donate_argnums=donate
        )
    return fn


def _donation_safe(x, *others):
    """A donated buffer must not alias another argument: ddim/flow at
    denoise=1.0 pass the same array as both the latent and the mask-noise
    reference. Copy the latent when aliased."""
    if any(o is x for o in others):
        return jnp.copy(x)
    return x


def _model_fn(apply, params, static_kwargs):
    def fn(x, t, context=None, **kwargs):
        return apply(params, x, t, context, **kwargs, **static_kwargs)

    return fn


def _post_from(mask, keep_at):
    if mask is None:
        return lambda i, x: x
    return lambda i, x: _mask_blend(x, mask, keep_at(i))


def _emit_numerics(out, emit: bool):
    """Attach the sentinel's aux outputs (utils/numerics.py) to a loop
    program's result inside the jitted body: final-latent stats vector +
    bf16 digest — computed on-device, read by the caller at a boundary that
    syncs anyway (the loop's own completion)."""
    if not emit:
        return out
    return out, numerics.array_stats(out), numerics.digest(out)


def _collect_numerics(out, emit: bool, program: str):
    """Unpack a loop program's numerics aux outputs and feed the sentinel:
    a non-finite final latent records an event (counter + last-event + trace
    span), and the digest lands in the bounded fingerprint ring. No-op (and
    no host pull) when the sentinel was off at trace time."""
    if not emit:
        return out
    out, stats, dig = out
    s = np.asarray(stats)
    if s[0] > 0:
        numerics.sentinel.record_event(
            "compiled-loop", program=program, **numerics.stats_to_dict(s)
        )
    numerics.sentinel.record_fingerprints(
        where=program, digests=[int(np.asarray(dig))]
    )
    return out


# ---------------------------------------------------------------------------
# entry points (called by sampling.runner when compile_loop=True)
# ---------------------------------------------------------------------------


def _prep(spec: TraceSpec, batch: int, trees: list):
    """Pad the batch to the data-axis width and place every input tree; returns
    (placed_trees, padded)."""
    if spec.mesh is not None:
        n = spec.mesh.shape[spec.data_axis]
    else:
        n = 1
    padded = batch + ((-batch) % n)
    return [
        _place_batch(t, batch, padded, spec.mesh, spec.data_axis) for t in trees
    ], padded


def compiled_k_sample(
    spec: TraceSpec, sampler: str, x, sigmas, context, *,
    cfg_scale, uncond_context, uncond_kwargs, acp, prediction, cfg_rescale,
    rng=None, mask=None, mask_init=None, mask_noise=None, model_kwargs=None,
):
    from ..parallel.split import partition_kwargs

    batch = x.shape[0]
    traced, static = partition_kwargs(model_kwargs or {})
    # Static (non-array) uncond kwargs are ignored: double_kwargs only swaps
    # batch-dim arrays into the uncond half, same as the eager denoiser.
    u_traced, _ = partition_kwargs(uncond_kwargs or {})
    keys = (
        step_keys(jax.random.fold_in(rng, 1), len(sigmas) - 1)
        if sampler in RNG_SAMPLERS
        else None
    )
    # Schedule-derived coefficient tables are integrated here from the
    # concrete sigmas (they are tracers inside the loop program).
    if sampler == "lms":
        aux = jnp.asarray(lms_coefficient_matrix(np.asarray(sigmas)), x.dtype)
    elif sampler in ("uni_pc", "uni_pc_bh2"):
        aux = jnp.asarray(
            unipc_coeff_table(
                np.asarray(sigmas),
                variant="bh2" if sampler.endswith("bh2") else "bh1",
            ),
            x.dtype,
        )
    else:
        aux = None
    x = _donation_safe(x, mask_noise, mask_init)
    placed, padded = _prep(
        spec, batch,
        [x, context, uncond_context, traced, u_traced, mask, mask_init, mask_noise],
    )
    x, context, uncond_context, traced, u_traced, mask, mask_init, mask_noise = placed
    # The sentinel flag is part of the program signature (stats/digest aux
    # outputs), so it keys the jit cache via meta — toggling it re-traces
    # instead of silently returning the wrong tuple shape.
    emit = numerics.on()
    meta = (sampler, float(cfg_scale), float(cfg_rescale), prediction, emit)
    apply_fn, mesh, axis = spec.apply, spec.mesh, spec.data_axis

    def build(bound_static):
        def impl(params, x, sigmas, keys, aux, context, uncond_context, kwargs,
                 u_kwargs, acp, mask, mask_init, mask_noise):
            denoise = EpsDenoiser(
                _model_fn(apply_fn, params, bound_static), context,
                cfg_scale=meta[1], uncond_context=uncond_context,
                uncond_kwargs=u_kwargs, alphas_cumprod=acp,
                prediction=meta[3], cfg_rescale=meta[2], **kwargs,
            )
            if meta[3] == "flow":
                # Flow forward process: keep-region re-pinned to
                # (1−t)·init + t·noise at each step's flow time.
                post = _post_from(
                    mask,
                    lambda i: (1.0 - sigmas[i + 1]) * mask_init
                    + sigmas[i + 1] * mask_noise,
                )
            else:
                post = _post_from(
                    mask, lambda i: mask_init + mask_noise * sigmas[i + 1]
                )
            constrain = lambda v: _constrain(v, mesh, axis)  # noqa: E731
            sampler_fn = SCAN_SAMPLERS[meta[0]]
            if meta[3] == "flow":
                sampler_fn = SCAN_FLOW_VARIANTS.get(meta[0], sampler_fn)
            if meta[0] in _AUX_SAMPLERS:
                out = sampler_fn(denoise, x, sigmas, keys, post, constrain,
                                 coeffs=aux)
            else:
                out = sampler_fn(denoise, x, sigmas, keys, post, constrain)
            return _emit_numerics(out, emit)

        return impl

    fn = _get_loop_jit("k", spec, static, meta, build)
    out = fn(
        spec.params, x, sigmas, keys, aux, context, uncond_context, traced,
        u_traced or None, acp, mask, mask_init, mask_noise,
    )
    out = _collect_numerics(out, emit, f"loop:k:{sampler}")
    return _slice_padded(out, batch, padded)


def compiled_ddim_sample(
    spec: TraceSpec, x, ts, acp, context, *,
    cfg_scale, uncond_context, uncond_kwargs, prediction, cfg_rescale,
    mask=None, mask_init=None, mask_noise=None, model_kwargs=None,
):
    from ..parallel.split import partition_kwargs

    batch_orig = x.shape[0]
    traced, static = partition_kwargs(model_kwargs or {})
    u_traced, _ = partition_kwargs(uncond_kwargs or {})
    a_t = acp[ts]
    a_prev = jnp.concatenate([acp[ts[1:]], jnp.ones((1,), acp.dtype)])
    x = _donation_safe(x, mask_noise, mask_init)
    placed, padded = _prep(
        spec, batch_orig,
        [x, context, uncond_context, traced, u_traced, mask, mask_init, mask_noise],
    )
    x, context, uncond_context, traced, u_traced, mask, mask_init, mask_noise = placed
    emit = numerics.on()
    meta = (float(cfg_scale), float(cfg_rescale), prediction, emit)
    apply_fn, mesh, axis = spec.apply, spec.mesh, spec.data_axis

    def build(bound_static):
        def impl(params, x, ts, a_t, a_prev, context, uncond_context, kwargs,
                 u_kwargs, mask, mask_init, mask_noise):
            model = _model_fn(apply_fn, params, bound_static)
            cfg_scale_, cfg_rescale_, prediction_ = meta[:3]
            batch = x.shape[0]
            use_cfg = cfg_scale_ != 1.0 and uncond_context is not None
            post = _post_from(
                mask,
                lambda i: jnp.sqrt(a_prev[i]) * mask_init
                + jnp.sqrt(1.0 - a_prev[i]) * mask_noise,
            )

            def body(x, per):
                i, t, at, aprev = per
                t_vec = jnp.full((batch,), t, jnp.float32)
                if use_cfg:
                    kw = double_kwargs(kwargs, u_kwargs, batch)
                    out_both = model(
                        jnp.concatenate([x, x], axis=0),
                        jnp.concatenate([t_vec, t_vec], axis=0),
                        jnp.concatenate([context, uncond_context], axis=0),
                        **kw,
                    )
                    out_c, out_u = jnp.split(out_both, 2, axis=0)
                    out = out_u + cfg_scale_ * (out_c - out_u)
                    out = rescale_guidance(out, out_c, cfg_rescale_)
                else:
                    out = model(x, t_vec, context, **kwargs)
                if prediction_ == "v":
                    x0 = jnp.sqrt(at) * x - jnp.sqrt(1.0 - at) * out
                    eps = (x - jnp.sqrt(at) * x0) / jnp.sqrt(1.0 - at)
                else:
                    eps = out
                    x0 = (x - jnp.sqrt(1.0 - at) * eps) / jnp.sqrt(at)
                x = jnp.sqrt(aprev) * x0 + jnp.sqrt(1.0 - aprev) * eps
                return _constrain(post(i, x), mesh, axis), None

            n = len(ts)
            x, _ = jax.lax.scan(body, x, (jnp.arange(n), ts, a_t, a_prev))
            return _emit_numerics(x, emit)

        return impl

    fn = _get_loop_jit("ddim", spec, static, meta, build)
    out = fn(
        spec.params, x, ts, a_t, a_prev, context, uncond_context, traced,
        u_traced or None, mask, mask_init, mask_noise,
    )
    out = _collect_numerics(out, emit, "loop:ddim")
    return _slice_padded(out, batch_orig, padded)


def compiled_flow_sample(
    spec: TraceSpec, x, ts, context, *,
    cfg_scale, uncond_context, uncond_kwargs, guidance, cfg_rescale,
    mask=None, mask_init=None, mask_noise=None, model_kwargs=None,
):
    from ..parallel.split import partition_kwargs

    batch_orig = x.shape[0]
    traced, static = partition_kwargs(model_kwargs or {})
    u_traced, _ = partition_kwargs(uncond_kwargs or {})
    x = _donation_safe(x, mask_noise, mask_init)
    placed, padded = _prep(
        spec, batch_orig,
        [x, context, uncond_context, traced, u_traced, mask, mask_init, mask_noise],
    )
    x, context, uncond_context, traced, u_traced, mask, mask_init, mask_noise = placed
    emit = numerics.on()
    meta = (
        float(cfg_scale), float(cfg_rescale),
        None if guidance is None else float(guidance), emit,
    )
    apply_fn, mesh, axis = spec.apply, spec.mesh, spec.data_axis

    def build(bound_static):
        def impl(params, x, ts, context, uncond_context, kwargs, u_kwargs,
                 mask, mask_init, mask_noise):
            model = _model_fn(apply_fn, params, bound_static)
            cfg_scale_, cfg_rescale_, guidance_ = meta[:3]
            batch = x.shape[0]
            use_cfg = cfg_scale_ != 1.0 and uncond_context is not None
            kw = dict(kwargs)
            if guidance_ is not None:
                kw["guidance"] = jnp.full((batch,), guidance_, jnp.float32)
            post = _post_from(
                mask,
                lambda i: (1.0 - ts[i + 1]) * mask_init + ts[i + 1] * mask_noise,
            )

            def body(x, per):
                i, t, t_next = per
                t_vec = jnp.full((batch,), t, jnp.float32)
                if use_cfg:
                    kw2 = double_kwargs(kw, u_kwargs, batch)
                    v_both = model(
                        jnp.concatenate([x, x], axis=0),
                        jnp.concatenate([t_vec, t_vec], axis=0),
                        jnp.concatenate([context, uncond_context], axis=0),
                        **kw2,
                    )
                    v_c, v_u = jnp.split(v_both, 2, axis=0)
                    v = v_u + cfg_scale_ * (v_c - v_u)
                    v = rescale_guidance(v, v_c, cfg_rescale_)
                else:
                    v = model(x, t_vec, context, **kw)
                x = x + (t_next - t) * v
                return _constrain(post(i, x), mesh, axis), None

            n = len(ts) - 1
            x, _ = jax.lax.scan(body, x, (jnp.arange(n), ts[:-1], ts[1:]))
            return _emit_numerics(x, emit)

        return impl

    fn = _get_loop_jit("flow", spec, static, meta, build)
    out = fn(
        spec.params, x, ts, context, uncond_context, traced, u_traced or None,
        mask, mask_init, mask_noise,
    )
    out = _collect_numerics(out, emit, "loop:flow")
    return _slice_padded(out, batch_orig, padded)


# ---------------------------------------------------------------------------
# per-lane batched step (round 7, generalized round 10, serving/): ONE
# compiled dispatch advances a fixed-width batch of lanes, each carrying its
# OWN (sigma, state, sampler) — the step-boundary seam continuous batching
# joins and leaves at. The model eval (the only FLOPs that matter) is shared;
# each lane's sampler update is the host-precomputed linear combination its
# LaneStepSpec emitted (sampling/lane_specs.py), so lanes running DIFFERENT
# samplers — including two-eval and stochastic families — ride one dispatch.
# Padded/retired lanes are masked with jnp.where (a select, so a junk
# pad-lane value can never leak into a live lane — per-sample independence of
# the model does the rest).
# ---------------------------------------------------------------------------


def lane_step_program(
    spec: TraceSpec, *, prediction: str, use_cfg: bool, cfg_rescale: float,
    static_kwargs: dict, emit_stats: bool = False, broadcast_cond: bool = False,
    broadcast_kwargs: bool = False, n_extra: int | None = None,
    mc_has_y: bool = False, control_apply=None, lora_sig: tuple = (),
):
    """The jitted per-step program for one serving bucket (W = lane width,
    b = per-request batch):

    ``fn(params, x[W,b,...], xe[W,b,...], h1[W,b,...], h2[W,b,...],
    sigma_eval[W], active[W] f32, cfg_scale[W], coef[W,4,6] f32,
    noise_keys[W,2] u32, context[W,b,L,D]|None, uncond_context|None, kwargs,
    u_kwargs, log_sigmas|None, mask[W,b,...], mask_init[W,b,...],
    mask_noise[W,b,...], mask_mix[W,3], [capability overlays...])
    -> (x', xe', h1', h2')``

    One batched model eval at per-lane ``(xe, sigma_eval)`` — the σ→timestep
    log-interp, 1/√(σ²+1) input scaling, and CFG mix (per-lane cfg_scale) all
    broadcast over the lane axis — produces the denoised estimate ``x0``;
    then every state slot updates as the ``coef``-weighted combination of
    ``(x, xe, x0, h1, h2, noise)``. ``noise`` is one per-lane draw from the
    lane's own key (threefry key data, occupancy-independent by the fold_in
    discipline), so stochastic lanes are bit-identical alone or co-batched.
    The sampler never appears in the program: traffic-mix changes can't
    recompile. Inactive lanes get sigma pinned to 1.0 (no divide-by-zero),
    identity coefficients, and a where-select pass-through. Cached via the
    loop-jit cache (bounded, clearable); all four state stacks are donated.

    ``emit_stats`` (the numerics sentinel, utils/numerics.py) appends two aux
    outputs — per-lane ``[W, 4]`` stats (non-finite count over x'∪xe', then
    max|x'|/mean/rms) and per-lane bf16 digests ``[W]`` — computed on-device
    inside the same dispatch, and keeps ``xe`` UNdonated so the quarantine
    path can re-run the failing eval input through the model's PipelineSpec
    stages after the fact.

    ``broadcast_cond`` (round 17, sibling-seed cond sharing): ``context`` /
    ``uncond_context`` arrive as ONE per-request tensor ``[b, L, D]``
    referenced by every lane — broadcast over the lane axis inside the
    program instead of stacked per-lane on the host. An N-seed fanout of one
    prompt then costs one cond tensor in HBM (not W copies) and zero
    per-lane cond transfers at seat time. Bit-discipline: the broadcast
    materializes the IDENTICAL ``[n, L, D]`` values the stacked path
    reshapes to, so everything downstream of the flatten is the same
    program graph on the same values (tests pin broadcast-vs-stacked
    equality bitwise on CPU).

    ``broadcast_kwargs`` (PR 12 remainder): the TRACED kwargs trees —
    ``kwargs`` / ``u_kwargs`` (pooled ``y`` vectors, per-request
    ``guidance``, the negative-prompt/uncond extras) — arrive as ONE
    per-request tree referenced by every lane and broadcast over the lane
    axis inside the program, exactly like ``broadcast_cond`` above. A
    sibling-seed fanout then stops stacking identical uncond rows too:
    same values, same downstream graph as the stacked variant (the flatten
    sees the identical ``[n, ...]`` tree either way).

    Capability axes (round 16, universal lane batching). Every feature that
    used to force inline fallback is per-lane STATE here, so a mixed queue
    shares the one dispatch:

    - **denoise mask** (img2img/inpaint) — always-on inputs ``mask`` /
      ``mask_init`` / ``mask_noise`` ``[W, b, ...]`` plus a per-dispatch
      ``mask_mix[W, 3]`` of ``(gate, keep_a, keep_b)`` host scalars. On
      σ-interval completion the lane's x'/xe' re-pin the keep region to
      ``keep_a·init + keep_b·noise`` (the eager masked_callback formula per
      prediction family); zero-gate lanes are a where-select pass-through, so
      plain txt2img lanes ride the SAME program — no variant, no recompile,
      bitwise across any traffic mix.
    - **multi-cond CFG** (``n_extra`` = the bucket's max extra-cond count K) —
      K extra eval row-blocks share the model call; per-lane weight maps
      ``mc_w0``/``mc_w`` (area/mask/strength composed host-side at seat,
      zero for non-users) and traced per-extra progress windows ``mc_win``
      reproduce EpsDenoiser._combine_conds op-for-op, with zero-weight lanes
      falling through to their own eps bitwise (den == 0 → primary).
    - **ControlNet** (``control_apply``) — the control trunk joins the shared
      eval over ALL rows with a per-lane hint stack and traced per-lane
      ``(strength, window)``; residuals scale by the apply_control gate and
      feed the base model's ``control`` kwarg. Zero-strength lanes get exact
      zero residual trees (additive no-op on values).
    - **per-lane LoRA** (``lora_sig`` = ordered ``(path, m, k)`` targets) —
      A/B factors arrive stacked on the lane axis (rank-padded to the
      bucket's max; zero factors → bitwise-identity delta) and the eval
      re-groups rows lane-major and vmaps the model with per-lane merged
      target leaves ``W + b @ a`` — the Punica/S-LoRA batched-adapter
      formulation, so any LoRA mix shares one compiled program.

    Each overlay is a cached program VARIANT (same bounded loop-jit cache the
    PR 12 shared→stacked demotion uses): materializing a capability the
    bucket epoch hasn't seen compiles once; traffic mix within a capability
    set never recompiles. Cross-variant legs are allclose-at-bf16, same-
    program legs stay bitwise (the serving equivalence matrix pins both)."""
    lora_sig = tuple(tuple(t) for t in lora_sig)
    meta = ("serve", prediction, bool(use_cfg), float(cfg_rescale),
            bool(emit_stats), bool(broadcast_cond), bool(broadcast_kwargs),
            None if n_extra is None else int(n_extra), bool(mc_has_y),
            control_apply, lora_sig)
    use_mc = n_extra is not None
    K = int(n_extra or 0)
    use_control = control_apply is not None
    apply_fn, mesh, axis = spec.apply, spec.mesh, spec.data_axis

    def build(bound_static):
        def impl(params, x, xe, h1, h2, sigma_eval, active, cfg_scale, coef,
                 noise_keys, context, uncond_context, kwargs, u_kwargs,
                 log_sigmas, mask, mask_init, mask_noise, mask_mix,
                 mc_w0=None, mc_ctx=None, mc_w=None, mc_win=None, mc_y=None,
                 ctrl_params=None, ctrl_hint=None, ctrl_strength=None,
                 ctrl_win=None, lora_ab=()):
            model = _model_fn(apply_fn, params, bound_static)
            W, b = x.shape[0], x.shape[1]
            n = W * b

            def flatten(tree):
                return jax.tree.map(
                    lambda l: l.reshape((n,) + l.shape[2:]), tree
                )

            def bcast(v, ndim):
                return v.reshape(v.shape + (1,) * (ndim - 1))

            lane = lambda v: jnp.repeat(v, b, total_repeat_length=n)  # noqa: E731
            if broadcast_cond:
                # Shared-cond lanes: one [b, ...] tensor broadcast to the
                # [W, b, ...] stack the flatten below expects — same values,
                # same downstream graph as the stacked variant.
                if context is not None:
                    context = jnp.broadcast_to(
                        context[None], (W,) + context.shape
                    )
                if uncond_context is not None:
                    uncond_context = jnp.broadcast_to(
                        uncond_context[None], (W,) + uncond_context.shape
                    )
            if broadcast_kwargs:
                # Shared traced kwargs (the PR 12 remainder): one [b, ...]
                # tree per request, broadcast over the lane axis — the
                # uncond/negative-prompt extras stop stacking too.
                bc = lambda l: jnp.broadcast_to(l[None], (W,) + l.shape)  # noqa: E731
                if kwargs:
                    kwargs = jax.tree.map(bc, kwargs)
                if u_kwargs:
                    u_kwargs = jax.tree.map(bc, u_kwargs)
            flat = xe.reshape((n,) + xe.shape[2:])
            s = jnp.where(active > 0, sigma_eval, jnp.float32(1.0))
            s_flat = lane(s)
            if prediction == "flow":
                # Flow time IS the sigma (EpsDenoiser flow branch).
                t_vec = s_flat
                x_in = flat
                scale_flat = None
            else:
                scale_flat = 1.0 / jnp.sqrt(s_flat**2 + 1.0)
                t_vec = jnp.interp(
                    jnp.log(s_flat), log_sigmas,
                    jnp.arange(log_sigmas.shape[0], dtype=jnp.float32),
                )
                x_in = flat * bcast(scale_flat, flat.ndim)
            ctx = None if context is None else flatten(context)
            kw = flatten(kwargs) if kwargs else {}

            # --- role blocks: [cond | uncond? | extra_0 .. extra_{K-1}],
            # each n rows of the ONE shared eval. Inline calls the model once
            # per extra (token lengths may differ there); bucket eligibility
            # pins extras to the primary's (L, D), so here they batch.
            roles_ctx = [ctx]
            roles_kw = [kw]
            if use_cfg:
                u_kw = flatten(u_kwargs) if u_kwargs else {}
                extra_keys = set(u_kw) - set(kw)
                if extra_keys:
                    raise ValueError(
                        f"uncond kwargs carry keys absent from cond kwargs: "
                        f"{sorted(extra_keys)}"
                    )
                roles_ctx.append(flatten(uncond_context))
                roles_kw.append({**kw, **u_kw})
            for k_i in range(K):
                roles_ctx.append(
                    mc_ctx[:, k_i].reshape((n,) + mc_ctx.shape[3:])
                )
                kw_e = dict(kw)
                if mc_has_y:
                    kw_e["y"] = mc_y[:, k_i].reshape((n,) + mc_y.shape[3:])
                roles_kw.append(kw_e)
            R = len(roles_kw)

            if use_control:
                hint_flat = ctrl_hint.reshape((n,) + ctrl_hint.shape[2:])
                # apply_control's gate, per lane: strength × progress window
                # (ops.basic.progress_window_gate with traced bounds; the
                # default (0, 1) window is exactly 1.0, matching the inline
                # no-window fast path bitwise). apply_control keeps the
                # eps/v linear-in-t approximation for every family.
                prog_c = 1.0 - t_vec / 999.0
                on = (prog_c >= lane(ctrl_win[:, 0])) & (
                    prog_c <= lane(ctrl_win[:, 1])
                )
                gain_flat = lane(ctrl_strength) * on.astype(jnp.float32)

            if lora_sig:
                # Lane-major layout: rows grouped per lane [W, R·b, ...] and
                # the model vmapped over lanes with per-lane merged LoRA
                # target leaves (W_eff = W + b @ a; zero-padded factors give
                # a bitwise-zero delta for LoRA-free lanes / rank slots).
                from ..models.lora import get_path as _getp, set_path as _setp

                group = lambda r_: r_.reshape((W, b) + r_.shape[1:])  # noqa: E731
                cat1 = lambda rs: jnp.concatenate(rs, axis=1)  # noqa: E731
                x_l = cat1([group(x_in)] * R)
                t_l = cat1([group(t_vec)] * R)
                ctx_l = (
                    None if ctx is None
                    else cat1([group(r_) for r_ in roles_ctx])
                )
                kw_l = {
                    k_: cat1([group(r_[k_]) for r_ in roles_kw])
                    for k_ in kw
                }
                hint_l = (
                    cat1([group(hint_flat)] * R) if use_control else None
                )
                gain_l = (
                    cat1([group(gain_flat)] * R) if use_control else None
                )

                def one_lane(ab, xr, tr, cr, kwr, hr, gr):
                    p = params
                    for (path, _m, _k), (a_, b_) in zip(lora_sig, ab):
                        w_ = _getp(p, path)
                        # nd targets: the factors address the
                        # (shape[0], prod(rest)) flattening (models/lora.py).
                        p = _setp(p, path, w_ + (b_ @ a_)
                                  .reshape(w_.shape).astype(w_.dtype))
                    call_kw = dict(kwr)
                    if use_control:
                        ctrl = control_apply(
                            ctrl_params, xr, tr, cr, hint=hr,
                            y=kwr.get("y"),
                        )
                        ctrl = jax.tree.map(
                            lambda r_: r_ * bcast(gr, r_.ndim), ctrl
                        )
                        call_kw["control"] = ctrl
                    return apply_fn(p, xr, tr, cr, **call_kw, **bound_static)

                out_l = jax.vmap(
                    one_lane,
                    in_axes=(0, 0, 0, None if ctx_l is None else 0, 0,
                             None if hint_l is None else 0,
                             None if gain_l is None else 0),
                )(lora_ab, x_l, t_l, ctx_l, kw_l, hint_l, gain_l)
                outs = [
                    r_.reshape((n,) + r_.shape[2:])
                    for r_ in jnp.split(out_l, R, axis=1)
                ]
            else:
                x_all = jnp.concatenate([x_in] * R, axis=0)
                t_all = jnp.concatenate([t_vec] * R, axis=0)
                ctx_all = (
                    None if ctx is None
                    else jnp.concatenate(roles_ctx, axis=0)
                )
                kw_all = {
                    k_: jnp.concatenate([r_[k_] for r_ in roles_kw], axis=0)
                    for k_ in kw
                }
                if use_control:
                    hint_all = jnp.concatenate([hint_flat] * R, axis=0)
                    gain_all = jnp.concatenate([gain_flat] * R, axis=0)
                    ctrl = control_apply(
                        ctrl_params, x_all, t_all, ctx_all, hint=hint_all,
                        y=kw_all.get("y"),
                    )
                    kw_all["control"] = jax.tree.map(
                        lambda r_: r_ * bcast(gain_all, r_.ndim), ctrl
                    )
                out = model(x_all, t_all, ctx_all, **kw_all)
                outs = (
                    jnp.split(out, R, axis=0) if R > 1 else [out]
                )

            eps_c = outs[0]
            if use_mc:
                # EpsDenoiser._combine_conds, lane-batched: per-lane weight
                # maps (strength/area/mask composed at seat, full [W, b, ...]
                # per-sample stacks) flatten like the state; zero-map lanes
                # give den == 0 → the primary eps passes through bitwise.
                m0_rows = mc_w0.reshape((n,) + mc_w0.shape[2:])
                num = m0_rows * eps_c
                den = m0_rows * jnp.ones_like(eps_c[..., :1])
                flow_t = prediction == "flow"
                prog_m = 1.0 - (t_vec if flow_t else t_vec / 999.0)
                for k_i in range(K):
                    eps_e = outs[1 + (1 if use_cfg else 0) + k_i]
                    g = (
                        (prog_m >= lane(mc_win[:, k_i, 0]))
                        & (prog_m <= lane(mc_win[:, k_i, 1]))
                    ).astype(jnp.float32)
                    m_k = mc_w[:, k_i].reshape(
                        (n,) + mc_w.shape[3:]
                    ) * g.reshape((-1,) + (1,) * (eps_e.ndim - 1))
                    num = num + m_k * eps_e
                    den = den + m_k * jnp.ones_like(eps_e[..., :1])
                eps_c = jnp.where(den > 0, num / jnp.maximum(den, 1e-8), eps_c)
            if use_cfg:
                eps_u = outs[1]
                cfg_flat = bcast(lane(cfg_scale), eps_c.ndim)
                eps = eps_u + cfg_flat * (eps_c - eps_u)
                eps = rescale_guidance(eps, eps_c, float(cfg_rescale))
            else:
                eps = eps_c
            if prediction == "v":
                x0_flat = (
                    flat / bcast(s_flat**2 + 1.0, flat.ndim)
                    - eps * bcast(s_flat * scale_flat, flat.ndim)
                )
            else:
                # eps: x0 = x − σ·eps. flow: x0 = x − σ·v — the same expression.
                x0_flat = flat - bcast(s_flat, flat.ndim) * eps
            x0 = x0_flat.reshape(x.shape)
            # Per-lane noise from per-lane key data: vmapped normal over lane
            # keys == each lane's solo normal(key, (b, ...)) draw, bitwise.
            noise = jax.vmap(
                lambda k: jax.random.normal(
                    jax.random.wrap_key_data(k), x.shape[1:], x.dtype
                )
            )(noise_keys)
            basis = (x, xe, x0, h1, h2, noise)

            def mix(j):
                acc = None
                for k, base in enumerate(basis):
                    term = bcast(coef[:, j, k], x.ndim) * base
                    acc = term if acc is None else acc + term
                return acc.astype(x.dtype)

            live = bcast(active > 0, x.ndim)
            new = tuple(
                _constrain(jnp.where(live, mix(j), old), mesh, axis)
                for j, old in enumerate((x, xe, h1, h2))
            )
            # Denoise-mask re-pin (always-on capability axis): on σ-interval
            # completion a masked lane's x'/xe' keep region re-pins to
            # keep_a·init + keep_b·noise — the eager masked_callback blend,
            # gated per lane by the host-computed mask_mix so maskless lanes
            # are a structural where-pass-through (histories untouched,
            # matching the inline path where the blend is a post-step
            # callback that never sees sampler history).
            m_gate = bcast(mask_mix[:, 0] > 0, x.ndim)
            keep = (
                bcast(mask_mix[:, 1], x.ndim) * mask_init
                + bcast(mask_mix[:, 2], x.ndim) * mask_noise
            )
            blend = lambda v: (  # noqa: E731
                _mask_blend(v, mask, keep)
            ).astype(x.dtype)
            new = (
                _constrain(jnp.where(m_gate, blend(new[0]), new[0]), mesh, axis),
                _constrain(jnp.where(m_gate, blend(new[1]), new[1]), mesh, axis),
                new[2], new[3],
            )
            if not emit_stats:
                return new
            # Per-lane stats (xe' folded into the non-finite count: a NaN a
            # two-eval sampler parks mid-step is caught at THIS dispatch) and
            # lane-local digests — tiny reductions riding the same program.
            return new + (
                numerics.lane_stats(new[0], extra=new[1]),
                numerics.lane_digest(new[0]),
            )

        return impl

    return _get_loop_jit("serve", spec, static_kwargs, meta, build,
                         donate=(1, 3, 4) if emit_stats else (1, 2, 3, 4))
