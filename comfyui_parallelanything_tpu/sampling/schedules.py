"""Noise schedules for the SD-family samplers."""

from __future__ import annotations

import jax.numpy as jnp


def scaled_linear_schedule(
    n_timesteps: int = 1000, beta_start: float = 0.00085, beta_end: float = 0.012
) -> jnp.ndarray:
    """SD's 'scaled_linear' betas → cumulative alphas (ᾱ_t), shape (n_timesteps,)."""
    betas = (
        jnp.linspace(beta_start**0.5, beta_end**0.5, n_timesteps, dtype=jnp.float32)
        ** 2
    )
    return jnp.cumprod(1.0 - betas)


def ddim_timesteps(n_steps: int, n_train: int = 1000) -> jnp.ndarray:
    """Evenly spaced sampling timesteps, descending (e.g. 20 of 1000)."""
    step = n_train // n_steps
    return jnp.arange(0, n_train, step, dtype=jnp.int32)[::-1]
