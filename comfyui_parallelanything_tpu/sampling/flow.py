"""Flow-matching Euler sampler (FLUX / rectified-flow family).

The model predicts velocity v(x_t, t); integration runs t: 1 → 0 with
x_{t'} = x_t + (t' − t)·v. Optional timestep shift (resolution-dependent, the
FLUX-dev recipe) warps the schedule toward high-noise steps for large images.
Host-side step loop like ddim.py — each step drives the (possibly parallelized)
model forward.
"""

from __future__ import annotations

import jax.numpy as jnp

from .cfg import apply_callback, double_kwargs, rescale_guidance


def apply_flow_shift(t: jnp.ndarray, shift: float) -> jnp.ndarray:
    """The rectified-flow resolution shift warp t ↦ s·t/(1+(s−1)·t) — the one
    implementation shared by the flow_euler ladder and the CONST sigma table
    the scheduler menu ranges over (k_samplers.flow_sigma_table)."""
    if shift == 1.0:
        return t
    return shift * t / (1.0 + (shift - 1.0) * t)


def flow_timesteps(steps: int, shift: float = 1.0) -> jnp.ndarray:
    """(steps+1,) descending t in [1, 0], with the rectified-flow shift applied."""
    return apply_flow_shift(
        jnp.linspace(1.0, 0.0, steps + 1, dtype=jnp.float32), shift
    )


def flow_euler_sample(
    model,
    x_init: jnp.ndarray,
    context: jnp.ndarray | None = None,
    *,
    steps: int = 20,
    shift: float = 1.0,
    guidance: float | None = None,
    cfg_scale: float = 1.0,
    uncond_context: jnp.ndarray | None = None,
    uncond_kwargs: dict | None = None,
    callback=None,
    ts: jnp.ndarray | None = None,
    cfg_rescale: float = 0.0,
    **model_kwargs,
) -> jnp.ndarray:
    """Euler-integrate the flow from noise (t=ts[0]) to sample (t=0).

    ``guidance`` feeds FLUX-dev's distilled guidance embedding; ``cfg_scale`` +
    ``uncond_context`` run true classifier-free guidance (batched, like ddim.py).
    ``ts`` overrides the schedule (img2img passes a truncated one and mixes
    ``x_init`` to ts[0] itself)."""
    if ts is None:
        ts = flow_timesteps(steps, shift)
    steps = len(ts) - 1
    batch = x_init.shape[0]
    use_cfg = cfg_scale != 1.0 and uncond_context is not None

    kw = dict(model_kwargs)
    if guidance is not None:
        kw["guidance"] = jnp.full((batch,), guidance, jnp.float32)

    x = x_init
    for i in range(steps):
        t_vec = jnp.full((batch,), ts[i], jnp.float32)
        if use_cfg:
            x_in = jnp.concatenate([x, x], axis=0)
            t_in = jnp.concatenate([t_vec, t_vec], axis=0)
            c_in = jnp.concatenate([context, uncond_context], axis=0)
            kw2 = double_kwargs(kw, uncond_kwargs, batch)
            v_both = model(x_in, t_in, c_in, **kw2)
            v_c, v_u = jnp.split(v_both, 2, axis=0)
            v = v_u + cfg_scale * (v_c - v_u)
            v = rescale_guidance(v, v_c, cfg_rescale)
        else:
            v = model(x, t_vec, context, **kw)
        x = x + (ts[i + 1] - ts[i]) * v
        x = apply_callback(callback, i, x)
    return x
