"""Per-lane step-program family: every k-sampler as a stateful lane (round 10).

The serving layer's dispatch unit is ONE batched model eval (the only thing
that costs FLOPs); everything a sampler does *around* that eval is elementwise
latent math with schedule-derived scalar weights. This module factors each
k-sampler's step into exactly that shape, so lanes running DIFFERENT samplers
can share one compiled dispatch:

- **Lane state** is the fixed pytree ``(x, xe, h1, h2)`` — the current latent,
  the next model-eval input (mid-step for two-eval samplers, else ``x``), and
  two history/stash slots (``old_x0``-style carries; the lane analogue of the
  fused-loop carries in ``sampling/compiled.py``, e.g. dpmpp_2m's
  ``(x, old_x0)`` scan carry).
- **A StepPlan** is one model eval plus a linear update: evaluate the model at
  ``(xe, sigma_eval)`` producing the denoised estimate ``x0``, then each state
  slot becomes a per-lane-scalar-weighted combination of the basis
  ``(x, xe, x0, h1, h2, noise)``. The weights depend only on the (host-known)
  schedule, step index, and phase — so they are precomputed here in float64
  and shipped to the device as a tiny ``[4, 6]`` matrix per lane per dispatch.
  Second-order samplers (heun, dpm_2, ...) emit TWO plans per σ-interval —
  the per-lane state machine the scheduler walks one eval at a time.
- **Stochastic samplers** are occupancy-independent by construction: the
  step-``i`` noise key is ``fold_in(request_rng, i)`` (``noise``/``step``
  fields below name which key), the same discipline the eager loops and the
  whole-loop compiled twins use (sampling/k_samplers.py), so a lane's output
  is bit-identical whether its prompt runs alone or co-batched.

``LANE_SPECS`` is the registry ``serving.scheduler.BATCHABLE_SAMPLERS`` is
derived from; ``tests/test_serving.py`` enforces that every entry here appears
in the lane-vs-solo equivalence matrix (a wired-but-unverified sampler fails
the build). Excluded by design: ``lms``/``uni_pc*`` (order-4 latent history /
predictor-corrector eval-at-next-sigma structure — a different dispatch
shape), and ``ddpm`` on flow schedules (``k_samplers.FLOW_REJECT``).

Reference behavior: each plan compiler transcribes its eager twin in
``k_samplers.py`` (which mirrors any_device_parallel.py:1287's host sampler
menu) op-for-op, with the sigma-dependent scalars lifted to the host.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable

import numpy as np

__all__ = [
    "LANE_SPECS",
    "LaneStepSpec",
    "StepPlan",
    "lane_eval_count",
    "plan_schedule",
]

# Basis indices for StepPlan.coef columns: current latent, eval input, fresh
# model estimate, history slots, per-step noise draw.
X, XE, E, H1, H2, N = range(6)


@dataclasses.dataclass(frozen=True)
class StepPlan:
    """One model eval + linear state update for one lane.

    ``coef[j]`` weights the basis ``(x, xe, x0, h1, h2, noise)`` into the new
    ``(x, xe, h1, h2)[j]``. ``noise`` selects the key for the basis noise
    draw: None (no draw consumed), ``"step"`` (``fold_in(rng, step)``), or
    ``"sde_mid"``/``"sde_end"`` (the two ``split(fold_in(rng, step))`` halves
    dpmpp_sde consumes per interval). ``completes`` marks the eval that
    finishes the σ-interval (the lane's step index advances; progress fires)."""

    sigma_eval: float
    coef: np.ndarray  # [4, 6] float32
    completes: bool = True
    noise: str | None = None
    step: int = 0


def _vec(x=0.0, xe=0.0, e=0.0, h1=0.0, h2=0.0, n=0.0) -> np.ndarray:
    return np.array([x, xe, e, h1, h2, n], np.float64)


_KEEP_H1 = _vec(h1=1.0)
_KEEP_H2 = _vec(h2=1.0)


def _mk(sigma_eval, x_row, *, xe_row=None, h1_row=None, h2_row=None,
        completes=True, noise=None, step=0) -> StepPlan:
    """Assemble a plan; ``xe`` follows the new ``x`` unless overridden (a
    completed step's next eval input IS its output latent), history slots
    default to carry-through."""
    coef = np.stack([
        x_row,
        xe_row if xe_row is not None else x_row,
        h1_row if h1_row is not None else _KEEP_H1,
        h2_row if h2_row is not None else _KEEP_H2,
    ]).astype(np.float32)
    return StepPlan(float(sigma_eval), coef, completes, noise, step)


def _ancestral(s: float, s_next: float, eta: float = 1.0):
    """Float64 twin of k_samplers.ancestral_steps."""
    su = min(
        s_next,
        eta * math.sqrt(max(s_next**2 * (s**2 - s_next**2) / s**2, 0.0)),
    )
    sd = math.sqrt(max(s_next**2 - su**2, 0.0))
    return sd, su


# ---------------------------------------------------------------------------
# plan compilers — one per sampler; (sigmas float64, prediction) -> [StepPlan].
# Each transcribes its eager twin's branch structure; eta is the eager default
# (1.0) because run_sampler never overrides it.
# ---------------------------------------------------------------------------


def _plans_euler(sig, prediction):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        r = (sn - s) / s
        out.append(_mk(s, _vec(x=1.0 + r, e=-r), step=i))
    return out


def _plans_euler_ancestral(sig, prediction, eta=1.0):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if prediction == "flow":
            # sample_euler_ancestral_rf: interpolant alpha-ratio renoise.
            if sn == 0.0:
                out.append(_mk(s, _vec(e=1.0), step=i))
                continue
            sd = sn * (1.0 + (sn / s - 1.0) * eta)
            a1, ad = 1.0 - sn, 1.0 - sd
            renoise = math.sqrt(max(sn**2 - sd**2 * a1**2 / ad**2, 0.0))
            g, ratio = a1 / ad, sd / s
            out.append(_mk(
                s, _vec(x=g * ratio, e=g * (1.0 - ratio), n=renoise),
                noise="step", step=i,
            ))
            continue
        sd, su = _ancestral(s, sn, eta)
        r = (sd - s) / s
        out.append(_mk(
            s, _vec(x=1.0 + r, e=-r, n=su if sn > 0 else 0.0),
            noise="step" if sn > 0 else None, step=i,
        ))
    return out


def _plans_heun(sig, prediction):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if sn == 0.0:
            # Final step is plain Euler to σ=0, which collapses to x0.
            out.append(_mk(s, _vec(e=1.0), step=i))
            continue
        r = (sn - s) / s
        out.append(_mk(
            s, _vec(x=1.0),
            xe_row=_vec(x=1.0 + r, e=-r),          # x_pred
            h1_row=_vec(x=1.0 / s, e=-1.0 / s),    # stash d
            completes=False, step=i,
        ))
        half = 0.5 * (sn - s)
        out.append(_mk(
            sn, _vec(x=1.0, h1=half, xe=half / sn, e=-half / sn), step=i,
        ))
    return out


def _plans_dpm_2(sig, prediction):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if sn == 0.0:
            out.append(_mk(s, _vec(e=1.0), step=i))
            continue
        smid = math.exp(0.5 * (math.log(s) + math.log(sn)))
        rm = (smid - s) / s
        out.append(_mk(s, _vec(x=1.0), xe_row=_vec(x=1.0 + rm, e=-rm),
                       completes=False, step=i))
        d = sn - s
        out.append(_mk(smid, _vec(x=1.0, xe=d / smid, e=-d / smid), step=i))
    return out


def _plans_dpm_2_ancestral(sig, prediction, eta=1.0):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        sd, su = _ancestral(s, sn, eta)
        nz = "step" if sn > 0 else None
        if sd == 0.0:
            r = (sd - s) / s
            out.append(_mk(s, _vec(x=1.0 + r, e=-r, n=su if sn > 0 else 0.0),
                           noise=nz, step=i))
            continue
        smid = math.exp(0.5 * (math.log(s) + math.log(sd)))
        rm = (smid - s) / s
        out.append(_mk(s, _vec(x=1.0), xe_row=_vec(x=1.0 + rm, e=-rm),
                       completes=False, step=i))
        d = sd - s
        out.append(_mk(smid,
                       _vec(x=1.0, xe=d / smid, e=-d / smid,
                            n=su if sn > 0 else 0.0),
                       noise=nz, step=i))
    return out


def _plans_dpmpp_2s_ancestral(sig, prediction, eta=1.0):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if prediction == "flow":
            # sample_dpmpp_2s_ancestral_rf: flow log-SNR midpoint + RF renoise.
            sd = sn * (1.0 + (sn / s - 1.0) * eta)
            if sn == 0.0:
                r = (sd - s) / s
                out.append(_mk(s, _vec(x=1.0 + r, e=-r), step=i))
                continue
            a1, ad = 1.0 - sn, 1.0 - sd
            renoise = math.sqrt(max(sn**2 - sd**2 * a1**2 / ad**2, 0.0))
            if s >= 1.0:
                smid = 0.9999  # λ diverges at σ=1 (host pin)
            else:
                t_i = math.log((1.0 - s) / s)
                t_dn = math.log((1.0 - sd) / sd)
                smid = 1.0 / (math.exp(t_i + 0.5 * (t_dn - t_i)) + 1.0)
            g = a1 / ad
            out.append(_mk(s, _vec(x=1.0),
                           xe_row=_vec(x=smid / s, e=1.0 - smid / s),
                           completes=False, step=i))
            out.append(_mk(smid,
                           _vec(x=g * (sd / s), e=g * (1.0 - sd / s),
                                n=renoise),
                           noise="step", step=i))
            continue
        sd, su = _ancestral(s, sn, eta)
        nz = "step" if sn > 0 else None
        if sd == 0.0:
            r = (sd - s) / s
            out.append(_mk(s, _vec(x=1.0 + r, e=-r, n=su if sn > 0 else 0.0),
                           noise=nz, step=i))
            continue
        t, tn = -math.log(s), -math.log(sd)
        h = tn - t
        smid = math.exp(-(t + 0.5 * h))
        out.append(_mk(s, _vec(x=1.0),
                       xe_row=_vec(x=smid / s, e=-math.expm1(-0.5 * h)),
                       completes=False, step=i))
        out.append(_mk(smid,
                       _vec(x=sd / s, e=-math.expm1(-h),
                            n=su if sn > 0 else 0.0),
                       noise=nz, step=i))
    return out


def _plans_dpmpp_sde(sig, prediction, eta=1.0, r=0.5):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if sn == 0.0:
            rr = (sn - s) / s
            out.append(_mk(s, _vec(x=1.0 + rr, e=-rr), step=i))
            continue
        t, tn = -math.log(s), -math.log(sn)
        h = tn - t
        smid = math.exp(-(t + r * h))
        fac = 1.0 / (2.0 * r)
        sd1, su1 = _ancestral(s, smid, eta)
        td1 = -math.log(max(sd1, 1e-10))
        out.append(_mk(
            s, _vec(x=1.0),
            xe_row=_vec(x=sd1 / s, e=-math.expm1(t - td1), n=su1),
            h1_row=_vec(e=1.0),  # stash x0 for the end-step blend
            completes=False, noise="sde_mid", step=i,
        ))
        sd2, su2 = _ancestral(s, sn, eta)
        td2 = -math.log(max(sd2, 1e-10))
        c = -math.expm1(t - td2)
        out.append(_mk(
            smid, _vec(x=sd2 / s, h1=c * (1.0 - fac), e=c * fac, n=su2),
            noise="sde_end", step=i,
        ))
    return out


def _plans_dpmpp_2m(sig, prediction):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        t, tn = -math.log(s), -math.log(max(sn, 1e-10))
        h = tn - t
        em = -math.expm1(-h)
        if i == 0 or sn == 0.0:
            out.append(_mk(s, _vec(x=sn / s, e=em), h1_row=_vec(e=1.0),
                           step=i))
            continue
        h_last = t - (-math.log(sig[i - 1]))
        rr = h_last / h
        out.append(_mk(
            s,
            _vec(x=sn / s, e=em * (1.0 + 1.0 / (2.0 * rr)),
                 h1=-em / (2.0 * rr)),
            h1_row=_vec(e=1.0), step=i,
        ))
    return out


def _plans_dpmpp_2m_sde(sig, prediction, eta=1.0):
    out = []
    h_last, have = 1.0, False
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if sn == 0.0:
            # Eager final step: x = x0; old_x0 still updated, h_last untouched.
            out.append(_mk(s, _vec(e=1.0), h1_row=_vec(e=1.0), step=i))
            continue
        t, tn = -math.log(s), -math.log(sn)
        h = tn - t
        eta_h = eta * h
        ce = -math.expm1(-h - eta_h)
        row = _vec(x=(sn / s) * math.exp(-eta_h), e=ce)
        if have:
            corr = 0.5 * ce * (h / h_last)
            row = row + _vec(e=corr, h1=-corr)
        if eta > 0:
            row = row + _vec(
                n=sn * math.sqrt(max(-math.expm1(-2.0 * eta_h), 0.0))
            )
        out.append(_mk(s, row, h1_row=_vec(e=1.0),
                       noise="step" if eta > 0 else None, step=i))
        h_last, have = h, True
    return out


def _plans_dpmpp_3m_sde(sig, prediction, eta=1.0):
    out = []
    h_1 = h_2 = None
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if sn == 0.0:
            # Eager: `x = x0; continue` — NO history update on a zero step.
            out.append(_mk(s, _vec(e=1.0), step=i))
            continue
        t, tn = -math.log(s), -math.log(sn)
        h = tn - t
        h_eta = h * (eta + 1.0)
        row = _vec(x=math.exp(-h_eta), e=-math.expm1(-h_eta))
        if h_2 is not None:
            r0, r1 = h_1 / h, h_2 / h
            phi_2 = math.expm1(-h_eta) / h_eta + 1.0
            phi_3 = phi_2 / h_eta - 0.5
            v10 = _vec(e=1.0 / r0, h1=-1.0 / r0)       # d1_0
            v11 = _vec(h1=1.0 / r1, h2=-1.0 / r1)      # d1_1
            d1 = v10 + (v10 - v11) * (r0 / (r0 + r1))
            d2 = (v10 - v11) / (r0 + r1)
            row = row + phi_2 * d1 - phi_3 * d2
        elif h_1 is not None:
            rr = h_1 / h
            phi_2 = math.expm1(-h_eta) / h_eta + 1.0
            row = row + phi_2 * _vec(e=1.0 / rr, h1=-1.0 / rr)
        if eta > 0:
            row = row + _vec(
                n=sn * math.sqrt(max(-math.expm1(-2.0 * eta * h), 0.0))
            )
        out.append(_mk(s, row, h1_row=_vec(e=1.0), h2_row=_vec(h1=1.0),
                       noise="step" if eta > 0 else None, step=i))
        h_1, h_2 = h, h_1
    return out


def _plans_lcm(sig, prediction):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        if sn <= 0.0:
            out.append(_mk(s, _vec(e=1.0), step=i))
        elif prediction == "flow":
            # sample_lcm_rf: flow-interpolant renoise t·n + (1−t)·x0.
            out.append(_mk(s, _vec(e=1.0 - sn, n=sn), noise="step", step=i))
        else:
            out.append(_mk(s, _vec(e=1.0, n=sn), noise="step", step=i))
    return out


def _plans_ddpm(sig, prediction):
    out = []
    for i in range(len(sig) - 1):
        s, sn = sig[i], sig[i + 1]
        acp = 1.0 / (s * s + 1.0)
        acp_prev = 1.0 / (sn * sn + 1.0)
        alpha = acp / acp_prev
        ia = math.sqrt(1.0 / alpha)
        k_eps = (1.0 - alpha) / (s * math.sqrt(1.0 - acp))
        cx = ia * (1.0 / math.sqrt(1.0 + s * s) - k_eps)
        ce = ia * k_eps
        if sn > 0:
            var = (1.0 - alpha) * (1.0 - acp_prev) / (1.0 - acp)
            sc = math.sqrt(1.0 + sn * sn)
            out.append(_mk(s, _vec(x=cx * sc, e=ce * sc,
                                   n=math.sqrt(max(var, 0.0)) * sc),
                           noise="step", step=i))
        else:
            out.append(_mk(s, _vec(x=cx, e=ce), step=i))
    return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LaneStepSpec:
    """One sampler's lane behavior: the plan compiler plus the routing facts
    the scheduler needs (does it consume rng? does dpmpp_sde's two-draw step
    need split keys? is there a rectified-flow form?)."""

    name: str
    compile_plans: Callable[[np.ndarray, str], list]
    needs_rng: bool = False
    split_keys: bool = False
    flow_ok: bool = True


LANE_SPECS: dict[str, LaneStepSpec] = {
    spec.name: spec
    for spec in (
        LaneStepSpec("euler", _plans_euler),
        LaneStepSpec("euler_ancestral", _plans_euler_ancestral,
                     needs_rng=True),
        LaneStepSpec("heun", _plans_heun),
        LaneStepSpec("dpm_2", _plans_dpm_2),
        LaneStepSpec("dpm_2_ancestral", _plans_dpm_2_ancestral,
                     needs_rng=True),
        LaneStepSpec("dpmpp_2s_ancestral", _plans_dpmpp_2s_ancestral,
                     needs_rng=True),
        LaneStepSpec("dpmpp_sde", _plans_dpmpp_sde, needs_rng=True,
                     split_keys=True),
        LaneStepSpec("dpmpp_2m", _plans_dpmpp_2m),
        LaneStepSpec("dpmpp_2m_sde", _plans_dpmpp_2m_sde, needs_rng=True),
        LaneStepSpec("dpmpp_3m_sde", _plans_dpmpp_3m_sde, needs_rng=True),
        LaneStepSpec("lcm", _plans_lcm, needs_rng=True),
        # ddpm's alpha-bar posterior has no flow form (k_samplers.FLOW_REJECT).
        LaneStepSpec("ddpm", _plans_ddpm, needs_rng=True, flow_ok=False),
    )
}


def plan_schedule(sampler: str, sigmas, prediction: str) -> list[StepPlan]:
    """The full eval-ordered plan list for one request's schedule."""
    sig = np.asarray(sigmas, np.float64)
    return LANE_SPECS[sampler].compile_plans(sig, prediction)


def lane_eval_count(sampler: str, sigmas, prediction: str = "eps") -> int:
    """Model evals this lane consumes for the schedule — the acceptance
    criterion's unit: a mixed batch completes in max(lane_eval_count) shared
    dispatches, not the sum."""
    return len(plan_schedule(sampler, sigmas, prediction))
