from .schedules import scaled_linear_schedule, ddim_timesteps
from .ddim import ddim_sample
from .flow import flow_euler_sample, flow_timesteps
from .k_samplers import (
    RNG_SAMPLERS,
    SAMPLERS,
    SCHEDULER_NAMES,
    EpsDenoiser,
    beta_sigmas,
    exponential_sigmas,
    karras_sigmas,
    make_sigmas,
    sampling_sigmas,
    sgm_uniform_sigmas,
    simple_sigmas,
    sample_euler,
    sample_euler_ancestral,
    sample_heun,
    sample_dpmpp_2m,
)

__all__ = [
    "scaled_linear_schedule",
    "ddim_timesteps",
    "ddim_sample",
    "flow_euler_sample",
    "flow_timesteps",
    "SAMPLERS",
    "RNG_SAMPLERS",
    "EpsDenoiser",
    "karras_sigmas",
    "sampling_sigmas",
    "exponential_sigmas",
    "sgm_uniform_sigmas",
    "simple_sigmas",
    "beta_sigmas",
    "make_sigmas",
    "SCHEDULER_NAMES",
    "sample_euler",
    "sample_euler_ancestral",
    "sample_heun",
    "sample_dpmpp_2m",
]
