from .schedules import scaled_linear_schedule, ddim_timesteps
from .ddim import ddim_sample
from .flow import flow_euler_sample, flow_timesteps

__all__ = [
    "scaled_linear_schedule",
    "ddim_timesteps",
    "ddim_sample",
    "flow_euler_sample",
    "flow_timesteps",
]
