from .schedules import scaled_linear_schedule, ddim_timesteps
from .ddim import ddim_sample

__all__ = ["scaled_linear_schedule", "ddim_timesteps", "ddim_sample"]
