"""Stock-ComfyUI node-name compatibility shims.

Workflows exported from a stock ComfyUI install reference the builtin node
class names — ``CheckpointLoaderSimple``, ``CLIPTextEncode``, ``KSampler``,
``VAEDecode``, … — not this package's ``TPU*`` names. The reference node pack
runs *inside* ComfyUI and gets those builtins for free
(any_device_parallel.py:1473-1483 registers only its own nodes); this package
hosts the graph itself (host.py), so builtin-name coverage is part of the
parity surface: with these shims an exported API-format workflow runs
unchanged.

Each shim is a thin adapter over the corresponding ``TPU*`` node: it renames
stock input keys (``latent_image``→``latent``, ``samples``→``latent``,
``pixels``→``image``), resolves bare file names against the ComfyUI directory
layout (``$PA_MODELS_DIR/checkpoints`` etc.), and sniffs what stock nodes
leave implicit (the model family, via ``models.loader.sniff_model_family``).
Custom-sampling nodes (RandomNoise, BasicScheduler, SamplerCustomAdvanced, …)
were already built with stock-matching input names and alias directly.

File resolution env vars (the stand-ins for ComfyUI's folder_paths):

- ``PA_MODELS_DIR``  (default ``models``): ``checkpoints/``, ``clip/``,
  ``vae/``, ``loras/`` subdirs are searched, then the dir itself, then the
  bare name as a path.
- ``PA_INPUT_DIR``   (default ``input``): ``LoadImage`` names.
- ``PA_TOKENIZER_JSON`` / ``PA_CLIP_VOCAB`` + ``PA_CLIP_MERGES``: tokenizer
  tables for CLIP towers extracted from bundled checkpoints (checkpoints
  carry encoder weights but never tokenizer data).
- ``PA_T5_TOKENIZER_JSON``: tokenizer for the T5/UMT5 tower
  (``DualCLIPLoader``).
"""

from __future__ import annotations

import os

CATEGORY = "TPU-ParallelAnything/compat"


def _models_dir() -> str:
    return os.environ.get("PA_MODELS_DIR", "models")


def resolve_model_file(name: str, *subdirs: str) -> str:
    """A stock widget's bare file name → an existing path, searched through
    the ComfyUI folder layout; falls back to the name itself (absolute paths
    and cwd-relative paths keep working)."""
    root = _models_dir()
    for sub in subdirs:
        cand = os.path.join(root, sub, name)
        if os.path.exists(cand):
            return cand
    cand = os.path.join(root, name)
    if os.path.exists(cand):
        return cand
    return name


def _clip_tokenizer(max_len: int = 77, pad_id: int | None = None):
    """CLIP BPE tokenizer from env-configured tables, or None (checkpoints
    bundle encoder weights but never tokenizer data — the error surfaces at
    encode time with instructions, not at load time)."""
    tok_json = os.environ.get("PA_TOKENIZER_JSON", "")
    vocab = os.environ.get("PA_CLIP_VOCAB", "")
    merges = os.environ.get("PA_CLIP_MERGES", "")
    from .utils.tokenizer import CLIPBPETokenizer, load_tokenizer_json

    if tok_json:
        return load_tokenizer_json(tok_json, max_len=max_len)
    if vocab and merges:
        return CLIPBPETokenizer.from_files(
            vocab, merges, max_len=max_len, pad_id=pad_id
        )
    return None


_TOKENIZER_HELP = (
    "checkpoints bundle text-encoder weights but never tokenizer tables; set "
    "PA_TOKENIZER_JSON (a tokenizer.json) or PA_CLIP_VOCAB + PA_CLIP_MERGES "
    "(vocab.json + merges.txt), or wire a TPUCLIPLoader node instead"
)


class CheckpointLoaderSimple:
    """Stock loader: (ckpt_name) → (MODEL, CLIP, VAE). Family is sniffed off
    the checkpoint keys (stock has no family widget); CLIP comes from the
    bundled ``cond_stage_model``/``conditioner`` towers for the SD families
    (SDXL gets the dual L+G wire TPUTextEncode combines)."""

    DESCRIPTION = "Stock-name checkpoint loader (family sniffed, bundled CLIP)."
    RETURN_TYPES = ("MODEL", "CLIP", "VAE")
    RETURN_NAMES = ("model", "clip", "vae")
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"ckpt_name": ("STRING", {"default": ""})}}

    def load(self, ckpt_name: str):
        from .models.loader import peek_safetensors, sniff_model_family
        from .nodes import TPUCheckpointLoader

        path = resolve_model_file(ckpt_name, "checkpoints")
        # Family sniffing needs only key names + two shapes: peek the header
        # instead of materializing a multi-GB file twice (the full read
        # happens once, inside TPUCheckpointLoader).
        family = sniff_model_family(peek_safetensors(path))
        model, vae = TPUCheckpointLoader().load(ckpt_path=path, family=family)
        # Source tag: the LoraLoader shim re-bakes from the original file
        # (LoRA applies to the checkpoint layout pre-conversion). `source`
        # is a plain DiffusionModel field (api.py) — ordinary assignment.
        model.source = {"path": path, "family": family}
        # source_ckpt marks this CLIP wire as rebuildable-from-checkpoint: the
        # LoraLoader shim's strength_clip rebuild must never clobber a wire
        # that came from DualCLIPLoader/TPUCLIPLoader instead.
        clip = {**self._bundled_clip(path, family), "source_ckpt": path}
        return model, clip, vae

    @staticmethod
    def _te_filtered(loras, *prefixes: str):
        """Per-tower text-encoder LoRA sub-stacks: keep only keys under the
        given kohya tower prefixes (te1 = CLIP-L, te2 = OpenCLIP-G) so a
        dual-tower LoRA can never bake its G deltas into the L tower via the
        suffix-match fallback."""
        from .models.loader import load_safetensors

        out = []
        for src, strength in loras or ():
            if strength == 0.0:
                continue
            sd = src if isinstance(src, dict) else load_safetensors(src)
            sub = {k: v for k, v in sd.items() if k.startswith(prefixes)}
            if sub:
                out.append((sub, strength))
        return out

    def _bundled_clip(self, path, family: str, te_loras=None):
        from .models import load_clip_text_checkpoint
        from .models.loader import load_safetensors_subset

        def error_wire(msg: str):
            return {"encoder": None, "tokenizer": None, "type": "error",
                    "tokenizer_error": msg}

        def stamp(ckpt_path, *parts):
            """Content model key for the cross-request embed cache
            (models/embed_cache.py): file identity (path+size+mtime — an
            in-place checkpoint replacement changes the key) + tower tag.
            LoRA-baked towers carry user deltas a file-derived key cannot
            see — they fall back to the cache's per-object lifetime token
            instead (None here)."""
            if te_loras:
                return None
            import hashlib

            from .models.embed_cache import file_stamp

            return hashlib.md5(
                repr((file_stamp(ckpt_path),) + parts).encode()
            ).hexdigest()

        try:
            if family in ("sd15", "sd21", "sd21-v", "sd21-unclip"):
                open_clip = family.startswith("sd21")
                cfg = None
                if open_clip:
                    from .models import open_clip_h_config

                    cfg = open_clip_h_config()
                tower = load_safetensors_subset(path, "cond_stage_model.")
                if not tower:
                    return error_wire(
                        "checkpoint has no bundled cond_stage_model tower; "
                        "wire a TPUCLIPLoader node instead"
                    )
                if te_loras:
                    from .models.convert import bake_lora

                    for sub, s in self._te_filtered(
                        te_loras, "lora_te_", "lora_te1_"
                    ):
                        tower = bake_lora(tower, sub, s)
                enc = load_clip_text_checkpoint(
                    tower, cfg=cfg, open_clip=open_clip
                )
                tok = _clip_tokenizer(
                    max_len=enc.cfg.max_len, pad_id=0 if open_clip else None
                )
                return {
                    "encoder": enc, "tokenizer": tok, "type": "clip",
                    "model_key": stamp(path, family, "cond_stage_model"),
                    "tokenizer_error": None if tok else _TOKENIZER_HELP,
                }
            if family == "sdxl-refiner":
                from .models import open_clip_g_config

                # The refiner bundles ONE tower: OpenCLIP-G under
                # conditioner.embedders.0.model.* (no CLIP-L). A plain
                # G-tower CLIP wire — CLIPTextEncodeSDXLRefiner consumes it
                # directly.
                tower = load_safetensors_subset(path, "conditioner.embedders.0.")
                if not tower:
                    return error_wire(
                        "sdxl-refiner checkpoint has no bundled conditioner "
                        "tower; wire TPUCLIPLoader type=open-clip-g instead"
                    )
                if te_loras:
                    from .models.convert import bake_lora

                    for sub, s in self._te_filtered(te_loras, "lora_te2_",
                                                    "lora_te_"):
                        tower = bake_lora(tower, sub, s)
                enc_g = load_clip_text_checkpoint(
                    tower, cfg=open_clip_g_config(), open_clip=True
                )
                tok_g = _clip_tokenizer(max_len=enc_g.cfg.max_len, pad_id=0)
                return {
                    "encoder": enc_g, "tokenizer": tok_g, "type": "clip",
                    "model_key": stamp(path, family, "conditioner.0"),
                    "tokenizer_error": None if tok_g else _TOKENIZER_HELP,
                }
            if family == "sdxl":
                from .models import open_clip_g_config

                # conditioner.embedders.0 = CLIP-L (HF layout),
                # conditioner.embedders.1 = OpenCLIP-G (resblocks layout).
                towers = load_safetensors_subset(
                    path, "conditioner.embedders.0.", "conditioner.embedders.1."
                )
                sub_l = {k: v for k, v in towers.items()
                         if k.startswith("conditioner.embedders.0.")}
                sub_g = {k: v for k, v in towers.items()
                         if k.startswith("conditioner.embedders.1.")}
                if not sub_l or not sub_g:
                    return error_wire(
                        "sdxl checkpoint has no bundled conditioner towers; "
                        "wire TPUCLIPLoader nodes instead"
                    )
                if te_loras:
                    from .models.convert import bake_lora

                    # kohya dual-tower convention: te1 = CLIP-L, te2 = G.
                    for sub, s in self._te_filtered(
                        te_loras, "lora_te1_", "lora_te_"
                    ):
                        sub_l = bake_lora(sub_l, sub, s)
                    for sub, s in self._te_filtered(te_loras, "lora_te2_"):
                        sub_g = bake_lora(sub_g, sub, s)
                enc_l = load_clip_text_checkpoint(sub_l)
                enc_g = load_clip_text_checkpoint(
                    sub_g, cfg=open_clip_g_config(), open_clip=True
                )
                tok_l = _clip_tokenizer(max_len=enc_l.cfg.max_len)
                tok_g = _clip_tokenizer(max_len=enc_g.cfg.max_len, pad_id=0)
                err = None if (tok_l and tok_g) else _TOKENIZER_HELP
                return {
                    "type": "sdxl-dual",
                    "l": {"encoder": enc_l, "tokenizer": tok_l, "type": "clip",
                          "model_key": stamp(path, family, "embedders.0"),
                          "tokenizer_error": err},
                    "g": {"encoder": enc_g, "tokenizer": tok_g, "type": "clip",
                          "model_key": stamp(path, family, "embedders.1"),
                          "tokenizer_error": err},
                    "tokenizer_error": err,
                }
            return error_wire(
                f"{family} checkpoints do not bundle text encoders; wire "
                "TPUCLIPLoader (or the DualCLIPLoader shim) instead"
            )
        except Exception as e:  # noqa: BLE001 — degrade to an encode-time error
            return error_wire(f"bundled text-encoder extraction failed: {e}")


class DualCLIPLoader:
    """Stock dual loader (FLUX/SD3 workflows): two encoder files → one CLIP
    wire. ``type=flux`` pairs T5-XXL (context) with CLIP-L (pooled)."""

    DESCRIPTION = "Stock-name dual text-encoder loader (flux/sdxl/sd3 pairs)."
    RETURN_TYPES = ("CLIP",)
    RETURN_NAMES = ("clip",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name1": ("STRING", {"default": ""}),
                "clip_name2": ("STRING", {"default": ""}),
                "type": (["flux", "sdxl", "sd3"], {"default": "flux"}),
            }
        }

    def load(self, clip_name1: str, clip_name2: str, type: str = "flux"):
        from .nodes import TPUCLIPLoader

        loader = TPUCLIPLoader()

        def clip_wire(name: str, encoder_type: str):
            path = resolve_model_file(name, "clip", "text_encoders")
            kw = {}
            if encoder_type in ("t5", "umt5"):
                tok_json = os.environ.get("PA_T5_TOKENIZER_JSON", "")
                if not tok_json:
                    raise ValueError(
                        "DualCLIPLoader t5 tower needs PA_T5_TOKENIZER_JSON "
                        "(no vocab/merges form exists for T5 tokenizers)"
                    )
                kw["tokenizer_json"] = tok_json
            else:
                tok_json = os.environ.get("PA_TOKENIZER_JSON", "")
                if tok_json:
                    kw["tokenizer_json"] = tok_json
                else:
                    kw["vocab_path"] = os.environ.get("PA_CLIP_VOCAB", "")
                    kw["merges_path"] = os.environ.get("PA_CLIP_MERGES", "")
            (wire,) = loader.load(path, encoder_type, **kw)
            return wire

        if type == "flux":
            # Stock convention: name1 = t5xxl, name2 = clip_l. A "t5" in
            # either file name corrects swapped wiring; with no match in
            # either, trust the positional convention (a rename like
            # flan_xxl.safetensors must not flip a correctly-ordered graph).
            n1 = os.path.basename(clip_name1).lower()
            n2 = os.path.basename(clip_name2).lower()
            swapped = "t5" not in n1 and "t5" in n2
            t5_name = clip_name2 if swapped else clip_name1
            l_name = clip_name1 if swapped else clip_name2
            return (
                {
                    "type": "flux-dual",
                    "t5": clip_wire(t5_name, "t5"),
                    "l": clip_wire(l_name, "clip-l"),
                    "tokenizer_error": None,
                },
            )
        if type == "sdxl":
            return (
                {
                    "type": "sdxl-dual",
                    "l": clip_wire(clip_name1, "clip-l"),
                    "g": clip_wire(clip_name2, "open-clip-g"),
                    "tokenizer_error": None,
                },
            )
        # type == "sd3": the two-tower form of the SD3 conditioning. Stock
        # detects which two of {clip_l, clip_g, t5xxl} were supplied from the
        # state dicts themselves, so the common clip_l+t5xxl / clip_g+t5xxl
        # pairings load correctly — classify both files (name markers, then
        # safetensors key signature) and leave the absent tower None; the
        # encode path zero-fills it like stock's SD3 CLIP. Files that defy
        # classification fall back to the positional (clip_l, clip_g)
        # convention, one per free CLIP slot.
        kinds = []
        for name in (clip_name1, clip_name2):
            path = resolve_model_file(name, "clip", "text_encoders")
            kinds.append(_classify_text_tower(name, path))
        if kinds[0] is not None and kinds[0] == kinds[1]:
            raise ValueError(
                f"DualCLIPLoader type=sd3 got two {kinds[0]} files "
                f"({clip_name1!r} and {clip_name2!r}); it needs two "
                "DIFFERENT towers of clip_l/clip_g/t5xxl"
            )
        for slot in ("clip-l", "open-clip-g"):
            if slot not in kinds and None in kinds:
                kinds[kinds.index(None)] = slot
        towers = dict(zip(kinds, (clip_name1, clip_name2)))
        wire_of = {
            "clip-l": ("l", "clip-l"),
            "open-clip-g": ("g", "open-clip-g"),
            "t5": ("t5", "t5"),
        }
        out = {"type": "sd3-triple", "l": None, "g": None, "t5": None,
               "tokenizer_error": None}
        for kind, name in towers.items():
            key, encoder_type = wire_of[kind]
            out[key] = clip_wire(name, encoder_type)
        return (out,)


class CLIPLoader:
    """Stock single-tower text-encoder loader: (clip_name, type) → CLIP.
    The ``type`` menu names the model family the tower serves; the tower
    architecture resolves from it (plus a t5-in-filename sniff for the
    families whose templates ship either tower). Tokenizer tables come from
    the PA_* env vars like the DualCLIPLoader shim. Host-provided builtin
    (any_device_parallel.py:1473-1483)."""

    DESCRIPTION = "Stock-name single text-encoder loader."
    RETURN_TYPES = ("CLIP",)
    RETURN_NAMES = ("clip",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    # Stock type menu → tower architecture. Families needing two towers
    # (flux/sdxl dual) still load their single named file here — stock wires
    # two CLIPLoaders or one DualCLIPLoader interchangeably.
    _TYPE_TOWER = {
        "stable_diffusion": "clip-l",
        "sdxl": "clip-l",
        "sd3": "clip-l",
        "flux": "clip-l",
        "stable_cascade": "clip-l",
        "wan": "umt5",
        "ltxv": "t5",
        "pixart": "t5",
        "cosmos": "t5",
        "lumina2": "t5",
        "hunyuan_video": "clip-l",
    }

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name": ("STRING", {"default": ""}),
                "type": (sorted(cls._TYPE_TOWER),
                         {"default": "stable_diffusion"}),
            },
            "optional": {
                "device": (["default", "cpu"], {"default": "default"}),
            },
        }

    def load(self, clip_name: str, type: str = "stable_diffusion",
             device: str = "default"):
        from .nodes import TPUCLIPLoader

        tower = self._TYPE_TOWER.get(type)
        if tower is None:
            raise ValueError(
                f"CLIPLoader type {type!r} is not supported — one of "
                f"{sorted(self._TYPE_TOWER)}"
            )
        name = os.path.basename(clip_name).lower()
        if "umt5" in name:
            tower = "umt5"
        elif "t5" in name:
            tower = "t5" if tower not in ("umt5",) else tower
        path = resolve_model_file(clip_name, "clip", "text_encoders")
        kw = {}
        if tower in ("t5", "umt5"):
            tok_json = os.environ.get("PA_T5_TOKENIZER_JSON", "")
            if not tok_json:
                raise ValueError(
                    f"CLIPLoader type={type!r} loads a T5-family tower and "
                    "needs PA_T5_TOKENIZER_JSON (no vocab/merges form exists)"
                )
            kw["tokenizer_json"] = tok_json
            # Stock T5 token budgets: WAN tokenizes umt5 at 512, the other
            # t5-served families at 256 — the CLIP default of 77 would
            # silently truncate typical video prompts.
            kw["max_len"] = 512 if type == "wan" else 256
        else:
            tok_json = os.environ.get("PA_TOKENIZER_JSON", "")
            if tok_json:
                kw["tokenizer_json"] = tok_json
            else:
                kw["vocab_path"] = os.environ.get("PA_CLIP_VOCAB", "")
                kw["merges_path"] = os.environ.get("PA_CLIP_MERGES", "")
        (wire,) = TPUCLIPLoader().load(path, tower, **kw)
        return (wire,)


def _classify_text_tower(name: str, path: str | None = None) -> str | None:
    """Which tower a text-encoder file holds: ``t5`` / ``open-clip-g`` /
    ``clip-l``. Filename markers first (the stock SD3 template ships
    clip_l/clip_g/t5xxl); unresolved names fall back to the safetensors key
    signature (header-only — no tensor reads except one embedding shape)."""
    n = os.path.basename(name).lower()
    if "t5" in n:
        return "t5"
    if "clip_g" in n or "clipg" in n:
        return "open-clip-g"
    if "clip_l" in n or "clipl" in n:
        return "clip-l"
    if not path or not os.path.isfile(path):
        return None
    try:
        from safetensors import safe_open

        with safe_open(path, framework="numpy") as f:
            keys = set(f.keys())
            if any(k.startswith("encoder.block.") for k in keys) \
                    or "shared.weight" in keys:
                return "t5"
            # open-clip layout: top-level token_embedding + text_projection.
            if "token_embedding.weight" in keys:
                return "open-clip-g"
            for k in keys:
                if k.endswith("token_embedding.weight"):
                    width = f.get_slice(k).get_shape()[1]
                    return "open-clip-g" if width >= 1024 else "clip-l"
    except Exception:
        return None
    return None


class TripleCLIPLoader:
    """Stock triple text-encoder loader (the SD3/SD3.5 templates): clip_l +
    clip_g + t5xxl files → ONE CLIP wire carrying all three towers. Encoding
    that wire assembles SD3's (context, y) — L⊕G penultimate streams padded
    to 4096 and sequence-concatenated with the T5 stream, y = pooled L⊕G
    (``models.text_encoders.sd3_text_conditioning``). Files are matched to
    towers by name markers, then by key signature — stock's widget order
    carries no typed meaning. Host-provided builtin
    (any_device_parallel.py:1473-1483)."""

    DESCRIPTION = "Stock-name triple text-encoder loader (SD3: L + G + T5)."
    RETURN_TYPES = ("CLIP",)
    RETURN_NAMES = ("clip",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name1": ("STRING", {"default": ""}),
                "clip_name2": ("STRING", {"default": ""}),
                "clip_name3": ("STRING", {"default": ""}),
            }
        }

    def load(self, clip_name1: str, clip_name2: str, clip_name3: str):
        from .nodes import TPUCLIPLoader

        names = [clip_name1, clip_name2, clip_name3]
        paths = [resolve_model_file(n, "clip", "text_encoders") for n in names]
        towers: dict[str, str] = {}
        for name, path in zip(names, paths):
            kind = _classify_text_tower(name, path)
            if kind is None:
                raise ValueError(
                    f"TripleCLIPLoader cannot tell which tower {name!r} holds "
                    "— name it with a clip_l/clip_g/t5 marker"
                )
            if kind in towers:
                raise ValueError(
                    f"TripleCLIPLoader got two {kind} files ({towers[kind]!r} "
                    f"and {name!r}); it needs one each of clip_l/clip_g/t5"
                )
            towers[kind] = path
        missing = {"clip-l", "open-clip-g", "t5"} - set(towers)
        if missing:
            raise ValueError(
                f"TripleCLIPLoader is missing {sorted(missing)} towers "
                f"(classified: { {k: os.path.basename(v) for k, v in towers.items()} })"
            )

        loader = TPUCLIPLoader()

        def clip_wire(path: str, encoder_type: str):
            kw = {}
            if encoder_type == "t5":
                tok_json = os.environ.get("PA_T5_TOKENIZER_JSON", "")
                if not tok_json:
                    raise ValueError(
                        "TripleCLIPLoader t5 tower needs PA_T5_TOKENIZER_JSON "
                        "(no vocab/merges form exists for T5 tokenizers)"
                    )
                kw["tokenizer_json"] = tok_json
                # Stock SD3 tokenizes T5 at 77 tokens to match the CLIP
                # streams' sequence budget — the default already fits.
            else:
                tok_json = os.environ.get("PA_TOKENIZER_JSON", "")
                if tok_json:
                    kw["tokenizer_json"] = tok_json
                else:
                    kw["vocab_path"] = os.environ.get("PA_CLIP_VOCAB", "")
                    kw["merges_path"] = os.environ.get("PA_CLIP_MERGES", "")
            (wire,) = loader.load(path, encoder_type, **kw)
            return wire

        return (
            {
                "type": "sd3-triple",
                "l": clip_wire(towers["clip-l"], "clip-l"),
                "g": clip_wire(towers["open-clip-g"], "open-clip-g"),
                "t5": clip_wire(towers["t5"], "t5"),
                "tokenizer_error": None,
            },
        )


class VAELoader:
    """Stock external-VAE loader: (vae_name) → VAE. Resolves through
    $PA_MODELS_DIR/vae; the file's key layout picks the family — WAN's causal
    3D video VAE (``encoder.downsamples``/``decoder.upsamples`` flat
    Sequentials) vs the AutoencoderKL image families (sniffed by
    sniff_vae_config: latent width, SDXL scaling). Host-provided builtin
    (any_device_parallel.py:1473-1483)."""

    DESCRIPTION = "Stock-name external VAE loader (image + WAN video layouts)."
    RETURN_TYPES = ("VAE",)
    RETURN_NAMES = ("vae",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"vae_name": ("STRING", {"default": ""})}}

    def load(self, vae_name: str):
        from .models.loader import (
            load_vae_checkpoint,
            load_wan_vae_checkpoint,
            peek_safetensors,
        )

        path = resolve_model_file(vae_name, "vae")
        if not os.path.isfile(path):
            raise ValueError(
                f"VAE file not found: {vae_name!r} (searched "
                "$PA_MODELS_DIR/vae and the name as a path)"
            )
        keys = peek_safetensors(path)
        if any("decoder.upsamples." in k for k in keys):
            return (load_wan_vae_checkpoint(path),)
        return (load_vae_checkpoint(path),)


class UNETLoader:
    """Stock diffusion-model-only loader (FLUX/WAN templates): (unet_name,
    weight_dtype) → MODEL. Family is sniffed off the keys like
    CheckpointLoaderSimple; ``weight_dtype`` is accepted for workflow
    compatibility but ignored — the load path's dtype policy (bf16 compute,
    fp8 upcast-on-load, mirroring the reference's fp8 handling at
    any_device_parallel.py:93-124) already covers every menu entry."""

    DESCRIPTION = "Stock-name bare diffusion-model loader (family sniffed)."
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "load_unet"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "unet_name": ("STRING", {"default": ""}),
                "weight_dtype": (
                    ["default", "fp8_e4m3fn", "fp8_e4m3fn_fast", "fp8_e5m2"],
                    {"default": "default"},
                ),
            }
        }

    def load_unet(self, unet_name: str, weight_dtype: str = "default"):
        from .models.loader import peek_safetensors, sniff_model_family
        from .nodes import TPUCheckpointLoader

        path = resolve_model_file(
            unet_name, "diffusion_models", "unet", "checkpoints"
        )
        family = sniff_model_family(peek_safetensors(path))
        model, _ = TPUCheckpointLoader().load(
            ckpt_path=path, family=family, load_vae=False
        )
        # Same source tag CheckpointLoaderSimple leaves: the LoraLoader shims
        # re-bake from the original file.
        model.source = {"path": path, "family": family}
        return (model,)


class unCLIPConditioning:  # noqa: N801 — stock node name
    """Stock unCLIP node: tags the conditioning with the CLIP image embeds +
    noise-augmentation level; the sampler assembles the model's adm vector
    from the tags (models/unet.unclip_adm — host SD21UNCLIP.encode_adm
    semantics: q_sample augmentation, level embedding, strength weighting,
    multi-tag merge). Chained nodes stack tags. Host-provided builtin
    (any_device_parallel.py:1473-1483)."""

    DESCRIPTION = "Stock-name unCLIP image conditioning (SD2.x-unCLIP)."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "apply_adm"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING", {}),
                "clip_vision_output": ("CLIP_VISION_OUTPUT", {}),
                "strength": ("FLOAT", {"default": 1.0, "min": -10.0,
                                       "max": 10.0, "step": 0.01}),
                "noise_augmentation": ("FLOAT", {"default": 0.0, "min": 0.0,
                                                 "max": 1.0, "step": 0.01}),
            }
        }

    def apply_adm(self, conditioning, clip_vision_output, strength: float,
                  noise_augmentation: float):
        tag = {
            "embeds": clip_vision_output["image_embeds"],
            "strength": float(strength),
            "noise_augmentation": float(noise_augmentation),
        }
        return (
            {
                **conditioning,
                "unclip": tuple(conditioning.get("unclip", ())) + (tag,),
            },
        )


class LoraLoader:
    """Stock LoRA node: (MODEL, CLIP, lora_name, strengths) → patched
    (MODEL, CLIP). LoRA bakes into the checkpoint layout BEFORE conversion
    (models/convert.bake_lora — the reference's patches-then-load order,
    any_device_parallel.py:971-1004), so this shim re-loads the tagged source
    checkpoint with the LoRA applied. Chained LoraLoaders STACK: each link
    appends to the accumulated ``(path, strength)`` list carried on the source
    tag and the whole stack re-bakes in chain order. ``strength_clip`` bakes
    the LoRA's text-encoder deltas (kohya ``lora_te*`` keys) into the bundled
    CLIP towers the same way — the returned CLIP wire is rebuilt from the
    source checkpoint when the LoRA carries te keys and strength_clip ≠ 0."""

    DESCRIPTION = "Stock-name LoRA loader (re-bakes from the source checkpoint)."
    RETURN_TYPES = ("MODEL", "CLIP")
    RETURN_NAMES = ("model", "clip")
    FUNCTION = "load_lora"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL", {}),
                "clip": ("CLIP", {}),
                "lora_name": ("STRING", {"default": ""}),
                "strength_model": (
                    "FLOAT", {"default": 1.0, "min": -4.0, "max": 4.0}
                ),
                "strength_clip": (
                    "FLOAT", {"default": 1.0, "min": -4.0, "max": 4.0}
                ),
            }
        }

    def load_lora(self, model, clip, lora_name: str,
                  strength_model: float = 1.0, strength_clip: float = 1.0):
        from .nodes import TPUCheckpointLoader

        source = getattr(model, "source", None)
        if source is not None and source.get("merged"):
            raise ValueError(
                "LoRA-after-merge is not supported: LoRA baking re-converts "
                "from the source checkpoint file, and a merged model has "
                "none — apply LoraLoader to each input model BEFORE "
                "ModelMergeSimple instead"
            )
        if source is None or not source.get("path"):
            raise ValueError(
                "LoraLoader needs a MODEL from CheckpointLoaderSimple (the "
                "source-checkpoint tag); for TPUCheckpointLoader models pass "
                "lora_path on the loader itself"
            )
        lora = resolve_model_file(lora_name, "loras")
        # An empty/missing name must not silently return an unpatched model
        # (TPUCheckpointLoader treats lora_path="" as no-LoRA).
        if not lora_name or not os.path.isfile(lora):
            raise ValueError(
                f"LoRA file not found: {lora_name!r} (searched "
                f"$PA_MODELS_DIR/loras and the name as a path)"
            )
        model_stack = list(source.get("loras", ())) + [(lora, strength_model)]
        patched, _ = TPUCheckpointLoader().load(
            ckpt_path=source["path"], family=source["family"],
            lora_path=model_stack,
            load_vae=False,  # re-bake only needs the diffusion model
        )
        clip_stack = list(source.get("te_loras", ())) + [(lora, strength_clip)]
        patched.source = {**source, "loras": model_stack,
                          "te_loras": clip_stack}
        patched.lora_delegate = self._lane_delegate(model, patched)
        clip = self._maybe_rebake_clip(clip, source, clip_stack)
        return patched, clip

    @staticmethod
    def _lane_delegate(model, patched):
        """The serving-tier twin of this bake: ``{"base", "factors"}`` when
        the whole bake recovers as exact low-rank factors against the
        unpatched base (models/lora.factorize_bake — SVD of the per-leaf
        delta, which works on the CONVERTED layout's head-split/renamed
        leaves where checkpoint-keyed extraction cannot). The continuous-
        batching scheduler then buckets LoRA prompts on the base model and
        carries the factors as per-lane state (one shared program for any
        LoRA mix), while inline legs keep the bake. None (= bake only)
        whenever any delta is unrepresentable — a partial factor map would
        make the served result diverge from the bake. Chained links resolve
        against the base-most model, so a LoRA stack is still ONE delegate."""
        from .models.lora import factorize_bake

        base = (getattr(model, "lora_delegate", None) or {}).get("base", model)
        if not isinstance(getattr(base, "params", None), dict) \
                or not isinstance(getattr(patched, "params", None), dict):
            return None
        factors = factorize_bake(base.params, patched.params)
        return {"base": base, "factors": factors} if factors else None

    @staticmethod
    def _maybe_rebake_clip(clip, source: dict, clip_stack: list):
        """Rebuild the CLIP wire with text-encoder LoRA deltas baked — only
        when there is anything to bake (te keys present at nonzero clip
        strength, checked from safetensors HEADERS before any tensor data is
        read) and only for wires that actually came from this checkpoint's
        bundled towers (``source_ckpt`` tag): an externally-loaded CLIP
        (DualCLIPLoader) must never be clobbered by a rebuild."""
        from .models.loader import load_safetensors, peek_safetensors
        from .utils.logging import get_logger

        te_prefixes = ("lora_te_", "lora_te1_", "lora_te2_")
        active = [
            (p, s) for p, s in clip_stack
            if s != 0.0 and any(
                k.startswith(te_prefixes) for k in peek_safetensors(p)
            )
        ]
        if not active:
            return clip
        if not isinstance(clip, dict) or clip.get("source_ckpt") != source["path"]:
            get_logger().warning(
                "LoraLoader strength_clip: the CLIP wire did not come from "
                "this checkpoint's bundled towers (DualCLIPLoader/TPUCLIPLoader"
                ") — text-encoder LoRA deltas are NOT baked; bake them into "
                "the encoder files offline if needed"
            )
            return clip
        # Each active file loads ONCE per link; _bundled_clip's per-tower
        # passes reuse the in-memory dicts (the source tag keeps paths, not
        # multi-MB state dicts).
        loaded = [(load_safetensors(p), s) for p, s in active]
        rebuilt = CheckpointLoaderSimple()._bundled_clip(
            source["path"], source["family"], te_loras=loaded
        )
        # Preserve wire state the chain added upstream (CLIPSetLastLayer's
        # clip_skip tag, source_ckpt itself, etc.): stock patches the incoming
        # clip object, so everything but the freshly-baked encoder fields must
        # survive.
        extra_state = {
            k: v for k, v in clip.items()
            if k not in rebuilt and k not in ("encoder", "tokenizer")
        }
        return {**rebuilt, **extra_state}


class LoraLoaderModelOnly:
    """Stock model-only LoRA link (the stock FLUX LoRA templates): same
    re-bake-from-source semantics as LoraLoader (the reference's
    bake-before-replicate order, any_device_parallel.py:971-1004) with no
    CLIP wire — ``strength_clip`` is fixed at 0 so the text towers are
    untouched."""

    DESCRIPTION = "Stock-name model-only LoRA loader."
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "load_lora_model_only"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL", {}),
                "lora_name": ("STRING", {"default": ""}),
                "strength_model": (
                    "FLOAT", {"default": 1.0, "min": -4.0, "max": 4.0}
                ),
            }
        }

    def load_lora_model_only(self, model, lora_name: str,
                             strength_model: float = 1.0):
        patched, _ = LoraLoader().load_lora(
            model, None, lora_name, strength_model, strength_clip=0.0
        )
        return (patched,)


class CLIPSetLastLayer:
    """Stock clip-skip node: tags the CLIP wire; TPUTextEncode honors the tag
    when its own clip_skip widget is 0 (host stop_at_clip_layer semantics:
    -1 = final layer, -2 = penultimate)."""

    DESCRIPTION = "Stock-name clip-skip (tags the CLIP wire)."
    RETURN_TYPES = ("CLIP",)
    RETURN_NAMES = ("clip",)
    FUNCTION = "set_last_layer"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP", {}),
                "stop_at_clip_layer": ("INT", {"default": -1, "min": -24, "max": -1}),
            }
        }

    def set_last_layer(self, clip, stop_at_clip_layer: int):
        if stop_at_clip_layer not in (-1, -2):
            raise ValueError(
                "only stop_at_clip_layer -1 (final) or -2 (penultimate) is "
                f"supported, got {stop_at_clip_layer}"
            )
        return ({**clip, "clip_skip": -stop_at_clip_layer},)


def _renamed(tpu_cls, rename: dict[str, str], *, name: str):
    """Adapter class factory: stock input keys → TPU node keys."""

    class Shim:
        DESCRIPTION = f"Stock-name alias of {tpu_cls.__name__}."
        RETURN_TYPES = tpu_cls.RETURN_TYPES
        RETURN_NAMES = getattr(tpu_cls, "RETURN_NAMES", None)
        FUNCTION = "run"
        CATEGORY = CATEGORY

        @classmethod
        def INPUT_TYPES(cls):
            spec = tpu_cls.INPUT_TYPES()
            back = {v: k for k, v in rename.items()}
            return {
                section: {back.get(k, k): v for k, v in entries.items()}
                for section, entries in spec.items()
            }

        def run(self, **kwargs):
            mapped = {rename.get(k, k): v for k, v in kwargs.items()}
            inner = tpu_cls()
            return getattr(inner, tpu_cls.FUNCTION)(**mapped)

    Shim.__name__ = Shim.__qualname__ = name
    return Shim


class LoadImage:
    """Stock image loader: names resolve against ``$PA_INPUT_DIR``."""

    DESCRIPTION = "Stock-name alias of TPULoadImage (input-dir resolution)."
    FUNCTION = "run"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("STRING", {"default": ""})}}

    def run(self, image: str):
        from .nodes import TPULoadImage

        base = os.environ.get("PA_INPUT_DIR", "input")
        cand = os.path.join(base, image)
        return TPULoadImage().load(cand if os.path.exists(cand) else image)

    # RETURN_TYPES mirror the TPU node (set below to avoid import cycles).


class LatentUpscale:
    """Stock latent upscale takes absolute target pixel dims; the TPU node
    takes scale factors — computed here from the wired latent at runtime,
    height and width independently. ``crop`` is accepted and ignored
    (center-crop after resize is a stock nicety, not a parity requirement —
    documented divergence)."""

    DESCRIPTION = "Stock-name latent upscale (absolute dims → scale factor)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "upscale"
    CATEGORY = CATEGORY

    _METHODS = {
        "nearest-exact": "nearest", "nearest": "nearest",
        "bilinear": "bilinear", "area": "bilinear",
        "bicubic": "bicubic", "bislerp": "bicubic",
    }

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT", {}),
                "upscale_method": (list(cls._METHODS), {"default": "bilinear"}),
                "width": ("INT", {"default": 1024, "min": 16, "max": 16384}),
                "height": ("INT", {"default": 1024, "min": 16, "max": 16384}),
            },
            "optional": {"crop": ("STRING", {"default": "disabled"})},
        }

    def upscale(self, samples, upscale_method: str, width: int, height: int,
                crop: str = "disabled"):
        from .nodes import TPULatentUpscale

        z = samples["samples"]
        h, w = z.shape[-3], z.shape[-2]
        # Stock dims are pixel-space; latents are 8x smaller. Height and
        # width scale independently (aspect-changing upscales resize exactly
        # to the stock target).
        scale_h = max(height // 8, 2) / h
        scale_w = max(width // 8, 2) / w
        method = self._METHODS.get(upscale_method, "bilinear")
        return TPULatentUpscale().upscale(
            samples, scale_h, method, scale_w=scale_w
        )


class _EmptyLatent16ch:
    """Stock EmptySD3LatentImage: 16-channel latents (SD3/FLUX), no channel
    widget."""

    DESCRIPTION = "Stock-name 16-channel empty latent (SD3/FLUX)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "generate"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 1024, "min": 16, "max": 16384}),
                "height": ("INT", {"default": 1024, "min": 16, "max": 16384}),
                "batch_size": ("INT", {"default": 1, "min": 1, "max": 4096}),
            }
        }

    def generate(self, width: int, height: int, batch_size: int = 1):
        from .nodes import TPUEmptyLatent

        return TPUEmptyLatent().generate(
            width=width, height=height, batch_size=batch_size, channels=16
        )


class UpscaleModelLoader:
    """Stock loader: model_name resolves via $PA_MODELS_DIR/upscale_models."""

    DESCRIPTION = "Stock-name upscale-model loader (folder-layout resolution)."
    RETURN_TYPES = ("UPSCALE_MODEL",)
    RETURN_NAMES = ("upscale_model",)
    FUNCTION = "load_model"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"model_name": ("STRING", {"default": ""})}}

    def load_model(self, model_name: str):
        from .nodes import TPUUpscaleModelLoader

        path = resolve_model_file(model_name, "upscale_models")
        if not model_name or not os.path.isfile(path):
            raise ValueError(
                f"upscale model not found: {model_name!r} (searched "
                "$PA_MODELS_DIR/upscale_models and the name as a path)"
            )
        return TPUUpscaleModelLoader().load(ckpt_path=path)


class CLIPVisionLoader:
    """Stock loader: clip_name resolves via $PA_MODELS_DIR/clip_vision; the
    tower (ViT-L/H/bigG) is sniffed off the HF-layout checkpoint
    (models/vision.py)."""

    DESCRIPTION = "Stock-name CLIP vision loader (tower sniffed)."
    RETURN_TYPES = ("CLIP_VISION",)
    RETURN_NAMES = ("clip_vision",)
    FUNCTION = "load_clip"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"clip_name": ("STRING", {"default": ""})}}

    def load_clip(self, clip_name: str):
        from .models.vision import load_clip_vision_checkpoint

        path = resolve_model_file(clip_name, "clip_vision")
        if not clip_name or not os.path.isfile(path):
            raise ValueError(
                f"CLIP vision model not found: {clip_name!r} (searched "
                "$PA_MODELS_DIR/clip_vision and the name as a path)"
            )
        return ({"model": load_clip_vision_checkpoint(path)},)


class CLIPVisionEncode:
    """Stock encode: IMAGE → CLIP_VISION_OUTPUT (projected image_embeds, RAW
    last_hidden — post_layernorm applies only to the pooled CLS, the HF
    convention — and the raw penultimate hidden states). Preprocessing is the
    host's clip_preprocess (bicubic short-side resize + center crop + CLIP
    normalization); ``crop`` "none" squashes to the square instead."""

    DESCRIPTION = "Stock-name CLIP vision encode."
    RETURN_TYPES = ("CLIP_VISION_OUTPUT",)
    RETURN_NAMES = ("clip_vision_output",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_vision": ("CLIP_VISION", {}),
                "image": ("IMAGE", {}),
            },
            "optional": {
                "crop": (["center", "none"], {"default": "center"}),
            },
        }

    def encode(self, clip_vision, image, crop: str = "center"):
        from .models.vision import clip_preprocess

        model = clip_vision["model"]
        px = clip_preprocess(
            image, size=model.cfg.image_size, crop=(crop != "none")
        )
        embeds, last, penultimate = model(px)
        return ({
            "image_embeds": embeds,
            "last_hidden": last,
            "penultimate": penultimate,
        },)


class WanImageToVideo:
    """Stock WAN i2v entry node: allocates the empty video latent and tags
    BOTH conditionings with the i2v conditioning the sampler composes into
    the model (nodes._model_with_control → models.wan.apply_i2v_conditioning):
    a 4-channel latent frame mask ‖ the VAE-encoded start frames
    (channel-concat, the WAN2.2 contract) plus, when ``clip_vision_output``
    is wired, the CLIP-vision penultimate states for WAN2.1-style
    checkpoints' img_emb branch. The stock node's
    concat_latent_image/concat_mask/clip_vision_output conditioning keys
    collapse into the single ``i2v`` tag here. Host-provided builtin
    (any_device_parallel.py:1473-1483 registers only the pack's own nodes)."""

    DESCRIPTION = "Stock-name WAN image→video conditioning + empty latent."
    RETURN_TYPES = ("CONDITIONING", "CONDITIONING", "LATENT")
    RETURN_NAMES = ("positive", "negative", "latent")
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "positive": ("CONDITIONING", {}),
                "negative": ("CONDITIONING", {}),
                "vae": ("VAE", {}),
                "width": ("INT", {"default": 832, "min": 16, "max": 8192,
                                  "step": 16}),
                "height": ("INT", {"default": 480, "min": 16, "max": 8192,
                                   "step": 16}),
                "length": ("INT", {"default": 81, "min": 1, "max": 1024,
                                   "step": 4}),
                "batch_size": ("INT", {"default": 1, "min": 1, "max": 16}),
            },
            "optional": {
                "clip_vision_output": ("CLIP_VISION_OUTPUT", {}),
                "start_image": ("IMAGE", {}),
            },
        }

    def encode(self, positive, negative, vae, width: int, height: int,
               length: int, batch_size: int, start_image=None,
               clip_vision_output=None):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from .models.vae import images_to_vae_input

        t_lat = vae.cfg.latent_frames(length)  # validates the 4k+1 schedule
        f = vae.spatial_factor
        zc = vae.cfg.z_channels
        latent = {
            "samples": jnp.zeros(
                (batch_size, t_lat, height // f, width // f, zc)
            )
        }
        tag: dict = {}
        if start_image is not None:
            img = jnp.asarray(start_image)
            if img.ndim == 3:
                img = img[None]
            F = min(img.shape[0], length)
            img = img[:F]
            if img.shape[1:3] != (height, width):
                img = jax.image.resize(
                    img, (F, height, width, img.shape[-1]), method="bilinear"
                )
            clip = jnp.concatenate(
                [
                    images_to_vae_input(img)[None],  # frames of ONE clip
                    jnp.zeros((1, length - F, height, width, img.shape[-1])),
                ],
                axis=1,
            )
            cond_latent = vae.encode(clip)
            h, w = cond_latent.shape[2], cond_latent.shape[3]
            # Frame mask: channel c of latent frame j marks the pixel frame it
            # folds — frame 0 fills all 4 channels of latent frame 0 (the
            # causal VAE's lone first frame, repeated like stock's msk
            # repeat), latent frame j≥1 channel c folds pixel 4(j-1)+1+c.
            mask = np.zeros((1, t_lat, h, w, 4), np.float32)
            for j in range(t_lat):
                for c in range(4):
                    pix = 0 if j == 0 else 4 * (j - 1) + 1 + c
                    if pix < F:
                        mask[:, j, :, :, c] = 1.0
            tag["cond"] = jnp.concatenate(
                [jnp.asarray(mask), cond_latent], axis=-1
            )
        if clip_vision_output is not None:
            tag["clip_fea"] = clip_vision_output["penultimate"]
        if tag:
            positive = {**positive, "i2v": tag}
            negative = {**negative, "i2v": tag}
        return positive, negative, latent


class ControlNetLoader:
    """Stock loader: control_net_name resolves via $PA_MODELS_DIR/controlnet."""

    DESCRIPTION = "Stock-name ControlNet loader (folder-layout resolution)."
    RETURN_TYPES = ("CONTROL_NET",)
    RETURN_NAMES = ("control_net",)
    FUNCTION = "load_controlnet"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"control_net_name": ("STRING", {"default": ""})}}

    def load_controlnet(self, control_net_name: str):
        from .nodes import TPUControlNetLoader

        path = resolve_model_file(control_net_name, "controlnet")
        if not control_net_name or not os.path.isfile(path):
            raise ValueError(
                f"ControlNet file not found: {control_net_name!r} (searched "
                "$PA_MODELS_DIR/controlnet and the name as a path)"
            )
        return TPUControlNetLoader().load(ckpt_path=path)


class ControlNetApply:
    """Stock apply: (conditioning, control_net, image, strength). The control
    trunk composes into the MODEL at sampling (one jit program), conditioning
    cond AND uncond calls — the host's semantics."""

    DESCRIPTION = "Stock-name ControlNet apply."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "apply_controlnet"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING", {}),
                "control_net": ("CONTROL_NET", {}),
                "image": ("IMAGE", {}),
                "strength": ("FLOAT", {"default": 1.0, "min": 0.0,
                                       "max": 10.0, "step": 0.01}),
            }
        }

    def apply_controlnet(self, conditioning, control_net, image,
                         strength: float = 1.0):
        from .nodes import TPUControlNetApply

        return TPUControlNetApply().apply(
            conditioning, control_net, image, strength
        )


class ControlNetApplyAdvanced:
    """Stock advanced apply: (positive, negative, control_net, image,
    strength, start_percent, end_percent) → (positive, negative). The control
    tag rides the positive; because the sampler composes control into the
    MODEL itself, the negative's calls are conditioned identically (stock
    applies the same control to both — same net effect, one tag)."""

    DESCRIPTION = "Stock-name ControlNet apply (strength window)."
    RETURN_TYPES = ("CONDITIONING", "CONDITIONING")
    RETURN_NAMES = ("positive", "negative")
    FUNCTION = "apply_controlnet"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "positive": ("CONDITIONING", {}),
                "negative": ("CONDITIONING", {}),
                "control_net": ("CONTROL_NET", {}),
                "image": ("IMAGE", {}),
                "strength": ("FLOAT", {"default": 1.0, "min": 0.0,
                                       "max": 10.0, "step": 0.01}),
                "start_percent": ("FLOAT", {"default": 0.0, "min": 0.0,
                                            "max": 1.0, "step": 0.001}),
                "end_percent": ("FLOAT", {"default": 1.0, "min": 0.0,
                                          "max": 1.0, "step": 0.001}),
            }
        }

    def apply_controlnet(self, positive, negative, control_net, image,
                         strength: float = 1.0, start_percent: float = 0.0,
                         end_percent: float = 1.0):
        from .nodes import TPUControlNetApply

        (tagged,) = TPUControlNetApply().apply(
            positive, control_net, image, strength,
            start_percent=start_percent, end_percent=end_percent,
        )
        return tagged, negative


def _tag_all_entries(conditioning: dict, tag: dict) -> dict:
    """Apply ``tag`` to the primary cond AND every combined extra — stock
    conditioning_set_values maps over every list entry (the one convention
    all the conditioning shims share)."""
    out = {**conditioning, **tag}
    if conditioning.get("extras"):
        out["extras"] = tuple({**e, **tag} for e in conditioning["extras"])
    return out


def _repeat_to_batch(a, batch: int):
    """Stock repeat_to_batch_size: cycle (tile) then truncate, so any source
    batch composites onto any destination batch (larger, smaller, or
    non-divisor alike)."""
    import jax.numpy as jnp

    if a.shape[0] == batch:
        return a
    reps = -(-batch // a.shape[0])
    return jnp.tile(a, (reps,) + (1,) * (a.ndim - 1))[:batch]


class ImageCompositeMasked:
    """Stock masked paste: source composites over destination at (x, y),
    optionally through a mask (1 = take source) — the standard inpaint
    post-step that pastes the regenerated region back into the original."""

    DESCRIPTION = "Stock-name masked image composite."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "composite"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "destination": ("IMAGE", {}),
                "source": ("IMAGE", {}),
                "x": ("INT", {"default": 0, "min": 0, "max": 16384}),
                "y": ("INT", {"default": 0, "min": 0, "max": 16384}),
                "resize_source": ("BOOLEAN", {"default": False}),
            },
            "optional": {"mask": ("MASK", {})},
        }

    def composite(self, destination, source, x: int, y: int,
                  resize_source: bool = False, mask=None):
        import jax
        import jax.numpy as jnp

        dst = jnp.asarray(destination)
        src = jnp.asarray(source)
        if dst.ndim == 3:
            dst = dst[None]
        if src.ndim == 3:
            src = src[None]
        B, H, W, C = dst.shape
        if resize_source:
            src = jax.image.resize(
                src, (src.shape[0], H, W, C), method="bilinear"
            )
        src = _repeat_to_batch(src, B)
        # Mask normalizes to the FULL source size first, THEN crops with the
        # paste window (stock composite order — squishing the whole mask down
        # to the clipped size would blend edge values instead of cropping).
        if mask is None:
            m_full = jnp.ones((1, *src.shape[1:3], 1), jnp.float32)
        else:
            from .models.vae import normalize_mask

            # Cycle the mask batch to the destination batch like stock's
            # repeat_to_batch_size treatment of source/mask — a mask batch
            # matching neither 1 nor B must not surface as an XLA broadcast
            # error.
            m_full = _repeat_to_batch(normalize_mask(mask, src.shape[1:3]), B)
        # Clip the paste window to the destination bounds.
        h = min(src.shape[1], H - y)
        w = min(src.shape[2], W - x)
        if h <= 0 or w <= 0:
            return (dst,)
        src = src[:, :h, :w, :]
        m = m_full[:, :h, :w, :]
        region = dst[:, y:y + h, x:x + w, :]
        blended = src * m + region * (1.0 - m)
        return (dst.at[:, y:y + h, x:x + w, :].set(blended),)


class LatentComposite:
    """Stock latent paste: samples_from over samples_to at (x, y) — widget
    coordinates are PIXELS, divided by 8 to latent cells like stock."""

    DESCRIPTION = "Stock-name latent composite."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "composite"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples_to": ("LATENT", {}),
                "samples_from": ("LATENT", {}),
                "x": ("INT", {"default": 0, "min": 0, "max": 16384, "step": 8}),
                "y": ("INT", {"default": 0, "min": 0, "max": 16384, "step": 8}),
                "feather": ("INT", {"default": 0, "min": 0, "max": 16384,
                                    "step": 8}),
            }
        }

    def composite(self, samples_to, samples_from, x: int, y: int,
                  feather: int = 0):
        import jax.numpy as jnp

        dst = jnp.asarray(samples_to["samples"])
        src = jnp.asarray(samples_from["samples"])
        xl, yl, fl = x // 8, y // 8, feather // 8
        B, H, W, C = dst.shape
        h = min(src.shape[1], H - yl)
        w = min(src.shape[2], W - xl)
        if h <= 0 or w <= 0:
            return ({**samples_to},)
        src = src[:, :h, :w, :]
        src = _repeat_to_batch(src, B)
        m = jnp.ones((h, w), jnp.float32)
        if fl > 0:
            # Feather ONLY the pasted edges that fall strictly inside the
            # destination — edges flush with the canvas border stay hard
            # (stock gates each ramp the same way).
            ones_h = jnp.ones((h,), jnp.float32)
            ramp_h = jnp.minimum(
                jnp.arange(1, h + 1, dtype=jnp.float32) / fl, 1.0
            )
            top_r = ramp_h if yl > 0 else ones_h
            bot_r = ramp_h[::-1] if yl + h < H else ones_h
            m = m * jnp.minimum(top_r, bot_r)[:, None]
            ones_w = jnp.ones((w,), jnp.float32)
            ramp_w = jnp.minimum(
                jnp.arange(1, w + 1, dtype=jnp.float32) / fl, 1.0
            )
            left_r = ramp_w if xl > 0 else ones_w
            right_r = ramp_w[::-1] if xl + w < W else ones_w
            m = m * jnp.minimum(left_r, right_r)[None, :]
        m = m[None, :, :, None]
        region = dst[:, yl:yl + h, xl:xl + w, :]
        return ({
            **samples_to,
            "samples": dst.at[:, yl:yl + h, xl:xl + w, :].set(
                src * m + region * (1.0 - m)
            ),
        },)


class SaveAnimatedWEBP:
    """Stock video save: a (B|F, H, W, 3) image sequence (e.g. WAN decode
    frames) → one animated WEBP under the served output root."""

    DESCRIPTION = "Stock-name animated WEBP save."
    RETURN_TYPES = ("STRING",)
    RETURN_NAMES = ("paths",)
    FUNCTION = "save_images"
    CATEGORY = CATEGORY
    OUTPUT_NODE = True

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "images": ("IMAGE", {}),
                "filename_prefix": ("STRING", {"default": "ComfyUI"}),
                "fps": ("FLOAT", {"default": 6.0, "min": 0.01, "max": 1000.0}),
                "lossless": ("BOOLEAN", {"default": True}),
                "quality": ("INT", {"default": 80, "min": 0, "max": 100}),
            }
        }

    def save_images(self, images, filename_prefix: str = "ComfyUI",
                    fps: float = 6.0, lossless: bool = True,
                    quality: int = 80):
        import numpy as np
        from PIL import Image

        arr = np.asarray(images)
        if arr.ndim == 3:
            arr = arr[None]
        if arr.ndim == 5:  # (B, F, H, W, 3) video batch → flatten clips
            arr = arr.reshape((-1,) + arr.shape[2:])
        frames = [
            Image.fromarray(
                (np.clip(f, 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)
            )
            for f in arr
        ]
        # Shared save-path semantics with TPUSaveImage (subfolder prefixes,
        # escape rejection, past-highest-index counter).
        from .nodes import resolve_save_target

        target_dir, name, start = resolve_save_target(
            filename_prefix or "ComfyUI", suffix="webp"
        )
        path = os.path.join(target_dir, f"{name}_{start:05d}.webp")
        frames[0].save(
            path, save_all=True, append_images=frames[1:],
            duration=max(1, int(round(1000.0 / fps))), loop=0,
            lossless=lossless, quality=quality,
        )
        return ((path,),)


class VAEEncodeForInpaint:
    """Stock soft-inpaint encode for REGULAR (4-channel) checkpoints: blanks
    the masked pixels before encoding (so the masked content cannot leak into
    the latent), grows the mask by ``grow_mask_by`` pixels (stock default 6 —
    seam room for the VAE's receptive field), and returns the latent with a
    ``noise_mask`` for the sampler's latent-noise-mask mechanism. Dedicated
    9-channel checkpoints use InpaintModelConditioning instead."""

    DESCRIPTION = "Stock-name inpaint encode (masked latent + noise_mask)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "vae": ("VAE", {}),
                "pixels": ("IMAGE", {}),
                "mask": ("MASK", {}),
                "grow_mask_by": ("INT", {"default": 6, "min": 0, "max": 64}),
            }
        }

    def encode(self, vae, pixels, mask, grow_mask_by: int = 6):
        import jax
        import jax.numpy as jnp

        from .models.vae import images_to_vae_input, normalize_mask

        px = images_to_vae_input(pixels)
        m = jnp.round(
            jnp.clip(normalize_mask(mask, px.shape[1:3]), 0.0, 1.0)
        )
        # Blank with the ORIGINAL rounded mask (0.0 == 0.5-gray in the VAE's
        # [-1, 1] input space — stock keeps the real-pixel context around the
        # seam); the GROWN mask serves only as the noise_mask.
        latent = vae.encode(px * (1.0 - m), None)
        grown = m
        if grow_mask_by > 1:
            # Stock's grow: a k×k max window (~(k-1)/2 px per side).
            k = int(grow_mask_by)
            grown = jax.lax.reduce_window(
                m, -jnp.inf, jax.lax.max,
                (1, k, k, 1), (1, 1, 1, 1), "SAME",
            )
        lat_mask = jax.image.resize(
            grown, (grown.shape[0], *latent.shape[1:3], 1), method="nearest"
        )
        return ({"samples": latent, "noise_mask": lat_mask},)


class ImagePadForOutpaint:
    """Stock outpaint prep: pad the image by left/top/right/bottom pixels
    (edge-replicated — gives the sampler a color hint) and return the matching
    regenerate mask, feathered ``feathering`` pixels into the original so the
    seam blends."""

    DESCRIPTION = "Stock-name outpaint padding (padded image + feathered mask)."
    RETURN_TYPES = ("IMAGE", "MASK")
    RETURN_NAMES = ("image", "mask")
    FUNCTION = "expand_image"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE", {}),
                "left": ("INT", {"default": 0, "min": 0, "max": 16384,
                                 "step": 8}),
                "top": ("INT", {"default": 0, "min": 0, "max": 16384,
                                "step": 8}),
                "right": ("INT", {"default": 0, "min": 0, "max": 16384,
                                  "step": 8}),
                "bottom": ("INT", {"default": 0, "min": 0, "max": 16384,
                                   "step": 8}),
                "feathering": ("INT", {"default": 40, "min": 0, "max": 16384,
                                       "step": 1}),
            }
        }

    def expand_image(self, image, left: int, top: int, right: int,
                     bottom: int, feathering: int = 40):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        B, H, W, C = img.shape
        padded = jnp.pad(
            img, ((0, 0), (top, bottom), (left, right), (0, 0)), mode="edge"
        )
        # Mask: 1 in the new border, feathered down to 0 inside the original.
        rows = jnp.arange(H, dtype=jnp.float32)
        cols = jnp.arange(W, dtype=jnp.float32)
        # Distance to the nearest PADDED edge of the original region; sides
        # without padding don't feather (jnp.inf distance).
        d = jnp.full((H, W), jnp.inf, jnp.float32)
        if top:
            d = jnp.minimum(d, rows[:, None])
        if bottom:
            d = jnp.minimum(d, (H - 1 - rows)[:, None])
        if left:
            d = jnp.minimum(d, cols[None, :])
        if right:
            d = jnp.minimum(d, (W - 1 - cols)[None, :])
        # Stock semantics: QUADRATIC ramp, and no feathering at all when the
        # requested feather would cover most of the image.
        if feathering > 0 and feathering * 2 < H and feathering * 2 < W:
            v = jnp.clip(1.0 - d / float(feathering), 0.0, 1.0)
            inner = v * v
        else:
            inner = jnp.zeros((H, W), jnp.float32)
        mask = jnp.pad(
            inner, ((top, bottom), (left, right)), constant_values=1.0
        )
        return padded, jnp.broadcast_to(mask[None], (B, *mask.shape))


class ConditioningSetTimestepRange:
    """Stock timestep-range gate: scope a conditioning to a sampling-progress
    window (start/end in [0, 1], 0 = first step). Effective on conds riding a
    Combine's ``extras`` (the stock multi-stage pattern: two prompts covering
    different ranges); on a lone PRIMARY cond the gate is ignored with a
    warning at sampling time (a step with no active cond has no stock
    fallback either)."""

    DESCRIPTION = "Stock-name conditioning timestep window."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "set_range"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING", {}),
                "start": ("FLOAT", {"default": 0.0, "min": 0.0, "max": 1.0,
                                    "step": 0.001}),
                "end": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0,
                                  "step": 0.001}),
            }
        }

    def set_range(self, conditioning, start: float, end: float):
        return (_tag_all_entries(
            conditioning, {"timestep_range": (float(start), float(end))}
        ),)


class ConditioningZeroOut:
    """Stock zero-out: the FLUX-workflow "negative" — a conditioning whose
    embeddings are all zeros (guidance-distilled models take it instead of a
    real negative prompt)."""

    DESCRIPTION = "Stock-name conditioning zero-out (FLUX negative)."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "zero_out"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"conditioning": ("CONDITIONING", {})}}

    def zero_out(self, conditioning):
        import jax.numpy as jnp

        out = dict(conditioning)
        for k in ("context", "penultimate", "pooled"):
            if out.get(k) is not None:
                out[k] = jnp.zeros_like(out[k])
        if out.get("extras"):
            out["extras"] = tuple(
                {**e, **{k: jnp.zeros_like(e[k])
                         for k in ("context", "pooled")
                         if e.get(k) is not None}}
                for e in out["extras"]
            )
        return (out,)


class CLIPTextEncodeSDXL:
    """Stock SDXL encode: both prompts (text_g/text_l) through the dual
    bundled towers with the full size/crop/target conditioning vector —
    TPUTextEncode's sdxl-dual path generalized to the stock widget surface."""

    DESCRIPTION = "Stock-name SDXL dual-prompt text encode."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP", {}),
                "width": ("INT", {"default": 1024, "min": 0, "max": 16384}),
                "height": ("INT", {"default": 1024, "min": 0, "max": 16384}),
                "crop_w": ("INT", {"default": 0, "min": 0, "max": 16384}),
                "crop_h": ("INT", {"default": 0, "min": 0, "max": 16384}),
                "target_width": ("INT", {"default": 1024, "min": 0,
                                         "max": 16384}),
                "target_height": ("INT", {"default": 1024, "min": 0,
                                          "max": 16384}),
                "text_g": ("STRING", {"default": "", "multiline": True}),
                "text_l": ("STRING", {"default": "", "multiline": True}),
            }
        }

    def encode(self, clip, width: int, height: int, crop_w: int, crop_h: int,
               target_width: int, target_height: int,
               text_g: str, text_l: str):
        from .models.text_encoders import sdxl_text_conditioning
        from .nodes import TPUTextEncode

        if clip.get("type") != "sdxl-dual":
            raise ValueError(
                "CLIPTextEncodeSDXL needs the dual L+G CLIP wire "
                "(CheckpointLoaderSimple on an SDXL checkpoint, or "
                "DualCLIPLoader type=sdxl)"
            )
        enc = TPUTextEncode()
        # Honor a CLIPSetLastLayer tag on the dual wire exactly like
        # TPUTextEncode's own sdxl-dual branch: default (0) = penultimate
        # (SDXL's training convention); an explicit skip selects each tower's
        # skip-resolved stream.
        clip_skip = int(clip.get("clip_skip", 0))
        (cl,) = enc.encode(clip["l"], text_l, clip_skip)
        (cg,) = enc.encode(clip["g"], text_g, clip_skip)
        str_l = cl["penultimate"] if clip_skip == 0 else cl["context"]
        str_g = cg["penultimate"] if clip_skip == 0 else cg["context"]
        context, y = sdxl_text_conditioning(
            str_l, str_g, cg["pooled"],
            width=width, height=height, crop_x=crop_w, crop_y=crop_h,
            target_width=target_width, target_height=target_height,
        )
        return ({"context": context, "penultimate": None, "pooled": y},)


class ConditioningCombine:
    """Stock combine: BOTH conditionings apply during sampling. The second
    cond (and any extras it accumulated) rides the first's ``extras`` tuple;
    the sampler blends per-cond predictions area-weight-normalized
    (sampling/k_samplers.EpsDenoiser._combine_conds — ComfyUI's
    calc_cond_batch rule, minus its crop-run optimization)."""

    DESCRIPTION = "Stock-name conditioning combine (both prompts apply)."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "combine"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning_1": ("CONDITIONING", {}),
                "conditioning_2": ("CONDITIONING", {}),
            }
        }

    def combine(self, conditioning_1, conditioning_2):
        second = {k: v for k, v in conditioning_2.items() if k != "extras"}
        extras = (
            tuple(conditioning_1.get("extras", ()))
            + (second,)
            + tuple(conditioning_2.get("extras", ()))
        )
        return ({**conditioning_1, "extras": extras},)


class ConditioningSetArea:
    """Stock area conditioning: scope a prompt to a latent-space box. Widgets
    are pixels (step 8, like stock); the wire stores latent units (//8)."""

    DESCRIPTION = "Stock-name area conditioning (regional prompting)."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "append"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING", {}),
                "width": ("INT", {"default": 64, "min": 8, "max": 16384,
                                  "step": 8}),
                "height": ("INT", {"default": 64, "min": 8, "max": 16384,
                                   "step": 8}),
                "x": ("INT", {"default": 0, "min": 0, "max": 16384, "step": 8}),
                "y": ("INT", {"default": 0, "min": 0, "max": 16384, "step": 8}),
                "strength": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 10.0}),
            }
        }

    def append(self, conditioning, width: int, height: int, x: int, y: int,
               strength: float = 1.0):
        # Stock conditioning_set_values maps over EVERY list entry — primary
        # and combined extras alike get the box. Clears any fractional box
        # (stock keeps one "area" key, later node wins).
        return (_tag_all_entries(conditioning, {
            "area": (height // 8, width // 8, y // 8, x // 8),
            "area_pct": None,
            "strength": float(strength),
        }),)


class ConditioningAverage:
    """Stock average: lerp ``from`` into ``to`` at (1 − strength). Token-wise
    over the overlap; ``to``'s trailing tokens survive unblended and a shorter
    ``from`` is zero-padded — the stock node's exact rule."""

    DESCRIPTION = "Stock-name conditioning average (prompt blending)."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "addWeighted"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning_to": ("CONDITIONING", {}),
                "conditioning_from": ("CONDITIONING", {}),
                "conditioning_to_strength": (
                    "FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0}
                ),
            }
        }

    def addWeighted(self, conditioning_to, conditioning_from,  # noqa: N802 — stock method name
                    conditioning_to_strength: float):
        import jax.numpy as jnp

        s = float(conditioning_to_strength)
        from_ctx = jnp.asarray(conditioning_from["context"])
        p_from = conditioning_from.get("pooled")

        def blend_one(cond: dict) -> dict:
            to_ctx = jnp.asarray(cond["context"])
            n = to_ctx.shape[1]
            f = from_ctx
            if f.shape[1] < n:
                pad = [(0, 0)] * f.ndim
                pad[1] = (0, n - f.shape[1])
                f = jnp.pad(f, pad)
            out = {**cond, "context": to_ctx * s + f[:, :n] * (1.0 - s)}
            p_to = cond.get("pooled")
            if p_to is not None and p_from is not None:
                out["pooled"] = (jnp.asarray(p_to) * s
                                 + jnp.asarray(p_from) * (1.0 - s))
            return out

        # Stock blends EVERY entry of the to-list — here the primary cond and
        # each combined extra alike.
        out = blend_one(conditioning_to)
        if conditioning_to.get("extras"):
            out["extras"] = tuple(
                blend_one(e) for e in conditioning_to["extras"]
            )
        return (out,)


# Stock upscale_method menu → jax.image.resize method. "area" has no jax
# equivalent; bilinear is the closest downscale behavior (documented
# divergence — stock uses adaptive average pooling there).
_STOCK_RESIZE = {
    "nearest-exact": "nearest",
    "bilinear": "bilinear",
    "area": "bilinear",
    "bicubic": "cubic",
    "lanczos": "lanczos3",
}


def _stock_resize(image, width: int, height: int, upscale_method: str,
                  crop: str = "disabled"):
    """The stock ImageScale core: optional center-crop to the target aspect
    ratio, then resize. Returns a (B, H, W, C) float image in [0, 1]."""
    import jax
    import jax.numpy as jnp

    method = _STOCK_RESIZE.get(upscale_method)
    if method is None:
        raise ValueError(
            f"upscale_method must be one of {sorted(_STOCK_RESIZE)}, "
            f"got {upscale_method!r}"
        )
    img = jnp.asarray(image)
    if img.ndim == 3:
        img = img[None]
    if crop == "center":
        b, h, w, c = img.shape
        aspect = width / height
        if w / h > aspect:  # too wide: crop columns
            new_w = max(1, round(h * aspect))
            x0 = (w - new_w) // 2
            img = img[:, :, x0:x0 + new_w, :]
        elif w / h < aspect:  # too tall: crop rows
            new_h = max(1, round(w / aspect))
            y0 = (h - new_h) // 2
            img = img[:, y0:y0 + new_h, :, :]
    elif crop != "disabled":
        raise ValueError(f"crop must be 'disabled' or 'center', got {crop!r}")
    out = jax.image.resize(
        img, (img.shape[0], height, width, img.shape[-1]), method=method
    )
    return jnp.clip(out, 0.0, 1.0)


class ImageScale:
    """Stock image resize: exact width/height with the stock method menu and
    center-crop option (TPUImageScale is the native sibling with the jax
    method names)."""

    DESCRIPTION = "Stock-name image resize (method menu + center crop)."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "upscale"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE", {}),
                "upscale_method": (sorted(_STOCK_RESIZE), {"default": "bilinear"}),
                "width": ("INT", {"default": 512, "min": 0, "max": 16384}),
                "height": ("INT", {"default": 512, "min": 0, "max": 16384}),
                "crop": (["disabled", "center"], {"default": "disabled"}),
            }
        }

    def upscale(self, image, upscale_method: str, width: int, height: int,
                crop: str = "disabled"):
        # Stock 0-sentinel: a zero dim derives from the other one keeping the
        # source aspect ratio (both zero is meaningless).
        if width == 0 and height == 0:
            raise ValueError("ImageScale: width and height cannot both be 0")
        if width == 0 or height == 0:
            import jax.numpy as jnp

            img = jnp.asarray(image)
            src_h, src_w = (img.shape[0:2] if img.ndim == 3
                            else img.shape[1:3])
            if width == 0:
                width = max(1, round(height * src_w / src_h))
            else:
                height = max(1, round(width * src_h / src_w))
        return (_stock_resize(image, width, height, upscale_method, crop),)


class ImageScaleBy:
    """Stock relative image resize: scale_by factor, no crop."""

    DESCRIPTION = "Stock-name relative image resize."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "upscale"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE", {}),
                "upscale_method": (sorted(_STOCK_RESIZE), {"default": "bilinear"}),
                "scale_by": ("FLOAT", {"default": 1.0, "min": 0.01, "max": 8.0,
                                       "step": 0.01}),
            }
        }

    def upscale(self, image, upscale_method: str, scale_by: float):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        h = max(1, round(img.shape[1] * scale_by))
        w = max(1, round(img.shape[2] * scale_by))
        return (_stock_resize(img, w, h, upscale_method),)


class PreviewImage:
    """Stock preview node: saves under ``<output_dir>/temp`` (the host's
    temp-image convention) via TPUSaveImage — headless, a preview IS a file
    the client fetches through /view."""

    DESCRIPTION = "Stock-name image preview (saves to the temp subfolder)."
    RETURN_TYPES = ("STRING",)
    RETURN_NAMES = ("paths",)
    FUNCTION = "preview"
    CATEGORY = CATEGORY
    OUTPUT_NODE = True

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"images": ("IMAGE", {})}}

    def preview(self, images):
        from .nodes import TPUSaveImage

        # temp/ subfolder under the served output root: /view can fetch it
        # (subfolder=temp) and the history's relpath logic tags it correctly.
        return TPUSaveImage().save(images, filename_prefix="temp/preview")


class CLIPTextEncodeSDXLRefiner:
    """Stock refiner encode: ONE prompt through the OpenCLIP-G tower with the
    refiner's (size, crop, aesthetic-score) conditioning vector. Accepts the
    sdxl-dual wire (uses its G tower — the stock base→refiner template wires
    the base checkpoint's CLIP here too) or a single G-tower CLIP wire."""

    DESCRIPTION = "Stock-name SDXL-refiner text encode (aesthetic score adm)."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP", {}),
                "ascore": ("FLOAT", {"default": 6.0, "min": 0.0,
                                     "max": 1000.0}),
                "width": ("INT", {"default": 1024, "min": 0, "max": 16384}),
                "height": ("INT", {"default": 1024, "min": 0, "max": 16384}),
                "text": ("STRING", {"default": "", "multiline": True}),
            }
        }

    def encode(self, clip, ascore: float, width: int, height: int, text: str):
        from .models.text_encoders import sdxl_refiner_text_conditioning
        from .nodes import TPUTextEncode

        g_wire = clip["g"] if clip.get("type") == "sdxl-dual" else clip
        if g_wire.get("encoder") is None:
            raise ValueError(
                "CLIPTextEncodeSDXLRefiner needs a G-tower CLIP wire (the "
                "sdxl-dual wire from an SDXL checkpoint, or TPUCLIPLoader "
                "type=open-clip-g)"
            )
        clip_skip = int(clip.get("clip_skip", g_wire.get("clip_skip", 0)))
        (cg,) = TPUTextEncode().encode(g_wire, text, clip_skip)
        stream = cg["penultimate"] if clip_skip == 0 else cg["context"]
        context, y = sdxl_refiner_text_conditioning(
            stream, cg["pooled"], width=width, height=height,
            ascore=float(ascore),
        )
        return ({"context": context, "penultimate": None, "pooled": y},)


class ConditioningConcat:
    """Stock concat: ``conditioning_from``'s tokens append onto
    ``conditioning_to``'s along the sequence axis (ONE longer prompt — unlike
    Combine, which keeps both prompts separate and blends predictions).
    conditioning_to's other fields (pooled, control tags, …) win."""

    DESCRIPTION = "Stock-name conditioning token concat."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "concat"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning_to": ("CONDITIONING", {}),
                "conditioning_from": ("CONDITIONING", {}),
            }
        }

    def concat(self, conditioning_to, conditioning_from):
        import jax.numpy as jnp

        to_ctx = conditioning_to.get("context")
        from_ctx = conditioning_from.get("context")
        if to_ctx is None or from_ctx is None:
            raise ValueError("ConditioningConcat needs text conditionings "
                             "with a context stream on both inputs")
        if to_ctx.shape[-1] != from_ctx.shape[-1]:
            raise ValueError(
                f"cannot concat conditionings of different widths "
                f"({to_ctx.shape[-1]} vs {from_ctx.shape[-1]} — e.g. an SDXL "
                "dual-tower cond with a plain CLIP-L one)"
            )
        if from_ctx.shape[0] != to_ctx.shape[0]:
            from_ctx = _repeat_to_batch(from_ctx, to_ctx.shape[0])
        return ({**conditioning_to,
                 "context": jnp.concatenate([to_ctx, from_ctx], axis=1)},)


class ImageInvert:
    DESCRIPTION = "Stock-name image invert (1 - pixels)."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "invert"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("IMAGE", {})}}

    def invert(self, image):
        import jax.numpy as jnp

        return (1.0 - jnp.asarray(image),)


class ImageBatch:
    """Stock batch join: the second image resizes (bilinear) to the first's
    spatial size when they differ, then both concatenate along batch."""

    DESCRIPTION = "Stock-name image batch concat."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "batch"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image1": ("IMAGE", {}),
                             "image2": ("IMAGE", {})}}

    def batch(self, image1, image2):
        import jax
        import jax.numpy as jnp

        a = jnp.asarray(image1)
        b = jnp.asarray(image2)
        if a.ndim == 3:
            a = a[None]
        if b.ndim == 3:
            b = b[None]
        if b.shape[1:3] != a.shape[1:3]:
            b = jax.image.resize(
                b, (b.shape[0], *a.shape[1:3], b.shape[-1]), method="bilinear"
            )
        return (jnp.concatenate([a, b], axis=0),)


class RepeatLatentBatch:
    DESCRIPTION = "Stock-name latent batch repeat."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "repeat"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"samples": ("LATENT", {}),
                             "amount": ("INT", {"default": 1, "min": 1,
                                                "max": 64})}}

    def repeat(self, samples, amount: int):
        import jax.numpy as jnp

        lat = jnp.asarray(samples["samples"])
        out = dict(samples)
        out["samples"] = jnp.tile(
            lat, (int(amount),) + (1,) * (lat.ndim - 1)
        )
        if samples.get("noise_mask") is not None:
            # Cycle the mask up to the SAMPLES batch first (stock
            # repeat_to_batch_size), then tile — so masks stay paired with
            # their samples instead of landing at a batch that matches
            # neither the latents nor 1.
            m = _repeat_to_batch(
                jnp.asarray(samples["noise_mask"]), lat.shape[0]
            )
            out["noise_mask"] = jnp.tile(
                m, (int(amount),) + (1,) * (m.ndim - 1)
            )
        return (out,)


class LatentFromBatch:
    DESCRIPTION = "Stock-name latent batch slice."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "frombatch"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"samples": ("LATENT", {}),
                             "batch_index": ("INT", {"default": 0, "min": 0,
                                                     "max": 4095}),
                             "length": ("INT", {"default": 1, "min": 1,
                                                "max": 4096})}}

    def frombatch(self, samples, batch_index: int, length: int):
        import jax.numpy as jnp

        lat = jnp.asarray(samples["samples"])
        i = min(int(batch_index), lat.shape[0] - 1)
        n = min(int(length), lat.shape[0] - i)
        out = dict(samples)
        out["samples"] = lat[i:i + n]
        if samples.get("noise_mask") is not None:
            m = jnp.asarray(samples["noise_mask"])
            if m.shape[0] > 1:
                # Cycle up to the samples batch BEFORE slicing (stock rule) —
                # a mask batch smaller than the latent batch would otherwise
                # slice short or empty.
                out["noise_mask"] = _repeat_to_batch(m, lat.shape[0])[i:i + n]
        return (out,)


def _latent_spatial_map(samples_dict, fn):
    """Apply ``fn`` (a spatial-axes transform over channels-last arrays) to
    the latent samples AND its noise_mask — both share rank and the
    (..., H, W, C) layout, so the −3/−2 spatial axes line up for image (NHWC)
    and video (NTHWC) latents alike."""
    import jax.numpy as jnp

    out = dict(samples_dict)
    out["samples"] = fn(jnp.asarray(samples_dict["samples"]))
    if samples_dict.get("noise_mask") is not None:
        out["noise_mask"] = fn(jnp.asarray(samples_dict["noise_mask"]))
    return out


class LatentFlip:
    """Stock latent flip: the menu strings name the axis being mirrored
    ACROSS — "x-axis: vertically" mirrors rows (H), "y-axis: horizontally"
    mirrors columns (W). The attached noise_mask flips with the samples."""

    DESCRIPTION = "Stock-name latent flip (vertical/horizontal)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "flip"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "samples": ("LATENT", {}),
            "flip_method": (["x-axis: vertically", "y-axis: horizontally"],
                            {"default": "x-axis: vertically"}),
        }}

    def flip(self, samples, flip_method: str):
        import jax.numpy as jnp

        axis = -3 if flip_method.startswith("x") else -2
        return (_latent_spatial_map(samples, lambda a: jnp.flip(a, axis)),)


class LatentRotate:
    """Stock latent rotate: clockwise quarter-turns over the spatial plane
    (channels-last: H=−3, W=−2; ``jnp.rot90`` with negative k is clockwise).
    The attached noise_mask rotates with the samples."""

    DESCRIPTION = "Stock-name latent rotation (90° steps, clockwise)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "rotate"
    CATEGORY = CATEGORY

    _TURNS = {"none": 0, "90 degrees": 1, "180 degrees": 2, "270 degrees": 3}

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "samples": ("LATENT", {}),
            "rotation": (list(cls._TURNS), {"default": "none"}),
        }}

    def rotate(self, samples, rotation: str):
        import jax.numpy as jnp

        k = self._TURNS.get(rotation)
        if k is None:
            raise ValueError(
                f"rotation {rotation!r} is not one of {list(self._TURNS)}"
            )
        if k == 0:
            return (samples,)
        return (_latent_spatial_map(
            samples, lambda a: jnp.rot90(a, k=-k, axes=(-3, -2))
        ),)


class LatentCrop:
    """Stock latent crop: pixel-space (width, height, x, y) → an 8×-downsampled
    latent window with stock's exact boundary rule: the origin clamps to
    (dim − 8) in latent units and the slice then truncates at the latent's
    edge — an oversized or out-of-range window therefore yields a
    smaller-than-requested latent, exactly as the stock node does (it never
    slides the window back to preserve the requested size)."""

    DESCRIPTION = "Stock-name latent crop (pixel coords, /8 latent grid)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "crop"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "samples": ("LATENT", {}),
            "width": ("INT", {"default": 512, "min": 64, "max": 16384,
                              "step": 8}),
            "height": ("INT", {"default": 512, "min": 64, "max": 16384,
                               "step": 8}),
            "x": ("INT", {"default": 0, "min": 0, "max": 16384, "step": 8}),
            "y": ("INT", {"default": 0, "min": 0, "max": 16384, "step": 8}),
        }}

    def crop(self, samples, width: int, height: int, x: int, y: int):
        lat = samples["samples"]
        H, W = lat.shape[-3], lat.shape[-2]
        # Stock boundary rule: clamp the origin to (dim − 8) latent units,
        # then let the slice truncate (smaller-than-requested output near the
        # edge). The extra max(…, 0) keeps sub-64px latents slicing from 0
        # instead of a negative index.
        y0 = min(int(y) // 8, max(H - 8, 0))
        x0 = min(int(x) // 8, max(W - 8, 0))
        h = max(1, int(height) // 8)
        w = max(1, int(width) // 8)

        def window(a):
            return a[..., y0:y0 + h, x0:x0 + w, :]

        return (_latent_spatial_map(samples, window),)


class SaveLatent:
    """Stock latent save: a safetensors file holding ``latent_tensor`` plus
    the ``latent_format_version_0`` marker (stock's un-scaled format signal;
    LoadLatent applies the legacy 1/0.18215 rescale only when it is absent).
    The file stores the public stock layout — channels-first NCHW (NCTHW for
    video latents) — so dumps interchange with the stock host; this
    framework's channels-last axes transpose at the file boundary, the same
    contract the checkpoint converters keep for single-file layouts. Saved
    under $PA_OUTPUT_DIR via the same counter/prefix rules as SaveImage."""

    DESCRIPTION = "Stock-name latent save (safetensors)."
    RETURN_TYPES = ()
    FUNCTION = "save"
    CATEGORY = CATEGORY
    OUTPUT_NODE = True

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "samples": ("LATENT", {}),
            "filename_prefix": ("STRING", {"default": "latents/ComfyUI"}),
        }}

    def save(self, samples, filename_prefix: str = "latents/ComfyUI"):
        import numpy as _np
        from safetensors.numpy import save_file

        from .nodes import resolve_save_target

        target_dir, name, idx = resolve_save_target(
            filename_prefix, suffix="latent"
        )
        path = os.path.join(target_dir, f"{name}_{idx:05}.latent")
        # Channels-last (..., H, W, C) → the stock file's channels-first
        # (..., C, H, W): axis -1 moves to position 1 for any latent rank
        # (NHWC image and NTHWC video alike).
        arr = _np.moveaxis(
            _np.asarray(samples["samples"], dtype=_np.float32), -1, 1
        )
        save_file(
            {
                "latent_tensor": arr,
                "latent_format_version_0": _np.zeros((0,), _np.float32),
            },
            path,
        )
        return {"ui": {"latents": [os.path.basename(path)]}}


class LoadLatent:
    """Stock latent load: reads a SaveLatent file from $PA_INPUT_DIR. The
    file holds the stock channels-first layout (NCHW/NCTHW) — axis 1 moves
    back to -1 on read, the inverse of SaveLatent's boundary transpose.
    Files without the ``latent_format_version_0`` marker are stock's legacy
    dumps, stored pre-scaled — multiply by 1/0.18215 to recover latent
    space."""

    DESCRIPTION = "Stock-name latent load (safetensors)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"latent": ("STRING", {"default": ""})}}

    def load(self, latent: str):
        import jax.numpy as jnp
        from safetensors.numpy import load_file

        path = latent
        if not os.path.isabs(path):
            path = os.path.join(os.environ.get("PA_INPUT_DIR", "."), path)
        if not os.path.isfile(path):
            raise ValueError(f"latent file not found: {path}")
        sd = load_file(path)
        if "latent_tensor" not in sd:
            raise ValueError(
                f"{path} is not a saved latent (no latent_tensor key)"
            )
        # Stock channels-first file → this framework's channels-last latents;
        # the legacy 1/0.18215 dumps are stored in the same NCHW layout.
        arr = jnp.moveaxis(jnp.asarray(sd["latent_tensor"], jnp.float32), 1, -1)
        if "latent_format_version_0" not in sd:
            arr = arr * (1.0 / 0.18215)
        return ({"samples": arr},)


class SolidMask:
    DESCRIPTION = "Stock-name constant mask."
    RETURN_TYPES = ("MASK",)
    RETURN_NAMES = ("mask",)
    FUNCTION = "solid"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "value": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0}),
            "width": ("INT", {"default": 512, "min": 1, "max": 16384}),
            "height": ("INT", {"default": 512, "min": 1, "max": 16384}),
        }}

    def solid(self, value: float, width: int, height: int):
        import jax.numpy as jnp

        return (jnp.full((1, int(height), int(width)), float(value),
                         jnp.float32),)


class InvertMask:
    DESCRIPTION = "Stock-name mask invert."
    RETURN_TYPES = ("MASK",)
    RETURN_NAMES = ("mask",)
    FUNCTION = "invert"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"mask": ("MASK", {})}}

    def invert(self, mask):
        import jax.numpy as jnp

        return (1.0 - jnp.asarray(mask, jnp.float32),)


class ImageToMask:
    DESCRIPTION = "Stock-name channel extract (image → mask)."
    RETURN_TYPES = ("MASK",)
    RETURN_NAMES = ("mask",)
    FUNCTION = "image_to_mask"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("IMAGE", {}),
                             "channel": (["red", "green", "blue", "alpha"],
                                         {"default": "red"})}}

    def image_to_mask(self, image, channel: str = "red"):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        idx = {"red": 0, "green": 1, "blue": 2, "alpha": 3}[channel]
        if idx >= img.shape[-1]:
            # Stock indexes an existing channel; a 3-channel image has no
            # alpha — fully-opaque is the faithful reading.
            return (jnp.ones(img.shape[:3], jnp.float32),)
        return (img[..., idx].astype(jnp.float32),)


class MaskToImage:
    DESCRIPTION = "Stock-name mask → grayscale image."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "mask_to_image"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"mask": ("MASK", {})}}

    def mask_to_image(self, mask):
        import jax.numpy as jnp

        m = jnp.asarray(mask, jnp.float32)
        if m.ndim == 2:
            m = m[None]
        if m.ndim == 4:
            m = m[..., 0]
        return (jnp.repeat(m[..., None], 3, axis=-1),)


class GrowMask:
    """Stock grow/shrink: |expand| iterations of a 3×3 max (grow) or min
    (shrink) window; ``tapered_corners`` excludes the diagonal neighbors
    (the stock plus-shaped kernel), rounding grown corners."""

    DESCRIPTION = "Stock-name mask dilate/erode."
    RETURN_TYPES = ("MASK",)
    RETURN_NAMES = ("mask",)
    FUNCTION = "expand_mask"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "mask": ("MASK", {}),
            "expand": ("INT", {"default": 0, "min": -16384, "max": 16384}),
            "tapered_corners": ("BOOLEAN", {"default": True}),
        }}

    def expand_mask(self, mask, expand: int, tapered_corners: bool = True):
        import jax.numpy as jnp

        m = jnp.asarray(mask, jnp.float32)
        if m.ndim == 2:
            m = m[None]
        grow = expand > 0
        n = min(abs(int(expand)), max(m.shape[1], m.shape[2]))
        for _ in range(n):
            # One 3×3 max/min step; the plus kernel = max over the 4-neighbor
            # shifts + center (diagonals excluded when tapered).
            shifts = [m]
            padded = jnp.pad(
                m, ((0, 0), (1, 1), (1, 1)),
                constant_values=0.0 if grow else 1.0,
            )
            offs = [(-1, 0), (1, 0), (0, -1), (0, 1)]
            if not tapered_corners:
                offs += [(-1, -1), (-1, 1), (1, -1), (1, 1)]
            for dy, dx in offs:
                shifts.append(
                    padded[:, 1 + dy:1 + dy + m.shape[1],
                           1 + dx:1 + dx + m.shape[2]]
                )
            m = (jnp.max(jnp.stack(shifts), axis=0) if grow
                 else jnp.min(jnp.stack(shifts), axis=0))
        return (m,)


class FeatherMask:
    """Stock feather: linear ramp to 0 over the given pixel depth from each
    selected edge."""

    DESCRIPTION = "Stock-name mask edge feather."
    RETURN_TYPES = ("MASK",)
    RETURN_NAMES = ("mask",)
    FUNCTION = "feather"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "mask": ("MASK", {}),
            "left": ("INT", {"default": 0, "min": 0, "max": 16384}),
            "top": ("INT", {"default": 0, "min": 0, "max": 16384}),
            "right": ("INT", {"default": 0, "min": 0, "max": 16384}),
            "bottom": ("INT", {"default": 0, "min": 0, "max": 16384}),
        }}

    def feather(self, mask, left: int, top: int, right: int, bottom: int):
        import jax.numpy as jnp

        m = jnp.asarray(mask, jnp.float32)
        if m.ndim == 2:
            m = m[None]
        _, H, W = m.shape
        rows = jnp.arange(H, dtype=jnp.float32)
        cols = jnp.arange(W, dtype=jnp.float32)
        scale = jnp.ones((H, W), jnp.float32)
        if top:
            scale = scale * jnp.clip((rows[:, None] + 1) / top, 0, 1)
        if bottom:
            scale = scale * jnp.clip((H - rows[:, None]) / bottom, 0, 1)
        if left:
            scale = scale * jnp.clip((cols[None, :] + 1) / left, 0, 1)
        if right:
            scale = scale * jnp.clip((W - cols[None, :]) / right, 0, 1)
        return (m * scale[None],)


class MaskComposite:
    """Stock mask composite: ``source`` pastes onto ``destination`` at
    (x, y) under the selected op (multiply/add/subtract/and/or/xor)."""

    DESCRIPTION = "Stock-name mask composite."
    RETURN_TYPES = ("MASK",)
    RETURN_NAMES = ("mask",)
    FUNCTION = "combine"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "destination": ("MASK", {}),
            "source": ("MASK", {}),
            "x": ("INT", {"default": 0, "min": 0, "max": 16384}),
            "y": ("INT", {"default": 0, "min": 0, "max": 16384}),
            "operation": (["multiply", "add", "subtract", "and", "or", "xor"],
                          {"default": "multiply"}),
        }}

    def combine(self, destination, source, x: int, y: int,
                operation: str = "multiply"):
        import jax.numpy as jnp

        dst = jnp.asarray(destination, jnp.float32)
        src = jnp.asarray(source, jnp.float32)
        if dst.ndim == 2:
            dst = dst[None]
        if src.ndim == 2:
            src = src[None]
        _, H, W = dst.shape
        h = min(src.shape[1], H - min(int(y), H))
        w = min(src.shape[2], W - min(int(x), W))
        if h <= 0 or w <= 0:
            return (dst,)
        src = _repeat_to_batch(src, dst.shape[0])[:, :h, :w]
        win = dst[:, y:y + h, x:x + w]
        ops = {
            "multiply": win * src,
            "add": win + src,
            "subtract": win - src,
            "and": jnp.round(win) * jnp.round(src),
            "or": jnp.clip(jnp.round(win) + jnp.round(src), 0, 1),
            "xor": jnp.abs(jnp.round(win) - jnp.round(src)),
        }
        out = jnp.clip(ops[operation], 0.0, 1.0)
        return (dst.at[:, y:y + h, x:x + w].set(out),)


class LoadImageMask:
    """Stock mask load: one channel of an input-directory image as a MASK
    (alpha inverts, matching stock's 1-alpha regenerate convention)."""

    DESCRIPTION = "Stock-name image-channel mask loader."
    RETURN_TYPES = ("MASK",)
    RETURN_NAMES = ("mask",)
    FUNCTION = "load_image"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("STRING", {"default": ""}),
                             "channel": (["alpha", "red", "green", "blue"],
                                         {"default": "alpha"})}}

    def load_image(self, image: str, channel: str = "alpha"):
        import jax.numpy as jnp
        import numpy as np

        px, alpha = LoadImage().run(image)
        if channel == "alpha":
            # LoadImage's MASK output is already stock's 1-alpha.
            return (jnp.asarray(alpha),)
        arr = np.asarray(px)
        idx = {"red": 0, "green": 1, "blue": 2}[channel]
        return (jnp.asarray(arr[..., idx], jnp.float32),)


class CLIPTextEncodeFlux:
    """Stock FLUX encode: SEPARATE prompts per tower (clip_l → pooled,
    t5xxl → context stream) + the distilled-guidance tag in one node — the
    stock FLUX template's text entry."""

    DESCRIPTION = "Stock-name FLUX dual-prompt encode with guidance tag."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "clip": ("CLIP", {}),
            "clip_l": ("STRING", {"default": "", "multiline": True}),
            "t5xxl": ("STRING", {"default": "", "multiline": True}),
            "guidance": ("FLOAT", {"default": 3.5, "min": 0.0,
                                   "max": 100.0}),
        }}

    def encode(self, clip, clip_l: str, t5xxl: str, guidance: float = 3.5):
        from .nodes import TPUFluxGuidance, TPUTextEncode

        if clip.get("type") != "flux-dual":
            raise ValueError(
                "CLIPTextEncodeFlux needs the dual T5+CLIP-L wire "
                "(DualCLIPLoader type=flux)"
            )
        # Honor a CLIPSetLastLayer tag on the dual wire (it lands on the
        # OUTER dict) — same convention as CLIPTextEncodeSDXL.
        clip_skip = int(clip.get("clip_skip", 0))
        enc = TPUTextEncode()
        (ct5,) = enc.encode(clip["t5"], t5xxl, clip_skip)
        (cl,) = enc.encode(clip["l"], clip_l, clip_skip)
        cond = {"context": ct5["context"], "penultimate": None,
                "pooled": cl["pooled"]}
        (tagged,) = TPUFluxGuidance().append(cond, float(guidance))
        return (tagged,)


class ConditioningSetAreaPercentage:
    """Stock percentage form of SetArea: the box is fractions of the LATENT
    frame, resolved per-sample at denoise time — here resolved against the
    stock 8× latent convention like the pixel form."""

    DESCRIPTION = "Stock-name fractional area conditioning."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "append"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "conditioning": ("CONDITIONING", {}),
            "width": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0,
                                "step": 0.01}),
            "height": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0,
                                 "step": 0.01}),
            "x": ("FLOAT", {"default": 0.0, "min": 0.0, "max": 1.0,
                            "step": 0.01}),
            "y": ("FLOAT", {"default": 0.0, "min": 0.0, "max": 1.0,
                            "step": 0.01}),
            "strength": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 10.0}),
        }}

    def append(self, conditioning, width: float, height: float, x: float,
               y: float, strength: float = 1.0):
        # Stock stores BOTH forms under one "area" key, so the later node
        # always wins; here the forms are separate keys — clear the sibling.
        return (_tag_all_entries(conditioning, {
            "area_pct": (float(height), float(width), float(y), float(x)),
            "area": None,
            "strength": float(strength),
        }),)


class ImageScaleToTotalPixels:
    """Stock megapixel-normalize (the FLUX template's input-size step):
    resize to ``megapixels`` total, aspect preserved."""

    DESCRIPTION = "Stock-name scale-to-megapixels."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "upscale"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "image": ("IMAGE", {}),
            "upscale_method": (list(_STOCK_RESIZE), {"default": "bilinear"}),
            "megapixels": ("FLOAT", {"default": 1.0, "min": 0.01,
                                     "max": 16.0, "step": 0.01}),
        }}

    def upscale(self, image, upscale_method: str, megapixels: float):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        _, H, W, _ = img.shape
        scale = (float(megapixels) * 1024 * 1024 / (H * W)) ** 0.5
        nh, nw = max(1, round(H * scale)), max(1, round(W * scale))
        # The shared stock-resize core: method validation + the [0,1] clip
        # (lanczos/bicubic overshoot) the sibling resize nodes apply.
        return (_stock_resize(img, nw, nh, upscale_method),)


class ModelMergeSimple:
    """Stock weighted model merge: ``ratio`` of model1 + ``1−ratio`` of
    model2, leaf-wise over the param pytrees. Both models must share a
    family/topology (identical tree structure — the stock constraint too)."""

    DESCRIPTION = "Stock-name weighted model merge."
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "merge"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model1": ("MODEL", {}),
            "model2": ("MODEL", {}),
            "ratio": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0,
                                "step": 0.01}),
        }}

    def merge(self, model1, model2, ratio: float):
        import dataclasses as dc

        import jax

        if not (dc.is_dataclass(model1) and dc.is_dataclass(model2)):
            raise ValueError(
                "ModelMergeSimple needs unwrapped MODELs; apply it before "
                "ParallelAnything"
            )
        r = float(ratio)

        def lerp(a, b):
            if getattr(a, "shape", None) != getattr(b, "shape", None):
                # Same tree structure but different widths (e.g. two UNets
                # built at different model_channels) must fail loudly, not
                # broadcast into silently corrupted params.
                raise ValueError(f"leaf shapes differ: {a.shape} vs {b.shape}")
            return a * r + b * (1.0 - r)

        try:
            merged = jax.tree.map(lerp, model1.params, model2.params)
        except (ValueError, TypeError) as e:
            raise ValueError(
                "models cannot merge — different families/topologies "
                f"({e})"
            ) from None
        # The merged weights correspond to neither source file, so the
        # re-bake LoRA path has nothing to re-bake from: a marker source
        # makes the downstream LoraLoader error name the real cause.
        return (dc.replace(model1, params=merged, source={"merged": True},
                           name=f"{model1.name}+merge"),)


class ImageCrop:
    DESCRIPTION = "Stock-name image crop."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "crop"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "image": ("IMAGE", {}),
            "width": ("INT", {"default": 512, "min": 1, "max": 16384}),
            "height": ("INT", {"default": 512, "min": 1, "max": 16384}),
            "x": ("INT", {"default": 0, "min": 0, "max": 16384}),
            "y": ("INT", {"default": 0, "min": 0, "max": 16384}),
        }}

    def crop(self, image, width: int, height: int, x: int, y: int):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        B, H, W, C = img.shape
        x = min(int(x), W - 1)
        y = min(int(y), H - 1)
        return (img[:, y:min(y + int(height), H), x:min(x + int(width), W)],)


def _gaussian_kernel1d(radius: int, sigma: float):
    import jax.numpy as jnp

    xs = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = jnp.exp(-(xs**2) / (2.0 * float(sigma) ** 2))
    return k / jnp.sum(k)


def _separable_blur(img, radius: int, sigma: float):
    """Edge-padded separable Gaussian over (B,H,W,C) — the shared primitive
    of the stock blur/sharpen pair."""
    import jax
    import jax.numpy as jnp

    k = _gaussian_kernel1d(radius, sigma)
    pad = int(radius)
    # reflect, not edge: stock's Blur/Sharpen pad reflectively — edge
    # replication over-weights the outermost row and diverges on borders.
    x = jnp.pad(img, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                mode="reflect")
    # Two depthwise 1-D convolutions (separable Gaussian).
    x = jax.lax.conv_general_dilated(
        x.transpose(0, 3, 1, 2), jnp.broadcast_to(
            k.reshape(1, 1, -1, 1), (img.shape[-1], 1, 2 * pad + 1, 1)),
        (1, 1), "VALID", feature_group_count=img.shape[-1],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    x = jax.lax.conv_general_dilated(
        x, jnp.broadcast_to(
            k.reshape(1, 1, 1, -1), (img.shape[-1], 1, 1, 2 * pad + 1)),
        (1, 1), "VALID", feature_group_count=img.shape[-1],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return x.transpose(0, 2, 3, 1)


class ImageBlur:
    DESCRIPTION = "Stock-name Gaussian image blur."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "blur"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "image": ("IMAGE", {}),
            "blur_radius": ("INT", {"default": 1, "min": 1, "max": 31}),
            "sigma": ("FLOAT", {"default": 1.0, "min": 0.1, "max": 10.0,
                                "step": 0.1}),
        }}

    def blur(self, image, blur_radius: int, sigma: float):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        return (_separable_blur(img, int(blur_radius), float(sigma)),)


class ImageSharpen:
    """Stock unsharp mask: img + alpha·(img − gaussian(img)), clipped."""

    DESCRIPTION = "Stock-name image sharpen (unsharp mask)."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "sharpen"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "image": ("IMAGE", {}),
            "sharpen_radius": ("INT", {"default": 1, "min": 1, "max": 31}),
            "sigma": ("FLOAT", {"default": 1.0, "min": 0.1, "max": 10.0,
                                "step": 0.1}),
            "alpha": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 5.0,
                                "step": 0.1}),
        }}

    def sharpen(self, image, sharpen_radius: int, sigma: float, alpha: float):
        import jax.numpy as jnp

        img = jnp.asarray(image)
        if img.ndim == 3:
            img = img[None]
        blurred = _separable_blur(img, int(sharpen_radius), float(sigma))
        return (jnp.clip(img + float(alpha) * (img - blurred), 0.0, 1.0),)


class LatentBlend:
    DESCRIPTION = "Stock-name latent lerp."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "blend"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "samples1": ("LATENT", {}),
            "samples2": ("LATENT", {}),
            "blend_factor": ("FLOAT", {"default": 0.5, "min": 0.0,
                                       "max": 1.0, "step": 0.01}),
        }}

    def blend(self, samples1, samples2, blend_factor: float):
        import jax.numpy as jnp

        a = jnp.asarray(samples1["samples"])
        b = _reshape_latent_to(a, jnp.asarray(samples2["samples"]))
        f = float(blend_factor)
        # Stock LatentBlend: samples1·factor + samples2·(1−factor).
        return ({**samples1, "samples": a * f + b * (1.0 - f)},)


def _reshape_latent_to(a, b):
    """Stock reshape_latent_to: resize ``b``'s SPATIAL grid to ``a``'s and
    cycle its batch up — the two-latent math nodes all normalize this way.
    Channel counts must already agree (resizing across channels would
    fabricate latent data; stock fails loudly there too)."""
    import jax
    import jax.numpy as jnp

    if a.shape[-1] != b.shape[-1]:
        raise ValueError(
            f"latent channel counts differ ({a.shape[-1]} vs {b.shape[-1]} — "
            "e.g. an SD1.5 latent mixed with an SD3/FLUX one); latent math "
            "needs same-family latents"
        )
    if a.shape[1:-1] != b.shape[1:-1]:
        b = jax.image.resize(
            b, (b.shape[0], *a.shape[1:-1], b.shape[-1]), method="bilinear"
        )
    return _repeat_to_batch(b, a.shape[0])


def _latent_binop(stock_name: str, fn):
    class _Op:
        DESCRIPTION = f"Stock-name latent op {stock_name}."
        RETURN_TYPES = ("LATENT",)
        RETURN_NAMES = ("latent",)
        FUNCTION = "op"
        CATEGORY = CATEGORY

        @classmethod
        def INPUT_TYPES(cls):
            return {"required": {"samples1": ("LATENT", {}),
                                 "samples2": ("LATENT", {})}}

        def op(self, samples1, samples2):
            import jax.numpy as jnp

            a = jnp.asarray(samples1["samples"])
            b = _reshape_latent_to(a, jnp.asarray(samples2["samples"]))
            return ({**samples1, "samples": fn(a, b)},)

    _Op.__name__ = stock_name
    return _Op


class LatentInterpolate:
    """Stock norm-preserving latent interpolation: directions lerp after
    per-pixel channel-norm normalization, magnitudes lerp separately, then
    recombine (nodes_latent.py LatentInterpolate)."""

    DESCRIPTION = "Stock-name norm-preserving latent interpolate."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "op"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "samples1": ("LATENT", {}),
            "samples2": ("LATENT", {}),
            "ratio": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0,
                                "step": 0.01}),
        }}

    def op(self, samples1, samples2, ratio: float):
        import jax.numpy as jnp

        a = jnp.asarray(samples1["samples"])
        b = _reshape_latent_to(a, jnp.asarray(samples2["samples"]))
        r = float(ratio)
        # Channel-axis norms (torch dim=1 on NCHW == our last axis).
        na = jnp.linalg.norm(a, axis=-1, keepdims=True)
        nb = jnp.linalg.norm(b, axis=-1, keepdims=True)
        da = jnp.where(na > 0, a / jnp.maximum(na, 1e-12), 0.0)
        db = jnp.where(nb > 0, b / jnp.maximum(nb, 1e-12), 0.0)
        t = da * r + db * (1.0 - r)
        nt = jnp.linalg.norm(t, axis=-1, keepdims=True)
        st = jnp.where(nt > 0, t / jnp.maximum(nt, 1e-12), 0.0)
        return ({**samples1,
                 "samples": st * (na * r + nb * (1.0 - r))},)


class LatentMultiply:
    """Stock scalar latent multiply (samples × multiplier) — unlike
    Add/Subtract this one takes a FLOAT, not a second latent."""

    DESCRIPTION = "Stock-name latent scalar multiply."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "op"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "samples": ("LATENT", {}),
            "multiplier": ("FLOAT", {"default": 1.0, "min": -10.0,
                                     "max": 10.0, "step": 0.01}),
        }}

    def op(self, samples, multiplier: float):
        import jax.numpy as jnp

        return ({**samples,
                 "samples": jnp.asarray(samples["samples"])
                 * float(multiplier)},)


class LatentBatch:
    """Stock latent batch join (resizes the second to the first's grid like
    ImageBatch)."""

    DESCRIPTION = "Stock-name latent batch concat."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "batch"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"samples1": ("LATENT", {}),
                             "samples2": ("LATENT", {})}}

    def batch(self, samples1, samples2):
        import jax
        import jax.numpy as jnp

        a = jnp.asarray(samples1["samples"])
        b = jnp.asarray(samples2["samples"])
        if a.shape[1:-1] != b.shape[1:-1]:
            b = jax.image.resize(
                b, (b.shape[0], *a.shape[1:-1], b.shape[-1]),
                method="bilinear",
            )
        return ({**samples1, "samples": jnp.concatenate([a, b], axis=0)},)


class KarrasScheduler:
    """Stock custom-sampling Karras sigma node → SIGMAS wire
    (sampling/k_samplers.karras_sigmas)."""

    DESCRIPTION = "Stock-name Karras sigma schedule."
    RETURN_TYPES = ("SIGMAS",)
    RETURN_NAMES = ("sigmas",)
    FUNCTION = "get_sigmas"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "steps": ("INT", {"default": 20, "min": 1, "max": 10000}),
            "sigma_max": ("FLOAT", {"default": 14.614642, "min": 0.0,
                                    "max": 5000.0, "step": 0.01}),
            "sigma_min": ("FLOAT", {"default": 0.0291675, "min": 0.0,
                                    "max": 5000.0, "step": 0.01}),
            "rho": ("FLOAT", {"default": 7.0, "min": 0.0, "max": 100.0,
                              "step": 0.01}),
        }}

    def get_sigmas(self, steps: int, sigma_max: float, sigma_min: float,
                   rho: float):
        from .sampling.k_samplers import karras_sigmas

        return (karras_sigmas(int(steps), sigma_min=float(sigma_min),
                              sigma_max=float(sigma_max), rho=float(rho)),)


class ExponentialScheduler:
    DESCRIPTION = "Stock-name exponential (log-uniform) sigma schedule."
    RETURN_TYPES = ("SIGMAS",)
    RETURN_NAMES = ("sigmas",)
    FUNCTION = "get_sigmas"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "steps": ("INT", {"default": 20, "min": 1, "max": 10000}),
            "sigma_max": ("FLOAT", {"default": 14.614642, "min": 0.0,
                                    "max": 5000.0, "step": 0.01}),
            "sigma_min": ("FLOAT", {"default": 0.0291675, "min": 0.0,
                                    "max": 5000.0, "step": 0.01}),
        }}

    def get_sigmas(self, steps: int, sigma_max: float, sigma_min: float):
        from .sampling.k_samplers import exponential_sigmas

        return (exponential_sigmas(int(steps), sigma_min=float(sigma_min),
                                   sigma_max=float(sigma_max)),)


class SDTurboScheduler:
    """Stock SD-Turbo schedule: the model's top ``steps`` trained sigmas
    offset by denoise (turbo models sample in 1-4 steps from raw table
    entries, not interpolated spacings)."""

    DESCRIPTION = "Stock-name SD-Turbo sigma schedule."
    RETURN_TYPES = ("SIGMAS",)
    RETURN_NAMES = ("sigmas",)
    FUNCTION = "get_sigmas"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "steps": ("INT", {"default": 1, "min": 1, "max": 10}),
            "denoise": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 1.0,
                                  "step": 0.01}),
        }}

    def get_sigmas(self, model, steps: int, denoise: float = 1.0):
        import jax.numpy as jnp

        from .sampling.k_samplers import model_sigmas
        from .sampling.schedules import scaled_linear_schedule

        pred = getattr(getattr(model, "config", None), "prediction", "eps")
        if pred == "flow":
            raise ValueError(
                "SDTurboScheduler reads the SD eps/v trained-sigma ladder — "
                "flow-family models schedule with BasicScheduler instead"
            )
        # Stock: a fixed 10-rung ladder of trained timesteps [999, 899, …,
        # 99], sliced [start : start+steps] with start = 10 − int(10·denoise)
        # — slicing TRUNCATES past the end (no clamping: a repeated sigma
        # would divide-by-zero the multistep samplers).
        table = model_sigmas(scaled_linear_schedule())
        ladder = [i * 100 - 1 for i in range(10, 0, -1)]
        start = 10 - int(10 * float(denoise))
        idx = ladder[start:start + int(steps)]
        if not idx:
            raise ValueError(
                f"denoise {denoise} leaves no turbo steps (start rung "
                f"{start} of 10)"
            )
        sig = table[jnp.asarray(idx, jnp.int32)]
        return (jnp.concatenate([sig, jnp.zeros((1,), jnp.float32)]),)


def _named_sampler(stock_name: str, sampler_name: str):
    """A stock named-sampler node (SamplerEulerAncestral, …) → SAMPLER wire.
    Stock variants carry eta/noise widgets; the TPU samplers run their
    k-diffusion defaults, so the wires are name-only (divergence documented
    in the sampler module)."""

    class _Named:
        DESCRIPTION = f"Stock-name SAMPLER wire for {sampler_name}."
        RETURN_TYPES = ("SAMPLER",)
        RETURN_NAMES = ("sampler",)
        FUNCTION = "get_sampler"
        CATEGORY = CATEGORY

        @classmethod
        def INPUT_TYPES(cls):
            return {"required": {}}

        def get_sampler(self, **_ignored):
            return ({"sampler": sampler_name},)

    _Named.__name__ = stock_name
    return _Named


class SamplerCustom:
    """Stock SamplerCustom — the older one-box custom-sampling driver (MODEL
    + conds + SAMPLER + SIGMAS in one node, vs SamplerCustomAdvanced's
    NOISE/GUIDER split). Composes the same wires and delegates."""

    DESCRIPTION = "Stock-name custom-sampling driver (pre-Advanced form)."
    RETURN_TYPES = ("LATENT", "LATENT")
    RETURN_NAMES = ("output", "denoised_output")
    FUNCTION = "sample"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "add_noise": ("BOOLEAN", {"default": True}),
            "noise_seed": ("INT", {"default": 0, "min": 0, "max": 2**64 - 1}),
            "cfg": ("FLOAT", {"default": 8.0, "min": 0.0, "max": 100.0}),
            "positive": ("CONDITIONING", {}),
            "negative": ("CONDITIONING", {}),
            "sampler": ("SAMPLER", {}),
            "sigmas": ("SIGMAS", {}),
            "latent_image": ("LATENT", {}),
        }}

    def sample(self, model, add_noise, noise_seed: int, cfg: float,
               positive, negative, sampler, sigmas, latent_image):
        from .nodes import TPUSamplerCustomAdvanced

        noise = {"seed": int(noise_seed) if add_noise else None}
        guider = {"model": model, "positive": positive,
                  "negative": negative, "cfg": float(cfg)}
        return TPUSamplerCustomAdvanced().sample(
            noise, guider, sampler, sigmas, latent_image
        )


class unCLIPCheckpointLoader:
    """Stock unCLIP loader: the sd21-unclip single file bundles a FOURTH
    component — its ViT-H image encoder (OpenCLIP layout under
    ``embedder.model.visual.*``) — which feeds CLIPVisionEncode →
    unCLIPConditioning. Model/CLIP/VAE load exactly like
    CheckpointLoaderSimple (family sniffed)."""

    DESCRIPTION = "Stock-name unCLIP checkpoint loader (incl. vision tower)."
    RETURN_TYPES = ("MODEL", "CLIP", "VAE", "CLIP_VISION")
    RETURN_NAMES = ("model", "clip", "vae", "clip_vision")
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"ckpt_name": ("STRING", {"default": ""})}}

    def load(self, ckpt_name: str):
        from .models.loader import (
            load_safetensors_subset,
            peek_safetensors,
        )
        from .models.vision import build_clip_vision, convert_clip_vision_checkpoint

        pfx = "embedder.model.visual."
        # Header peek BEFORE materializing anything: pointing this node at a
        # plain multi-GB checkpoint must fail in milliseconds, not after the
        # whole model/clip/vae convert.
        path = resolve_model_file(ckpt_name, "checkpoints")
        if not any(k.startswith(pfx) for k in peek_safetensors(path)):
            raise ValueError(
                "checkpoint has no bundled image encoder "
                f"({pfx}*) — not an unCLIP checkpoint; use "
                "CheckpointLoaderSimple + CLIPVisionLoader instead"
            )
        model, clip, vae = CheckpointLoaderSimple().load(ckpt_name)
        tower = load_safetensors_subset(path, pfx)
        params, vcfg = convert_clip_vision_checkpoint(
            {k[len(pfx):]: v for k, v in tower.items()}
        )
        vision = build_clip_vision(vcfg, params=params, name="unclip-vision")
        return model, clip, vae, {"model": vision}


class ModelSamplingDiscrete:
    """Stock prediction-type override: exported workflows fix v-prediction
    checkpoints (weight-indistinguishable from eps — see the sniffing
    warning in models/loader.py) with this node; here it rewrites
    ``config.prediction``, which the samplers read. ``zsnr`` (zero-terminal-
    SNR sigma rescale) is accepted but not applied — logged divergence, the
    sampling still runs."""

    DESCRIPTION = "Stock-name prediction-type (eps/v) model patch."
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "patch"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "sampling": (["eps", "v_prediction", "lcm", "x0"],
                         {"default": "eps"}),
            "zsnr": ("BOOLEAN", {"default": False}),
        }}

    def patch(self, model, sampling: str = "eps", zsnr: bool = False):
        import dataclasses as dc

        from .utils.logging import get_logger

        pred = {"eps": "eps", "v_prediction": "v"}.get(sampling)
        if pred is None:
            raise ValueError(
                f"ModelSamplingDiscrete sampling={sampling!r} is not "
                "supported (eps / v_prediction are)"
            )
        if zsnr:
            get_logger().warning(
                "ModelSamplingDiscrete zsnr=True: zero-terminal-SNR sigma "
                "rescale is not applied (documented divergence) — sampling "
                "proceeds with the standard schedule"
            )
        cfg = getattr(model, "config", None)
        if (not dc.is_dataclass(model) or cfg is None
                or not dc.is_dataclass(cfg) or not hasattr(cfg, "prediction")):
            # A ParallelModel's .config is a ParallelConfig (dataclass, no
            # prediction field) — the guard must catch it, not fall through
            # to an opaque dc.replace TypeError.
            raise ValueError(
                "ModelSamplingDiscrete needs an unwrapped MODEL whose config "
                f"carries a prediction field (got {type(model).__name__}); "
                "apply it before ParallelAnything"
            )
        # source/sampler_prefs are DiffusionModel FIELDS, so dc.replace
        # carries them (downstream LoraLoader depends on source).
        return (dc.replace(model, config=dc.replace(cfg, prediction=pred)),)


class EmptyHunyuanLatentVideo:
    """Stock empty VIDEO latent (the t2v entry of WAN/Hunyuan template
    exports): 16-channel, 8x spatial, 4x temporal compression —
    (B, (length-1)//4+1, H/8, W/8, 16) in this repo's NTHWC convention."""

    DESCRIPTION = "Stock-name empty video latent (WAN/Hunyuan t2v)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "generate"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "width": ("INT", {"default": 848, "min": 16, "max": 8192,
                              "step": 16}),
            "height": ("INT", {"default": 480, "min": 16, "max": 8192,
                               "step": 16}),
            "length": ("INT", {"default": 25, "min": 1, "max": 1024,
                               "step": 4}),
            "batch_size": ("INT", {"default": 1, "min": 1, "max": 16}),
        }}

    def generate(self, width: int, height: int, length: int,
                 batch_size: int = 1):
        from .nodes import TPUEmptyVideoLatent

        # Stock floors off-schedule lengths (((length-1)//4)+1 latent
        # frames); API submissions bypass widget steps, so accept any length.
        frames = max(1, (int(length) - 1) // 4 * 4 + 1)
        # Delegate: the TPU node derives t_lat/spatial factor AND the
        # default channel count from wan_vae_config (single owner).
        return TPUEmptyVideoLatent().generate(
            width=width, height=height, frames=frames, batch_size=batch_size
        )


class _FreeUBase:
    """Shared FreeU patch machinery: rebuild the UNet module around the SAME
    params with ``cfg.freeu`` set (the patch is an architecture knob here, so
    it survives conversion/parallelize like any other config field). Applies
    to SD-family UNET models, before ParallelAnything — stock ordering."""

    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "patch"
    CATEGORY = CATEGORY
    _VERSION = 2

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "b1": ("FLOAT", {"default": 1.3 if cls._VERSION >= 2 else 1.1,
                             "min": 0.0, "max": 10.0, "step": 0.01}),
            "b2": ("FLOAT", {"default": 1.4 if cls._VERSION >= 2 else 1.2,
                             "min": 0.0, "max": 10.0, "step": 0.01}),
            "s1": ("FLOAT", {"default": 0.9, "min": 0.0, "max": 10.0,
                             "step": 0.01}),
            "s2": ("FLOAT", {"default": 0.2, "min": 0.0, "max": 10.0,
                             "step": 0.01}),
        }}

    def patch(self, model, b1: float, b2: float, s1: float, s2: float):
        import dataclasses as dc

        from .models import build_unet
        from .models.unet import UNetConfig

        cfg = getattr(model, "config", None)
        if not isinstance(cfg, UNetConfig):
            raise ValueError(
                "FreeU patches SD-family UNET models (config "
                f"{type(cfg).__name__}); apply it between the checkpoint "
                "loader and ParallelAnything/KSampler"
            )
        patched = build_unet(
            dc.replace(cfg, freeu=(float(b1), float(b2), float(s1),
                                   float(s2), self._VERSION)),
            params=model.params, name=f"{model.name}+freeu",
        )
        # build_unet constructs a FRESH DiffusionModel: carry the loader's
        # source tag (LoraLoader re-bakes from it) and any sampler prefs.
        return (dc.replace(patched, sampler_prefs=model.sampler_prefs,
                           source=getattr(model, "source", None)),)


class FreeU(_FreeUBase):
    DESCRIPTION = "Stock-name FreeU model patch (v1: constant backbone scale)."
    _VERSION = 1


class FreeU_V2(_FreeUBase):
    DESCRIPTION = "Stock-name FreeU_V2 model patch (hidden-mean-modulated)."
    _VERSION = 2


class RescaleCFG:
    """Stock RescaleCFG model patch: tags the MODEL with a cfg_rescale
    default the samplers honor (sampling/cfg.rescale_guidance — Lin et al.
    2023). An explicit non-zero cfg_rescale widget on a sampler node wins."""

    DESCRIPTION = "Stock-name CFG-rescale model patch."
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "patch"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "multiplier": ("FLOAT", {"default": 0.7, "min": 0.0, "max": 1.0,
                                     "step": 0.01}),
        }}

    def patch(self, model, multiplier: float):
        import copy
        import dataclasses as dc

        prefs = {**(getattr(model, "sampler_prefs", None) or {}),
                 "cfg_rescale": float(multiplier)}
        if dc.is_dataclass(model) and not isinstance(model, type):
            return (dc.replace(model, sampler_prefs=prefs),)
        # ParallelModel and friends: shallow-copy the wrapper (placements are
        # shared; the copy carries no GC finalizer, the original owns
        # teardown) and tag the copy.
        m = copy.copy(model)
        m.sampler_prefs = prefs
        return (m,)


def _patch_sampler_prefs(model, **updates):
    """Merge ``updates`` into the MODEL's sampler_prefs (the RescaleCFG
    carrier): dataclass models get dc.replace, ParallelModel wrappers a
    shallow copy (placements shared; the copy carries no GC finalizer)."""
    import copy
    import dataclasses as dc

    prefs = {**(getattr(model, "sampler_prefs", None) or {}), **updates}
    if dc.is_dataclass(model) and not isinstance(model, type):
        return dc.replace(model, sampler_prefs=prefs)
    m = copy.copy(model)
    m.sampler_prefs = prefs
    return m


class ModelSamplingSD3:
    """Stock SD3 schedule patch: tags the MODEL with the rectified-flow
    timestep shift (default 3.0 — SD3's trained resolution shift). The
    samplers and BasicScheduler read it as their shift default; an explicit
    non-default shift widget wins (same precedence as RescaleCFG's
    cfg_rescale)."""

    DESCRIPTION = "Stock-name SD3 flow-shift model patch."
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "patch"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "shift": ("FLOAT", {"default": 3.0, "min": 0.0, "max": 100.0,
                                "step": 0.01}),
        }}

    def patch(self, model, shift: float = 3.0):
        return (_patch_sampler_prefs(model, shift=float(shift)),)


class ModelSamplingFlux:
    """Stock FLUX schedule patch: the resolution-dependent flow shift. Stock
    linearly interpolates the LOG-shift (mu) over the latent token count —
    base_shift at 256 tokens to max_shift at 4096 — and warps with
    exp(mu)·t/(1+(exp(mu)−1)·t); at the 1024² defaults the effective shift is
    exp(1.15) ≈ 3.16. The exp(mu) value lands in sampler_prefs as the
    samplers' shift default (explicit non-default widget wins)."""

    DESCRIPTION = "Stock-name FLUX resolution-shift model patch."
    RETURN_TYPES = ("MODEL",)
    RETURN_NAMES = ("model",)
    FUNCTION = "patch"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "model": ("MODEL", {}),
            "max_shift": ("FLOAT", {"default": 1.15, "min": 0.0, "max": 100.0,
                                    "step": 0.01}),
            "base_shift": ("FLOAT", {"default": 0.5, "min": 0.0, "max": 100.0,
                                     "step": 0.01}),
            "width": ("INT", {"default": 1024, "min": 16, "max": 16384}),
            "height": ("INT", {"default": 1024, "min": 16, "max": 16384}),
        }}

    def patch(self, model, max_shift: float = 1.15, base_shift: float = 0.5,
              width: int = 1024, height: int = 1024):
        import math

        # Latent tokens: 8x VAE downsample then 2x2 patchify → (w/16)·(h/16).
        tokens = (width / 16.0) * (height / 16.0)
        m = (max_shift - base_shift) / (4096.0 - 256.0)
        mu = tokens * m + (base_shift - m * 256.0)
        return (_patch_sampler_prefs(model, shift=float(math.exp(mu))),)


class ConditioningSetMask:
    """Stock mask-scoped conditioning: the cond's prediction applies with
    per-pixel weight from a MASK (resized to the latent grid at sampling
    time). ``set_cond_area`` accepted for export parity — "mask bounds" is
    stock's compute-crop optimization and produces the same weights as
    "default" here."""

    DESCRIPTION = "Stock-name mask-scoped conditioning."
    RETURN_TYPES = ("CONDITIONING",)
    RETURN_NAMES = ("conditioning",)
    FUNCTION = "append"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {
            "conditioning": ("CONDITIONING", {}),
            "mask": ("MASK", {}),
            "strength": ("FLOAT", {"default": 1.0, "min": 0.0, "max": 10.0,
                                   "step": 0.01}),
            "set_cond_area": (["default", "mask bounds"],
                              {"default": "default"}),
        }}

    def append(self, conditioning, mask, strength: float = 1.0,
               set_cond_area: str = "default"):
        import jax.numpy as jnp

        # Own key, NOT "strength": stock keeps area strength and mask
        # strength separate and MULTIPLIES them (get_area_and_mult) — a
        # shared key would have SetArea/SetMask clobber each other.
        tag = {"mask": jnp.asarray(mask, jnp.float32),
               "mask_strength": float(strength)}
        return (_tag_all_entries(conditioning, tag),)


class VAEDecodeTiled:
    """Stock tiled decode: bounded activation memory at any resolution.
    ``tile_size`` is in PIXELS like stock (converted to latent cells by the
    VAE's spatial factor); the tile/overlap policy itself lives with its
    single owner, ``models/vae.decode_maybe_tiled``. Stock's newer
    ``overlap``/``temporal_size``/``temporal_overlap`` widgets are accepted
    so current exports run unchanged — overlap is owner-derived and the
    temporal knobs don't apply to spatial tiling here."""

    DESCRIPTION = "Stock-name tiled VAE decode."
    RETURN_TYPES = ("IMAGE",)
    RETURN_NAMES = ("image",)
    FUNCTION = "decode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT", {}),
                "vae": ("VAE", {}),
                "tile_size": ("INT", {"default": 512, "min": 64, "max": 4096,
                                      "step": 32}),
            },
            "optional": {
                "overlap": ("INT", {"default": 64, "min": 0, "max": 4096}),
                "temporal_size": ("INT", {"default": 64, "min": 8,
                                          "max": 4096}),
                "temporal_overlap": ("INT", {"default": 8, "min": 4,
                                             "max": 4096}),
            },
        }

    def decode(self, samples, vae, tile_size: int = 512, overlap: int = 64,
               temporal_size: int = 64, temporal_overlap: int = 8):
        from .models.vae import decode_maybe_tiled, vae_output_to_images

        factor = getattr(vae, "spatial_factor", 8)
        tile = max(8, int(tile_size) // factor)
        return (vae_output_to_images(
            decode_maybe_tiled(vae, samples["samples"], tile)
        ),)


class VAEEncodeTiled:
    """Stock tiled encode — the img2img counterpart of VAEDecodeTiled for
    resolutions whose encoder activations exceed HBM. Tile/overlap policy via
    its owner ``models/vae.encode_maybe_tiled`` (pixel-unit tile, overlap
    floored to the VAE's spatial-factor alignment)."""

    DESCRIPTION = "Stock-name tiled VAE encode."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "encode"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "pixels": ("IMAGE", {}),
                "vae": ("VAE", {}),
                "tile_size": ("INT", {"default": 512, "min": 64, "max": 4096,
                                      "step": 64}),
            },
            "optional": {
                "overlap": ("INT", {"default": 64, "min": 0, "max": 4096}),
                "temporal_size": ("INT", {"default": 64, "min": 8,
                                          "max": 4096}),
                "temporal_overlap": ("INT", {"default": 8, "min": 4,
                                             "max": 4096}),
            },
        }

    def encode(self, pixels, vae, tile_size: int = 512, overlap: int = 64,
               temporal_size: int = 64, temporal_overlap: int = 8):
        import jax.numpy as jnp

        from .models.vae import encode_maybe_tiled, images_to_vae_input

        img = jnp.asarray(pixels)
        if img.ndim == 3:
            img = img[None]
        z = encode_maybe_tiled(vae, images_to_vae_input(img), int(tile_size))
        return ({"samples": z},)


def stock_node_mappings() -> dict[str, type]:
    """All stock-name shims, keyed by the stock class name (merged into
    ``nodes.NODE_CLASS_MAPPINGS`` so exported workflows resolve directly)."""
    from . import nodes as n

    LoadImage.RETURN_TYPES = n.TPULoadImage.RETURN_TYPES
    LoadImage.RETURN_NAMES = getattr(n.TPULoadImage, "RETURN_NAMES", None)

    mappings = {
        "CheckpointLoaderSimple": CheckpointLoaderSimple,
        "DualCLIPLoader": DualCLIPLoader,
        "CLIPLoader": CLIPLoader,
        "TripleCLIPLoader": TripleCLIPLoader,
        "VAELoader": VAELoader,
        "UNETLoader": UNETLoader,
        "unCLIPConditioning": unCLIPConditioning,
        "LoraLoader": LoraLoader,
        "LoraLoaderModelOnly": LoraLoaderModelOnly,
        "CLIPSetLastLayer": CLIPSetLastLayer,
        "LoadImage": LoadImage,
        "LatentUpscale": LatentUpscale,
        # Pure renames.
        "CLIPTextEncode": _renamed(n.TPUTextEncode, {}, name="CLIPTextEncode"),
        "EmptyLatentImage": _renamed(
            n.TPUEmptyLatent, {}, name="EmptyLatentImage"
        ),
        "EmptySD3LatentImage": _EmptyLatent16ch,
        "KSampler": _renamed(
            n.TPUKSampler, {"latent_image": "latent"}, name="KSampler"
        ),
        "KSamplerAdvanced": _renamed(
            n.TPUKSamplerAdvanced, {}, name="KSamplerAdvanced"
        ),
        "VAEDecode": _renamed(
            n.TPUVAEDecode, {"samples": "latent"}, name="VAEDecode"
        ),
        "VAEEncode": _renamed(
            n.TPUVAEEncode, {"pixels": "image"}, name="VAEEncode"
        ),
        "SaveImage": _renamed(n.TPUSaveImage, {}, name="SaveImage"),
        "ImageScale": ImageScale,
        "ImageScaleBy": ImageScaleBy,
        "PreviewImage": PreviewImage,
        "ConditioningCombine": ConditioningCombine,
        "ConditioningSetArea": ConditioningSetArea,
        "ConditioningSetMask": ConditioningSetMask,
        "ConditioningSetAreaPercentage": ConditioningSetAreaPercentage,
        "CLIPTextEncodeFlux": CLIPTextEncodeFlux,
        "FreeU": FreeU,
        "FreeU_V2": FreeU_V2,
        "RescaleCFG": RescaleCFG,
        "ModelSamplingDiscrete": ModelSamplingDiscrete,
        "ModelSamplingSD3": ModelSamplingSD3,
        "ModelSamplingFlux": ModelSamplingFlux,
        "unCLIPCheckpointLoader": unCLIPCheckpointLoader,
        "SamplerCustom": SamplerCustom,
        "ImageCrop": ImageCrop,
        "ImageScaleToTotalPixels": ImageScaleToTotalPixels,
        "ModelMergeSimple": ModelMergeSimple,
        "ImageBlur": ImageBlur,
        "ImageSharpen": ImageSharpen,
        "LatentBlend": LatentBlend,
        "LatentBatch": LatentBatch,
        "LatentAdd": _latent_binop("LatentAdd", lambda a, b: a + b),
        "LatentSubtract": _latent_binop("LatentSubtract", lambda a, b: a - b),
        "LatentInterpolate": LatentInterpolate,
        "LatentMultiply": LatentMultiply,
        "KarrasScheduler": KarrasScheduler,
        "ExponentialScheduler": ExponentialScheduler,
        "SDTurboScheduler": SDTurboScheduler,
        "SamplerEulerAncestral": _named_sampler("SamplerEulerAncestral",
                                                "euler_ancestral"),
        "SamplerDPMPP_2M_SDE": _named_sampler("SamplerDPMPP_2M_SDE",
                                              "dpmpp_2m_sde"),
        "SamplerDPMPP_SDE": _named_sampler("SamplerDPMPP_SDE", "dpmpp_sde"),
        "SamplerDPMPP_3M_SDE": _named_sampler("SamplerDPMPP_3M_SDE",
                                              "dpmpp_3m_sde"),
        "SamplerLMS": _named_sampler("SamplerLMS", "lms"),
        "EmptyHunyuanLatentVideo": EmptyHunyuanLatentVideo,
        "ConditioningAverage": ConditioningAverage,
        "ConditioningZeroOut": ConditioningZeroOut,
        "ConditioningSetTimestepRange": ConditioningSetTimestepRange,
        "ConditioningConcat": ConditioningConcat,
        "CLIPTextEncodeSDXL": CLIPTextEncodeSDXL,
        "CLIPTextEncodeSDXLRefiner": CLIPTextEncodeSDXLRefiner,
        "ImageInvert": ImageInvert,
        "ImageBatch": ImageBatch,
        "RepeatLatentBatch": RepeatLatentBatch,
        "LatentFromBatch": LatentFromBatch,
        "LatentFlip": LatentFlip,
        "LatentRotate": LatentRotate,
        "LatentCrop": LatentCrop,
        "SaveLatent": SaveLatent,
        "LoadLatent": LoadLatent,
        "SolidMask": SolidMask,
        "InvertMask": InvertMask,
        "ImageToMask": ImageToMask,
        "MaskToImage": MaskToImage,
        "GrowMask": GrowMask,
        "FeatherMask": FeatherMask,
        "MaskComposite": MaskComposite,
        "LoadImageMask": LoadImageMask,
        "VAEEncodeForInpaint": VAEEncodeForInpaint,
        "VAEDecodeTiled": VAEDecodeTiled,
        "VAEEncodeTiled": VAEEncodeTiled,
        "ImagePadForOutpaint": ImagePadForOutpaint,
        "ImageCompositeMasked": ImageCompositeMasked,
        "LatentComposite": LatentComposite,
        "SaveAnimatedWEBP": SaveAnimatedWEBP,
        "ControlNetLoader": ControlNetLoader,
        "ControlNetApply": ControlNetApply,
        "ControlNetApplyAdvanced": ControlNetApplyAdvanced,
        "CLIPVisionLoader": CLIPVisionLoader,
        "CLIPVisionEncode": CLIPVisionEncode,
        "WanImageToVideo": WanImageToVideo,
        "UpscaleModelLoader": UpscaleModelLoader,
        "ImageUpscaleWithModel": _renamed(
            n.TPUImageUpscaleWithModel, {}, name="ImageUpscaleWithModel"
        ),
        # Stock-shaped from the start (same widget names).
        "InpaintModelConditioning": _renamed(
            n.TPUInpaintModelConditioning, {}, name="InpaintModelConditioning"
        ),
        "LatentUpscaleBy": _renamed(
            n.TPULatentUpscale, {"samples": "latent", "scale_by": "scale",
                                 "upscale_method": "method"},
            name="LatentUpscaleBy",
        ),
        "SetLatentNoiseMask": _renamed(
            n.TPUSetLatentNoiseMask, {"samples": "latent"},
            name="SetLatentNoiseMask",
        ),
        # Custom-sampling family: built stock-shaped from the start.
        "RandomNoise": _renamed(n.TPURandomNoise, {}, name="RandomNoise"),
        "DisableNoise": _renamed(n.TPUDisableNoise, {}, name="DisableNoise"),
        "KSamplerSelect": _renamed(
            n.TPUKSamplerSelect, {}, name="KSamplerSelect"
        ),
        "BasicScheduler": _renamed(
            n.TPUBasicScheduler, {}, name="BasicScheduler"
        ),
        "BasicGuider": _renamed(n.TPUBasicGuider, {}, name="BasicGuider"),
        "CFGGuider": _renamed(n.TPUCFGGuider, {}, name="CFGGuider"),
        "FluxGuidance": _renamed(n.TPUFluxGuidance, {}, name="FluxGuidance"),
        "SamplerCustomAdvanced": _renamed(
            n.TPUSamplerCustomAdvanced, {}, name="SamplerCustomAdvanced"
        ),
        "SplitSigmas": _renamed(n.TPUSplitSigmas, {}, name="SplitSigmas"),
        "FlipSigmas": _renamed(n.TPUFlipSigmas, {}, name="FlipSigmas"),
    }
    return mappings


def register(
    node_class_mappings: dict[str, type],
    display_name_mappings: dict[str, str] | None = None,
) -> None:
    """Merge the shims into a registry without overriding native names."""
    for name, cls in stock_node_mappings().items():
        node_class_mappings.setdefault(name, cls)
        if display_name_mappings is not None:
            display_name_mappings.setdefault(name, f"{name} (stock compat)")
