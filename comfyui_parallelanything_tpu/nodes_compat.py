"""Stock-ComfyUI node-name compatibility shims.

Workflows exported from a stock ComfyUI install reference the builtin node
class names — ``CheckpointLoaderSimple``, ``CLIPTextEncode``, ``KSampler``,
``VAEDecode``, … — not this package's ``TPU*`` names. The reference node pack
runs *inside* ComfyUI and gets those builtins for free
(any_device_parallel.py:1473-1483 registers only its own nodes); this package
hosts the graph itself (host.py), so builtin-name coverage is part of the
parity surface: with these shims an exported API-format workflow runs
unchanged.

Each shim is a thin adapter over the corresponding ``TPU*`` node: it renames
stock input keys (``latent_image``→``latent``, ``samples``→``latent``,
``pixels``→``image``), resolves bare file names against the ComfyUI directory
layout (``$PA_MODELS_DIR/checkpoints`` etc.), and sniffs what stock nodes
leave implicit (the model family, via ``models.loader.sniff_model_family``).
Custom-sampling nodes (RandomNoise, BasicScheduler, SamplerCustomAdvanced, …)
were already built with stock-matching input names and alias directly.

File resolution env vars (the stand-ins for ComfyUI's folder_paths):

- ``PA_MODELS_DIR``  (default ``models``): ``checkpoints/``, ``clip/``,
  ``vae/``, ``loras/`` subdirs are searched, then the dir itself, then the
  bare name as a path.
- ``PA_INPUT_DIR``   (default ``input``): ``LoadImage`` names.
- ``PA_TOKENIZER_JSON`` / ``PA_CLIP_VOCAB`` + ``PA_CLIP_MERGES``: tokenizer
  tables for CLIP towers extracted from bundled checkpoints (checkpoints
  carry encoder weights but never tokenizer data).
- ``PA_T5_TOKENIZER_JSON``: tokenizer for the T5/UMT5 tower
  (``DualCLIPLoader``).
"""

from __future__ import annotations

import os

CATEGORY = "TPU-ParallelAnything/compat"


def _models_dir() -> str:
    return os.environ.get("PA_MODELS_DIR", "models")


def resolve_model_file(name: str, *subdirs: str) -> str:
    """A stock widget's bare file name → an existing path, searched through
    the ComfyUI folder layout; falls back to the name itself (absolute paths
    and cwd-relative paths keep working)."""
    root = _models_dir()
    for sub in subdirs:
        cand = os.path.join(root, sub, name)
        if os.path.exists(cand):
            return cand
    cand = os.path.join(root, name)
    if os.path.exists(cand):
        return cand
    return name


def _clip_tokenizer(max_len: int = 77, pad_id: int | None = None):
    """CLIP BPE tokenizer from env-configured tables, or None (checkpoints
    bundle encoder weights but never tokenizer data — the error surfaces at
    encode time with instructions, not at load time)."""
    tok_json = os.environ.get("PA_TOKENIZER_JSON", "")
    vocab = os.environ.get("PA_CLIP_VOCAB", "")
    merges = os.environ.get("PA_CLIP_MERGES", "")
    from .utils.tokenizer import CLIPBPETokenizer, load_tokenizer_json

    if tok_json:
        return load_tokenizer_json(tok_json, max_len=max_len)
    if vocab and merges:
        return CLIPBPETokenizer.from_files(
            vocab, merges, max_len=max_len, pad_id=pad_id
        )
    return None


_TOKENIZER_HELP = (
    "checkpoints bundle text-encoder weights but never tokenizer tables; set "
    "PA_TOKENIZER_JSON (a tokenizer.json) or PA_CLIP_VOCAB + PA_CLIP_MERGES "
    "(vocab.json + merges.txt), or wire a TPUCLIPLoader node instead"
)


class CheckpointLoaderSimple:
    """Stock loader: (ckpt_name) → (MODEL, CLIP, VAE). Family is sniffed off
    the checkpoint keys (stock has no family widget); CLIP comes from the
    bundled ``cond_stage_model``/``conditioner`` towers for the SD families
    (SDXL gets the dual L+G wire TPUTextEncode combines)."""

    DESCRIPTION = "Stock-name checkpoint loader (family sniffed, bundled CLIP)."
    RETURN_TYPES = ("MODEL", "CLIP", "VAE")
    RETURN_NAMES = ("model", "clip", "vae")
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"ckpt_name": ("STRING", {"default": ""})}}

    def load(self, ckpt_name: str):
        from .models.loader import peek_safetensors, sniff_model_family
        from .nodes import TPUCheckpointLoader

        path = resolve_model_file(ckpt_name, "checkpoints")
        # Family sniffing needs only key names + two shapes: peek the header
        # instead of materializing a multi-GB file twice (the full read
        # happens once, inside TPUCheckpointLoader).
        family = sniff_model_family(peek_safetensors(path))
        model, vae = TPUCheckpointLoader().load(ckpt_path=path, family=family)
        # Source tag: the LoraLoader shim re-bakes from the original file
        # (LoRA applies to the checkpoint layout pre-conversion). Same
        # object.__setattr__ route the frozen dataclass uses for _jit_cache.
        object.__setattr__(model, "source", {"path": path, "family": family})
        return model, self._bundled_clip(path, family), vae

    def _bundled_clip(self, path, family: str):
        from .models import load_clip_text_checkpoint
        from .models.loader import load_safetensors_subset

        def error_wire(msg: str):
            return {"encoder": None, "tokenizer": None, "type": "error",
                    "tokenizer_error": msg}

        try:
            if family in ("sd15", "sd21", "sd21-v"):
                open_clip = family.startswith("sd21")
                cfg = None
                if open_clip:
                    from .models import open_clip_h_config

                    cfg = open_clip_h_config()
                tower = load_safetensors_subset(path, "cond_stage_model.")
                if not tower:
                    return error_wire(
                        "checkpoint has no bundled cond_stage_model tower; "
                        "wire a TPUCLIPLoader node instead"
                    )
                enc = load_clip_text_checkpoint(
                    tower, cfg=cfg, open_clip=open_clip
                )
                tok = _clip_tokenizer(
                    max_len=enc.cfg.max_len, pad_id=0 if open_clip else None
                )
                return {
                    "encoder": enc, "tokenizer": tok, "type": "clip",
                    "tokenizer_error": None if tok else _TOKENIZER_HELP,
                }
            if family == "sdxl":
                from .models import open_clip_g_config

                # conditioner.embedders.0 = CLIP-L (HF layout),
                # conditioner.embedders.1 = OpenCLIP-G (resblocks layout).
                towers = load_safetensors_subset(
                    path, "conditioner.embedders.0.", "conditioner.embedders.1."
                )
                sub_l = {k: v for k, v in towers.items()
                         if k.startswith("conditioner.embedders.0.")}
                sub_g = {k: v for k, v in towers.items()
                         if k.startswith("conditioner.embedders.1.")}
                if not sub_l or not sub_g:
                    return error_wire(
                        "sdxl checkpoint has no bundled conditioner towers; "
                        "wire TPUCLIPLoader nodes instead"
                    )
                enc_l = load_clip_text_checkpoint(sub_l)
                enc_g = load_clip_text_checkpoint(
                    sub_g, cfg=open_clip_g_config(), open_clip=True
                )
                tok_l = _clip_tokenizer(max_len=enc_l.cfg.max_len)
                tok_g = _clip_tokenizer(max_len=enc_g.cfg.max_len, pad_id=0)
                err = None if (tok_l and tok_g) else _TOKENIZER_HELP
                return {
                    "type": "sdxl-dual",
                    "l": {"encoder": enc_l, "tokenizer": tok_l, "type": "clip",
                          "tokenizer_error": err},
                    "g": {"encoder": enc_g, "tokenizer": tok_g, "type": "clip",
                          "tokenizer_error": err},
                    "tokenizer_error": err,
                }
            return error_wire(
                f"{family} checkpoints do not bundle text encoders; wire "
                "TPUCLIPLoader (or the DualCLIPLoader shim) instead"
            )
        except Exception as e:  # noqa: BLE001 — degrade to an encode-time error
            return error_wire(f"bundled text-encoder extraction failed: {e}")


class DualCLIPLoader:
    """Stock dual loader (FLUX/SD3 workflows): two encoder files → one CLIP
    wire. ``type=flux`` pairs T5-XXL (context) with CLIP-L (pooled)."""

    DESCRIPTION = "Stock-name dual text-encoder loader (flux/sdxl/sd3 pairs)."
    RETURN_TYPES = ("CLIP",)
    RETURN_NAMES = ("clip",)
    FUNCTION = "load"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name1": ("STRING", {"default": ""}),
                "clip_name2": ("STRING", {"default": ""}),
                "type": (["flux", "sdxl", "sd3"], {"default": "flux"}),
            }
        }

    def load(self, clip_name1: str, clip_name2: str, type: str = "flux"):
        from .nodes import TPUCLIPLoader

        loader = TPUCLIPLoader()

        def clip_wire(name: str, encoder_type: str):
            path = resolve_model_file(name, "clip", "text_encoders")
            kw = {}
            if encoder_type in ("t5", "umt5"):
                tok_json = os.environ.get("PA_T5_TOKENIZER_JSON", "")
                if not tok_json:
                    raise ValueError(
                        "DualCLIPLoader t5 tower needs PA_T5_TOKENIZER_JSON "
                        "(no vocab/merges form exists for T5 tokenizers)"
                    )
                kw["tokenizer_json"] = tok_json
            else:
                tok_json = os.environ.get("PA_TOKENIZER_JSON", "")
                if tok_json:
                    kw["tokenizer_json"] = tok_json
                else:
                    kw["vocab_path"] = os.environ.get("PA_CLIP_VOCAB", "")
                    kw["merges_path"] = os.environ.get("PA_CLIP_MERGES", "")
            (wire,) = loader.load(path, encoder_type, **kw)
            return wire

        if type == "flux":
            # Stock convention: name1 = t5xxl, name2 = clip_l. A "t5" in
            # either file name corrects swapped wiring; with no match in
            # either, trust the positional convention (a rename like
            # flan_xxl.safetensors must not flip a correctly-ordered graph).
            n1 = os.path.basename(clip_name1).lower()
            n2 = os.path.basename(clip_name2).lower()
            swapped = "t5" not in n1 and "t5" in n2
            t5_name = clip_name2 if swapped else clip_name1
            l_name = clip_name1 if swapped else clip_name2
            return (
                {
                    "type": "flux-dual",
                    "t5": clip_wire(t5_name, "t5"),
                    "l": clip_wire(l_name, "clip-l"),
                    "tokenizer_error": None,
                },
            )
        if type == "sdxl":
            return (
                {
                    "type": "sdxl-dual",
                    "l": clip_wire(clip_name1, "clip-l"),
                    "g": clip_wire(clip_name2, "open-clip-g"),
                    "tokenizer_error": None,
                },
            )
        raise ValueError(
            "DualCLIPLoader type=sd3 needs three towers — wire TPUCLIPLoader "
            "nodes + TPUConditioningCombine(mode='sd3') instead"
        )


class LoraLoader:
    """Stock LoRA node: (MODEL, CLIP, lora_name, strengths) → patched
    (MODEL, CLIP). LoRA bakes into the checkpoint layout BEFORE conversion
    (models/convert.bake_lora — the reference's patches-then-load order,
    any_device_parallel.py:971-1004), so this shim re-loads the tagged source
    checkpoint with the LoRA applied. One LoRA per model (chain a second via
    TPUCheckpointLoader's lora_path or bake offline); ``strength_clip`` is
    accepted and ignored — text-encoder LoRA is a documented divergence."""

    DESCRIPTION = "Stock-name LoRA loader (re-bakes from the source checkpoint)."
    RETURN_TYPES = ("MODEL", "CLIP")
    RETURN_NAMES = ("model", "clip")
    FUNCTION = "load_lora"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL", {}),
                "clip": ("CLIP", {}),
                "lora_name": ("STRING", {"default": ""}),
                "strength_model": (
                    "FLOAT", {"default": 1.0, "min": -4.0, "max": 4.0}
                ),
                "strength_clip": (
                    "FLOAT", {"default": 1.0, "min": -4.0, "max": 4.0}
                ),
            }
        }

    def load_lora(self, model, clip, lora_name: str,
                  strength_model: float = 1.0, strength_clip: float = 1.0):
        from .nodes import TPUCheckpointLoader

        source = getattr(model, "source", None)
        if source is None:
            raise ValueError(
                "LoraLoader needs a MODEL from CheckpointLoaderSimple (the "
                "source-checkpoint tag); for TPUCheckpointLoader models pass "
                "lora_path on the loader itself"
            )
        if source.get("lora"):
            raise ValueError(
                "stacking a second LoraLoader is not supported — bake "
                "multiple LoRAs offline or use TPUCheckpointLoader lora_path"
            )
        lora = resolve_model_file(lora_name, "loras")
        # An empty/missing name must not silently return an unpatched model
        # (TPUCheckpointLoader treats lora_path="" as no-LoRA).
        if not lora_name or not os.path.isfile(lora):
            raise ValueError(
                f"LoRA file not found: {lora_name!r} (searched "
                f"$PA_MODELS_DIR/loras and the name as a path)"
            )
        patched, _ = TPUCheckpointLoader().load(
            ckpt_path=source["path"], family=source["family"],
            lora_path=lora, lora_strength=strength_model,
            load_vae=False,  # re-bake only needs the diffusion model
        )
        object.__setattr__(
            patched, "source", {**source, "lora": lora}
        )
        return patched, clip


class CLIPSetLastLayer:
    """Stock clip-skip node: tags the CLIP wire; TPUTextEncode honors the tag
    when its own clip_skip widget is 0 (host stop_at_clip_layer semantics:
    -1 = final layer, -2 = penultimate)."""

    DESCRIPTION = "Stock-name clip-skip (tags the CLIP wire)."
    RETURN_TYPES = ("CLIP",)
    RETURN_NAMES = ("clip",)
    FUNCTION = "set_last_layer"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP", {}),
                "stop_at_clip_layer": ("INT", {"default": -1, "min": -24, "max": -1}),
            }
        }

    def set_last_layer(self, clip, stop_at_clip_layer: int):
        if stop_at_clip_layer not in (-1, -2):
            raise ValueError(
                "only stop_at_clip_layer -1 (final) or -2 (penultimate) is "
                f"supported, got {stop_at_clip_layer}"
            )
        return ({**clip, "clip_skip": -stop_at_clip_layer},)


def _renamed(tpu_cls, rename: dict[str, str], *, name: str):
    """Adapter class factory: stock input keys → TPU node keys."""

    class Shim:
        DESCRIPTION = f"Stock-name alias of {tpu_cls.__name__}."
        RETURN_TYPES = tpu_cls.RETURN_TYPES
        RETURN_NAMES = getattr(tpu_cls, "RETURN_NAMES", None)
        FUNCTION = "run"
        CATEGORY = CATEGORY

        @classmethod
        def INPUT_TYPES(cls):
            spec = tpu_cls.INPUT_TYPES()
            back = {v: k for k, v in rename.items()}
            return {
                section: {back.get(k, k): v for k, v in entries.items()}
                for section, entries in spec.items()
            }

        def run(self, **kwargs):
            mapped = {rename.get(k, k): v for k, v in kwargs.items()}
            inner = tpu_cls()
            return getattr(inner, tpu_cls.FUNCTION)(**mapped)

    Shim.__name__ = Shim.__qualname__ = name
    return Shim


class LoadImage:
    """Stock image loader: names resolve against ``$PA_INPUT_DIR``."""

    DESCRIPTION = "Stock-name alias of TPULoadImage (input-dir resolution)."
    FUNCTION = "run"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("STRING", {"default": ""})}}

    def run(self, image: str):
        from .nodes import TPULoadImage

        base = os.environ.get("PA_INPUT_DIR", "input")
        cand = os.path.join(base, image)
        return TPULoadImage().load(cand if os.path.exists(cand) else image)

    # RETURN_TYPES mirror the TPU node (set below to avoid import cycles).


class LatentUpscale:
    """Stock latent upscale takes absolute target pixel dims; the TPU node
    takes scale factors — computed here from the wired latent at runtime,
    height and width independently. ``crop`` is accepted and ignored
    (center-crop after resize is a stock nicety, not a parity requirement —
    documented divergence)."""

    DESCRIPTION = "Stock-name latent upscale (absolute dims → scale factor)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "upscale"
    CATEGORY = CATEGORY

    _METHODS = {
        "nearest-exact": "nearest", "nearest": "nearest",
        "bilinear": "bilinear", "area": "bilinear",
        "bicubic": "bicubic", "bislerp": "bicubic",
    }

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT", {}),
                "upscale_method": (list(cls._METHODS), {"default": "bilinear"}),
                "width": ("INT", {"default": 1024, "min": 16, "max": 16384}),
                "height": ("INT", {"default": 1024, "min": 16, "max": 16384}),
            },
            "optional": {"crop": ("STRING", {"default": "disabled"})},
        }

    def upscale(self, samples, upscale_method: str, width: int, height: int,
                crop: str = "disabled"):
        from .nodes import TPULatentUpscale

        z = samples["samples"]
        h, w = z.shape[-3], z.shape[-2]
        # Stock dims are pixel-space; latents are 8x smaller. Height and
        # width scale independently (aspect-changing upscales resize exactly
        # to the stock target).
        scale_h = max(height // 8, 2) / h
        scale_w = max(width // 8, 2) / w
        method = self._METHODS.get(upscale_method, "bilinear")
        return TPULatentUpscale().upscale(
            samples, scale_h, method, scale_w=scale_w
        )


class _EmptyLatent16ch:
    """Stock EmptySD3LatentImage: 16-channel latents (SD3/FLUX), no channel
    widget."""

    DESCRIPTION = "Stock-name 16-channel empty latent (SD3/FLUX)."
    RETURN_TYPES = ("LATENT",)
    RETURN_NAMES = ("latent",)
    FUNCTION = "generate"
    CATEGORY = CATEGORY

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 1024, "min": 16, "max": 16384}),
                "height": ("INT", {"default": 1024, "min": 16, "max": 16384}),
                "batch_size": ("INT", {"default": 1, "min": 1, "max": 4096}),
            }
        }

    def generate(self, width: int, height: int, batch_size: int = 1):
        from .nodes import TPUEmptyLatent

        return TPUEmptyLatent().generate(
            width=width, height=height, batch_size=batch_size, channels=16
        )


def stock_node_mappings() -> dict[str, type]:
    """All stock-name shims, keyed by the stock class name (merged into
    ``nodes.NODE_CLASS_MAPPINGS`` so exported workflows resolve directly)."""
    from . import nodes as n

    LoadImage.RETURN_TYPES = n.TPULoadImage.RETURN_TYPES
    LoadImage.RETURN_NAMES = getattr(n.TPULoadImage, "RETURN_NAMES", None)

    mappings = {
        "CheckpointLoaderSimple": CheckpointLoaderSimple,
        "DualCLIPLoader": DualCLIPLoader,
        "LoraLoader": LoraLoader,
        "CLIPSetLastLayer": CLIPSetLastLayer,
        "LoadImage": LoadImage,
        "LatentUpscale": LatentUpscale,
        # Pure renames.
        "CLIPTextEncode": _renamed(n.TPUTextEncode, {}, name="CLIPTextEncode"),
        "EmptyLatentImage": _renamed(
            n.TPUEmptyLatent, {}, name="EmptyLatentImage"
        ),
        "EmptySD3LatentImage": _EmptyLatent16ch,
        "KSampler": _renamed(
            n.TPUKSampler, {"latent_image": "latent"}, name="KSampler"
        ),
        "VAEDecode": _renamed(
            n.TPUVAEDecode, {"samples": "latent"}, name="VAEDecode"
        ),
        "VAEEncode": _renamed(
            n.TPUVAEEncode, {"pixels": "image"}, name="VAEEncode"
        ),
        "SaveImage": _renamed(n.TPUSaveImage, {}, name="SaveImage"),
        "LatentUpscaleBy": _renamed(
            n.TPULatentUpscale, {"samples": "latent", "scale_by": "scale",
                                 "upscale_method": "method"},
            name="LatentUpscaleBy",
        ),
        "SetLatentNoiseMask": _renamed(
            n.TPUSetLatentNoiseMask, {"samples": "latent"},
            name="SetLatentNoiseMask",
        ),
        # Custom-sampling family: built stock-shaped from the start.
        "RandomNoise": _renamed(n.TPURandomNoise, {}, name="RandomNoise"),
        "DisableNoise": _renamed(n.TPUDisableNoise, {}, name="DisableNoise"),
        "KSamplerSelect": _renamed(
            n.TPUKSamplerSelect, {}, name="KSamplerSelect"
        ),
        "BasicScheduler": _renamed(
            n.TPUBasicScheduler, {}, name="BasicScheduler"
        ),
        "BasicGuider": _renamed(n.TPUBasicGuider, {}, name="BasicGuider"),
        "CFGGuider": _renamed(n.TPUCFGGuider, {}, name="CFGGuider"),
        "FluxGuidance": _renamed(n.TPUFluxGuidance, {}, name="FluxGuidance"),
        "SamplerCustomAdvanced": _renamed(
            n.TPUSamplerCustomAdvanced, {}, name="SamplerCustomAdvanced"
        ),
        "SplitSigmas": _renamed(n.TPUSplitSigmas, {}, name="SplitSigmas"),
        "FlipSigmas": _renamed(n.TPUFlipSigmas, {}, name="FlipSigmas"),
    }
    return mappings


def register(
    node_class_mappings: dict[str, type],
    display_name_mappings: dict[str, str] | None = None,
) -> None:
    """Merge the shims into a registry without overriding native names."""
    for name, cls in stock_node_mappings().items():
        node_class_mappings.setdefault(name, cls)
        if display_name_mappings is not None:
            display_name_mappings.setdefault(name, f"{name} (stock compat)")
