"""ControlNet for the SD-family UNets — flax.linen, NHWC, TPU-first.

The reference wraps whatever MODEL its host hands it — a ControlNet-patched
model included (the host computes control residuals and the UNet consumes
them; the reference's duck-typed unwrap at any_device_parallel.py:921-930 is
agnostic to it). Standalone, this module is that capability: the ControlNet
trunk (a copy of the UNet encoder + middle with zero-conv taps and a hint
encoder) producing per-skip residuals that ``UNet2D`` consumes via its
``control`` kwarg.

TPU-first composition: ``apply_control`` merges base UNet + ControlNet into
ONE DiffusionModel whose apply computes the residuals and the denoise step in
a single jit program — XLA fuses/schedules both trunks; nothing crosses the
host boundary per step, and the merged pytree places/shards through
``parallelize`` like any other model (DP/FSDP work unchanged).

Structure mirrors the public ControlNet layout (lucidrains/lllyasviel lineage,
as shipped in ldm-format ``.safetensors``): ``input_hint_block`` (8 convs,
8× spatial reduction from pixels to latents), the UNet ``input_blocks`` +
``middle_block`` trunk, one zero conv per skip (``zero_convs``) and a middle
zero conv (``middle_block_out``). Conversion: convert_unet.py
``convert_controlnet_checkpoint``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.basic import timestep_embedding
from .api import DiffusionModel
from .unet import (
    Downsample,
    ResBlock,
    SpatialTransformer,
    UNetConfig,
    middle_depth,
)

# input_hint_block conv ladder: (out_channels, stride) per conv, pixels → 8×
# reduced latent grid, final zero conv to model_channels appended dynamically.
_HINT_LADDER = ((16, 1), (16, 1), (32, 2), (32, 1), (96, 2), (96, 1), (256, 2))


class ControlNet2D(nn.Module):
    """forward(x NHWC latents, hint NHWC pixels (8× the latent grid),
    timesteps (B,), context, y) → {"input": (residual, ...), "middle": (r,)}.

    Residual list order matches UNet2D's ``skips`` list (consumed in reverse
    by the up path). Zero convs initialize to zero, so an untrained ControlNet
    is an exact no-op on the base model."""

    cfg: UNetConfig
    hint_channels: int = 3

    @nn.compact
    def __call__(self, x, hint, timesteps, context=None, y=None):
        cfg = self.cfg
        ch = cfg.model_channels
        t_emb = timestep_embedding(timesteps, ch).astype(cfg.dtype)
        emb = nn.Dense(ch * 4, dtype=cfg.dtype, name="time_embed_0")(t_emb)
        emb = nn.Dense(ch * 4, dtype=cfg.dtype, name="time_embed_2")(nn.silu(emb))
        if cfg.adm_in_channels is not None:
            if y is None:
                raise ValueError("this config requires vector conditioning `y`")
            y_emb = nn.Dense(ch * 4, dtype=cfg.dtype, name="label_embed_0")(
                y.astype(cfg.dtype)
            )
            emb = emb + nn.Dense(ch * 4, dtype=cfg.dtype, name="label_embed_2")(
                nn.silu(y_emb)
            )

        x = x.astype(cfg.dtype)
        if context is not None:
            context = context.astype(cfg.dtype)

        if hint.shape[1:3] != (x.shape[1] * 8, x.shape[2] * 8):
            raise ValueError(
                f"hint image {hint.shape[1:3]} must be 8x the latent grid "
                f"{x.shape[1:3]} (pixels vs latents)"
            )
        g = hint.astype(cfg.dtype)
        for i, (out_ch, stride) in enumerate(_HINT_LADDER):
            g = nn.Conv(out_ch, (3, 3), strides=(stride, stride), padding=1,
                        dtype=cfg.dtype, name=f"hint_{i}")(g)
            g = nn.silu(g)
        g = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype,
                    kernel_init=nn.initializers.zeros,
                    name=f"hint_{len(_HINT_LADDER)}")(g)

        def zero_conv(h, idx):
            return nn.Conv(
                h.shape[-1], (1, 1), dtype=cfg.dtype,
                kernel_init=nn.initializers.zeros, name=f"zero_conv_{idx}"
            )(h)

        h = nn.Conv(ch, (3, 3), padding=1, dtype=cfg.dtype, name="input_conv")(x)
        h = h + g
        outs = [zero_conv(h, 0)]
        zi = 1
        # Encoder trunk: identical structure (and module names) to UNet2D's
        # input path, so the checkpoint converter shares its mapping.
        for level, mult in enumerate(cfg.channel_mult):
            out_ch = ch * mult
            for i in range(cfg.num_res_blocks):
                h = ResBlock(cfg, out_ch, name=f"in_{level}_{i}_res")(h, emb)
                if level in cfg.attention_levels and cfg.transformer_depth[level] > 0:
                    h = SpatialTransformer(
                        cfg, out_ch, cfg.transformer_depth[level],
                        name=f"in_{level}_{i}_attn",
                    )(h, context)
                outs.append(zero_conv(h, zi))
                zi += 1
            if level != len(cfg.channel_mult) - 1:
                h = Downsample(cfg, out_ch, name=f"down_{level}")(h)
                outs.append(zero_conv(h, zi))
                zi += 1
        mid_ch = ch * cfg.channel_mult[-1]
        mid_depth = middle_depth(cfg)
        h = ResBlock(cfg, mid_ch, name="mid_res1")(h, emb)
        if mid_depth > 0:
            h = SpatialTransformer(cfg, mid_ch, mid_depth, name="mid_attn")(h, context)
        h = ResBlock(cfg, mid_ch, name="mid_res2")(h, emb)
        mid = nn.Conv(mid_ch, (1, 1), dtype=cfg.dtype,
                      kernel_init=nn.initializers.zeros, name="mid_out")(h)
        return {"input": tuple(outs), "middle": (mid,)}


def build_controlnet(
    cfg: UNetConfig,
    rng=None,
    sample_shape=(1, 64, 64, 4),
    hint_channels: int = 3,
    name="controlnet",
    params=None,
) -> DiffusionModel:
    """Build a ControlNet as a DiffusionModel handle (apply + params); the
    apply signature is ``(params, x, timesteps, context=None, hint=..., y=...)``
    — hint is keyword-only past the shared prefix so generic model plumbing
    still sees the (x, t, context) convention."""
    module = ControlNet2D(cfg, hint_channels=hint_channels)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        x = jnp.zeros(sample_shape, jnp.float32)
        hint = jnp.zeros(
            (sample_shape[0], sample_shape[1] * 8, sample_shape[2] * 8,
             hint_channels), jnp.float32,
        )
        t = jnp.zeros((sample_shape[0],), jnp.float32)
        ctx = jnp.zeros((sample_shape[0], 77, cfg.context_dim), jnp.float32)
        kwargs = {}
        if cfg.adm_in_channels is not None:
            kwargs["y"] = jnp.zeros(
                (sample_shape[0], cfg.adm_in_channels), jnp.float32
            )
        params = module.init(rng, x, hint, t, ctx, **kwargs)["params"]

    def apply(params, x, timesteps, context=None, *, hint, y=None):
        kw = {} if y is None else {"y": y}
        return module.apply({"params": params}, x, hint, timesteps, context, **kw)

    return DiffusionModel(apply=apply, params=params, name=name, config=cfg)


def apply_control(
    base: DiffusionModel,
    control_net: DiffusionModel,
    hint,
    strength: float = 1.0,
    start_percent: float = 0.0,
    end_percent: float = 1.0,
) -> DiffusionModel:
    """Compose base UNet + ControlNet into one DiffusionModel.

    The merged params pytree carries both networks AND the hint image, so the
    composition places/shards through ``parallelize`` like a single model and
    the whole denoise step (control trunk + base trunk) is one jit program.

    ``start_percent``/``end_percent`` gate the residuals by sampling progress
    (the stock ControlNetApplyAdvanced knobs), approximated as linear in the
    timestep: progress = 1 − t/999 for the eps/v UNet families this serves.
    Documented divergence: stock maps percents through model_sampling's sigma
    table; at the default (0, 1) the gate is exactly a no-op either way.
    """
    strength = float(strength)
    start_p, end_p = float(start_percent), float(end_percent)
    merged = {
        "base": base.params,
        "ctrl": control_net.params,
        "hint": jnp.asarray(hint, jnp.float32),
    }
    base_apply, ctrl_apply = base.apply, control_net.apply

    def apply(p, x, timesteps, context=None, control=None, **kw):
        hint_img = p["hint"]
        if hint_img.ndim == 3:
            hint_img = hint_img[None]
        if hint_img.shape[0] != x.shape[0]:
            if hint_img.shape[0] != 1:
                # A per-sample hint batch cannot survive data-parallel
                # splitting (the hint rides the REPLICATED params pytree while
                # x shards) — only a single shared hint broadcasts safely.
                raise ValueError(
                    f"hint batch {hint_img.shape[0]} != latent batch "
                    f"{x.shape[0]}: pass ONE hint image (it broadcasts to the "
                    "batch); per-sample hints are not supported"
                )
            hint_img = jnp.repeat(hint_img, x.shape[0], axis=0)
        want_hw = (x.shape[1] * 8, x.shape[2] * 8)
        if hint_img.shape[1:3] != want_hw:
            # Stock auto-resizes the hint to the generation size
            # (common_upscale); shapes are static under jit so this traces.
            hint_img = jax.image.resize(
                hint_img,
                (hint_img.shape[0], *want_hw, hint_img.shape[-1]),
                method="bilinear",
            )
        ctrl = ctrl_apply(
            p["ctrl"], x, timesteps, context, hint=hint_img, y=kw.get("y"),
        )
        gate = jnp.float32(strength)
        if (start_p, end_p) != (0.0, 1.0):
            from ..ops.basic import progress_window_gate

            gate = gate * progress_window_gate(
                timesteps, start_p, end_p, x.ndim
            )
        ctrl = jax.tree.map(lambda a: a * gate, ctrl)
        if control is not None:
            # Stacked ControlNets (a chain of apply_control compositions):
            # residuals from the outer net(s) arrive via the ``control``
            # kwarg and SUM with this net's — the host's multi-controlnet
            # accumulation. Structures match because every net shares the
            # base UNet's skip layout.
            ctrl = jax.tree.map(lambda a, b: a + b, ctrl, control)
        return base_apply(p["base"], x, timesteps, context, control=ctrl, **kw)

    return DiffusionModel(
        apply=apply,
        params=merged,
        name=f"{base.name}+control",
        config=base.config,
        # Serving delegation (round 16): the scheduler buckets this
        # composition on the BASE model and carries the control net as
        # per-lane state, so ControlNet lanes co-batch with plain txt2img.
        # Chained compositions (base is itself merged) stay opaque — the
        # lane program carries ONE control trunk per bucket epoch.
        control_delegate=(
            None
            if getattr(base, "control_delegate", None) is not None
            else {
                "base": base,
                "ctrl_apply": ctrl_apply,
                "ctrl_params": control_net.params,
                "hint": merged["hint"],
                "strength": strength,
                "start": start_p,
                "end": end_p,
            }
        ),
    )


# Re-exported config alias: ControlNets share the UNet config surface.
ControlNetConfig = UNetConfig
