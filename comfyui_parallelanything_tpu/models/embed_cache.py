"""Content-addressed text-encoder output cache — cross-request compute reuse.

Production diffusion traffic is massively redundant: the same prompts, the
same negative prompts, and N-seed fanouts of one prompt dominate real queues,
yet every request pays a full text-encode unless something remembers the
answer. The reference's only memoization is ComfyUI's node-output cache —
node-id-scoped, latest-signature-only (``host.WorkflowCache`` mirrors it), so
alternating prompts A,B,A,B re-encode every time. This cache is the
cross-request layer underneath it:

- **content-addressed**: entries are keyed by (model key, tower type, token
  ids, mask) through the same md5 ``stable_hash`` discipline as
  ``fleet/registry.py`` — process-independent, node-id-independent. The
  model key is the loader's content stamp (checkpoint path + tower) when one
  exists, else a per-encoder-object lifetime token (``encoder_token``) —
  unique for the object's lifetime, so a torn-down encoder's entries can
  never serve a successor's lookups.
- **LRU-bounded in bytes** (``PA_EMBED_CACHE_BYTES``, default 256 MiB;
  ``0`` disables caching entirely — every encode computes): embeds are small
  (a CLIP context is ~230 KB) but a zipf tail is long; the bound holds under
  churn with evictions counted.
- **concurrency-safe per the ``host.WorkflowCache`` snapshot/merge pattern**:
  lookups and inserts are lock-scoped; when two workers race the same miss,
  the first ``put`` wins and the loser's duplicate is returned to its caller
  un-cached (never torn down — the caller still holds it) exactly like
  ``WorkflowCache.merge``'s incumbent rule.
- **metered**: ``pa_embed_cache_{hits,misses,bytes,evictions}`` gauges plus
  the ``pa_encoder_invocations_total`` counter (every *real* encoder program
  run, cache enabled or not) — the pair ``scripts/loadgen.py`` diffs into
  ``embed_cache_hit_rate`` / ``encoder_invocations``.

Hits return the cached device arrays THEMSELVES (no copy): cached-vs-fresh
is bitwise-equal by construction, and downstream consumers see one shared
cond object — which is exactly what lets the serving tier seat sibling-seed
lanes against ONE broadcast cond tensor (serving/bucket.py shared-cond mode).
"""

from __future__ import annotations

import hashlib
import os
import threading
import uuid
from collections import OrderedDict

import numpy as np

from ..utils.metrics import registry

DEFAULT_BYTES = 256 * 1024 * 1024


def cache_budget_bytes() -> int:
    """The byte bound from ``PA_EMBED_CACHE_BYTES`` (0 disables)."""
    try:
        return int(os.environ.get("PA_EMBED_CACHE_BYTES", DEFAULT_BYTES))
    except ValueError:
        return DEFAULT_BYTES


def lifetime_token(obj, attr: str = "_pa_embed_token") -> str:
    """A lifetime-unique token for one object. Unlike ``id()``, a token is
    never reused after the object dies, so keys derived from it can only
    ever miss, never alias. Works on frozen dataclasses (TextEncoder, VAE)
    via the same ``object.__setattr__`` side-channel their jit caches use.
    Shared by this cache's encoder fallback keys and the decode queue's
    VAE group keys (serving/decode.py)."""
    tok = getattr(obj, attr, None)
    if tok is None:
        tok = uuid.uuid4().hex
        object.__setattr__(obj, attr, tok)
    return tok


def encoder_token(enc) -> str:
    """The model-key fallback when no loader content stamp exists."""
    return lifetime_token(enc, "_pa_embed_token")


def file_stamp(path: str) -> tuple:
    """(path, size, mtime_ns) — the content identity loader stamps fold
    into model keys, so replacing a checkpoint file IN PLACE changes the
    key (a path string alone would serve the old file's embeds). Missing
    or unstattable paths degrade to the bare path (in-memory towers)."""
    try:
        st = os.stat(path)
        return (path, st.st_size, st.st_mtime_ns)
    except OSError:
        return (path, None, None)


def stable_key(model_key: str, tower: str, ids, mask=None) -> str:
    """md5 content address over (model key, tower, token ids, mask) — the
    ``fleet/registry.stable_hash`` discipline (``hash()`` is salted per
    process; a content address must not be). Keying on the token IDS (not
    the raw text) folds the tokenizer tables and max_len in for free."""
    h = hashlib.md5()
    h.update(str(model_key).encode())
    h.update(b"\x00" + str(tower).encode() + b"\x00")
    h.update(np.ascontiguousarray(np.asarray(ids, np.int32)).tobytes())
    h.update(b"\x00")
    if mask is not None:
        h.update(np.ascontiguousarray(np.asarray(mask, np.int32)).tobytes())
    return h.hexdigest()


def _value_bytes(value) -> int:
    """Total device-array bytes of a cached value (a single array or a tuple
    of arrays / Nones — the encoder output shapes)."""
    leaves = value if isinstance(value, (tuple, list)) else (value,)
    return sum(int(getattr(l, "nbytes", 0) or 0) for l in leaves if l is not None)


class EmbedCache:
    """Byte-bounded LRU of encoder outputs, with per-owner release so a
    torn-down encoder (WorkflowCache eviction) frees its embeds eagerly
    instead of waiting for LRU churn."""

    def __init__(self, max_bytes: int | None = None):
        self._max_bytes = max_bytes
        self._lock = threading.Lock()
        # key -> (value, nbytes, owner_token) in LRU order (oldest first).
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()  # guarded-by: _lock
        self._owners: dict[str, set[str]] = {}  # guarded-by: _lock
        self._bytes = 0      # guarded-by: _lock
        self._hits = 0       # guarded-by: _lock
        self._misses = 0     # guarded-by: _lock
        self._evictions = 0  # guarded-by: _lock

    def budget(self) -> int:
        return self._max_bytes if self._max_bytes is not None \
            else cache_budget_bytes()

    def enabled(self) -> bool:
        return self.budget() > 0

    def get(self, key: str):
        """The cached value (moved to MRU) or None; hit/miss accounted."""
        if not self.enabled():
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._misses += 1
            else:
                self._entries.move_to_end(key)
                self._hits += 1
        return entry[0] if entry is not None else None

    def put(self, key: str, value, owner: str | None = None):
        """Insert under the merge discipline: an incumbent wins and is
        returned (the caller's duplicate stays caller-owned, never cached —
        a racing double-encode costs the race loser its own compute, not a
        teardown). Inserting evicts LRU entries until the byte bound holds;
        a value larger than the whole budget is returned un-cached."""
        if not self.enabled():
            return value
        nbytes = _value_bytes(value)
        with self._lock:
            incumbent = self._entries.get(key)
            if incumbent is not None:
                self._entries.move_to_end(key)
                return incumbent[0]
            if nbytes > self.budget():
                return value
            self._entries[key] = (value, nbytes, owner)
            self._bytes += nbytes
            if owner is not None:
                self._owners.setdefault(owner, set()).add(key)
            while self._bytes > self.budget() and len(self._entries) > 1:
                self._evict_oldest()
        return value

    def _evict_oldest(self) -> None:  # palint: holds _lock
        old_key, (_, old_bytes, old_owner) = self._entries.popitem(last=False)
        self._bytes -= old_bytes
        self._evictions += 1
        if old_owner is not None:
            keys = self._owners.get(old_owner)
            if keys is not None:
                keys.discard(old_key)
                if not keys:
                    self._owners.pop(old_owner, None)

    def release_owner(self, owner: str) -> int:
        """Drop every entry an owner token holds — the WorkflowCache
        teardown hook (host.py): an evicted CLIP wire's embeds free their
        bytes NOW, the same eager-teardown discipline the node cache applies
        to models. Returns how many entries dropped."""
        with self._lock:
            keys = self._owners.pop(owner, None)
            if not keys:
                return 0
            n = 0
            for key in keys:
                entry = self._entries.pop(key, None)
                if entry is not None:
                    self._bytes -= entry[1]
                    n += 1
        return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._owners.clear()
            self._bytes = 0

    def stats(self) -> dict:
        """The /health ``reuse.embed_cache`` section (and test read side)."""
        with self._lock:
            return {
                "enabled": self.enabled(),
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self.budget(),
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
            }

    def publish_gauges(self) -> None:
        """The pa_embed_cache_* gauges (monotonic totals except bytes —
        loadgen diffs them like counters). Called at /metrics SCRAPE time
        (server.py), the only moment the gauge values are read — the hot
        encode path never pays the registry lock per lookup."""
        with self._lock:
            hits, misses = self._hits, self._misses
            nbytes, evictions = self._bytes, self._evictions
        registry.gauge("pa_embed_cache_hits", hits,
                       help="embed-cache lookups served without an encode")
        registry.gauge("pa_embed_cache_misses", misses,
                       help="embed-cache lookups that paid an encode")
        registry.gauge("pa_embed_cache_bytes", nbytes,
                       help="bytes of cached encoder outputs (LRU-bounded "
                            "by PA_EMBED_CACHE_BYTES)")
        registry.gauge("pa_embed_cache_evictions", evictions,
                       help="entries evicted to hold the byte bound")


# The process-wide cache every encode site consults. Tests may clear() it.
cache = EmbedCache()


# ---------------------------------------------------------------------------
# remote tier (round 21, the PR 12 remainder): cross-host embed fetch
# ---------------------------------------------------------------------------
# In a role-disaggregated fleet (fleet/roles.py) the ENCODE pool fronts this
# cache: encode hosts serve their entries over ``GET /embed/{key}``
# (server.py), and a denoise host that misses locally asks the encode hosts
# listed for the current prompt before paying a local encode. The denoise
# host's own EmbedCache is the "bounded local LRU" of the tier — a fetched
# value lands in it under the normal byte bound, so repeat prompts stop
# crossing the network. Sources are per-prompt, per-thread (the server sets
# them from the dispatch's stage metadata); with no sources set the seam is
# bitwise the single-tier cache. A remote miss or transport error falls
# through to the local encode — NEVER an error.

_remote = threading.local()


def remote_sources() -> tuple:
    return getattr(_remote, "sources", ())


def set_remote_sources(bases) -> None:
    """Install the encode-host bases the CURRENT thread's prompt may fetch
    conds from (empty/None tears down). server.py brackets each staged
    execution with this."""
    _remote.sources = tuple(b.rstrip("/") for b in (bases or ()))


def remote_fetch(key: str, timeout_s: float = 5.0):
    """Try each source's ``GET /embed/{key}``; first 200 wins and is banked
    in the local cache. Counts ``pa_embed_cache_remote_{hits,misses}``.
    Returns None (a miss) on any failure — callers encode locally."""
    sources = remote_sources()
    if not sources or not cache.enabled():
        return None
    import urllib.request

    from ..fleet.roles import deserialize_value

    for base in sources:
        try:
            with urllib.request.urlopen(
                f"{base}/embed/{key}", timeout=timeout_s
            ) as r:
                blob = r.read()
            value = deserialize_value(blob)
        except Exception:
            continue
        registry.counter("pa_embed_cache_remote_hits",
                         help="embed-cache lookups served by an encode "
                              "host's remote tier")
        return cache.put(key, value)
    registry.counter("pa_embed_cache_remote_misses",
                     help="remote embed fetches that missed every encode "
                          "host (fell back to a local encode)")
    return None


def export_blob(key: str):
    """Serve one cached entry as wire bytes (the ``GET /embed/{key}``
    response body), or None when absent/unserializable. Serialization is
    the stage-store walker (fleet/roles.py): device arrays → numpy →
    pickle, so the fetching host never receives a live device buffer."""
    value = cache.get(key)
    if value is None:
        return None
    try:
        from ..fleet.roles import serialize_value

        return serialize_value(value)
    except Exception:
        return None


def cached_encode(enc, model_key: str | None, tower: str, ids, mask, compute):
    """The ONE encode seam: look up (model key, tower, ids, mask); on a miss
    try the remote tier (encode-pool hosts, when the prompt carries
    sources), then run ``compute()`` (the real encoder program — counted in
    ``pa_encoder_invocations_total`` whether or not caching is on) and bank
    it under the merge discipline. ``model_key`` None falls back to the
    per-object lifetime token."""
    owner = encoder_token(enc)
    key = stable_key(model_key or owner, tower, ids, mask)
    hit = cache.get(key)
    if hit is not None:
        return hit
    hit = remote_fetch(key)
    if hit is not None:
        return hit
    registry.counter("pa_encoder_invocations_total",
                     help="real text-encoder program runs (cache misses + "
                          "uncached encodes)")
    value = compute()
    return cache.put(key, value, owner=owner)


def release_wire(value) -> None:
    """Release the embeds of every encoder reachable inside a node-cache
    value (a CLIP wire dict, possibly nesting l/g/t5 sub-wires) — called by
    ``host.WorkflowCache`` when it evicts an entry. Best-effort and
    identity-safe: tokens are lifetime-unique, so releasing can only free
    memory, never corrupt a lookup."""
    if not isinstance(value, dict):
        return
    enc = value.get("encoder")
    if enc is not None:
        tok = getattr(enc, "_pa_embed_token", None)
        if tok is not None:
            cache.release_owner(tok)
    for sub in ("l", "g", "t5"):
        release_wire(value.get(sub))
