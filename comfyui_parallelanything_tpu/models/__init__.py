from .api import DiffusionModel
from .unet import UNet2D, UNetConfig, sd15_config, sdxl_config, build_unet
from .flux import (
    FluxModel,
    FluxConfig,
    flux_dev_config,
    flux_schnell_config,
    build_flux,
)

__all__ = [
    "DiffusionModel",
    "UNet2D",
    "UNetConfig",
    "sd15_config",
    "sdxl_config",
    "build_unet",
    "FluxModel",
    "FluxConfig",
    "flux_dev_config",
    "flux_schnell_config",
    "build_flux",
]
