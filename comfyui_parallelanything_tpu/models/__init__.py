from .api import DiffusionModel, PipelineSegment, PipelineSpec
from .unet import UNet2D, UNetConfig, sd15_config, sdxl_config, build_unet
from .flux import (
    FluxModel,
    FluxConfig,
    flux_dev_config,
    flux_schnell_config,
    z_image_turbo_config,
    build_flux,
)
from .wan import WanModel, WanConfig, wan_1_3b_config, wan_14b_config, build_wan
from .vae import (
    VAE,
    VAEConfig,
    AutoencoderKL,
    sd_vae_config,
    sdxl_vae_config,
    flux_vae_config,
    build_vae,
)
from .convert import bake_lora, convert_flux_checkpoint
from .convert_vae import convert_vae_checkpoint, strip_vae_prefix
from .convert_unet import convert_sd_unet_checkpoint, strip_prefix
from .loader import (
    load_safetensors,
    load_flux_checkpoint,
    load_sd_unet_checkpoint,
    load_vae_checkpoint,
    load_wan_checkpoint,
)
from .checkpoint import save_params, load_params

__all__ = [
    "DiffusionModel",
    "PipelineSegment",
    "PipelineSpec",
    "UNet2D",
    "UNetConfig",
    "sd15_config",
    "sdxl_config",
    "build_unet",
    "FluxModel",
    "FluxConfig",
    "flux_dev_config",
    "flux_schnell_config",
    "z_image_turbo_config",
    "build_flux",
    "WanModel",
    "WanConfig",
    "wan_1_3b_config",
    "wan_14b_config",
    "build_wan",
    "VAE",
    "VAEConfig",
    "AutoencoderKL",
    "sd_vae_config",
    "sdxl_vae_config",
    "flux_vae_config",
    "build_vae",
    "bake_lora",
    "convert_flux_checkpoint",
    "convert_vae_checkpoint",
    "strip_vae_prefix",
    "convert_sd_unet_checkpoint",
    "strip_prefix",
    "load_safetensors",
    "load_flux_checkpoint",
    "load_sd_unet_checkpoint",
    "load_vae_checkpoint",
    "load_wan_checkpoint",
    "save_params",
    "load_params",
]
