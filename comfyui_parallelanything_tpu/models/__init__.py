from .api import DiffusionModel
from .unet import UNet2D, UNetConfig, sd15_config, sdxl_config, build_unet

__all__ = [
    "DiffusionModel",
    "UNet2D",
    "UNetConfig",
    "sd15_config",
    "sdxl_config",
    "build_unet",
]
