"""Text-encoder checkpoints → models/text_encoders.py param trees.

Three source layouts cover the checkpoints the supported model families ship with
(the reference's host app loads these same towers; conditioning arrives at its
``forward(x, t, context)`` boundary pre-encoded, any_device_parallel.py:1287):

- **HF CLIPTextModel** (``text_model.*``): SD1.5's ``cond_stage_model.transformer``
  subtree, SDXL's ``conditioner.embedders.0.transformer``, FLUX's clip_l file.
- **OpenCLIP** (``transformer.resblocks.*`` with fused ``in_proj``): SDXL's
  ``conditioner.embedders.1.model`` subtree.
- **HF T5 encoder** (``encoder.block.*``): FLUX/WAN t5xxl files.

Same conventions as convert.py: fp8/f16/bf16 upcast to f32 numpy, torch (out,in)
linears → flax (in,out) kernels, consumed-key tracking absent here because text
checkpoints routinely carry decoder/logit heads we deliberately ignore.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np

from .convert import linear_kernel, to_numpy, tree_to_jnp
from .text_encoders import CLIPTextConfig, T5Config


def _dense(sd: Mapping[str, Any], key: str, bias: bool = True) -> dict:
    out = {"kernel": linear_kernel(sd[f"{key}.weight"])}
    if bias and f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _ln(sd: Mapping[str, Any], key: str) -> dict:
    return {"scale": to_numpy(sd[f"{key}.weight"]), "bias": to_numpy(sd[f"{key}.bias"])}


def _strip(state_dict: Mapping[str, Any], anchor: str) -> dict:
    """Select the encoder subtree by locating ``anchor`` (a key every layout of the
    family contains, e.g. ``token_embedding.weight``), treating everything before it
    as the wrapper prefix (``cond_stage_model.transformer.``,
    ``conditioner.embedders.1.model.`` …) and stripping that prefix from ALL keys —
    sibling keys that don't contain the anchor come along too."""
    for k in state_dict:
        if k.endswith(anchor):
            prefix = k[: len(k) - len(anchor)]
            if not prefix:
                return dict(state_dict)
            return {
                key[len(prefix) :]: v
                for key, v in state_dict.items()
                if key.startswith(prefix)
            }
    return dict(state_dict)


def convert_clip_text_checkpoint(
    state_dict: Mapping[str, Any], cfg: CLIPTextConfig
) -> dict:
    """HF CLIPTextModel layout (``text_model.*``, any wrapper prefix) → CLIPTextModel
    params."""
    sd = _strip(state_dict, "text_model.embeddings.token_embedding.weight")
    p: dict[str, Any] = {
        "tok_emb": {
            "embedding": to_numpy(sd["text_model.embeddings.token_embedding.weight"])
        },
        "pos_emb": to_numpy(sd["text_model.embeddings.position_embedding.weight"]),
        "final_ln": _ln(sd, "text_model.final_layer_norm"),
    }
    for i in range(cfg.num_layers):
        t = f"text_model.encoder.layers.{i}"
        p[f"layers_{i}"] = {
            "ln1": _ln(sd, f"{t}.layer_norm1"),
            "q": _dense(sd, f"{t}.self_attn.q_proj"),
            "k": _dense(sd, f"{t}.self_attn.k_proj"),
            "v": _dense(sd, f"{t}.self_attn.v_proj"),
            "out": _dense(sd, f"{t}.self_attn.out_proj"),
            "ln2": _ln(sd, f"{t}.layer_norm2"),
            "fc1": _dense(sd, f"{t}.mlp.fc1"),
            "fc2": _dense(sd, f"{t}.mlp.fc2"),
        }
    if cfg.projection_dim is not None:
        # HF stores text_projection as a Linear (out,in); some exports as a matrix.
        w = to_numpy(sd["text_projection.weight"])
        p["text_proj"] = {"kernel": w.T}
    return tree_to_jnp(p)


def convert_open_clip_checkpoint(
    state_dict: Mapping[str, Any], cfg: CLIPTextConfig
) -> dict:
    """OpenCLIP text-tower layout (``transformer.resblocks.*``, fused qkv
    ``in_proj``) → CLIPTextModel params. SDXL's second encoder
    (``conditioner.embedders.1.model.*``) is exactly this."""
    # Anchor on a key unique to the OpenCLIP layout: a combined SDXL checkpoint
    # also holds the HF tower's ...text_model.embeddings.token_embedding.weight,
    # so anchoring on token_embedding.weight would lock onto the wrong subtree.
    sd = _strip(state_dict, "positional_embedding")
    if "token_embedding.weight" not in sd:
        raise KeyError("token_embedding.weight not found — not an OpenCLIP text dict")
    H = cfg.hidden_size
    p: dict[str, Any] = {
        "tok_emb": {"embedding": to_numpy(sd["token_embedding.weight"])},
        "pos_emb": to_numpy(sd["positional_embedding"]),
        "final_ln": _ln(sd, "ln_final"),
    }
    for i in range(cfg.num_layers):
        t = f"transformer.resblocks.{i}"
        w = to_numpy(sd[f"{t}.attn.in_proj_weight"])  # (3H, H)
        b = to_numpy(sd[f"{t}.attn.in_proj_bias"])  # (3H,)
        blk: dict[str, Any] = {
            "ln1": _ln(sd, f"{t}.ln_1"),
            "ln2": _ln(sd, f"{t}.ln_2"),
            "out": _dense(sd, f"{t}.attn.out_proj"),
            "fc1": _dense(sd, f"{t}.mlp.c_fc"),
            "fc2": _dense(sd, f"{t}.mlp.c_proj"),
        }
        for j, n in enumerate("qkv"):
            blk[n] = {"kernel": w[j * H : (j + 1) * H].T, "bias": b[j * H : (j + 1) * H]}
        p[f"layers_{i}"] = blk
    if cfg.projection_dim is not None:
        # OpenCLIP's text_projection is a raw (hidden, proj) matrix — NOT a torch
        # Linear — so it maps to the flax kernel without transposition.
        p["text_proj"] = {"kernel": to_numpy(sd["text_projection"])}
    return tree_to_jnp(p)


def convert_t5_checkpoint(state_dict: Mapping[str, Any], cfg: T5Config) -> dict:
    """HF T5 v1.1 layout → T5Encoder params (encoder stack only; decoder/lm_head
    keys in full-model checkpoints are ignored)."""
    sd = _strip(state_dict, "encoder.final_layer_norm.weight")
    emb_key = "shared.weight" if "shared.weight" in sd else "encoder.embed_tokens.weight"
    p: dict[str, Any] = {
        "tok_emb": {"embedding": to_numpy(sd[emb_key])},
        "final_ln": {"scale": to_numpy(sd["encoder.final_layer_norm.weight"])},
    }
    rel = ".layer.0.SelfAttention.relative_attention_bias.weight"
    if cfg.per_layer_bias:
        # UMT5: one table per layer.
        for i in range(cfg.num_layers):
            p[f"rel_bias_{i}"] = to_numpy(sd[f"encoder.block.{i}{rel}"])
    else:
        p["rel_bias"] = to_numpy(sd[f"encoder.block.0{rel}"])
    for i in range(cfg.num_layers):
        t = f"encoder.block.{i}"
        p[f"blocks_{i}"] = {
            "ln1": {"scale": to_numpy(sd[f"{t}.layer.0.layer_norm.weight"])},
            "q": _dense(sd, f"{t}.layer.0.SelfAttention.q", bias=False),
            "k": _dense(sd, f"{t}.layer.0.SelfAttention.k", bias=False),
            "v": _dense(sd, f"{t}.layer.0.SelfAttention.v", bias=False),
            "o": _dense(sd, f"{t}.layer.0.SelfAttention.o", bias=False),
            "ln2": {"scale": to_numpy(sd[f"{t}.layer.1.layer_norm.weight"])},
            "wi_0": _dense(sd, f"{t}.layer.1.DenseReluDense.wi_0", bias=False),
            "wi_1": _dense(sd, f"{t}.layer.1.DenseReluDense.wi_1", bias=False),
            "wo": _dense(sd, f"{t}.layer.1.DenseReluDense.wo", bias=False),
        }
    return tree_to_jnp(p)
