"""FLUX-class MMDiT — flax.linen, bf16, TPU-first. The flagship model family.

Capability target: the reference's headline workloads are FLUX.1 and Z_Image-class
DiTs (/root/reference/README.md:5), and its pipeline mode walks exactly the block
lists this model exposes — ``double_blocks`` then ``single_blocks``
(any_device_parallel.py:1156). The config knobs mirror the ctor kwargs the reference
scrapes off live FLUX models when cloning: ``vec_in_dim``, ``context_in_dim``,
``depth``, ``depth_single_blocks``, ``axes_dim``, ``theta``, ``guidance_embed``
(any_device_parallel.py:286-296). Fresh TPU implementation — joint attention through
the pluggable backend (pallas flash qualifies: head_dim 128), f32 modulation/softmax,
bf16 matmuls.

Architecture (public FLUX.1 recipe): latent 2×2-patchified to 64-ch tokens; text
tokens projected from T5 features; (timestep, pooled-clip, guidance) → modulation
vector; `depth` double-stream blocks (separate img/txt weights, joint attention);
`depth_single_blocks` fused-stream blocks; adaLN-modulated final projection.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.basic import modulate as _modulate, rms_normalize, timestep_embedding
from ..ops.rope import apply_rope, axis_rope_freqs
from .api import DiffusionModel, PipelineSegment, PipelineSpec


@dataclasses.dataclass(frozen=True)
class FluxConfig:
    in_channels: int = 64          # 16 latent ch × 2×2 patch
    hidden_size: int = 3072
    num_heads: int = 24            # head_dim 128
    depth: int = 19                # double blocks
    depth_single_blocks: int = 38
    mlp_ratio: float = 4.0
    context_in_dim: int = 4096     # T5 features
    vec_in_dim: int = 768          # pooled CLIP
    axes_dim: tuple[int, ...] = (16, 56, 56)
    theta: float = 10000.0
    guidance_embed: bool = True
    patch_size: int = 2
    dtype: Any = jnp.bfloat16
    # Rectified-flow velocity parameterization: the KSampler node reads this to
    # route flux-family models through flow-time k-sampling (sampling/runner.py)
    # instead of the eps sigma table.
    prediction: str = "flow"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def flux_dev_config(**overrides) -> FluxConfig:
    return dataclasses.replace(FluxConfig(), **overrides)


def flux_schnell_config(**overrides) -> FluxConfig:
    return dataclasses.replace(FluxConfig(guidance_embed=False), **overrides)


def z_image_turbo_config(**overrides) -> FluxConfig:
    """Z_Image-class turbo DiT — the reference's headline benchmark model
    (/root/reference/README.md:46-60: batch=21 @1024², 26.00 s/it on one RTX 3090).

    Z-Image is a ~6B single-stream-heavy MMDiT distilled for few-step sampling (no
    CFG pass, no guidance embed). Modeled here as the single-stream-dominant point
    in the MMDiT family: a handful of double blocks feeding a deep single-block
    stack at FLUX's hidden width but roughly half the total depth.
    """
    base = FluxConfig(
        depth=6,
        depth_single_blocks=26,
        guidance_embed=False,
    )
    return dataclasses.replace(base, **overrides)


class MLPEmbedder(nn.Module):
    cfg: FluxConfig

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype, name="in_layer")(x)
        return nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype, name="out_layer")(
            nn.silu(h)
        )


class Modulation(nn.Module):
    """vec → (shift, scale, gate) × n sets, computed in f32 for stability."""

    cfg: FluxConfig
    n_sets: int

    @nn.compact
    def __call__(self, vec):
        out = nn.Dense(3 * self.n_sets * self.cfg.hidden_size, dtype=jnp.float32, name="lin")(
            nn.silu(vec.astype(jnp.float32))
        )
        return jnp.split(out[:, None, :], 3 * self.n_sets, axis=-1)


class QKNorm(nn.Module):
    """Per-head RMSNorm on q and k (f32), FLUX-style."""

    @nn.compact
    def __call__(self, q, k):
        def rms(x, name):
            scale = self.param(name, nn.initializers.ones, (x.shape[-1],))
            return rms_normalize(x, scale)

        return rms(q, "query_norm"), rms(k, "key_norm")


class DoubleBlock(nn.Module):
    """Separate img/txt streams; one joint attention over [txt ‖ img] tokens."""

    cfg: FluxConfig

    @nn.compact
    def __call__(self, img, txt, vec, rope):
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        mlp_dim = int(cfg.hidden_size * cfg.mlp_ratio)

        im_shift1, im_scale1, im_gate1, im_shift2, im_scale2, im_gate2 = Modulation(
            cfg, 2, name="img_mod"
        )(vec)
        tx_shift1, tx_scale1, tx_gate1, tx_shift2, tx_scale2, tx_gate2 = Modulation(
            cfg, 2, name="txt_mod"
        )(vec)

        def qkv(stream, x, name):
            h = nn.DenseGeneral((3, H, D), dtype=cfg.dtype, name=f"{name}_qkv")(x)
            q, k, v = h[:, :, 0], h[:, :, 1], h[:, :, 2]
            q, k = QKNorm(name=f"{name}_norm")(q, k)
            return q, k, v

        img_n = _modulate(nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype,
                                       name="img_norm1")(img), im_shift1, im_scale1)
        txt_n = _modulate(nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype,
                                       name="txt_norm1")(txt), tx_shift1, tx_scale1)
        iq, ik, iv = qkv("img", img_n, "img_attn")
        tq, tk, tv = qkv("txt", txt_n, "txt_attn")

        q = jnp.concatenate([tq, iq], axis=1)
        k = jnp.concatenate([tk, ik], axis=1)
        v = jnp.concatenate([tv, iv], axis=1)
        cos, sin = rope
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = attention(q, k, v)
        attn = attn.reshape(attn.shape[0], attn.shape[1], -1)
        txt_len = txt.shape[1]
        txt_attn, img_attn = attn[:, :txt_len], attn[:, txt_len:]

        img = img + im_gate1.astype(cfg.dtype) * nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="img_attn_proj")(img_attn)
        txt = txt + tx_gate1.astype(cfg.dtype) * nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="txt_attn_proj")(txt_attn)

        img_m = _modulate(nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype,
                                       name="img_norm2")(img), im_shift2, im_scale2)
        txt_m = _modulate(nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype,
                                       name="txt_norm2")(txt), tx_shift2, tx_scale2)
        img = img + im_gate2.astype(cfg.dtype) * nn.Sequential([
            nn.Dense(mlp_dim, dtype=cfg.dtype, name="img_mlp_in"),
            nn.gelu,
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="img_mlp_out"),
        ])(img_m)
        txt = txt + tx_gate2.astype(cfg.dtype) * nn.Sequential([
            nn.Dense(mlp_dim, dtype=cfg.dtype, name="txt_mlp_in"),
            nn.gelu,
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="txt_mlp_out"),
        ])(txt_m)
        return img, txt


class SingleBlock(nn.Module):
    """Fused stream: one linear makes qkv + mlp_in together, one linear closes."""

    cfg: FluxConfig

    @nn.compact
    def __call__(self, x, vec, rope):
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        mlp_dim = int(cfg.hidden_size * cfg.mlp_ratio)
        shift, scale, gate = Modulation(cfg, 1, name="modulation")(vec)

        x_n = _modulate(nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype,
                                     name="pre_norm")(x), shift, scale)
        fused = nn.Dense(3 * cfg.hidden_size + mlp_dim, dtype=cfg.dtype, name="linear1")(x_n)
        qkv, mlp = fused[..., : 3 * cfg.hidden_size], fused[..., 3 * cfg.hidden_size :]
        q, k, v = (
            qkv.reshape(x.shape[0], x.shape[1], 3, H, D)[:, :, i] for i in range(3)
        )
        q, k = QKNorm(name="norm")(q, k)
        cos, sin = rope
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = attention(q, k, v).reshape(x.shape[0], x.shape[1], -1)
        out = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="linear2")(
            jnp.concatenate([attn, nn.gelu(mlp)], axis=-1)
        )
        return x + gate.astype(cfg.dtype) * out


class FluxModel(nn.Module):
    """forward(x latent NHWC, timesteps (B,), context (B,S,ctx_dim),
    y=(B,vec_dim) pooled vector, guidance=(B,) optional).

    Setup-style (not @nn.compact) so the forward decomposes into staged methods —
    ``prepare`` / ``double_step`` / ``single_step`` / ``finalize`` — callable
    individually via ``module.apply(..., method=...)`` with only the parameter
    sub-pytree each stage owns. That is what makes the batch==1 pipeline placement
    mode (reference: block-list walk, any_device_parallel.py:1152-1198) expressible
    as per-device jit programs instead of monkey-patched module wrappers. The carry
    between stages is a flat dict of arrays: img, txt, vec, rope_cos, rope_sin.
    """

    cfg: FluxConfig

    def setup(self):
        cfg = self.cfg
        self.img_in = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
        self.txt_in = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
        self.time_in = MLPEmbedder(cfg)
        if cfg.guidance_embed:
            self.guidance_in = MLPEmbedder(cfg)
        self.vector_in = MLPEmbedder(cfg)
        self.double_blocks = [DoubleBlock(cfg) for _ in range(cfg.depth)]
        self.single_blocks = [SingleBlock(cfg) for _ in range(cfg.depth_single_blocks)]
        self.final_mod = nn.Dense(2 * cfg.hidden_size, dtype=jnp.float32)
        self.final_norm = nn.LayerNorm(use_bias=False, use_scale=False, dtype=cfg.dtype)
        # in_channels is already the patchified token width (p*p*latent_ch), so the
        # projection back to patches has exactly in_channels features.
        self.final_proj = nn.Dense(cfg.in_channels, dtype=jnp.float32)

    def prepare(self, x, timesteps, context=None, y=None, guidance=None, **kwargs):
        """Embeddings + position tables → the stage carry (runs on the lead device)."""
        cfg = self.cfg
        B, Hh, Ww, C = x.shape
        p = cfg.patch_size
        hp, wp = Hh // p, Ww // p

        # 2×2 patchify → (B, hp*wp, in_channels)
        img = x.astype(cfg.dtype).reshape(B, hp, p, wp, p, C)
        img = img.transpose(0, 1, 3, 2, 4, 5).reshape(B, hp * wp, p * p * C)
        img = self.img_in(img)

        if context is None:
            raise ValueError("FLUX requires text context tokens")
        txt = self.txt_in(context.astype(cfg.dtype))

        vec = self.time_in(
            timestep_embedding(timesteps, 256, time_factor=1000.0).astype(cfg.dtype)
        )
        if cfg.guidance_embed:
            if guidance is None:
                guidance = jnp.full((B,), 4.0, jnp.float32)
            vec = vec + self.guidance_in(
                timestep_embedding(guidance, 256, time_factor=1000.0).astype(cfg.dtype)
            )
        if y is None:
            y = jnp.zeros((B, cfg.vec_in_dim), jnp.float32)
        vec = vec + self.vector_in(y.astype(cfg.dtype))

        # Position ids: txt tokens at axis-0 index 0, img tokens on the (h, w) grid.
        txt_len = txt.shape[1]
        txt_ids = jnp.zeros((B, txt_len, 3), jnp.int32)
        hh = jnp.arange(hp, dtype=jnp.int32)
        ww = jnp.arange(wp, dtype=jnp.int32)
        grid = jnp.stack(
            [
                jnp.zeros((hp, wp), jnp.int32),
                jnp.broadcast_to(hh[:, None], (hp, wp)),
                jnp.broadcast_to(ww[None, :], (hp, wp)),
            ],
            axis=-1,
        ).reshape(1, hp * wp, 3)
        img_ids = jnp.broadcast_to(grid, (B, hp * wp, 3))
        ids = jnp.concatenate([txt_ids, img_ids], axis=1)
        cos, sin = axis_rope_freqs(ids, cfg.axes_dim, cfg.theta)
        return {"img": img, "txt": txt, "vec": vec, "rope_cos": cos, "rope_sin": sin}

    def double_step(self, carry, i: int):
        img, txt = self.double_blocks[i](
            carry["img"], carry["txt"], carry["vec"], (carry["rope_cos"], carry["rope_sin"])
        )
        return {**carry, "img": img, "txt": txt}

    def single_step(self, carry, i: int):
        # Single blocks run on the fused [txt ‖ img] stream; the carry keeps the two
        # streams separate (uniform structure across every segment) and fuses/splits
        # at the block boundary — XLA folds the concat/slice into the block program.
        txt_len = carry["txt"].shape[1]
        x = jnp.concatenate([carry["txt"], carry["img"]], axis=1)
        x = self.single_blocks[i](x, carry["vec"], (carry["rope_cos"], carry["rope_sin"]))
        return {**carry, "txt": x[:, :txt_len], "img": x[:, txt_len:]}

    def finalize(self, carry, out_shape: tuple[int, ...]):
        """Final adaLN + projection back to NHWC patches (runs on the lead device)."""
        cfg = self.cfg
        img, vec = carry["img"], carry["vec"]
        B, Hh, Ww, C = out_shape
        p = cfg.patch_size
        hp, wp = Hh // p, Ww // p
        shift, scale = jnp.split(
            self.final_mod(nn.silu(vec.astype(jnp.float32)))[:, None, :], 2, axis=-1
        )
        img = _modulate(self.final_norm(img), shift, scale)
        img = self.final_proj(img.astype(jnp.float32))
        img = img.reshape(B, hp, wp, p, p, C).transpose(0, 1, 3, 2, 4, 5)
        return img.reshape(B, Hh, Ww, C)

    def __call__(self, x, timesteps, context=None, y=None, guidance=None, **kwargs):
        carry = self.prepare(x, timesteps, context, y=y, guidance=guidance)
        for i in range(self.cfg.depth):
            carry = self.double_step(carry, i)
        for i in range(self.cfg.depth_single_blocks):
            carry = self.single_step(carry, i)
        return self.finalize(carry, x.shape)


def _flux_pipeline_spec(module: FluxModel, cfg: FluxConfig) -> PipelineSpec:
    """Stage decomposition mirroring the reference's block-list walk order
    (double_blocks then single_blocks, any_device_parallel.py:1156): embeddings on
    the lead device, one segment per block, final adaLN/projection on the lead."""

    def prepare(params, x, t, context=None, **kw):
        return module.apply(
            {"params": params}, x, t, context, method=FluxModel.prepare, **kw
        )

    def make_double(i):
        def fn(params, carry):
            return module.apply(
                {"params": params}, carry, i, method=FluxModel.double_step
            )

        return fn

    def make_single(i):
        def fn(params, carry):
            return module.apply(
                {"params": params}, carry, i, method=FluxModel.single_step
            )

        return fn

    def finalize(params, carry, out_shape):
        return module.apply(
            {"params": params}, carry, out_shape, method=FluxModel.finalize
        )

    segments = tuple(
        PipelineSegment((f"double_blocks_{i}",), make_double(i), f"double_blocks[{i}]")
        for i in range(cfg.depth)
    ) + tuple(
        PipelineSegment((f"single_blocks_{i}",), make_single(i), f"single_blocks[{i}]")
        for i in range(cfg.depth_single_blocks)
    )
    prepare_keys = ["img_in", "txt_in", "time_in", "vector_in"]
    if cfg.guidance_embed:
        prepare_keys.append("guidance_in")
    return PipelineSpec(
        prepare_keys=tuple(prepare_keys),
        prepare=prepare,
        segments=segments,
        # final_norm is scale/bias-free (no params) — only parameterized modules
        # appear in the param pytree.
        finalize_keys=("final_mod", "final_proj"),
        finalize=finalize,
    )


def flux_abstract_params(cfg: FluxConfig, sample_shape=(1, 32, 32, 16), txt_len=128):
    """Shape/dtype pytree of FLUX parameters WITHOUT materializing a single byte
    (``jax.eval_shape`` over init). The entry point for sharded-from-birth
    placement of models too big for one chip: feed the result to
    ``parallel.mesh.materialize_params_sharded`` (or a sharded checkpoint
    restore) so a flux-dev-class 12B pytree never exists unsharded anywhere."""
    module = FluxModel(cfg)
    x = jax.ShapeDtypeStruct(sample_shape, jnp.float32)
    t = jax.ShapeDtypeStruct((sample_shape[0],), jnp.float32)
    ctx = jax.ShapeDtypeStruct((sample_shape[0], txt_len, cfg.context_in_dim), jnp.float32)
    y = jax.ShapeDtypeStruct((sample_shape[0], cfg.vec_in_dim), jnp.float32)
    return jax.eval_shape(
        lambda r, x_, t_, c_, y_: module.init(r, x_, t_, c_, y=y_)["params"],
        jax.random.key(0), x, t, ctx, y,
    )


def build_flux(
    cfg: FluxConfig,
    rng=None,
    sample_shape=(1, 32, 32, 16),
    txt_len=128,
    name="flux",
    params=None,
) -> DiffusionModel:
    """Build a FLUX DiffusionModel. ``params`` skips initialization entirely (the
    checkpoint-load path — initializing billions of params just to overwrite them
    would double the load cost)."""
    module = FluxModel(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        x = jnp.zeros(sample_shape, jnp.float32)
        t = jnp.zeros((sample_shape[0],), jnp.float32)
        ctx = jnp.zeros((sample_shape[0], txt_len, cfg.context_in_dim), jnp.float32)
        y = jnp.zeros((sample_shape[0], cfg.vec_in_dim), jnp.float32)
        params = module.init(rng, x, t, ctx, y=y)["params"]

    def apply(params, x, timesteps, context=None, **kw):
        return module.apply({"params": params}, x, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply,
        params=params,
        name=name,
        config=cfg,
        block_lists={
            "double_blocks": cfg.depth,
            "single_blocks": cfg.depth_single_blocks,
        },
        pipeline_spec=_flux_pipeline_spec(module, cfg),
    )
