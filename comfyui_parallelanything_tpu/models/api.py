"""The model handle the orchestrator consumes.

The reference duck-types ComfyUI's MODEL wrapper down to a bare ``diffusion_model``
with ``forward(x, timesteps, context=None, **kwargs)`` (any_device_parallel.py:921-930,
1287). The functional analogue is this dataclass: a pure ``apply`` + ``params`` pytree
+ metadata the parallel layers need (block lists for pipeline placement, preferred
dtype). ``parallelize`` accepts it directly (it satisfies the .apply/.params protocol).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax


@dataclasses.dataclass
class DiffusionModel:
    """A diffusion network as data: pure apply fn + weights + metadata."""

    apply: Callable[..., Any]
    params: Any
    name: str = "model"
    config: Any = None
    # Pipeline metadata — the analogue of the reference's block-list discovery over
    # ['double_blocks', 'single_blocks', 'transformer_blocks', 'layers'] (1156):
    # maps block-list name -> number of blocks, in execution order.
    block_lists: dict[str, int] | None = None

    def __call__(self, x, timesteps, context=None, **kwargs):
        """Jit-compiled forward (cached per shape); kwargs must be arrays here —
        route python-valued kwargs through ``apply`` directly."""
        if not hasattr(self, "_jit_apply"):
            object.__setattr__(self, "_jit_apply", jax.jit(self.apply))
        return self._jit_apply(self.params, x, timesteps, context, **kwargs)

    def n_params(self) -> int:
        import jax

        return sum(int(l.size) for l in jax.tree.leaves(self.params))
