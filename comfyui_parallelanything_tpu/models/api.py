"""The model handle the orchestrator consumes.

The reference duck-types ComfyUI's MODEL wrapper down to a bare ``diffusion_model``
with ``forward(x, timesteps, context=None, **kwargs)`` (any_device_parallel.py:921-930,
1287). The functional analogue is this dataclass: a pure ``apply`` + ``params`` pytree
+ metadata the parallel layers need (block lists for pipeline placement, preferred
dtype). ``parallelize`` accepts it directly (it satisfies the .apply/.params protocol).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax


@dataclasses.dataclass(frozen=True)
class PipelineSegment:
    """One pipeline-schedulable unit of the forward pass — usually a single block of a
    block list (the things the reference wraps in ParallelBlock, 1180-1198).

    ``param_keys`` names the top-level entries of the parameter pytree this segment
    reads, so the pipeline runner can place exactly that sub-pytree on the owning
    device. ``fn(params, carry) -> carry`` runs the segment; ``carry`` is a flat dict
    of arrays with a stable structure across every segment of the model, so stage
    programs compose and activations hop devices as one pytree.
    """

    param_keys: tuple[str, ...]
    fn: Callable[[Any, dict], dict]
    label: str = ""


@dataclasses.dataclass(frozen=True)
class PipelineSpec:
    """A model's pipeline decomposition: prepare (lead) → segments (staged) → finalize
    (lead). The functional analogue of the reference's block-list walk + ParallelBlock
    wrapping (any_device_parallel.py:1152-1198): non-block layers (embeddings, final
    norm/projection) always run on the lead device (SURVEY §3.4), block segments are
    assigned contiguous ranges proportional to device weights.
    """

    prepare_keys: tuple[str, ...]
    prepare: Callable[..., dict]  # (params, x, t, context, **kwargs) -> carry
    segments: tuple[PipelineSegment, ...]
    finalize_keys: tuple[str, ...]
    # (params, carry, out_shape) -> output; out_shape is the original input's shape
    # tuple (static at trace time), so the head can recover un-patchify geometry
    # without dragging the input array itself across devices.
    finalize: Callable[[Any, dict, tuple], Any]


@dataclasses.dataclass
class DiffusionModel:
    """A diffusion network as data: pure apply fn + weights + metadata."""

    apply: Callable[..., Any]
    params: Any
    name: str = "model"
    config: Any = None
    # Pipeline metadata — the analogue of the reference's block-list discovery over
    # ['double_blocks', 'single_blocks', 'transformer_blocks', 'layers'] (1156):
    # maps block-list name -> number of blocks, in execution order.
    block_lists: dict[str, int] | None = None
    # Staged decomposition for the batch==1 pipeline mode; None → model cannot
    # pipeline and the router falls back to single-device (parity: no known block
    # list found, 1156-1166).
    pipeline_spec: PipelineSpec | None = None
    # Model-level sampling preferences set by patch nodes (the host's
    # model_options analogue): e.g. {"cfg_rescale": 0.7} from RescaleCFG.
    # Samplers read these as defaults; explicit widget values win.
    sampler_prefs: dict | None = None
    # Loader provenance ({"path", "family"}, set by the checkpoint loaders):
    # the LoraLoader shims re-bake from the ORIGINAL file, so this must
    # survive every patch node's dataclasses.replace — hence a field, not an
    # object.__setattr__ side channel.
    source: dict | None = None
    # Serving delegation for ControlNet compositions (models/controlnet.
    # apply_control): {"base", "ctrl_apply", "ctrl_params", "hint",
    # "strength", "start", "end"}. The continuous-batching scheduler buckets
    # such a model on its BASE, carrying the control net as per-lane state —
    # so ControlNet traffic co-batches with plain txt2img instead of each
    # composition getting a private bucket. None → serve as an opaque model.
    control_delegate: dict | None = None
    # Serving delegation for baked-LoRA models (the LoraLoader shims): the
    # {"base", "factors"} pair behind this bake — ``base`` is the UNPATCHED
    # model object (the checkpoint loader's cached output, so identity
    # matches plain-traffic prompts) and ``factors`` the extracted
    # {param_path: (a, b)} map with strength pre-folded. Samplers that see
    # this submit (base, factors) to the serving tier so per-request LoRA
    # rides as per-lane state (one shared program, any LoRA mix); inline
    # legs keep using THIS model's baked params. None → bake only.
    lora_delegate: dict | None = None

    def __call__(self, x, timesteps, context=None, **kwargs):
        """Jit-compiled forward (cached per shape and per ambient sequence_parallel
        context — the ctx is read at trace time inside ops.attention); kwargs must be
        arrays here — route python-valued kwargs through ``apply`` directly."""
        from ..ops.attention import sequence_ctx_key

        if not hasattr(self, "_jit_cache"):
            object.__setattr__(self, "_jit_cache", {})
        key = sequence_ctx_key()
        fn = self._jit_cache.get(key)
        if fn is None:
            from ..utils.telemetry import instrument_jit

            # palint: allow[recompile-hazard] one name per LOADED MODEL
            # (bounded; per-model compile attribution is the point)
            fn = self._jit_cache[key] = instrument_jit(
                self.apply, f"model-apply:{self.name}"
            )
        return fn(self.params, x, timesteps, context, **kwargs)

    def n_params(self) -> int:
        import jax

        return sum(int(l.size) for l in jax.tree.leaves(self.params))
