"""WAN-class causal 3D video VAE — flax.linen, NTHWC, TPU-first.

The reference parallelizes the diffusion network only and leaves decode to the
host app; its WAN2.2 support (reference README.md:5 "Tested on … WAN2.2") therefore
presumes a host-side video VAE. Standalone, this module is that stage: it maps
pixel clips (B, T, H, W, 3) to latent clips (B, 1+(T-1)/4, H/8, W/8, z) and back.

Compression semantics match the WAN family: 8× spatial, 4× temporal, with the
first frame kept un-downsampled in time so a clip of T = 4k+1 frames encodes to
k+1 latent frames (a single image, T=1, encodes to one latent frame — the video
VAE subsumes the image case). All temporal convolutions are *causal* (front-
padded only), so frame t's latent never depends on frames > t.

TPU-first choices versus the torch original's streaming design: the torch
implementation processes 4-frame chunks with a per-conv feature cache (a mutable
device-pinned structure of exactly the kind SURVEY §2c's `clear_flux_caches`
exists to clean up). Here the whole clip is one fixed-shape program — causality
comes from explicit front padding, XLA sees static shapes, and there is no cache
state at all. Memory at large resolutions is bounded by `decode_tiled` (spatial
tiling with blended overlaps, one compiled program per tile shape), which works
for video because spatial convs never mix across tiles' interiors beyond the
overlap and temporal convs are tile-local.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import attention_local
from ..ops.basic import rms_normalize
from .tiling import blend_mask1d, tile_starts

# Per-channel latent statistics of the WAN 16-channel VAE (the published
# normalization constants; latents are stored as (z - mean) / std).
WAN_LATENT_MEAN = (
    -0.7571, -0.7089, -0.9113, 0.1075, -0.1745, 0.9653, -0.1517, 1.5508,
    0.4134, -0.0715, 0.5517, -0.3632, -0.1922, -0.9497, 0.2503, -0.2921,
)
WAN_LATENT_STD = (
    2.8184, 1.4541, 2.3275, 2.6558, 1.2196, 1.7708, 2.6052, 2.0743,
    3.2687, 2.1526, 2.8652, 1.5579, 1.6382, 1.1253, 2.8251, 1.9160,
)


@dataclasses.dataclass(frozen=True)
class VideoVAEConfig:
    in_channels: int = 3
    z_channels: int = 16
    base_channels: int = 96
    channel_mult: tuple[int, ...] = (1, 2, 4, 4)
    num_res_blocks: int = 2
    # Per non-final level: does the downsample at the end of this level also
    # halve time? (False, True, True) → spatial 8x, temporal 4x.
    temporal_downsample: tuple[bool, ...] = (False, True, True)
    latent_mean: tuple[float, ...] = WAN_LATENT_MEAN
    latent_std: tuple[float, ...] = WAN_LATENT_STD
    dtype: Any = jnp.bfloat16

    @property
    def spatial_factor(self) -> int:
        return 2 ** (len(self.channel_mult) - 1)

    @property
    def temporal_factor(self) -> int:
        return 2 ** sum(self.temporal_downsample)

    def latent_frames(self, t: int) -> int:
        """Pixel frames → latent frames (first frame never merged)."""
        f = self.temporal_factor
        if (t - 1) % f:
            raise ValueError(f"frame count must be 1 mod {f}, got {t}")
        return 1 + (t - 1) // f


def wan_vae_config(**overrides) -> VideoVAEConfig:
    return dataclasses.replace(VideoVAEConfig(), **overrides)


class _RMSNormC(nn.Module):
    """Channel-wise RMS norm over the last axis (WAN's `F.normalize * √C * γ`
    form is algebraically this), optional bias for the attention-block variant."""

    use_bias: bool = False

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        gamma = self.param("scale", nn.initializers.ones, (c,))
        y = rms_normalize(x, gamma, eps=1e-12)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (c,))
            y = (y.astype(jnp.float32) + bias).astype(x.dtype)
        return y


class CausalConv3d(nn.Module):
    """3D conv on NTHWC with causal (front-only) time padding and SAME spatial
    padding. With time stride s and kernel kt, front pad kt-1 gives
    T → (T-1)//s + 1 — exactly the first-frame-preserving schedule."""

    features: int
    kernel: tuple[int, int, int] = (3, 3, 3)
    strides: tuple[int, int, int] = (1, 1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kt, kh, kw = self.kernel
        x = jnp.pad(
            x,
            (
                (0, 0),
                (kt - 1, 0),
                (kh // 2, kh // 2),
                (kw // 2, kw // 2),
                (0, 0),
            ),
        )
        return nn.Conv(
            self.features, self.kernel, strides=self.strides, padding="VALID",
            dtype=self.dtype, name="conv",
        )(x)


class VideoResBlock(nn.Module):
    cfg: VideoVAEConfig
    out_ch: int

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = _RMSNormC(name="norm1")(x)
        h = nn.silu(h)
        h = CausalConv3d(self.out_ch, dtype=cfg.dtype, name="conv1")(h)
        h = _RMSNormC(name="norm2")(h)
        h = nn.silu(h)
        h = CausalConv3d(self.out_ch, dtype=cfg.dtype, name="conv2")(h)
        if x.shape[-1] != self.out_ch:
            x = CausalConv3d(
                self.out_ch, kernel=(1, 1, 1), dtype=cfg.dtype, name="shortcut"
            )(x)
        return x + h


class FrameAttnBlock(nn.Module):
    """Per-frame 2D single-head spatial attention (the mid-block attention);
    frames fold into the batch so time never mixes here."""

    cfg: VideoVAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, H, W, C = x.shape
        h = _RMSNormC(use_bias=True, name="norm")(x)
        qkv = nn.Conv(3 * C, (1, 1, 1), dtype=cfg.dtype, name="to_qkv")(h)
        q, k, v = jnp.split(qkv.reshape(B * T, H * W, 1, 3 * C), 3, axis=-1)
        h = attention_local(q, k, v).reshape(B, T, H, W, C)
        h = nn.Conv(C, (1, 1, 1), dtype=cfg.dtype, name="proj")(h)
        return x + h


class SpatialDownsample(nn.Module):
    """(0,1)×(0,1) zero pad + stride-2 VALID conv on H,W (frame-local)."""

    cfg: VideoVAEConfig
    temporal: bool = False

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        h = jnp.pad(x, ((0, 0), (0, 0), (0, 1), (0, 1), (0, 0)))
        h = nn.Conv(
            c, (1, 3, 3), strides=(1, 2, 2), padding="VALID",
            dtype=self.cfg.dtype, name="conv",
        )(h)
        if self.temporal:
            # Causal stride-2 time conv: front pad 2, kernel 3 → (T-1)//2 + 1.
            h = CausalConv3d(
                c, kernel=(3, 1, 1), strides=(2, 1, 1),
                dtype=self.cfg.dtype, name="time_conv",
            )(h)
        return h


class SpatialUpsample(nn.Module):
    """Nearest 2× on H,W + 3×3 conv halving channels; in temporal mode a causal
    time conv emits two frames per input frame and the first duplicate is
    dropped, so T latent frames → 2T-1 pixel-side frames (inverse of the causal
    downsample schedule)."""

    cfg: VideoVAEConfig
    temporal: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, T, H, W, C = x.shape
        if self.temporal:
            h = CausalConv3d(
                2 * C, kernel=(3, 1, 1), dtype=cfg.dtype, name="time_conv"
            )(x)
            # (B,T,H,W,2C) → interleave the two C-halves along time → (B,2T,…,C)
            h = (
                h.reshape(B, T, H, W, 2, C)
                .transpose(0, 1, 4, 2, 3, 5)
                .reshape(B, 2 * T, H, W, C)
            )
            x = h[:, 1:]  # first frame contributes once
            T = 2 * T - 1
        x = jax.image.resize(x, (B, T, 2 * H, 2 * W, x.shape[-1]), method="nearest")
        return nn.Conv(
            x.shape[-1] // 2, (1, 3, 3), padding=(0, 1, 1),
            dtype=cfg.dtype, name="conv",
        )(x)


class VideoEncoder(nn.Module):
    cfg: VideoVAEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = CausalConv3d(cfg.base_channels, dtype=cfg.dtype, name="conv_in")(
            x.astype(cfg.dtype)
        )
        for level, mult in enumerate(cfg.channel_mult):
            ch = cfg.base_channels * mult
            for i in range(cfg.num_res_blocks):
                h = VideoResBlock(cfg, ch, name=f"down_{level}_block_{i}")(h)
            if level != len(cfg.channel_mult) - 1:
                h = SpatialDownsample(
                    cfg, temporal=cfg.temporal_downsample[level],
                    name=f"down_{level}_downsample",
                )(h)
        h = VideoResBlock(cfg, h.shape[-1], name="mid_block_1")(h)
        h = FrameAttnBlock(cfg, name="mid_attn_1")(h)
        h = VideoResBlock(cfg, h.shape[-1], name="mid_block_2")(h)
        h = _RMSNormC(name="norm_out")(h)
        h = nn.silu(h)
        return CausalConv3d(2 * cfg.z_channels, dtype=cfg.dtype, name="conv_out")(h)


class VideoDecoder(nn.Module):
    """Mirror of the encoder. Channel plan follows the WAN decoder: each
    upsample halves channels, so the first block of every post-upsample level
    re-expands from half the previous level's width."""

    cfg: VideoVAEConfig

    @nn.compact
    def __call__(self, z):
        cfg = self.cfg
        ch = cfg.base_channels * cfg.channel_mult[-1]
        h = CausalConv3d(ch, dtype=cfg.dtype, name="conv_in")(z.astype(cfg.dtype))
        h = VideoResBlock(cfg, ch, name="mid_block_1")(h)
        h = FrameAttnBlock(cfg, name="mid_attn_1")(h)
        h = VideoResBlock(cfg, ch, name="mid_block_2")(h)
        temporal_up = tuple(reversed(cfg.temporal_downsample))
        n = len(cfg.channel_mult)
        for j, level in enumerate(reversed(range(n))):
            ch = cfg.base_channels * cfg.channel_mult[level]
            for i in range(cfg.num_res_blocks + 1):
                h = VideoResBlock(cfg, ch, name=f"up_{level}_block_{i}")(h)
            if j != n - 1:
                h = SpatialUpsample(
                    cfg, temporal=temporal_up[j], name=f"up_{level}_upsample"
                )(h)
        h = _RMSNormC(name="norm_out")(h)
        h = nn.silu(h)
        return CausalConv3d(cfg.in_channels, dtype=cfg.dtype, name="conv_out")(h)


class VideoAutoencoderKL(nn.Module):
    cfg: VideoVAEConfig

    def setup(self):
        cfg = self.cfg
        self.encoder = VideoEncoder(cfg, name="encoder")
        self.decoder = VideoDecoder(cfg, name="decoder")
        self.quant_conv = CausalConv3d(
            2 * cfg.z_channels, kernel=(1, 1, 1), dtype=cfg.dtype, name="quant_conv"
        )
        self.post_quant_conv = CausalConv3d(
            cfg.z_channels, kernel=(1, 1, 1), dtype=cfg.dtype, name="post_quant_conv"
        )

    def moments(self, x):
        h = self.quant_conv(self.encoder(x))
        mean, logvar = jnp.split(h, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def encode(self, x, rng=None):
        """Clip (B,T,H,W,3 in [-1,1], T ≡ 1 mod temporal_factor) → normalized
        latent (B, 1+(T-1)/tf, H/8, W/8, z). Posterior mean unless ``rng``."""
        mean, logvar = self.moments(x)
        z = mean
        if rng is not None:
            z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
                rng, mean.shape, mean.dtype
            )
        mu = jnp.asarray(self.cfg.latent_mean, z.dtype)
        sd = jnp.asarray(self.cfg.latent_std, z.dtype)
        return (z - mu) / sd

    def decode(self, z):
        mu = jnp.asarray(self.cfg.latent_mean, z.dtype)
        sd = jnp.asarray(self.cfg.latent_std, z.dtype)
        return self.decoder(self.post_quant_conv(z * sd + mu))

    def __call__(self, x, rng=None):
        return self.decode(self.encode(x, rng))


@dataclasses.dataclass(frozen=True)
class VideoVAE:
    """Video VAE as data: jit-cached encode/decode + weights (same shape as
    models.vae.VAE so nodes/pipelines treat image and video VAEs uniformly)."""

    cfg: VideoVAEConfig
    params: Any

    def _jitted(self, method):
        if not hasattr(self, "_jit_cache"):
            object.__setattr__(self, "_jit_cache", {})
        fn = self._jit_cache.get(method)
        if fn is None:
            module = VideoAutoencoderKL(self.cfg)
            fn = self._jit_cache[method] = jax.jit(
                lambda p, *a: module.apply({"params": p}, *a, method=method)
            )
        return fn

    def encode(self, x, rng=None):
        return self._jitted(VideoAutoencoderKL.encode)(self.params, x, rng)

    def decode(self, z):
        return self._jitted(VideoAutoencoderKL.decode)(self.params, z)

    @property
    def spatial_factor(self) -> int:
        return self.cfg.spatial_factor

    @property
    def temporal_factor(self) -> int:
        return self.cfg.temporal_factor

    def decode_tiled(self, z, tile: int = 32, overlap: int = 8):
        """Spatially tiled decode with linear overlap blending (time stays
        whole — temporal convs are causal along an axis tiling never cuts)."""
        B, T, H, W, C = z.shape
        if H <= tile and W <= tile:
            return self.decode(z)
        if not 0 <= overlap < tile:
            raise ValueError(f"need 0 <= overlap < tile, got {overlap=} {tile=}")
        f = self.spatial_factor
        t_out = self.cfg.temporal_factor * (T - 1) + 1
        stride = tile - overlap
        decode = functools.partial(
            self._jitted(VideoAutoencoderKL.decode), self.params
        )
        th, tw = min(tile, H), min(tile, W)
        mask = (
            blend_mask1d(th, overlap, f)[:, None]
            * blend_mask1d(tw, overlap, f)[None, :]
        )[None, None, :, :, None]
        out = np.zeros((B, t_out, H * f, W * f, self.cfg.in_channels), np.float32)
        weight = np.zeros((1, 1, H * f, W * f, 1), np.float32)
        for hs in tile_starts(H, th, stride):
            for ws in tile_starts(W, tw, stride):
                dec = np.asarray(
                    decode(z[:, :, hs : hs + th, ws : ws + tw, :]), np.float32
                )
                out[:, :, hs * f : (hs + th) * f, ws * f : (ws + tw) * f] += dec * mask
                weight[:, :, hs * f : (hs + th) * f, ws * f : (ws + tw) * f] += mask
        return jnp.asarray(out / weight)


def build_video_vae(
    cfg: VideoVAEConfig, rng=None, params=None, sample_thw=(5, 16, 16)
) -> VideoVAE:
    """Initialize (or wrap pre-converted ``params``) a video VAE."""
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        module = VideoAutoencoderKL(cfg)
        t, h, w = sample_thw
        x = jnp.zeros((1, t, h, w, cfg.in_channels), jnp.float32)
        params = module.init(rng, x)["params"]
    return VideoVAE(cfg=cfg, params=params)
