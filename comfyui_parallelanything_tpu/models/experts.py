"""Timestep-boundary expert switching — the WAN2.2 A14B two-expert denoiser.

WAN2.2's 14B release splits denoising between two full DiT checkpoints: a
high-noise expert for early steps and a low-noise expert for the rest, switched
at a fixed flow-time boundary. The reference handles this transparently because
its host app picks the model per step and the wrapper only patches whichever
forward it is given (any_device_parallel.py:1450-1451); standalone, this wrapper
is that per-step selection.

Design: the samplers are host-side loops (sampling/ddim.py docstring) whose
timestep values are concrete at each call, so the switch is plain Python — no
`lax.cond` over two 14B parameter sets (which would force both experts resident
in one program). Each expert can be `parallelize`d independently, and each keeps
its own compiled programs; the boundary never recompiles anything.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp

# Official WAN2.2 A14B switch points (flow time in [0, 1]).
WAN22_T2V_BOUNDARY = 0.875
WAN22_I2V_BOUNDARY = 0.900


@dataclasses.dataclass
class TimestepExpertSwitch:
    """Callable denoiser that routes each step to one of two experts by the
    step's flow time: ``t >= boundary`` → ``high_noise``, else ``low_noise``.

    Timestep units follow the sampler driving it (flow samplers pass t ∈ [0, 1];
    pass a boundary in the same units if driving with another family). Both
    experts may be bare DiffusionModels or ParallelModels — parallelize them
    separately, with different chains if desired.
    """

    high_noise: Any
    low_noise: Any
    boundary: float = WAN22_T2V_BOUNDARY

    def expert_for(self, timesteps) -> Any:
        t = float(jnp.max(jnp.asarray(timesteps)))
        return self.high_noise if t >= self.boundary else self.low_noise

    def __call__(self, x, timesteps, context=None, **kwargs):
        return self.expert_for(timesteps)(x, timesteps, context, **kwargs)

    @property
    def model_config(self):
        from ..parallel.orchestrator import model_config_of

        return model_config_of(self.high_noise)

    def cleanup(self) -> None:
        for expert in (self.high_noise, self.low_noise):
            fn = getattr(expert, "cleanup", None)
            if fn is not None:
                fn()
