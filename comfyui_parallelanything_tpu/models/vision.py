"""CLIP vision towers (ViT) — flax.linen, NHWC, TPU-first.

The host's CLIPVisionLoader/CLIPVisionEncode family: the image half of CLIP,
consumed by unCLIP checkpoints, IPAdapter-style image prompting, and
image-conditioned video models. Standalone implementation of the HF
``CLIPVisionModel`` architecture (patch-conv embed + CLS token + learned
positions, pre-LN transformer — the same block as the text towers,
``text_encoders._CLIPBlock`` — post-LN pooled CLS, optional visual
projection), converted from the HF-layout safetensors the public clip-vision
checkpoints ship (``vision_model.*`` keys).

Outputs follow the host's CLIP_VISION_OUTPUT shape: projected
``image_embeds``, final-LN ``last_hidden``, and the raw ``penultimate``
hidden states (what IPAdapter-plus style consumers read).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from .text_encoders import CLIPTextConfig, _CLIPBlock

# OpenAI CLIP preprocessing constants (the host's clip_preprocess).
CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
CLIP_STD = (0.26862954, 0.26130258, 0.27577711)


@dataclasses.dataclass(frozen=True)
class CLIPVisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int | None = None  # default 4*hidden
    act: str = "quick_gelu"               # ViT-L; ViT-H/bigG use "gelu"
    projection_dim: int | None = 768
    dtype: Any = jnp.bfloat16

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def block_cfg(self) -> CLIPTextConfig:
        """The shared-transformer-block view of this config (the text and
        vision towers use the identical pre-LN block)."""
        return CLIPTextConfig(
            hidden_size=self.hidden_size, num_heads=self.num_heads,
            intermediate_size=self.intermediate_size, act=self.act,
            dtype=self.dtype,
        )


def clip_vit_l_14_config(**overrides) -> CLIPVisionConfig:
    """OpenAI CLIP ViT-L/14 vision tower (SD unCLIP-small / IPAdapter sd15)."""
    return dataclasses.replace(CLIPVisionConfig(), **overrides)


def clip_vit_h_14_config(**overrides) -> CLIPVisionConfig:
    """OpenCLIP ViT-H/14 vision tower (the common IPAdapter image encoder)."""
    base = CLIPVisionConfig(
        hidden_size=1280, num_layers=32, num_heads=16, act="gelu",
        projection_dim=1024,
    )
    return dataclasses.replace(base, **overrides)


def clip_vit_bigg_14_config(**overrides) -> CLIPVisionConfig:
    """OpenCLIP bigG/14 vision tower (SDXL-family image conditioning)."""
    base = CLIPVisionConfig(
        hidden_size=1664, num_layers=48, num_heads=16,
        intermediate_size=8192, act="gelu", projection_dim=1280,
    )
    return dataclasses.replace(base, **overrides)


class CLIPVisionModel(nn.Module):
    """forward(images NHWC, already clip-preprocessed to
    (B, image_size, image_size, 3)) → (image_embeds, last_hidden,
    penultimate)."""

    cfg: CLIPVisionConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        x = images.astype(cfg.dtype)
        p = cfg.patch_size
        # HF patch_embedding: Conv(3→hidden, k=p, s=p, bias=False).
        x = nn.Conv(
            cfg.hidden_size, (p, p), strides=(p, p), use_bias=False,
            dtype=cfg.dtype, name="patch_embed",
        )(x)
        B = x.shape[0]
        x = x.reshape(B, -1, cfg.hidden_size)
        cls = self.param(
            "class_embedding", nn.initializers.normal(0.02), (cfg.hidden_size,)
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(cfg.dtype), (B, 1, cfg.hidden_size)), x],
            axis=1,
        )
        pos = self.param(
            "pos_emb", nn.initializers.normal(0.02),
            (cfg.num_patches + 1, cfg.hidden_size),
        )
        x = x + pos[None].astype(cfg.dtype)
        x = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="pre_ln")(x)
        bias = jnp.zeros((1, 1, 1, 1), jnp.float32)  # no mask for vision
        block_cfg = cfg.block_cfg()
        penultimate = None
        for i in range(cfg.num_layers):
            if i == cfg.num_layers - 1:
                penultimate = x
            x = _CLIPBlock(block_cfg, name=f"layers_{i}")(x, bias)
        post_ln = nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name="post_ln")
        pooled = post_ln(x[:, 0])
        if cfg.projection_dim is not None:
            pooled = nn.Dense(
                cfg.projection_dim, use_bias=False, dtype=cfg.dtype,
                name="visual_proj",
            )(pooled)
        # HF convention: last_hidden_state is the RAW encoder output —
        # post_layernorm applies only to the pooled CLS token.
        return pooled, x, penultimate


@dataclasses.dataclass
class VisionEncoder:
    """A vision tower as data (the TextEncoder pattern)."""

    apply: Any
    params: Any
    cfg: CLIPVisionConfig
    name: str = "clip-vision"

    def __call__(self, images):
        import jax

        if not hasattr(self, "_jit"):
            object.__setattr__(self, "_jit", jax.jit(self.apply))
        return self._jit(self.params, images)


def build_clip_vision(cfg: CLIPVisionConfig, rng=None, params=None,
                      name="clip-vision") -> VisionEncoder:
    module = CLIPVisionModel(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        params = module.init(
            rng, jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
        )["params"]

    def apply(p, images):
        return module.apply({"params": p}, images)

    return VisionEncoder(apply=apply, params=params, cfg=cfg, name=name)


def clip_preprocess(images, size: int = 224, crop: bool = True):
    """The host's clip_preprocess: [0,1] NHWC images → ``size``-square,
    CLIP-normalized input. ``crop=True`` resizes the short side bicubically
    then center-crops (the OpenAI/HF image processor); ``crop=False``
    squashes straight to the square (the stock node's crop="none")."""
    import jax
    import jax.numpy as jnp

    img = jnp.asarray(images)
    if img.ndim == 3:
        img = img[None]
    B, H, W, C = img.shape
    if crop:
        scale = size / min(H, W)
        nh, nw = max(size, round(H * scale)), max(size, round(W * scale))
        img = jax.image.resize(img, (B, nh, nw, C), method="cubic")
        y0, x0 = (nh - size) // 2, (nw - size) // 2
        img = img[:, y0:y0 + size, x0:x0 + size, :]
    else:
        img = jax.image.resize(img, (B, size, size, C), method="cubic")
    mean = jnp.asarray(CLIP_MEAN, jnp.float32)
    std = jnp.asarray(CLIP_STD, jnp.float32)
    return (jnp.clip(img, 0.0, 1.0) - mean) / std


# ---------------------------------------------------------------------------
# Checkpoint conversion (HF CLIPVisionModel layout)
# ---------------------------------------------------------------------------


def sniff_vision_config(sd) -> CLIPVisionConfig:
    """Infer the tower from an HF-layout state dict: width/patch from the
    patch conv, depth from the layer indices, act by the known families."""
    import re

    pe = np.asarray(sd["vision_model.embeddings.patch_embedding.weight"])
    hidden, _, patch, _ = pe.shape
    pos = np.asarray(sd["vision_model.embeddings.position_embedding.weight"])
    image_size = int(round((pos.shape[0] - 1) ** 0.5)) * patch
    layers = 1 + max(
        int(m.group(1)) for k in sd
        if (m := re.match(r"vision_model\.encoder\.layers\.(\d+)\.", k))
    )
    fc1 = np.asarray(sd["vision_model.encoder.layers.0.mlp.fc1.weight"])
    proj = None
    if "visual_projection.weight" in sd:
        proj = int(np.asarray(sd["visual_projection.weight"]).shape[0])
    # Head counts by family: OpenAI ViT-B/L keep 64-wide heads (12/16), but
    # OpenCLIP ViT-H (1280) and bigG (1664) both use 16 heads (head widths
    # 80/104) — see clip_vit_h_14_config/clip_vit_bigg_14_config above.
    heads = {768: 12, 1024: 16, 1280: 16, 1664: 16}.get(
        hidden, max(1, hidden // 64)
    )
    # quick_gelu is the OpenAI ViT-L convention, exact gelu everything larger.
    return CLIPVisionConfig(
        image_size=image_size, patch_size=patch, hidden_size=hidden,
        num_layers=layers, num_heads=heads,
        intermediate_size=int(fc1.shape[0]),
        act="quick_gelu" if hidden <= 1024 else "gelu",
        projection_dim=proj,
    )


def openclip_visual_to_hf(sd) -> dict:
    """OpenCLIP ``visual.*`` layout → HF ``vision_model.*`` key layout.

    The sd21-unclip checkpoints bundle their ViT-H image encoder in OpenCLIP
    form (``embedder.model.visual.*`` — fused qkv ``in_proj``, ``ln_pre``/
    ``ln_post``, ``mlp.c_fc``/``c_proj``, a raw ``proj`` matrix); the host's
    unCLIPCheckpointLoader reads it directly from the checkpoint. Pure key
    rewrite (+ the qkv third-split and proj transpose) into the HF names
    ``convert_clip_vision_checkpoint`` consumes. Keys are expected relative
    to the ``visual.`` root (strip any outer prefix first)."""
    from .convert import to_numpy

    out: dict = {}
    for k, v in sd.items():
        parts = k.split(".")
        if k == "conv1.weight":
            out["vision_model.embeddings.patch_embedding.weight"] = v
        elif k == "class_embedding":
            out["vision_model.embeddings.class_embedding"] = v
        elif k == "positional_embedding":
            out["vision_model.embeddings.position_embedding.weight"] = v
        elif parts[0] == "ln_pre":
            out[f"vision_model.pre_layrnorm.{parts[1]}"] = v
        elif parts[0] == "ln_post":
            out[f"vision_model.post_layernorm.{parts[1]}"] = v
        elif k == "proj":
            out["visual_projection.weight"] = to_numpy(v).T
        elif parts[0] == "transformer" and parts[1] == "resblocks":
            n = parts[2]
            lp = f"vision_model.encoder.layers.{n}."
            rest = ".".join(parts[3:])
            if rest in ("attn.in_proj_weight", "attn.in_proj_bias"):
                arr = to_numpy(v)
                third = arr.shape[0] // 3
                kind = "weight" if rest.endswith("weight") else "bias"
                for i, name in enumerate(("q_proj", "k_proj", "v_proj")):
                    out[f"{lp}self_attn.{name}.{kind}"] = (
                        arr[i * third:(i + 1) * third]
                    )
            else:
                sub = {
                    "ln_1": "layer_norm1", "ln_2": "layer_norm2",
                    "attn": "self_attn", "mlp": "mlp",
                    "c_fc": "fc1", "c_proj": "fc2", "out_proj": "out_proj",
                }
                mapped = ".".join(sub.get(p, p) for p in parts[3:])
                out[lp + mapped] = v
        else:
            raise KeyError(f"unrecognized OpenCLIP visual key: {k}")
    return out


def convert_clip_vision_checkpoint(sd, cfg: CLIPVisionConfig | None = None):
    """HF ``vision_model.*`` state dict → ``CLIPVisionModel`` params (+cfg).
    OpenCLIP ``visual.*``-layout dicts (unclip checkpoints' bundled tower)
    are detected and remapped first."""
    from .convert import conv_kernel, dense_params, to_numpy, tree_to_jnp

    if "conv1.weight" in sd and "class_embedding" in sd:
        sd = openclip_visual_to_hf(sd)
    if cfg is None:
        cfg = sniff_vision_config(sd)
    pre = "vision_model."
    p: dict = {
        "class_embedding": to_numpy(sd[f"{pre}embeddings.class_embedding"]).reshape(-1),
        "patch_embed": {
            "kernel": conv_kernel(sd[f"{pre}embeddings.patch_embedding.weight"])
        },
        "pos_emb": to_numpy(sd[f"{pre}embeddings.position_embedding.weight"]),
        "pre_ln": {
            "scale": to_numpy(sd[f"{pre}pre_layrnorm.weight"]),  # HF's typo'd name
            "bias": to_numpy(sd[f"{pre}pre_layrnorm.bias"]),
        },
        "post_ln": {
            "scale": to_numpy(sd[f"{pre}post_layernorm.weight"]),
            "bias": to_numpy(sd[f"{pre}post_layernorm.bias"]),
        },
    }
    for i in range(cfg.num_layers):
        lp = f"{pre}encoder.layers.{i}."
        p[f"layers_{i}"] = {
            "ln1": {"scale": to_numpy(sd[f"{lp}layer_norm1.weight"]),
                    "bias": to_numpy(sd[f"{lp}layer_norm1.bias"])},
            "ln2": {"scale": to_numpy(sd[f"{lp}layer_norm2.weight"]),
                    "bias": to_numpy(sd[f"{lp}layer_norm2.bias"])},
            "q": dense_params(sd, f"{lp}self_attn.q_proj"),
            "k": dense_params(sd, f"{lp}self_attn.k_proj"),
            "v": dense_params(sd, f"{lp}self_attn.v_proj"),
            "out": dense_params(sd, f"{lp}self_attn.out_proj"),
            "fc1": dense_params(sd, f"{lp}mlp.fc1"),
            "fc2": dense_params(sd, f"{lp}mlp.fc2"),
        }
    if cfg.projection_dim is not None and "visual_projection.weight" in sd:
        p["visual_proj"] = {
            "kernel": to_numpy(sd["visual_projection.weight"]).T
        }
    return tree_to_jnp(p), cfg


def load_clip_vision_checkpoint(src, cfg: CLIPVisionConfig | None = None,
                                name: str = "clip-vision") -> VisionEncoder:
    from .loader import _resolve_state_dict

    params, cfg = convert_clip_vision_checkpoint(_resolve_state_dict(src), cfg)
    return build_clip_vision(cfg, params=params, name=name)
