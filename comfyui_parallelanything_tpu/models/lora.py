"""Per-lane LoRA as data: low-rank factor maps oriented to a param pytree.

The reference patches LoRA weights into the ONE live model (its host's
ModelPatcher bakes deltas in place), so two prompts wanting different LoRAs
serialize on patch/unpatch. The serving tier instead treats LoRA as request
state: a factor map ``{param_path: (a, b)}`` with ``W_eff = W + b @ a`` rides
the ServeRequest, the bucket stacks factors on the lane axis (rank-padded,
zero rows for LoRA-free lanes), and the lane-step program applies the deltas
inside the shared eval — the Punica/S-LoRA batched-adapter formulation
(PAPERS.md), so any LoRA mix shares one compiled program.

Orientation contract: for a target leaf ``W`` of shape ``(m, k)``, the factor
pair is ``a: (r, k)``, ``b: (m, r)`` and the merge is ``W + b @ a`` — strength
and alpha/rank are pre-folded into ``b``. Checkpoint LoRA pairs (torch
``up @ down`` on ``[out, in]`` weights) are re-oriented at extraction time, so
flax ``kernel`` leaves (``[in, out]``, see convert.linear_kernel) get the
transposed pair. v1 scope: 2-D targets only (attention/MLP matmuls — where
LoRA rank lives); conv targets fall back to ``bake_lora`` via merge.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..utils.logging import get_logger

# kohya flattens dots to underscores and prefixes the module-tree root; the
# same strip list bake_lora uses (convert.py) so both patch paths agree.
_PREFIXES = ("lora_unet_", "lora_transformer_", "lora_te1_", "lora_te2_",
             "lora_te_", "lora_")


def flatten_params(params, prefix=""):
    """Nested dict pytree → {'/'-joined path: leaf}. Dict-only trees (the flax
    convention every converter in this repo produces)."""
    out = {}
    if isinstance(params, dict):
        for k in params:
            out.update(flatten_params(params[k], f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = params
    return out


def get_path(params, path):
    node = params
    for part in path.split("/"):
        node = node[part]
    return node


def set_path(params, path, value):
    """Functional path update: returns a new tree sharing unmodified subtrees."""
    parts = path.split("/")
    def rec(node, i):
        if i == len(parts) - 1:
            new = dict(node)
            new[parts[i]] = value
            return new
        new = dict(node)
        new[parts[i]] = rec(node[parts[i]], i + 1)
        return new
    return rec(params, 0)


def extract_lora_factors(lora_sd, params, strength=1.0, unmatched_out=None):
    """LoRA state dict → ``{param_path: (a, b)}`` oriented to ``params``.

    Matching mirrors convert.bake_lora (prefix strip, underscore-normalized
    lookup, unique-suffix fallback) but against '/'-joined pytree paths with
    the flax ``kernel`` leaf standing in for torch ``.weight``. Non-2-D and
    unmatched targets are logged and skipped (reference prints-and-continues
    on patch failures, any_device_parallel.py:1002-1004);
    ``unmatched_out`` (a list) additionally collects the skipped base keys,
    so a caller deciding whether the factor map fully covers a bake (the
    LoraLoader serving delegate) can tell "clean" from "partial".
    """
    from .convert import _lora_pairs, to_numpy

    flat = flatten_params(params)
    by_norm: dict[str, list[str]] = {}
    for path in flat:
        norm = path.replace("/", "_").replace(".", "_")
        for leaf in ("_kernel", "_weight"):
            if norm.endswith(leaf):
                norm = norm[: -len(leaf)]
                break
        by_norm.setdefault(norm, []).append(path)

    out: dict[str, tuple] = {}
    unmatched = []
    for base, (down, up, alpha) in _lora_pairs(lora_sd).items():
        stripped = base
        for prefix in _PREFIXES:
            if stripped.startswith(prefix):
                stripped = stripped[len(prefix):]
                break
        norm = stripped.replace(".", "_")
        hits = by_norm.get(norm)
        if not hits:
            suffix_hits = [v for k, v in by_norm.items()
                           if k.endswith("_" + norm)]
            hits = suffix_hits[0] if len(suffix_hits) == 1 else None
        if not hits or len(hits) != 1:
            unmatched.append(base)
            continue
        path = hits[0]
        w = flat[path]
        down_a = np.asarray(to_numpy(down), np.float32)
        up_a = np.asarray(to_numpy(up), np.float32)
        rank = down_a.shape[0]
        scale = float(strength) * ((alpha / rank) if alpha is not None else 1.0)
        if getattr(w, "ndim", 0) != 2 or down_a.ndim != 2 or up_a.ndim != 2:
            unmatched.append(base)
            continue
        if w.shape == (up_a.shape[0], down_a.shape[1]):
            # torch orientation [out, in]: delta = (scale·up) @ down
            a, b = down_a, up_a * scale
        elif w.shape == (down_a.shape[1], up_a.shape[0]):
            # flax kernel [in, out]: delta = down.T @ (scale·up).T
            a, b = (up_a * scale).T, down_a.T
        else:
            unmatched.append(base)
            continue
        out[path] = (jnp.asarray(a), jnp.asarray(b))
    if unmatched:
        get_logger().warning(
            "extract_lora_factors: %d LoRA key(s) had no batchable 2-D base "
            "match and were skipped: %s", len(unmatched), unmatched[:5],
        )
        if unmatched_out is not None:
            unmatched_out.extend(unmatched)
    return out


def combine_factors(maps):
    """N adapter factor maps → one, by rank concatenation (the multi-LoRA
    request: Σⱼ bⱼ @ aⱼ == concat(b) @ concat(a), so a 2-LoRA lane costs one
    padded rank slot, not two program variants)."""
    maps = [m for m in maps if m]
    if not maps:
        return {}
    if len(maps) == 1:
        return dict(maps[0])
    out: dict[str, tuple] = {}
    for m in maps:
        for path, (a, b) in m.items():
            if path in out:
                a0, b0 = out[path]
                out[path] = (jnp.concatenate([a0, a], axis=0),
                             jnp.concatenate([b0, b], axis=1))
            else:
                out[path] = (a, b)
    return out


def lora_signature(factors, params):
    """Hashable shape signature ``((path, m, k), ...)`` sorted by path, or
    None when any factor does not line up with a leaf of ``params`` — the
    scheduler's batchability check. nd leaves (head-split attention kernels,
    conv) are addressed through their ``(shape[0], prod(rest))`` flattening;
    the merge reshapes the delta back."""
    if not factors:
        return ()
    flat = flatten_params(params)
    sig = []
    for path in sorted(factors):
        a, b = factors[path]
        w = flat.get(path)
        if w is None or getattr(w, "ndim", 0) < 2:
            return None
        m = int(w.shape[0])
        k = 1
        for d in w.shape[1:]:
            k *= int(d)
        if a.ndim != 2 or b.ndim != 2 or a.shape[1] != k or b.shape[0] != m \
                or a.shape[0] != b.shape[1]:
            return None
        sig.append((path, m, k))
    return tuple(sig)


def pad_rank(a, b, r_max):
    """Zero-pad a factor pair to rank ``r_max`` (zero rank slots contribute a
    bitwise-zero delta, so rank masking is structural, not arithmetic)."""
    r = a.shape[0]
    if r == r_max:
        return a, b
    a = jnp.pad(a, ((0, r_max - r), (0, 0)))
    b = jnp.pad(b, ((0, 0), (0, r_max - r)))
    return a, b


def merge_lora_params(params, factors):
    """Eager merge: new pytree with ``W + b @ a`` at each factor path (shares
    every untouched subtree). The inline-fallback / width-1-lane twin of the
    batched in-eval delta. nd targets get the delta reshaped from the
    ``(shape[0], prod(rest))`` flattening the factors address."""
    out = params
    for path, (a, b) in factors.items():
        w = get_path(out, path)
        out = set_path(out, path,
                       (w + (b @ a).reshape(w.shape).astype(w.dtype)))
    return out


def factorize_bake(base_params, baked_params, max_rank=64, rtol=1e-5):
    """Exact low-rank factor recovery from an eager bake: SVD each changed
    leaf's delta (flattened to ``(shape[0], prod(rest))``) and keep the
    factors when the truncation reproduces it. Returns ``{path: (a, b)}``,
    or None when the bake is not representable — mismatched trees, a
    changed sub-2-D leaf (bias), or a delta that is not low-rank at
    ``max_rank`` (then the bake stays authoritative; a PARTIAL factor map
    must never ship, it would diverge from the bake).

    This is how the LoraLoader shims derive a serving delegate for CONVERTED
    param layouts (head-split attention kernels, renamed paths) that the
    checkpoint-keyed ``extract_lora_factors`` cannot address: the bake
    happens at checkpoint layout, conversion reshapes it, and the delta's
    rank survives both — so the factors come out of the weights themselves.
    """
    flat0 = flatten_params(base_params)
    flat1 = flatten_params(baked_params)
    if set(flat0) != set(flat1):
        return None
    out: dict[str, tuple] = {}
    for path, w0 in flat0.items():
        w1 = flat1[path]
        if tuple(getattr(w0, "shape", ())) != tuple(getattr(w1, "shape", ())):
            return None
        d = np.asarray(w1, np.float32) - np.asarray(w0, np.float32)
        if not d.any():
            continue
        if d.ndim < 2:
            return None  # a changed bias has no (a, b) form
        d2 = d.reshape(d.shape[0], -1)
        u, s, vt = np.linalg.svd(d2, full_matrices=False)
        cut = s[0] * rtol if s.size else 0.0
        r = int((s > cut).sum())
        if r == 0 or r > max_rank:
            return None
        b = u[:, :r] * s[:r]
        a = vt[:r]
        if not np.allclose(b @ a, d2, rtol=1e-4, atol=max(cut, 1e-7)):
            return None  # not actually low-rank at this cut
        out[path] = (jnp.asarray(a), jnp.asarray(b))
    return out or None


def lora_model(model, factors):
    """DiffusionModel with the factors merged — the eager twin used by inline
    fallback and width-1 eager lanes. A fresh handle (fresh jit cache), the
    base model object is untouched.

    Parallel chains (no ``.params`` attribute) merge on their traceable spec
    and rewrap as a plain DiffusionModel — correctness-preserving inline
    fallback (the merged single program runs unsharded; the serving lane
    path is where mesh LoRA traffic belongs)."""
    if not factors:
        return model
    if dataclasses.is_dataclass(model) and hasattr(model, "params"):
        return dataclasses.replace(
            model,
            params=merge_lora_params(model.params, factors),
            name=f"{model.name}+lora",
        )
    from ..sampling.compiled import trace_spec_of

    spec = trace_spec_of(model)
    if spec is None or not isinstance(spec.params, dict):
        raise TypeError(
            "per-request LoRA needs a model with an addressable param "
            f"pytree; {type(model).__name__} exposes none"
        )
    from .api import DiffusionModel

    return DiffusionModel(
        apply=spec.apply,
        params=merge_lora_params(spec.params, factors),
        name=f"{getattr(model, 'name', 'model')}+lora",
    )
