"""SD3/SD3.5-class MMDiT — flax.linen, bf16, TPU-first.

The reference wraps whatever diffusion model its host hands it (duck-typed
unwrap, any_device_parallel.py:921-930) — SD3-family checkpoints included.
Standalone, this is that family: dual-stream joint-attention blocks the whole
depth (no fused single blocks — the FLUX distinction), learned-at-checkpoint
sincos position table cropped to the sample grid (no RoPE), pooled CLIP(L+G)
vector + timestep modulation, optional per-head q/k RMS norm (the 3.5 models).

Same staged decomposition as models/flux.py (prepare / block_step / finalize)
so the batch==1 pipeline placement mode works identically. All three public
variants convert and run: sd3-medium, sd3.5-large, and sd3.5-medium — the
mmdit-x dual-attention x-blocks the medium model adds are implemented via
``x_block_self_attn_layers`` below (the converter infers the indices from the
checkpoint's ``joint_blocks.{i}.x_block.attn2`` keys; loader preset
``sd35-medium``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import attention
from ..ops.basic import modulate as _modulate, rms_normalize, timestep_embedding
from .api import DiffusionModel, PipelineSegment, PipelineSpec


@dataclasses.dataclass(frozen=True)
class MMDiTConfig:
    in_channels: int = 16          # latent channels (token width = p²·C)
    patch_size: int = 2
    depth: int = 24                # joint blocks; hidden = 64·depth, heads = depth
    context_in_dim: int = 4096     # T5 ‖ padded CLIP joint stream
    pooled_dim: int = 2048         # CLIP-L ‖ CLIP-G pooled
    pos_embed_max: int = 192       # checkpoint pos table is (max², hidden), cropped
    mlp_ratio: float = 4.0
    qk_norm: bool = False          # SD3.5 adds per-head q/k RMS norm
    # SD3.5-medium (mmdit-x): block indices with a SECOND self-attention over the
    # x stream only (dual attention). The converter infers this from which
    # joint_blocks.{i}.x_block.attn2 keys exist in the checkpoint.
    x_block_self_attn_layers: tuple[int, ...] = ()
    dtype: Any = jnp.bfloat16
    # SD3-family MMDiTs are rectified-flow models (see models/flux.py): the
    # KSampler node reads this to route them through flow-time k-sampling.
    prediction: str = "flow"

    @property
    def hidden_size(self) -> int:
        return 64 * self.depth

    @property
    def num_heads(self) -> int:
        return self.depth

    @property
    def head_dim(self) -> int:
        return 64


def sd3_medium_config(**overrides) -> MMDiTConfig:
    """SD3-medium (2B): depth 24, no q/k norm."""
    return dataclasses.replace(MMDiTConfig(), **overrides)


def sd35_large_config(**overrides) -> MMDiTConfig:
    """SD3.5-large (8B): depth 38, q/k RMS norm."""
    base = MMDiTConfig(depth=38, qk_norm=True)
    return dataclasses.replace(base, **overrides)


def sd35_medium_config(**overrides) -> MMDiTConfig:
    """SD3.5-medium (2.5B, mmdit-x): depth 24, q/k RMS norm, dual attention in
    the first 13 blocks (the published checkpoint's x_block_self_attn_layers —
    convert_mmdit_checkpoint re-infers the exact set from the state dict)."""
    base = MMDiTConfig(
        depth=24,
        qk_norm=True,
        pos_embed_max=384,
        x_block_self_attn_layers=tuple(range(13)),
    )
    return dataclasses.replace(base, **overrides)


def sincos_pos_embed(max_size: int, dim: int) -> np.ndarray:
    """The fixed 2-D sincos table SD3 ships in its checkpoints (stored there;
    regenerated here for from-scratch init): (max_size², dim), half the width
    per axis."""
    def axis_table(n, d):
        omega = 1.0 / (10000 ** (np.arange(d // 2, dtype=np.float64) / (d // 2)))
        out = np.einsum("p,f->pf", np.arange(n, dtype=np.float64), omega)
        return np.concatenate([np.sin(out), np.cos(out)], axis=1)

    grid_h = axis_table(max_size, dim // 2)
    grid_w = axis_table(max_size, dim // 2)
    # SAI's get_2d_sincos_pos_embed concatenates the WIDTH-axis embedding
    # first (meshgrid(grid_w, grid_h), grid[0] = w); match it so regenerated
    # tables line up with checkpoint-shipped ones.
    table = np.concatenate(
        [
            np.tile(grid_w, (max_size, 1)),
            np.repeat(grid_h, max_size, axis=0),
        ],
        axis=1,
    )
    return table.astype(np.float32)


class _VecEmbedder(nn.Module):
    """timestep/pooled MLP (SiLU between two Dense) — SAI's TimestepEmbedder/
    VectorEmbedder shape."""

    cfg: MMDiTConfig

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype, name="in_layer")(x)
        return nn.Dense(
            self.cfg.hidden_size, dtype=self.cfg.dtype, name="out_layer"
        )(nn.silu(h))


class _AdaLN(nn.Module):
    """vec → n_chunks modulation tensors (f32), SAI chunk order."""

    cfg: MMDiTConfig
    n_chunks: int

    @nn.compact
    def __call__(self, vec):
        out = nn.Dense(
            self.n_chunks * self.cfg.hidden_size, dtype=jnp.float32, name="lin"
        )(nn.silu(vec.astype(jnp.float32)))
        return jnp.split(out[:, None, :], self.n_chunks, axis=-1)


class _StreamAttnIn(nn.Module):
    """Pre-norm + modulation + fused qkv (+ optional per-head q/k RMS)."""

    cfg: MMDiTConfig

    @nn.compact
    def __call__(self, x, shift, scale):
        cfg = self.cfg
        H, D = cfg.num_heads, cfg.head_dim
        h = nn.LayerNorm(
            use_bias=False, use_scale=False, epsilon=1e-6, dtype=cfg.dtype,
            name="norm",
        )(x)
        h = _modulate(h, shift, scale)
        qkv = nn.DenseGeneral((3, H, D), dtype=cfg.dtype, name="qkv")(h)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.qk_norm:
            q = rms_normalize(q, self.param("ln_q", nn.initializers.ones, (D,)))
            k = rms_normalize(k, self.param("ln_k", nn.initializers.ones, (D,)))
        return h, q, k, v


class JointBlock(nn.Module):
    """One MMDiT block: context + x streams modulate/qkv separately, attend
    jointly over [context ‖ x], then per-stream proj/MLP. ``pre_only`` (the
    final block's context side) contributes qkv to the joint attention but has
    no output path — the context stream ends there."""

    cfg: MMDiTConfig
    pre_only: bool = False
    dual_attn: bool = False

    @nn.compact
    def __call__(self, x, ctx, vec):
        cfg = self.cfg
        mlp_dim = int(cfg.hidden_size * cfg.mlp_ratio)

        if self.dual_attn:
            # mmdit-x (SD3.5-medium): 9-chunk x-side adaLN — the extra triple
            # modulates a SECOND self-attention over the x stream alone, fed from
            # the same pre-norm output (SAI chunk order: attn, mlp, attn2).
            (xs1, xc1, xg1, xs2, xc2, xg2, x2s, x2c, x2g) = _AdaLN(
                cfg, 9, name="x_adaln"
            )(vec)
            _, q2, k2, v2 = _StreamAttnIn(cfg, name="x_attn_in2")(x, x2s, x2c)
        else:
            x_mods = _AdaLN(cfg, 6, name="x_adaln")(vec)
            (xs1, xc1, xg1, xs2, xc2, xg2) = x_mods
        _, xq, xk, xv = _StreamAttnIn(cfg, name="x_attn_in")(x, xs1, xc1)

        if self.pre_only:
            cs1, cc1 = _AdaLN(cfg, 2, name="ctx_adaln")(vec)
            _, cq, ck, cv = _StreamAttnIn(cfg, name="ctx_attn_in")(ctx, cs1, cc1)
        else:
            (cs1, cc1, cg1, cs2, cc2, cg2) = _AdaLN(cfg, 6, name="ctx_adaln")(vec)
            _, cq, ck, cv = _StreamAttnIn(cfg, name="ctx_attn_in")(ctx, cs1, cc1)

        ctx_len = ctx.shape[1]
        q = jnp.concatenate([cq, xq], axis=1)
        k = jnp.concatenate([ck, xk], axis=1)
        v = jnp.concatenate([cv, xv], axis=1)
        attn_out = attention(q, k, v)
        attn_out = attn_out.reshape(attn_out.shape[0], attn_out.shape[1], -1)
        ctx_attn, x_attn = attn_out[:, :ctx_len], attn_out[:, ctx_len:]

        x = x + xg1.astype(cfg.dtype) * nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, name="x_attn_proj"
        )(x_attn)
        if self.dual_attn:
            attn2 = attention(q2, k2, v2)
            attn2 = attn2.reshape(attn2.shape[0], attn2.shape[1], -1)
            x = x + x2g.astype(cfg.dtype) * nn.Dense(
                cfg.hidden_size, dtype=cfg.dtype, name="x_attn2_proj"
            )(attn2)
        xm = nn.LayerNorm(
            use_bias=False, use_scale=False, epsilon=1e-6, dtype=cfg.dtype,
            name="x_norm2",
        )(x)
        x = x + xg2.astype(cfg.dtype) * nn.Sequential([
            nn.Dense(mlp_dim, dtype=cfg.dtype, name="x_mlp_in"),
            lambda t: nn.gelu(t, approximate=True),
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="x_mlp_out"),
        ])(_modulate(xm, xs2, xc2))

        if self.pre_only:
            return x, ctx
        ctx = ctx + cg1.astype(cfg.dtype) * nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, name="ctx_attn_proj"
        )(ctx_attn)
        cm = nn.LayerNorm(
            use_bias=False, use_scale=False, epsilon=1e-6, dtype=cfg.dtype,
            name="ctx_norm2",
        )(ctx)
        ctx = ctx + cg2.astype(cfg.dtype) * nn.Sequential([
            nn.Dense(mlp_dim, dtype=cfg.dtype, name="ctx_mlp_in"),
            lambda t: nn.gelu(t, approximate=True),
            nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="ctx_mlp_out"),
        ])(_modulate(cm, cs2, cc2))
        return x, ctx


class _PosTable(nn.Module):
    """The checkpoint's (max², hidden) sincos table as a lazily-materialized
    submodule (a bare self.param in setup would be demanded by every staged
    sub-pytree apply; submodule params materialize only when called)."""

    cfg: MMDiTConfig

    @nn.compact
    def __call__(self):
        return self.param(
            "table",
            lambda key: jnp.asarray(
                sincos_pos_embed(self.cfg.pos_embed_max, self.cfg.hidden_size)
            ),
        )


class MMDiTModel(nn.Module):
    """forward(x latent NHWC, timesteps (B,) flow-time in [0,1], context
    (B,S,4096), y=(B,2048) pooled). Staged like FluxModel for pipeline mode."""

    cfg: MMDiTConfig

    def setup(self):
        cfg = self.cfg
        token_dim = cfg.patch_size * cfg.patch_size * cfg.in_channels
        self.x_in = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
        self.pos_embed = _PosTable(cfg)
        self.context_in = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)
        self.time_in = _VecEmbedder(cfg)
        self.vector_in = _VecEmbedder(cfg)
        self.blocks = [
            JointBlock(
                cfg,
                pre_only=(i == cfg.depth - 1),
                dual_attn=(i in cfg.x_block_self_attn_layers),
            )
            for i in range(cfg.depth)
        ]
        self.final_mod = nn.Dense(2 * cfg.hidden_size, dtype=jnp.float32)
        self.final_norm = nn.LayerNorm(
            use_bias=False, use_scale=False, epsilon=1e-6, dtype=cfg.dtype
        )
        self.final_proj = nn.Dense(token_dim, dtype=jnp.float32)

    def _cropped_pos(self, hp: int, wp: int):
        """Center-crop the (max², hidden) table to the (hp, wp) token grid —
        SD3's cropped_pos_embed."""
        m = self.cfg.pos_embed_max
        if hp > m or wp > m:
            raise ValueError(f"latent grid {hp}x{wp} exceeds pos table {m}x{m}")
        top = (m - hp) // 2
        left = (m - wp) // 2
        table = self.pos_embed().reshape(m, m, -1)
        return table[top : top + hp, left : left + wp].reshape(1, hp * wp, -1)

    def prepare(self, x, timesteps, context=None, y=None, **kwargs):
        cfg = self.cfg
        B, Hh, Ww, C = x.shape
        p = cfg.patch_size
        hp, wp = Hh // p, Ww // p

        img = x.astype(cfg.dtype).reshape(B, hp, p, wp, p, C)
        img = img.transpose(0, 1, 3, 2, 4, 5).reshape(B, hp * wp, p * p * C)
        img = self.x_in(img) + self._cropped_pos(hp, wp).astype(cfg.dtype)

        if context is None:
            raise ValueError("SD3 requires text context tokens")
        ctx = self.context_in(context.astype(cfg.dtype))

        vec = self.time_in(
            timestep_embedding(timesteps, 256, time_factor=1000.0).astype(cfg.dtype)
        )
        if y is None:
            y = jnp.zeros((B, cfg.pooled_dim), jnp.float32)
        vec = vec + self.vector_in(y.astype(cfg.dtype))
        return {"img": img, "ctx": ctx, "vec": vec}

    def block_step(self, carry, i: int):
        img, ctx = self.blocks[i](carry["img"], carry["ctx"], carry["vec"])
        return {**carry, "img": img, "ctx": ctx}

    def finalize(self, carry, out_shape: tuple[int, ...]):
        cfg = self.cfg
        img, vec = carry["img"], carry["vec"]
        B, Hh, Ww, C = out_shape
        p = cfg.patch_size
        hp, wp = Hh // p, Ww // p
        shift, scale = jnp.split(
            self.final_mod(nn.silu(vec.astype(jnp.float32)))[:, None, :], 2, axis=-1
        )
        img = _modulate(self.final_norm(img), shift, scale)
        img = self.final_proj(img.astype(jnp.float32))
        img = img.reshape(B, hp, wp, p, p, C).transpose(0, 1, 3, 2, 4, 5)
        return img.reshape(B, Hh, Ww, C)

    def __call__(self, x, timesteps, context=None, y=None, **kwargs):
        carry = self.prepare(x, timesteps, context, y=y)
        for i in range(self.cfg.depth):
            carry = self.block_step(carry, i)
        return self.finalize(carry, x.shape)


def _mmdit_pipeline_spec(module: MMDiTModel, cfg: MMDiTConfig) -> PipelineSpec:
    def prepare(params, x, t, context=None, **kw):
        return module.apply({"params": params}, x, t, context, **kw,
                            method=MMDiTModel.prepare)

    def make_block(i):
        def fn(params, carry):
            return module.apply({"params": params}, carry, i,
                                method=MMDiTModel.block_step)
        return fn

    def finalize(params, carry, out_shape):
        return module.apply({"params": params}, carry, out_shape,
                            method=MMDiTModel.finalize)

    prepare_keys = ("x_in", "pos_embed", "context_in", "time_in", "vector_in")
    return PipelineSpec(
        prepare_keys=prepare_keys,
        prepare=prepare,
        segments=tuple(
            PipelineSegment((f"blocks_{i}",), make_block(i), label=f"joint_{i}")
            for i in range(cfg.depth)
        ),
        finalize_keys=("final_mod", "final_proj"),  # final_norm is affine-free (no params)
        finalize=finalize,
    )


def build_mmdit(
    cfg: MMDiTConfig,
    rng=None,
    params=None,
    sample_shape=(1, 32, 32, 16),
    txt_len: int = 77,
    name: str = "mmdit",
) -> DiffusionModel:
    """Initialize (or wrap converted ``params``) an SD3-class MMDiT."""
    module = MMDiTModel(cfg)
    if params is None:
        if rng is None:
            raise ValueError("need rng to initialize (or pass params=)")
        x = jnp.zeros(sample_shape, jnp.float32)
        t = jnp.zeros((sample_shape[0],), jnp.float32)
        c = jnp.zeros((sample_shape[0], txt_len, cfg.context_in_dim), jnp.float32)
        params = module.init(rng, x, t, c)["params"]

    def apply(params, x, timesteps, context=None, **kw):
        return module.apply({"params": params}, x, timesteps, context, **kw)

    return DiffusionModel(
        apply=apply,
        params=params,
        name=name,
        config=cfg,
        block_lists={"joint_blocks": cfg.depth},
        pipeline_spec=_mmdit_pipeline_spec(module, cfg),
    )
