"""WAN video-VAE checkpoint (official Wan2.x layout) → models/video_vae.py params.

The reference's WAN2.2 workload (/root/reference/README.md:5) decodes through the
host app's torch VAE; standalone, the official ``Wan2.x_VAE.pth``-style state dict
converts once into the functional param tree here. Layout map (torch names left):

- ``encoder.conv1`` / ``decoder.conv1``      → ``{en,de}coder/conv_in``
- ``encoder.head.{0,2}`` / ``decoder.head.{0,2}`` → ``norm_out`` / ``conv_out``
  (index 1 is the parameterless SiLU)
- ``conv1`` / ``conv2`` (top level)          → ``quant_conv`` / ``post_quant_conv``
- ``encoder.downsamples.{seq}`` — a flat Sequential; indices are recomputed here
  from the config: per level ``num_res_blocks`` ResidualBlocks then (below the
  last level) one Resample. ResidualBlock subkeys: ``residual.0``/``residual.3``
  (RMS norms), ``residual.2``/``residual.6`` (causal convs), ``shortcut`` when
  channels change. Resample subkeys: ``resample.1`` (spatial conv behind the
  ZeroPad/Upsample at index 0) and ``time_conv`` for the 3d modes.
- ``decoder.upsamples.{seq}`` — same flattening with ``num_res_blocks + 1``
  blocks per level.
- ``encoder.middle.{0,1,2}`` / ``decoder.middle.{0,1,2}`` → ``mid_block_1`` /
  ``mid_attn_1`` / ``mid_block_2``; the attention block's ``to_qkv``/``proj``
  are per-frame 1×1 Conv2d, its norm an RMS norm whose optional bias we zero-fill.

Transforms: Conv3d (O,I,T,H,W) → (T,H,W,I,O); Conv2d (O,I,H,W) → (1,H,W,I,O);
RMS gammas (C,1,1[,1]) → (C,). Semantics note (documented divergence): the torch
implementation streams 4-frame chunks through per-conv feature caches; this
framework runs the whole clip as one causal fixed-shape program. Weights map 1:1,
interior frames match; the torch streaming seam-handling at the very first chunk
is replaced by explicit causal front-padding.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np

from .convert import to_numpy, tree_to_jnp
from .video_vae import VideoVAEConfig


def _conv3d(sd: Mapping[str, Any], key: str) -> dict:
    w = to_numpy(sd[f"{key}.weight"])
    out = {"kernel": w.transpose(2, 3, 4, 1, 0)}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return {"conv": out}


def _conv2d(sd: Mapping[str, Any], key: str) -> dict:
    w = to_numpy(sd[f"{key}.weight"])
    out = {"kernel": w.transpose(2, 3, 1, 0)[None]}
    if f"{key}.bias" in sd:
        out["bias"] = to_numpy(sd[f"{key}.bias"])
    return out


def _rms(sd: Mapping[str, Any], key: str, want_bias: bool = False) -> dict:
    gamma = to_numpy(sd[f"{key}.gamma"]).reshape(-1)
    out = {"scale": gamma}
    if want_bias:
        bias = sd.get(f"{key}.bias")
        out["bias"] = (
            to_numpy(bias).reshape(-1)
            if bias is not None
            else np.zeros_like(gamma)
        )
    return out


def _res_block(sd: Mapping[str, Any], key: str) -> dict:
    out = {
        "norm1": _rms(sd, f"{key}.residual.0"),
        "conv1": _conv3d(sd, f"{key}.residual.2"),
        "norm2": _rms(sd, f"{key}.residual.3"),
        "conv2": _conv3d(sd, f"{key}.residual.6"),
    }
    if f"{key}.shortcut.weight" in sd:
        out["shortcut"] = _conv3d(sd, f"{key}.shortcut")
    return out


def _attn_block(sd: Mapping[str, Any], key: str) -> dict:
    return {
        "norm": _rms(sd, f"{key}.norm", want_bias=True),
        "to_qkv": _conv2d(sd, f"{key}.to_qkv"),
        "proj": _conv2d(sd, f"{key}.proj"),
    }


def _resample(sd: Mapping[str, Any], key: str, temporal: bool) -> dict:
    # The spatial conv is a plain nn.Conv child named "conv"; the temporal one a
    # CausalConv3d wrapper (hence the extra nesting level).
    out: dict[str, Any] = {"conv": _conv2d(sd, f"{key}.resample.1")}
    if temporal:
        out["time_conv"] = _conv3d(sd, f"{key}.time_conv")
    return out


def convert_wan_vae_checkpoint(
    state_dict: Mapping[str, Any], cfg: VideoVAEConfig
) -> dict:
    """Official WAN VAE state dict → the ``VideoAutoencoderKL`` param pytree
    (pass to ``build_video_vae(cfg, params=...)``)."""
    sd = dict(state_dict)
    n = len(cfg.channel_mult)

    enc: dict[str, Any] = {
        "conv_in": _conv3d(sd, "encoder.conv1"),
        "mid_block_1": _res_block(sd, "encoder.middle.0"),
        "mid_attn_1": _attn_block(sd, "encoder.middle.1"),
        "mid_block_2": _res_block(sd, "encoder.middle.2"),
        "norm_out": _rms(sd, "encoder.head.0"),
        "conv_out": _conv3d(sd, "encoder.head.2"),
    }
    seq = 0
    for level in range(n):
        for i in range(cfg.num_res_blocks):
            enc[f"down_{level}_block_{i}"] = _res_block(
                sd, f"encoder.downsamples.{seq}"
            )
            seq += 1
        if level != n - 1:
            enc[f"down_{level}_downsample"] = _resample(
                sd, f"encoder.downsamples.{seq}", cfg.temporal_downsample[level]
            )
            seq += 1

    dec: dict[str, Any] = {
        "conv_in": _conv3d(sd, "decoder.conv1"),
        "mid_block_1": _res_block(sd, "decoder.middle.0"),
        "mid_attn_1": _attn_block(sd, "decoder.middle.1"),
        "mid_block_2": _res_block(sd, "decoder.middle.2"),
        "norm_out": _rms(sd, "decoder.head.0"),
        "conv_out": _conv3d(sd, "decoder.head.2"),
    }
    temporal_up = tuple(reversed(cfg.temporal_downsample))
    seq = 0
    for j, level in enumerate(reversed(range(n))):
        for i in range(cfg.num_res_blocks + 1):
            dec[f"up_{level}_block_{i}"] = _res_block(
                sd, f"decoder.upsamples.{seq}"
            )
            seq += 1
        if j != n - 1:
            dec[f"up_{level}_upsample"] = _resample(
                sd, f"decoder.upsamples.{seq}", temporal_up[j]
            )
            seq += 1

    params = {
        "encoder": enc,
        "decoder": dec,
        "quant_conv": _conv3d(sd, "conv1"),
        "post_quant_conv": _conv3d(sd, "conv2"),
    }
    return tree_to_jnp(params)
